#!/usr/bin/env python
"""pstop: top-like live console over the scheduler's telemetry ring.

``core/telemetry.py``'s :class:`TelemetryAggregator` appends one derived
row per ingested TELEMETRY frame to a bounded per-node ring and (when
constructed with ``jsonl_path=``) spills the same rows to a JSONL file.
This tool renders that stream as a fleet table — per-node message/byte
rates, deliver latency, staleness quantiles, straggler flags, SLO
verdicts, active migrations — refreshed in place like ``top``.

It reads the JSONL spill, so it runs out-of-process against a live
training job (the writer flushes whole lines only, so a concurrent
reader never sees a torn row) or after the fact against a saved file::

    python tools/pstop.py traces/telemetry.jsonl            # live, 1 Hz
    python tools/pstop.py --interval 0.2 traces/telemetry.jsonl
    python tools/pstop.py --once traces/telemetry.jsonl     # one snapshot

Columns:

- ``SEQ``       last frame sequence number ingested from the node;
- ``AGE``       seconds since that frame arrived, relative to the newest
                ingest stamp in the file (exact for live tails);
- ``MSG/S`` / ``KB/S``  transport rates over the node's originated links;
- ``P99ms``     inter-frame deliver-latency p99 (this frame's link deltas);
- ``STALE p50/p99``  worst staleness series (update version-lag, in
                VERSIONS behind the server, not time) — ``-`` until the
                node has recorded staleness samples;
- ``INF``       in-flight device applies (the ApplyLedger's
                ``inflight_bundles`` gauge, servers only);
- ``BKLG``      age of the oldest un-retired device apply, seconds;
- ``APLYms``    p99 of the worst ``apply.*`` total-latency digest
                (submit -> retire), milliseconds;
- ``WIREus``    sampled-request wire-transit p99 (the ``trace.wire``
                digest: worker submit stamp -> van receive, ISSUE 18),
                microseconds — ``-`` until a sampled request crossed a
                wire transport (loopback runs never populate it);
- ``SQus``      server receive -> handler dispatch p99 (``trace.sq``),
                microseconds — the server-queue plane of the same
                sampled requests;
- ``APLY%``     share of the apply plane in the traced server-side
                p99 budget: ``trace.apply`` p99 over the sum of the
                wire/server-queue/apply p99s, percent;
- ``RO/S``      read-only fast-path pulls answered per second (servers)
                — the serving plane's throughput column;
- ``HIT%``      lifetime hot-row cache hit ratio (serving workers) —
                ``-`` until the node has looked up at least one key;
- ``GRP%``      group fan-in: wire PUSH applies as % of the raw member
                pushes they stand for (servers; 100 = no pre-reduction,
                25 = 4-member groups fully merged) — ``-`` until a
                group-stamped push arrives;
- ``SHED/S``    reads shed by admission control per second (serving
                workers; the ``serve.shed`` event rate);
- ``CKPT``      seconds since the node's shard last committed to (or
                restored from) a durable snapshot — the durability
                plane's ``ckpt_age_s`` gauge (servers only; ``-`` for
                nodes that never snapshot);
- ``MODE``      consistency-plane mode on the node's gated tables
                (``bsp``/``ssp``/``asp``, servers; ``-`` = ungated,
                ISSUE 20);
- ``BOUND``     the active SSP staleness bound (``0`` under BSP,
                ``inf`` under ASP) — live, so a BoundTuner retune shows
                up within one telemetry beat;
- ``GATEms``    p99 wall time a gated pull/push spent parked on
                ``__wait__`` replies before admission (the worker's
                ``consist.gate_wait`` digest), milliseconds;
- ``DRP``       cumulative telemetry frames the aggregator dropped for
                this node (duplicates/stale seq — control-plane health);
- ``MIG``       active migrations (begin - commit - abort event totals);
- ``SLO``       ``ok`` / ``BREACH:<spec,...>`` from the live engine;
- ``FLAGS``     FleetMonitor straggler flags (``latency``, ``gap``).

Below the table a ``== FLEET ... ==`` footer rolls the whole fleet into
one row — aggregate MSG/S, the worst node's staleness p99, the running
SLO-breach-minutes and the current war-game scenario phase (the last two
ride each row's ``ctl`` block when a scenario is active) — so 200-node
drills stay readable without scanning 200 rows.

``--json`` swaps the table for ONE machine-readable JSON document per
refresh (``snapshot()``'s shape: reference stamp, per-node latest rows,
breached-node list), so downstream tooling — autoscalers, dashboards, CI
gates — can consume the same stream pstop renders.

``render`` and ``snapshot`` are pure functions over
``TelemetryAggregator.latest()``-shaped dicts, so tests and in-process
callers can use them without a terminal.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional

#: ANSI: clear screen + home — the in-place refresh between frames.
_CLEAR = "\x1b[2J\x1b[H"

_HEADER = (
    f"{'NODE':<10} {'SEQ':>5} {'AGE':>6} {'MSG/S':>8} {'KB/S':>9} "
    f"{'P99ms':>8} {'STALE p50/p99':>14} {'INF':>4} {'BKLG':>6} "
    f"{'APLYms':>7} {'WIREus':>7} {'SQus':>6} {'APLY%':>6} "
    f"{'RO/S':>7} {'HIT%':>5} {'CMPR%':>6} {'GRP%':>6} "
    f"{'SHED/S':>7} {'CKPT':>6} "
    f"{'MODE':>4} {'BOUND':>5} {'GATEms':>7} "
    f"{'DRP':>4} {'MIG':>3} {'SLO':<18} FLAGS"
)

#: consistency-plane mode gauge decode (mirrors kv/consistency.MODE_CODES;
#: 0 / absent = no gated tables on the node).
_MODE_NAMES = {0: "-", 1: "bsp", 2: "ssp", 3: "asp"}


def _consist_columns(row: dict):
    """(mode_str, bound_str, gate_p99_ms) for the consistency plane.

    Mode/bound come from the aggregator's derived gauges (servers with a
    gated table); the gate-wait p99 comes from the WORKER's
    ``consist.gate_wait`` digest — so in a fleet view the server rows
    show MODE/BOUND and the worker rows show GATEms, which is where each
    number is actually measured.
    """
    mode = row.get("consist_mode")
    mode_s = _MODE_NAMES.get(int(mode), "?") if mode is not None else None
    bound = row.get("consist_bound")
    bound_s = None
    if mode is not None and bound is not None:
        bound_s = "inf" if int(bound) < 0 else str(int(bound))
    gate = _trace_p99_s(row, "consist.gate_wait")
    return mode_s, bound_s, None if gate is None else 1e3 * gate


def load_rows(path: str) -> Dict[str, dict]:
    """Latest row per node from a telemetry JSONL spill.

    Tolerates a torn final line (a reader racing the writer's rotation)
    by skipping undecodable lines; keeps the row with the highest ``seq``
    per node so replayed files collapse to current state.
    """
    latest: Dict[str, dict] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            node = row.get("node")
            if not isinstance(node, str):
                continue
            have = latest.get(node)
            if have is None or int(row.get("seq") or 0) >= int(have.get("seq") or 0):
                latest[node] = row
    return latest


def _worst_staleness(row: dict) -> Optional[dict]:
    """The staleness series with the highest p99 (the one that matters)."""
    series = row.get("staleness")
    if not isinstance(series, dict) or not series:
        return None
    return max(
        (s for s in series.values() if isinstance(s, dict)),
        key=lambda s: float(s.get("p99") or 0.0),
        default=None,
    )


def _apply_p99_ms(row: dict) -> Optional[float]:
    """p99 of the worst ``apply.*`` TOTAL-latency digest, in ms.

    Reads the device-plane ``digests`` row field (seconds axis); the
    attribution splits (``apply_host.*``/``apply_h2d.*``/``apply_dev.*``)
    are deliberately skipped — the column answers "how late is the device
    plane", not "where inside the apply".
    """
    digs = row.get("digests")
    if not isinstance(digs, dict):
        return None
    worst = None
    for name, s in digs.items():
        if not name.startswith("apply.") or not isinstance(s, dict):
            continue
        p99 = float(s.get("p99") or 0.0)
        if worst is None or p99 > worst:
            worst = p99
    return None if worst is None else 1e3 * worst


def _trace_p99_s(row: dict, name: str) -> Optional[float]:
    """p99 of one tracing-plane digest (``trace.wire``/``trace.sq``/
    ``trace.apply``), in seconds — None until the node has samples."""
    digs = row.get("digests")
    if not isinstance(digs, dict):
        return None
    s = digs.get(name)
    if not isinstance(s, dict):
        return None
    p99 = s.get("p99")
    return None if p99 is None else float(p99)


def _trace_columns(row: dict):
    """(wire_p99_us, sq_p99_us, apply_share_pct) for the traced planes.

    The share is ``trace.apply`` p99 over the wire+queue+apply p99 sum —
    "of the server-side budget a sampled request pays, how much is the
    device apply" — and needs the apply digest present; absent planes
    (loopback has no wire) contribute zero to the denominator.
    """
    wire = _trace_p99_s(row, "trace.wire")
    sq = _trace_p99_s(row, "trace.sq")
    apply_ = _trace_p99_s(row, "trace.apply")
    share = None
    if apply_ is not None:
        denom = (wire or 0.0) + (sq or 0.0) + apply_
        if denom > 0:
            share = 100.0 * apply_ / denom
    return (
        None if wire is None else 1e6 * wire,
        None if sq is None else 1e6 * sq,
        share,
    )


def fleet_summary(latest: Dict[str, dict]) -> dict:
    """Fleet-wide roll-up for the footer row (ISSUE 19, satellite).

    Aggregates the numbers a 200-node run needs readable without 200
    rows: total MSG/S across the fleet, the worst single node's
    staleness p99, the running SLO-breach-minutes and the current
    scenario phase.  The last two ride every row's ``ctl`` block (the
    aggregator stamps them fleet-wide), so the freshest row — highest
    ``t_ingest`` — wins; older rows may predate a phase change.
    """
    msgs_total = 0.0
    have_msgs = False
    worst_stale = None
    for row in latest.values():
        m = row.get("msgs_per_s")
        if m is not None:
            msgs_total += float(m)
            have_msgs = True
        stale = _worst_staleness(row)
        if stale is not None:
            p99 = float(stale.get("p99") or 0.0)
            if worst_stale is None or p99 > worst_stale:
                worst_stale = p99
    phase = None
    breach_min = None
    for row in sorted(
        latest.values(), key=lambda r: float(r.get("t_ingest") or 0.0)
    ):
        ctl = row.get("ctl") or {}
        if ctl.get("phase") is not None:
            phase = ctl["phase"]
        if ctl.get("breach_min") is not None:
            breach_min = float(ctl["breach_min"])
    return {
        "msgs_per_s": round(msgs_total, 3) if have_msgs else None,
        "worst_stale_p99": worst_stale,
        "breach_minutes": breach_min,
        "phase": phase,
    }


def snapshot(latest: Dict[str, dict], now: Optional[float] = None) -> dict:
    """One machine-readable fleet snapshot (the ``--json`` payload).

    Same inputs as :func:`render`; carries the raw latest rows verbatim
    (counters, staleness, digests, ctl, breaches — nothing re-derived that
    downstream tooling might disagree with) plus the derived roll-ups the
    table prints: reference stamp, per-node age, breached-node list.
    """
    stamps = [float(r.get("t_ingest") or 0.0) for r in latest.values()]
    ref = (max(stamps) if stamps else 0.0) if now is None else now
    breached = sorted(
        n for n, r in latest.items() if r.get("healthy") is False
    )
    return {
        "t_ref": round(ref, 6),
        "n_nodes": len(latest),
        "breached": breached,
        "fleet": fleet_summary(latest),
        "nodes": {
            n: dict(
                latest[n],
                age_s=round(
                    max(ref - float(latest[n].get("t_ingest") or ref), 0.0), 3
                ),
            )
            for n in sorted(latest)
        },
    }


def render(latest: Dict[str, dict], now: Optional[float] = None) -> List[str]:
    """Format the fleet table; returns lines (no trailing newline).

    ``latest`` is ``{node: row}`` as produced by
    ``TelemetryAggregator.latest()`` or :func:`load_rows`.  ``now`` is the
    reference for the AGE column, in the same clock domain as the rows'
    ``t_ingest`` stamps; defaults to the newest stamp present, which makes
    offline replays show age-at-capture instead of nonsense.
    """
    if not latest:
        return ["(no telemetry rows yet)"]
    stamps = [
        float(r.get("t_ingest") or 0.0) for r in latest.values()
    ]
    ref = max(stamps) if now is None else now
    lines = [_HEADER]
    breached_total = 0
    for node in sorted(latest):
        row = latest[node]
        age = max(ref - float(row.get("t_ingest") or ref), 0.0)
        msgs = row.get("msgs_per_s")
        kbs = (
            float(row["bytes_per_s"]) / 1e3
            if row.get("bytes_per_s") is not None else None
        )
        p99 = row.get("deliver_p99_ms")
        stale = _worst_staleness(row)
        stale_s = (
            f"{stale['p50']:.0f}/{stale['p99']:.0f}" if stale else "-"
        )
        mig = row.get("migrations_active") or 0
        # device plane: ApplyLedger gauges ride the cumulative counters,
        # apply latency rides the digests field, drops ride ctl
        counters = row.get("counters") or {}
        inf = counters.get("inflight_bundles")
        bklg = counters.get("backlog_age_s")
        aply = _apply_p99_ms(row)
        # tracing plane (ISSUE 18): sampled-request wire/queue p99s and
        # the apply plane's share of the traced server-side budget
        wire_us, sq_us, aply_pct = _trace_columns(row)
        # serving plane: rates derived by the aggregator per beat; the hit
        # ratio is lifetime-cumulative (see core/telemetry.py)
        ro_s = row.get("ro_per_s")
        hitp = row.get("cache_hit_pct")
        # quantized wire plane: compressed bytes as % of raw (lifetime-
        # cumulative, derived by the aggregator from MeteredVan counters)
        cmpr = row.get("cmpr_pct")
        # hierarchical push: group-reduced PUSH requests as % of the raw
        # member pushes they carry (lifetime-cumulative, servers only)
        grp = row.get("grp_pct")
        shed_s = row.get("shed_per_s")
        # durability plane: seconds since the shard's last snapshot commit
        # (the ckpt_age_s gauge, surfaced by the aggregator like ro_per_s)
        ckpt = row.get("ckpt_age_s")
        if ckpt is None:
            ckpt = counters.get("ckpt_age_s")
        # consistency plane (ISSUE 20): mode/bound gauges + gate-wait p99
        mode_s, bound_s, gate_ms = _consist_columns(row)
        drops = (row.get("ctl") or {}).get("drops")
        healthy = row.get("healthy")
        if healthy is None:
            slo = "-"
        elif healthy:
            slo = "ok"
        else:
            breaches = row.get("breaches") or []
            breached_total += 1
            slo = "BREACH:" + ",".join(breaches) if breaches else "BREACH"
        flags = ",".join(row.get("straggler") or []) or "-"
        lines.append(
            f"{node:<10} {int(row.get('seq') or 0):>5} {age:>5.1f}s "
            f"{msgs if msgs is not None else '-':>8} "
            f"{f'{kbs:.1f}' if kbs is not None else '-':>9} "
            f"{p99 if p99 is not None else '-':>8} {stale_s:>14} "
            f"{int(inf) if inf is not None else '-':>4} "
            f"{f'{bklg:.1f}' if bklg is not None else '-':>6} "
            f"{f'{aply:.1f}' if aply is not None else '-':>7} "
            f"{f'{wire_us:.0f}' if wire_us is not None else '-':>7} "
            f"{f'{sq_us:.0f}' if sq_us is not None else '-':>6} "
            f"{f'{aply_pct:.1f}' if aply_pct is not None else '-':>6} "
            f"{f'{ro_s:.1f}' if ro_s is not None else '-':>7} "
            f"{f'{hitp:.1f}' if hitp is not None else '-':>5} "
            f"{f'{cmpr:.1f}' if cmpr is not None else '-':>6} "
            f"{f'{grp:.1f}' if grp is not None else '-':>6} "
            f"{f'{shed_s:.1f}' if shed_s is not None else '-':>7} "
            f"{f'{float(ckpt):.1f}' if ckpt is not None else '-':>6} "
            f"{mode_s if mode_s is not None else '-':>4} "
            f"{bound_s if bound_s is not None else '-':>5} "
            f"{f'{gate_ms:.1f}' if gate_ms is not None else '-':>7} "
            f"{int(drops) if drops is not None else '-':>4} "
            f"{mig:>3} {slo:<18} {flags}"
        )
    fleet = fleet_summary(latest)
    msgs = fleet["msgs_per_s"]
    stale = fleet["worst_stale_p99"]
    bmin = fleet["breach_minutes"]
    lines.append(
        f"== FLEET  MSG/S={f'{msgs:.1f}' if msgs is not None else '-'}  "
        f"worst STALE p99="
        f"{f'{stale:.0f}' if stale is not None else '-'}  "
        f"breach-min={f'{bmin:.2f}' if bmin is not None else '-'}  "
        f"phase={fleet['phase'] or '-'} =="
    )
    lines.append(
        f"-- {len(latest)} nodes, {breached_total} breached; "
        "staleness in versions, rates per second --"
    )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="live fleet console over a telemetry JSONL spill"
    )
    ap.add_argument("path", help="telemetry.jsonl written by the aggregator")
    ap.add_argument(
        "--interval", type=float, default=1.0,
        help="refresh period in seconds (default: %(default)s)",
    )
    ap.add_argument(
        "--once", action="store_true",
        help="print one snapshot and exit (no screen clearing)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit one machine-readable JSON snapshot per refresh "
        "(one document per line; no screen clearing)",
    )
    args = ap.parse_args(argv)
    if args.interval <= 0:
        print("pstop: --interval must be > 0", file=sys.stderr)
        return 2
    while True:
        try:
            latest = load_rows(args.path)
        except OSError as e:
            print(f"pstop: {e}", file=sys.stderr)
            return 1
        if args.json:
            out = json.dumps(snapshot(latest))
        else:
            out = "\n".join(render(latest))
        if args.once:
            print(out)
            return 0
        if args.json:
            sys.stdout.write(out + "\n")
        else:
            sys.stdout.write(_CLEAR + out + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
