#!/usr/bin/env python
"""Merge flight-recorder bundles into one causal, clock-rebased timeline.

``core/flightrec.py`` dumps one JSON bundle per node on failure (recv-thread
exception, failing chaos test, explicit ``dump()``).  Loaded alone those are
N disconnected rings; merged, the recipient's ``fence.routing`` lines up
with the donor's ``resend.retransmit`` and the scheduler's ``node.restart``
— the fence -> retransmit -> restart story a postmortem actually needs.

Clock alignment reuses the two mechanisms the plane already has:

- each bundle carries paired ``wall_anchor_s`` / ``mono_anchor_s`` anchors
  captured together at recorder construction, so every monotonic event
  stamp rebases onto the wall clock exactly as ``tools/merge_traces.py``
  rebases chrome spans via ``metadata.clock_t0_s``;
- each bundle's ``clock_offset_s`` (this node's monotonic clock minus the
  scheduler's, from the heartbeat min-RTT sync —
  ``FleetMonitor.clock_offset``) is subtracted, so cross-host rings line up
  to RTT/2 accuracy.  In-process bundles share one clock and carry 0.

Ordering is causal within the accuracy of those offsets: rebased time
first, then (node, seq) — seq is per-recorder monotonic, so two events from
one node can never invert.

Usage::

    python tools/postmortem.py bundles/flightrec_*.json
    python tools/postmortem.py -o timeline.json --last 40 bundles/*.json

The report prints the merged timeline tail — the "last N events before the
first anomaly" (gave-up, fence, restart, abort, recv.exception,
slo.breach...), plus everything after it — and ``-o`` writes the full
merged timeline as JSON for tooling.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

#: event kinds that count as "something went wrong" for the report anchor.
#: Mirrors ``flightrec.anomaly_kinds()`` — kept literal here so the tool
#: runs standalone against bundle files with no package import.
ANOMALY_KINDS = frozenset({
    "frame.reject",
    "resend.gave_up",
    "fence.incarnation",
    "fence.routing",
    "node.restart",
    "migrate.abort",
    "recv.exception",
    "slo.breach",
    "apply.backlog",
    "serve.shed",
    "group.fallback",
    "ckpt.abort",
})


def load_bundle(path: str) -> dict:
    """Read one per-node bundle; tolerates missing optional sections."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc.get("events"), list):
        raise ValueError(f"{path}: not a flight-recorder bundle (no events)")
    doc.setdefault(
        "node", os.path.splitext(os.path.basename(path))[0]
    )
    return doc


def merge_bundles(paths: List[str]) -> dict:
    """Merge bundles into one causally ordered timeline document.

    Every event gains ``node``, ``t_s`` (rebased wall-clock seconds), and
    keeps its per-node ``seq``.  Rebase: ``wall_anchor + (t_mono -
    mono_anchor) - clock_offset`` — subtracting the offset maps each node's
    clock onto the shared scheduler reference.
    """
    bundles = [load_bundle(p) for p in paths]
    events: List[dict] = []
    for b in bundles:
        wall = float(b.get("wall_anchor_s") or 0.0)
        mono = float(b.get("mono_anchor_s") or 0.0)
        off = float(b.get("clock_offset_s") or 0.0)
        node = str(b["node"])
        for ev in b["events"]:
            ev = dict(ev)
            t_mono = float(ev.get("t_mono_s") or 0.0)
            ev["t_s"] = wall + (t_mono - mono) - off
            ev.setdefault("node", node)
            events.append(ev)
    # causal order: rebased time, then (node, seq) so one node's events
    # never invert even when stamps collide at clock resolution
    events.sort(key=lambda e: (e["t_s"], str(e["node"]), e.get("seq", 0)))
    return {
        "nodes": sorted({str(b["node"]) for b in bundles}),
        "counters": {
            str(b["node"]): b.get("counters") or {} for b in bundles
        },
        "events": events,
    }


def first_anomaly(events: List[dict]) -> Optional[int]:
    """Index of the first anomalous event in a merged timeline, or None."""
    for i, ev in enumerate(events):
        if ev.get("kind") in ANOMALY_KINDS:
            return i
    return None


def report(merged: dict, *, last: int = 30) -> List[str]:
    """Human-readable postmortem: the ``last`` events leading up to the
    first anomaly, then everything from the anomaly on.  Returns lines."""
    events = merged["events"]
    lines = [
        f"postmortem: {len(events)} events across "
        f"{len(merged['nodes'])} nodes ({', '.join(merged['nodes'])})"
    ]
    if not events:
        return lines + ["  (empty timeline)"]
    anom = first_anomaly(events)
    if anom is None:
        lines.append("no anomalies recorded; timeline tail:")
        window = events[-last:]
    else:
        ev = events[anom]
        lines.append(
            f"first anomaly: [{anom}] {ev['kind']} on {ev['node']} "
            f"at t={ev['t_s']:.6f}"
        )
        lines.append(f"last {last} events before it, then the aftermath:")
        window = events[max(0, anom - last):]
    t0 = window[0]["t_s"]
    for ev in window:
        extras = {
            k: v for k, v in ev.items()
            if k not in ("t_s", "t_mono_s", "seq", "kind", "node")
        }
        mark = "!" if ev.get("kind") in ANOMALY_KINDS else " "
        detail = " ".join(f"{k}={v}" for k, v in extras.items())
        lines.append(
            f" {mark} +{ev['t_s'] - t0:9.6f}s {str(ev['node']):>12s} "
            f"{ev['kind']:<20s} {detail}".rstrip()
        )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge flight-recorder bundles into one causal timeline"
    )
    ap.add_argument("bundles", nargs="+", help="flightrec_*.json bundle files")
    ap.add_argument(
        "-o", "--output", default=None,
        help="write the merged timeline JSON here (default: report only)",
    )
    ap.add_argument(
        "--last", type=int, default=30,
        help="events to show before the first anomaly (default: %(default)s)",
    )
    args = ap.parse_args(argv)
    try:
        merged = merge_bundles(args.bundles)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"postmortem: {e}", file=sys.stderr)
        return 1
    if args.output:
        with open(args.output, "w") as f:
            json.dump(merged, f)
    print("\n".join(report(merged, last=args.last)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
