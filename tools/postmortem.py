#!/usr/bin/env python
"""Merge flight-recorder bundles into one causal, clock-rebased timeline.

``core/flightrec.py`` dumps one JSON bundle per node on failure (recv-thread
exception, failing chaos test, explicit ``dump()``).  Loaded alone those are
N disconnected rings; merged, the recipient's ``fence.routing`` lines up
with the donor's ``resend.retransmit`` and the scheduler's ``node.restart``
— the fence -> retransmit -> restart story a postmortem actually needs.

Clock alignment reuses the two mechanisms the plane already has:

- each bundle carries paired ``wall_anchor_s`` / ``mono_anchor_s`` anchors
  captured together at recorder construction, so every monotonic event
  stamp rebases onto the wall clock exactly as ``tools/merge_traces.py``
  rebases chrome spans via ``metadata.clock_t0_s``;
- each bundle's ``clock_offset_s`` (this node's monotonic clock minus the
  scheduler's, from the heartbeat min-RTT sync —
  ``FleetMonitor.clock_offset``) is subtracted, so cross-host rings line up
  to RTT/2 accuracy.  In-process bundles share one clock and carry 0.

Ordering is causal within the accuracy of those offsets: rebased time
first, then (node, seq) — seq is per-recorder monotonic, so two events from
one node can never invert.

Usage::

    python tools/postmortem.py bundles/flightrec_*.json
    python tools/postmortem.py -o timeline.json --last 40 bundles/*.json

The report prints the merged timeline tail — the "last N events before the
first anomaly" (gave-up, fence, restart, abort, recv.exception,
slo.breach...), plus everything after it — and ``-o`` writes the full
merged timeline as JSON for tooling.  Two synthesized anchors rank
alongside journaled anomalies: an unclosed sampled span tree (ISSUE 18,
``trace.submit`` never acked) and an unreleased consistency gate
(ISSUE 20, ``consist.gate`` with no later ``consist.release`` for the
same server/sender/table — the fleet-minimum-stalled deadlock signature).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

#: event kinds that count as "something went wrong" for the report anchor.
#: Mirrors ``flightrec.anomaly_kinds()`` — kept literal here so the tool
#: runs standalone against bundle files with no package import.
ANOMALY_KINDS = frozenset({
    "frame.reject",
    "resend.gave_up",
    "fence.incarnation",
    "fence.routing",
    "node.restart",
    "migrate.abort",
    "recv.exception",
    "slo.breach",
    "apply.backlog",
    "serve.shed",
    "group.fallback",
    "ckpt.abort",
    "scenario.inject",
    "consist.shed",
})


def load_bundle(path: str) -> dict:
    """Read one per-node bundle; tolerates missing optional sections."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc.get("events"), list):
        raise ValueError(f"{path}: not a flight-recorder bundle (no events)")
    doc.setdefault(
        "node", os.path.splitext(os.path.basename(path))[0]
    )
    return doc


def merge_bundles(paths: List[str]) -> dict:
    """Merge bundles into one causally ordered timeline document.

    Every event gains ``node``, ``t_s`` (rebased wall-clock seconds), and
    keeps its per-node ``seq``.  Rebase: ``wall_anchor + (t_mono -
    mono_anchor) - clock_offset`` — subtracting the offset maps each node's
    clock onto the shared scheduler reference.
    """
    bundles = [load_bundle(p) for p in paths]
    events: List[dict] = []
    for b in bundles:
        wall = float(b.get("wall_anchor_s") or 0.0)
        mono = float(b.get("mono_anchor_s") or 0.0)
        off = float(b.get("clock_offset_s") or 0.0)
        node = str(b["node"])
        for ev in b["events"]:
            ev = dict(ev)
            t_mono = float(ev.get("t_mono_s") or 0.0)
            ev["t_s"] = wall + (t_mono - mono) - off
            ev.setdefault("node", node)
            events.append(ev)
    # causal order: rebased time, then (node, seq) so one node's events
    # never invert even when stamps collide at clock resolution
    events.sort(key=lambda e: (e["t_s"], str(e["node"]), e.get("seq", 0)))
    return {
        "nodes": sorted({str(b["node"]) for b in bundles}),
        "counters": {
            str(b["node"]): b.get("counters") or {} for b in bundles
        },
        "events": events,
    }


def orphan_traces(merged: dict) -> List[dict]:
    """Sampled requests whose span tree never closed (ISSUE 18).

    A ``trace.submit`` with no matching ``trace.ack`` means the worker
    never saw the last leg return — the request died somewhere between
    the submit and the ack (dropped past the resend budget, dead server,
    fenced-and-lost reply).  Each orphan is returned with its tid,
    submitting node, rebased submit time, and the partial causal chain:
    every merged trace event that mentions the tid (directly or inside a
    bundle's ``tids`` list), in timeline order — exactly the events a
    postmortem walks to see WHERE the request stopped.
    """
    events = merged["events"]
    submits: Dict[str, dict] = {}
    acked = set()
    chains: Dict[str, List[dict]] = {}
    for ev in events:
        kind = ev.get("kind") or ""
        if not kind.startswith("trace."):
            continue
        tids = ev.get("tids") or ([ev["tid"]] if ev.get("tid") else [])
        for tid in tids:
            chains.setdefault(tid, []).append(ev)
        if kind == "trace.submit" and ev.get("tid"):
            submits.setdefault(ev["tid"], ev)
        elif kind == "trace.ack" and ev.get("tid"):
            acked.add(ev["tid"])
    return [
        {
            "tid": tid,
            "node": sub.get("node"),
            "t_s": sub["t_s"],
            "chain": chains.get(tid, []),
        }
        for tid, sub in submits.items()
        if tid not in acked
    ]


def unreleased_gates(merged: dict) -> List[dict]:
    """Consistency gates that never released (ISSUE 20).

    The server records ``consist.gate`` the FIRST time it defers a
    sender on a table and ``consist.release`` when that sender's next
    stamped request is admitted — so in a healthy fleet every gate event
    eventually pairs with a release (or the sender degrades through a
    ``consist.shed`` and re-pairs on its next admitted step).  A gate
    with no later release for the same (server, sender, table) is the
    consistency plane's deadlock signature: the fleet minimum stopped
    advancing while this sender was parked — a dead straggler that was
    never pruned, or a barrier the rest of the fleet never reached.
    """
    events = merged["events"]
    open_gates: Dict[tuple, dict] = {}
    for ev in events:
        kind = ev.get("kind")
        if kind not in ("consist.gate", "consist.release"):
            continue
        key = (ev.get("node"), ev.get("sender"), ev.get("table"))
        if kind == "consist.gate":
            open_gates.setdefault(key, ev)
        else:
            open_gates.pop(key, None)
    return sorted(open_gates.values(), key=lambda e: e["t_s"])


def first_anomaly(events: List[dict]) -> Optional[int]:
    """Index of the first anomalous event in a merged timeline, or None."""
    for i, ev in enumerate(events):
        if ev.get("kind") in ANOMALY_KINDS:
            return i
    return None


def _row(ev: dict, t0: float) -> str:
    extras = {
        k: v for k, v in ev.items()
        if k not in ("t_s", "t_mono_s", "seq", "kind", "node")
    }
    mark = "!" if ev.get("kind") in ANOMALY_KINDS else " "
    detail = " ".join(f"{k}={v}" for k, v in extras.items())
    return (
        f" {mark} +{ev['t_s'] - t0:9.6f}s {str(ev['node']):>12s} "
        f"{ev['kind']:<20s} {detail}".rstrip()
    )


def report(merged: dict, *, last: int = 30) -> List[str]:
    """Human-readable postmortem: the ``last`` events leading up to the
    first anomaly, then everything from the anomaly on.  Returns lines.

    An unclosed sampled span tree (ISSUE 18: ``trace.submit`` with no
    ``trace.ack``) anchors the report exactly like a journaled anomaly —
    its submit is the last confirmed sighting of a request that never
    came back — and each orphan's partial causal chain is appended so
    the reader sees WHICH hop the request died after.
    """
    events = merged["events"]
    lines = [
        f"postmortem: {len(events)} events across "
        f"{len(merged['nodes'])} nodes ({', '.join(merged['nodes'])})"
    ]
    if not events:
        return lines + ["  (empty timeline)"]
    anom = first_anomaly(events)
    orphans = orphan_traces(merged)
    idx = {id(e): i for i, e in enumerate(events)}
    o_first = None
    if orphans:
        o_first = min(
            (idx[id(o["chain"][0])] for o in orphans if o["chain"]),
            default=None,
        )
    # a gate the server never released anchors the report exactly like an
    # orphaned span: the defer is the last confirmed sighting of a sender
    # the fleet minimum then strands (ISSUE 20)
    gates = unreleased_gates(merged)
    g_first = min((idx[id(g)] for g in gates), default=None)
    candidates = [i for i in (anom, o_first, g_first) if i is not None]
    if not candidates:
        lines.append("no anomalies recorded; timeline tail:")
        window = events[-last:]
    else:
        anchor = min(candidates)
        ev = events[anchor]
        if anchor == o_first and (anom is None or anchor < anom):
            lines.append(
                f"first anomaly: [{anchor}] unclosed span tree "
                f"{(ev.get('tid') or (ev.get('tids') or ['?'])[0])} "
                f"({ev['kind']} on {ev['node']} at t={ev['t_s']:.6f}, "
                "no trace.ack ever followed)"
            )
        elif anchor == g_first and (anom is None or anchor < anom):
            lines.append(
                f"first anomaly: [{anchor}] consistency gate never "
                f"released: {ev['node']} deferred {ev.get('sender')} on "
                f"{ev.get('table')!r} at t={ev['t_s']:.6f} and no "
                "consist.release ever followed (fleet minimum stalled)"
            )
        else:
            lines.append(
                f"first anomaly: [{anchor}] {ev['kind']} on {ev['node']} "
                f"at t={ev['t_s']:.6f}"
            )
        lines.append(f"last {last} events before it, then the aftermath:")
        window = events[max(0, anchor - last):]
    t0 = window[0]["t_s"]
    for ev in window:
        lines.append(_row(ev, t0))
    if orphans:
        lines.append(
            f"unclosed span trees: {len(orphans)} sampled request(s) "
            "submitted but never acked"
        )
        for o in orphans:
            lines.append(
                f"  trace {o['tid']} (submitted on {o['node']} at "
                f"t={o['t_s']:.6f}) — partial causal chain:"
            )
            chain_t0 = o["chain"][0]["t_s"] if o["chain"] else o["t_s"]
            for ev in o["chain"]:
                lines.append(" " + _row(ev, chain_t0))
    if gates:
        lines.append(
            f"unreleased consistency gates: {len(gates)} sender(s) "
            "deferred and never re-admitted"
        )
        for g in gates:
            lines.append(
                f"  {g['node']} gated {g.get('sender')} on "
                f"{g.get('table')!r} at t={g['t_s']:.6f} "
                f"(step={g.get('step')}, fleet_min={g.get('fleet_min')})"
            )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge flight-recorder bundles into one causal timeline"
    )
    ap.add_argument("bundles", nargs="+", help="flightrec_*.json bundle files")
    ap.add_argument(
        "-o", "--output", default=None,
        help="write the merged timeline JSON here (default: report only)",
    )
    ap.add_argument(
        "--last", type=int, default=30,
        help="events to show before the first anomaly (default: %(default)s)",
    )
    args = ap.parse_args(argv)
    try:
        merged = merge_bundles(args.bundles)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"postmortem: {e}", file=sys.stderr)
        return 1
    if args.output:
        with open(args.output, "w") as f:
            json.dump(merged, f)
    print("\n".join(report(merged, last=args.last)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
