#!/usr/bin/env python
"""Static contract check for VanWrapper subclasses.

The Van decorator stack (``ReliableVan(ChaosVan(LoopbackVan()))`` +
``CoalescingVan`` + ``MeteredVan``) relies on two conventions that, until
PR 6, nothing enforced:

1. **flush/close delegate down the chain.**  ``VanWrapper`` provides
   delegating defaults, but a subclass that overrides either (to drain its
   own buffers / join its own threads) MUST still call ``self.inner.flush``
   / ``self.inner.close`` (or ``super()``'s) — otherwise a buffering layer
   below it silently never drains, which reads as message loss only under
   load.  This was a real latent bug: ``ReliableVan.flush`` drained its own
   inflight table but swallowed the rest of the stack.

2. **counters() does NOT recurse.**  ``utils.metrics.transport_counters``
   walks the ``.inner`` chain itself and sums each layer's ``counters()``;
   a layer that also merged its inner's counters would double-count every
   key below it.

3. **No pickle on the frame hot path.**  The flat wire codec
   (``core/frame.py`` + its users ``core/tcp_van.py``, ``core/resender.py``,
   ``core/coalesce.py``) exists to kill the per-message pickle serialize/
   copy tax; an ``import pickle`` (or ``cPickle``/``dill``) creeping back
   into any of those modules silently re-introduces it — and puts
   arbitrary-code-execution deserialization back on a network-facing path.
   Enforced as a module-level import ban on :data:`NO_PICKLE_MODULES`
   (``check_no_pickle``).

4. **Flight-recorder kinds come from the closed registry.**  Every
   ``flightrec.record("<kind>", ...)`` call site (and the aliased/method
   forms ``rec(...)``, ``recorder.record(...)``) must pass a LITERAL kind
   string present in ``core/flightrec.py``'s ``EVENTS`` frozenset —
   otherwise the event taxonomy drifts stringly-typed and
   ``tools/postmortem.py`` / the SLO plane silently miss events
   (``check_flightrec_calls``; registry parsed by AST via
   ``load_event_registry``, which fails loudly if the literal moves).

5. **CONTROL verbs come from the closed registry.**  Every
   ``{"cmd": ...}`` payload literal must name a verb from
   ``core/manager.py``'s ``CONTROL_VERBS`` frozenset — either as one of
   the module's verb constants (``HEARTBEAT``, ``TELEMETRY``, ...) or as
   a literal string in the set.  A stringly-typed ``{"cmd": "telemtry"}``
   typo would otherwise fall through ``Manager.handle_request``'s elif
   chain and be silently acked as a no-op (``check_control_verbs``;
   registry parsed by AST via ``load_verb_registry``, same loud-failure
   stance as the event registry).

6. **The PUSH-ack path never blocks on device work.**  The server's
   bundle-batched apply engine (ISSUE 11) acks a push as soon as the
   donated-buffer device apply is DISPATCHED; a ``np.asarray`` /
   ``np.array`` / ``jax.device_get`` / ``.block_until_ready`` creeping
   into the post-dispatch bookkeeping (:data:`SYNC_FREE_FUNCS` in
   ``kv/server.py``) would silently put the whole device apply latency
   back on every worker's ack round trip.  Enforced per registered
   function (``check_push_ack_sync_free``); a registered function that
   disappears (rename) is itself a loud failure, never a vacuous pass.

7. **The ApplyLedger's submit side is sync-free too.**  The device-plane
   ledger (ISSUE 12, ``kv/ledger.py``) runs its registration methods
   (:data:`LEDGER_SYNC_FREE_FUNCS`: ``begin``/``mark_host``/``mark_h2d``/
   ``submit``/``overloaded``) ON the ack path — a device sync creeping into
   any of them would reintroduce exactly the latency the ledger exists to
   observe.  Same checker, same loud-failure stance.  The ``apply.*``
   event kinds the ledger journals must also be present in the EVENTS
   registry (:data:`REQUIRED_EVENTS`) — a registry edit that drops them
   would silence the device plane while every record call still "worked".

8. **The shm fast path is copy-free.**  Transport v2's whole win
   (ISSUE 17) is that a frame crosses a colocated link with ONE data
   movement (the slice-assign into the shared mapping) and is decoded as
   views in place on the other side.  A ``.tobytes()``, ``bytes(...)``
   staging copy, or ``ctypes.string_at`` creeping into the registered
   hot-path functions (:data:`SHM_COPY_FREE_FUNCS` in
   ``core/shm_ring.py``, :data:`VAN_COPY_FREE_FUNCS` in
   ``core/tcp_van.py`` — which also guards the borrowed-native-buffer
   recv path) silently reintroduces the per-frame copy tax the ring
   exists to kill.  Same loud-failure stance as the sync-free checks: a
   registered function that disappears is itself a violation.

9. **Trace-span recording is gated behind the sampling predicate.**  The
   request-tracing plane (ISSUE 18) promises ZERO per-message overhead
   for unsampled traffic: a ``trace.*`` flightrec record (or the aliased
   ``self._record("trace.*", ...)`` form) reached unconditionally on the
   hot path would put a span allocation on every message at 1/1024
   sampling.  Every registered hot-path function
   (:data:`TRACE_GATED_FUNCS`) must emit its ``trace.*`` records under an
   ``if`` — the sampling/context-presence gate — and a registered
   function that stops recording any ``trace.*`` kind (refactored away)
   is itself a violation (``check_trace_gated``).  The ``trace.*`` kinds
   are pinned in :data:`REQUIRED_EVENTS` so a registry edit cannot
   silence the plane.

Pure-AST check (no imports of the checked modules), so it runs in any
environment and is wired as a tier-1 test (``tests/test_wrapper_contract.py``).
Exit code 0 = clean; 1 = violations (one line each).
"""

from __future__ import annotations

import ast
import pathlib
import sys
from typing import Iterator, List

PKG = pathlib.Path(__file__).resolve().parent.parent / "parameter_server_tpu"

#: methods that must delegate to the inner van when overridden.
DELEGATING = ("flush", "close")

#: frame hot-path modules where any pickle-family import is banned —
#: encode/decode (tcp_van + frame), stamp/verify (resender), bundling
#: (coalesce).  Paths relative to the package root.
NO_PICKLE_MODULES = (
    "core/frame.py",
    "core/tcp_van.py",
    "core/resender.py",
    "core/coalesce.py",
)

#: module names whose import re-introduces the serialization tax (and an
#: arbitrary-code-execution decode) on the hot path.
_PICKLE_NAMES = frozenset(
    {"pickle", "cPickle", "_pickle", "dill", "cloudpickle", "marshal"}
)

#: module holding the closed event-kind registry (``EVENTS`` frozenset
#: literal), relative to the package root.
FLIGHTREC_MODULE = "core/flightrec.py"

#: module holding the closed CONTROL-verb registry (``CONTROL_VERBS``
#: frozenset literal + the verb constants), relative to the package root.
MANAGER_MODULE = "core/manager.py"

#: bare-callable names treated as flight-recorder record aliases (the
#: ``rec = recorder.record or flightrec.record`` pattern in utils/slo.py).
_RECORD_ALIASES = frozenset({"record", "rec"})

#: module holding the server's push-ack path, relative to the package root.
SERVER_MODULE = "kv/server.py"

#: ``kv/server.py`` functions on the PUSH-ack path — everything that runs
#: AFTER the device apply is dispatched and BEFORE the ack returns.  These
#: must never observe a device result: the ack's latency is host
#: bookkeeping only.  (``_upload_values`` / ``_handle_push_single`` stay
#: unregistered: their ``np.asarray`` touches the HOST wire plane before
#: dispatch; ``_forward_push`` is wire I/O that deliberately blocks on the
#: replica CHAIN ack in sync mode, not on device work.)
SYNC_FREE_FUNCS = frozenset(
    {
        "_ack_push",
        "_apply_push_group",
        "_push_group_rounds",
        "_push_group_combined",
    }
)

#: module holding the device-plane apply ledger, relative to the package
#: root (ISSUE 12).
LEDGER_MODULE = "kv/ledger.py"

#: ``kv/ledger.py`` methods that run on the server's ack path (register /
#: split-point stamping / the overload read in ``_ack_push``) — host
#: bookkeeping only, same contract as :data:`SYNC_FREE_FUNCS`.  The reaper
#: (``_reap_loop``/``_reap_once``/``_retire``) polls device readiness by
#: design and is deliberately NOT registered.
LEDGER_SYNC_FREE_FUNCS = frozenset(
    {
        "begin",
        "mark_host",
        "mark_h2d",
        "submit",
        "overloaded",
    }
)

#: event kinds that MUST exist in the EVENTS registry: the device-plane
#: taxonomy the ApplyLedger journals (ISSUE 12) plus the serving-plane
#: taxonomy the hot-row cache and admission control journal (ISSUE 13).
#: Checked in ``main`` so a registry edit dropping them fails loudly
#: instead of silencing either plane.
REQUIRED_EVENTS = frozenset({
    "apply.submit",
    "apply.done",
    "apply.backlog",
    "cache.hit",
    "cache.miss",
    "cache.invalidate",
    "serve.shed",
    # quantized wire plane (ISSUE 14): encode/decode hooks plus the
    # error-feedback residual lifecycle — dropping any of these would
    # silence the compression plane's observability
    "compress.encode",
    "compress.decode",
    "compress.residual_reset",
    # hierarchical push (ISSUE 15): pre-reduction, leader election, and
    # the degradation-to-direct-push edge — dropping any of these would
    # silence the group plane's observability
    "group.reduce",
    "group.elect",
    "group.fallback",
    # durability plane (ISSUE 16): the partitioned-snapshot lifecycle —
    # dropping any of these would silence the checkpoint plane (and lose
    # the interrupted-snapshot anomaly anchor, ckpt.abort)
    "ckpt.begin",
    "ckpt.segment",
    "ckpt.commit",
    "ckpt.restore",
    "ckpt.abort",
    # transport v2 (ISSUE 17): shm-ring and epoll write-queue backpressure
    # — dropping either would silence the fast path's only pressure signal
    "net.ring_full",
    "net.writeq_full",
    # request tracing plane (ISSUE 18): the sampled span taxonomy —
    # submit/dispatch/reply/apply/ack form the span tree critpath.py
    # decomposes; wire_tx/wire_rx/bundle/retransmit are the transport
    # hops merge_traces.py stitches into flow arrows.  Dropping any of
    # these silently unstitches the cross-node timeline.
    "trace.submit",
    "trace.wire_tx",
    "trace.wire_rx",
    "trace.bundle",
    "trace.dispatch",
    "trace.reply",
    "trace.apply",
    "trace.ack",
    "trace.retransmit",
    # war-game plane (ISSUE 19): the scenario runner's schedule must leave
    # a reconstructable trail — begin/phase/inject/heal/action/end — or
    # the scorecard's incident report loses its causal anchors.
    "scenario.begin",
    "scenario.phase",
    "scenario.inject",
    "scenario.heal",
    "scenario.action",
    "scenario.end",
    # consistency plane (ISSUE 20): gate/release pair the postmortem
    # wedged-gate anchor matches on, the graceful-degradation shed edge,
    # and the BoundTuner's retune trail — dropping any of these would
    # silence the enforcement plane's observability.
    "consist.gate",
    "consist.release",
    "consist.shed",
    "consist.retune",
})

#: ``np.<attr>`` calls that materialize a device array on the host.
_SYNC_BANNED_NP = frozenset({"asarray", "array"})

#: hot-path functions (module-relpath -> function names) whose ``trace.*``
#: record sites must sit behind an ``if`` — the sampling / trace-context
#: gate (ISSUE 18).  An unconditional record here would allocate a span
#: per MESSAGE, not per sampled request; a registered function that stops
#: recording any ``trace.*`` kind, or disappears, fails loudly
#: (``check_trace_gated``).  ``unbundle`` is CoalescingVan's nested
#: dispatch closure; the rest are methods.
TRACE_GATED_FUNCS = {
    "kv/worker.py": frozenset({"_trace_submitted", "_on_response"}),
    "kv/server.py": frozenset(
        {"_trace_dispatch", "_stamp_version", "_fence_reply", "_wait_reply"}
    ),
    "kv/ledger.py": frozenset({"_retire"}),
    "core/tcp_van.py": frozenset({"_send_on_conn", "_dispatch_frame"}),
    "core/coalesce.py": frozenset({"unbundle"}),
    "core/resender.py": frozenset({"_retransmit_loop"}),
}

#: module holding the SPSC shared-memory ring (ISSUE 17), relative to the
#: package root.
SHM_RING_MODULE = "core/shm_ring.py"

#: ``core/shm_ring.py`` functions on the per-frame fast path — writer
#: (``write``: the ONE slice-assign into the mapping), reader
#: (``poll``/``read``: zero-copy record views), and slot reclamation
#: (``release``).  Copy-free by contract (:func:`check_copy_free`).
SHM_COPY_FREE_FUNCS = frozenset({"write", "poll", "read", "release"})

#: ``core/tcp_van.py`` functions on the per-frame fast path — the per-conn
#: send choke point (ring write / vectored TCP), the ring reader, and the
#: two receive-side functions that decode borrowed buffers in place.
#: (``_wire_send_segs`` is deliberately NOT registered: its single-buffer
#: fallback legitimately joins segments for the legacy ``ps_van_send``.)
VAN_COPY_FREE_FUNCS = frozenset(
    {"_send_on_conn", "_shm_reader", "_dispatch_loop", "_dispatch_frame"}
)


def _base_names(cls: ast.ClassDef) -> List[str]:
    out = []
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return out


def _calls(fn: ast.FunctionDef) -> Iterator[ast.Call]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            yield node


def _is_inner_call(call: ast.Call, method: str) -> bool:
    """Matches ``self.inner.<method>(...)`` and ``super().<method>(...)``."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == method):
        return False
    v = f.value
    if (
        isinstance(v, ast.Attribute)
        and v.attr == "inner"
        and isinstance(v.value, ast.Name)
        and v.value.id == "self"
    ):
        return True
    if (
        isinstance(v, ast.Call)
        and isinstance(v.func, ast.Name)
        and v.func.id == "super"
    ):
        return True
    return False


def _rel(path: pathlib.Path) -> str:
    try:
        return str(path.relative_to(PKG.parent))
    except ValueError:  # checked file outside the repo (e.g. test fixtures)
        return str(path)


def check_file(path: pathlib.Path) -> List[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: List[str] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        if "VanWrapper" not in _base_names(cls):
            continue
        methods = {
            n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)
        }
        for name in DELEGATING:
            fn = methods.get(name)
            if fn is None:
                continue  # inherits VanWrapper's delegating default — fine
            if not any(_is_inner_call(c, name) for c in _calls(fn)):
                problems.append(
                    f"{_rel(path)}:{fn.lineno}: "
                    f"{cls.name}.{name} overrides VanWrapper.{name} without "
                    f"delegating to self.inner.{name} (or super().{name}) — "
                    "layers below it never drain"
                )
        fn = methods.get("counters")
        if fn is not None and any(
            _is_inner_call(c, "counters") for c in _calls(fn)
        ):
            problems.append(
                f"{_rel(path)}:{fn.lineno}: "
                f"{cls.name}.counters merges self.inner.counters — "
                "transport_counters walks the chain itself; this "
                "double-counts every layer below"
            )
    return problems


def check_no_pickle(path: pathlib.Path) -> List[str]:
    """Ban pickle-family imports anywhere in ``path`` (module or nested)."""
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: List[str] = []
    for node in ast.walk(tree):
        names: List[str] = []
        if isinstance(node, ast.Import):
            names = [a.name.split(".")[0] for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            names = [node.module.split(".")[0]]
        for name in names:
            if name in _PICKLE_NAMES:
                problems.append(
                    f"{_rel(path)}:{node.lineno}: imports {name!r} — the "
                    "frame hot path is pickle-free by contract (flat binary "
                    "codec in core/frame.py); route any object serialization "
                    "through the meta codec instead"
                )
    return problems


def _parse_frozenset_literal(
    path: pathlib.Path, tree: ast.Module, var: str, moved_hint: str
) -> frozenset:
    """Extract a module-level ``<var> = frozenset({...})`` string literal.

    Parsed without importing (same stance as the rest of this tool), which
    is why the registry modules keep their sets plain literals — no
    comprehension, no concatenation.  Raises ``ValueError`` when the
    assignment is missing, non-literal, or empty: a refactor that moves a
    registry must break this check loudly, never let every call site pass
    vacuously against an empty set.
    """
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == var for t in node.targets
        ):
            continue
        value = node.value
        if not (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "frozenset"
            and len(value.args) == 1
            and isinstance(value.args[0], (ast.Set, ast.List, ast.Tuple))
        ):
            raise ValueError(
                f"{_rel(path)}:{node.lineno}: {var} must be a plain "
                "frozenset({...}) literal of string constants (AST-parsed)"
            )
        items = []
        for elt in value.args[0].elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, str)
            ):
                raise ValueError(
                    f"{_rel(path)}:{elt.lineno}: non-literal element in "
                    f"{var} — every entry must be a plain string constant"
                )
            items.append(elt.value)
        if not items:
            raise ValueError(f"{_rel(path)}: {var} registry is empty")
        return frozenset(items)
    raise ValueError(
        f"{_rel(path)}: no module-level {var} assignment found — "
        f"{moved_hint}"
    )


def load_event_registry(path: pathlib.Path) -> frozenset:
    """Extract the ``EVENTS`` frozenset literal from ``core/flightrec.py``."""
    tree = ast.parse(path.read_text(), filename=str(path))
    return _parse_frozenset_literal(
        path, tree, "EVENTS",
        "the flight-recorder kind registry moved; update FLIGHTREC_MODULE",
    )


def load_verb_registry(path: pathlib.Path):
    """Extract ``core/manager.py``'s verb registry.

    Returns ``(verbs, names)``: the ``CONTROL_VERBS`` frozenset literal
    plus a map of module-level verb constants (``NAME = "literal"``
    string assignments whose value is in the set) — ``{"HEARTBEAT":
    "heartbeat", "TELEMETRY": "telemetry", ...}``.  Same loud-failure
    stance as :func:`load_event_registry`: a moved or computed registry
    raises ``ValueError`` instead of letting every ``{"cmd": ...}`` site
    pass vacuously.
    """
    tree = ast.parse(path.read_text(), filename=str(path))
    verbs = _parse_frozenset_literal(
        path, tree, "CONTROL_VERBS",
        "the CONTROL-verb registry moved; update MANAGER_MODULE",
    )
    names = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not (
            isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            and node.value.value in verbs
        ):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                names[t.id] = node.value.value
    if not names:
        raise ValueError(
            f"{_rel(path)}: no verb constants found — CONTROL_VERBS exists "
            "but no NAME = \"<verb>\" module-level assignments match it"
        )
    return verbs, names


def _record_kind_arg(call: ast.Call):
    """Classify ``call`` as a flight-recorder record site.

    Returns ``(definitive, first_arg)`` for record-shaped calls, else None:

    - ``flightrec.record(...)`` — the canonical module form — is DEFINITIVE:
      a non-literal kind there is itself a violation;
    - ``<expr>.record(...)`` / bare ``record(...)`` / ``rec(...)`` are
      aliased forms, checked only when the first argument is a literal
      dotted string (so ``histogram.record(0.003)`` never false-positives).
    """
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "record"
        and isinstance(f.value, ast.Name)
        and f.value.id == "flightrec"
    ):
        return True, (call.args[0] if call.args else None)
    shaped = (
        (isinstance(f, ast.Attribute) and f.attr == "record")
        or (isinstance(f, ast.Name) and f.id in _RECORD_ALIASES)
    )
    if shaped:
        return False, (call.args[0] if call.args else None)
    return None


def check_flightrec_calls(path: pathlib.Path, events: frozenset) -> List[str]:
    """Flag record calls whose kind is absent from the EVENTS registry."""
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        classified = _record_kind_arg(node)
        if classified is None:
            continue
        definitive, arg = classified
        literal = (
            arg.value
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            else None
        )
        if literal is None:
            if definitive:
                problems.append(
                    f"{_rel(path)}:{node.lineno}: flightrec.record called "
                    "with a non-literal kind — kinds must be literal strings "
                    "from core/flightrec.py EVENTS so this check (and "
                    "tools/postmortem.py) can see them statically"
                )
            continue  # aliased .record with non-string arg: not a recorder
        if "." not in literal and not definitive:
            continue  # aliased form with an undotted string: unrelated API
        if literal not in events:
            problems.append(
                f"{_rel(path)}:{node.lineno}: record kind {literal!r} is not "
                "in the EVENTS registry (core/flightrec.py) — add it there "
                "or fix the typo; unknown kinds never reach postmortem / SLO "
                "tooling"
            )
    return problems


def check_push_ack_sync_free(
    path: pathlib.Path,
    funcs_registry: frozenset = SYNC_FREE_FUNCS,
    registry_name: str = "SYNC_FREE_FUNCS",
) -> List[str]:
    """Ban blocking device syncs inside the registered sync-free functions.

    Flags ``np.asarray`` / ``np.array`` / ``jax.device_get`` calls and any
    ``.block_until_ready()`` inside a ``funcs_registry`` function (the
    push-ack path by default; the ApplyLedger's submit side via
    :data:`LEDGER_SYNC_FREE_FUNCS`).  A registry entry with no matching
    function definition is ITSELF a violation — a rename must break this
    check loudly, never let the contract pass vacuously against code it no
    longer reads.
    """
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: List[str] = []
    funcs = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in funcs_registry
        ):
            funcs[node.name] = node
    missing = sorted(funcs_registry - set(funcs))
    if missing:
        problems.append(
            f"{_rel(path)}: sync-free functions missing: "
            f"{missing} — renamed?  Update {registry_name} in "
            "tools/check_wrappers.py so the contract keeps checking the "
            "real ack path"
        )
    for name, fn in sorted(funcs.items()):
        for call in _calls(fn):
            f = call.func
            label = None
            if isinstance(f, ast.Attribute):
                if f.attr == "block_until_ready":
                    label = ".block_until_ready()"
                elif isinstance(f.value, ast.Name):
                    if f.value.id == "np" and f.attr in _SYNC_BANNED_NP:
                        label = f"np.{f.attr}()"
                    elif f.value.id == "jax" and f.attr == "device_get":
                        label = "jax.device_get()"
            if label is not None:
                problems.append(
                    f"{_rel(path)}:{call.lineno}: {name} calls {label} — "
                    "the push-ack path is sync-free by contract (the ack "
                    "returns while the device apply is in flight); move "
                    "the readback off this path"
                )
    return problems


def check_copy_free(
    path: pathlib.Path,
    funcs_registry: frozenset,
    registry_name: str,
) -> List[str]:
    """Ban per-frame copies inside the registered fast-path functions.

    Flags ``.tobytes()`` calls, ``bytes(...)`` constructions, and
    ``ctypes.string_at`` (module-qualified or bare) inside a
    ``funcs_registry`` function.  A registry entry with no matching
    function definition is ITSELF a violation — a rename must break this
    check loudly, never let the contract pass vacuously against code it no
    longer reads.
    """
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: List[str] = []
    funcs = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in funcs_registry
        ):
            funcs[node.name] = node
    missing = sorted(funcs_registry - set(funcs))
    if missing:
        problems.append(
            f"{_rel(path)}: copy-free fast-path functions missing: "
            f"{missing} — renamed?  Update {registry_name} in "
            "tools/check_wrappers.py so the contract keeps checking the "
            "real hot path"
        )
    for name, fn in sorted(funcs.items()):
        for call in _calls(fn):
            f = call.func
            label = None
            if isinstance(f, ast.Attribute):
                if f.attr == "tobytes":
                    label = ".tobytes()"
                elif f.attr == "string_at":
                    label = "ctypes.string_at()"
            elif isinstance(f, ast.Name):
                if f.id == "bytes":
                    label = "bytes()"
                elif f.id == "string_at":
                    label = "string_at()"
            if label is not None:
                problems.append(
                    f"{_rel(path)}:{call.lineno}: {name} calls {label} — "
                    "the shm/recv fast path is copy-free by contract "
                    "(ISSUE 17: one slice-assign in, zero-copy views out); "
                    "decode over the borrowed buffer instead"
                )
    return problems


def _trace_record_kind(call: ast.Call):
    """Return the literal ``trace.*`` kind of a record-shaped ``call``.

    Matches every recorder spelling used in the package — module
    ``flightrec.record(...)``, method ``<expr>.record(...)`` and the
    ledger's injected ``<expr>._record(...)``, plus bare ``record`` /
    ``rec`` aliases — but only when the first argument is a literal
    string starting with ``"trace."`` (so ``histogram.record(0.003)``
    never false-positives).  Returns ``None`` otherwise.
    """
    f = call.func
    shaped = (
        (isinstance(f, ast.Attribute) and f.attr in ("record", "_record"))
        or (
            isinstance(f, ast.Name)
            and f.id in (_RECORD_ALIASES | {"_record"})
        )
    )
    if not shaped or not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if arg.value.startswith("trace."):
            return arg.value
    return None


def check_trace_gated(
    path: pathlib.Path,
    funcs_registry: frozenset,
    registry_name: str = "TRACE_GATED_FUNCS",
) -> List[str]:
    """Require every ``trace.*`` record in a registered function to sit
    under an ``if`` — the sampling / trace-context-presence gate.

    The tracing plane's hot-path promise (ISSUE 18) is zero span
    allocation for unsampled traffic; an unconditional record here turns
    1/1024 sampling into per-message work.  Two loud-failure modes keep
    the check honest: a registry entry with no matching function
    definition (rename), and a registered function that records NO
    ``trace.*`` kind at all (the instrumentation was refactored away but
    the registry still claims it is checked).
    """
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: List[str] = []
    funcs = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in funcs_registry
        ):
            funcs[node.name] = node
    missing = sorted(funcs_registry - set(funcs))
    if missing:
        problems.append(
            f"{_rel(path)}: trace-gated functions missing: {missing} — "
            f"renamed?  Update {registry_name} in tools/check_wrappers.py "
            "so the contract keeps checking the real hot path"
        )
    for name, fn in sorted(funcs.items()):
        parents = {}
        for parent in ast.walk(fn):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        recorded = 0
        for call in _calls(fn):
            kind = _trace_record_kind(call)
            if kind is None:
                continue
            recorded += 1
            node, gated = call, False
            while node is not fn:
                node = parents.get(node)
                if node is None:
                    break
                if isinstance(node, ast.If):
                    gated = True
                    break
            if not gated:
                problems.append(
                    f"{_rel(path)}:{call.lineno}: {name} records {kind!r} "
                    "unconditionally — hot-path trace spans must be gated "
                    "behind the sampling predicate (no per-message span "
                    "allocation when unsampled)"
                )
        if not recorded:
            problems.append(
                f"{_rel(path)}:{fn.lineno}: {name} records no trace.* "
                "events — instrumentation refactored away?  Update "
                f"{registry_name} or restore the span record"
            )
    return problems


def check_control_verbs(
    path: pathlib.Path, verbs: frozenset, names: dict
) -> List[str]:
    """Flag ``{"cmd": ...}`` dict literals naming an unregistered verb.

    A value passes when it is a literal string in ``CONTROL_VERBS``, a
    bare ``Name`` (or dotted ``Attribute`` tail) matching one of the verb
    constants, and fails otherwise — unknown literal, unknown name, or a
    computed expression the AST cannot vouch for.  Dynamic routing code
    that reads ``payload.get("cmd")`` is untouched: only dict DISPLAYS
    with a literal ``"cmd"`` key are payload-construction sites.
    """
    tree = ast.parse(path.read_text(), filename=str(path))
    problems: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key, value in zip(node.keys, node.values):
            if not (
                isinstance(key, ast.Constant) and key.value == "cmd"
            ):
                continue
            if isinstance(value, ast.Constant) and isinstance(value.value, str):
                if value.value in verbs:
                    continue
                problems.append(
                    f"{_rel(path)}:{value.lineno}: cmd literal "
                    f"{value.value!r} is not in CONTROL_VERBS "
                    "(core/manager.py) — Manager.handle_request would "
                    "silently ack it as a no-op; add the verb to the "
                    "registry or fix the typo"
                )
                continue
            const = None
            if isinstance(value, ast.Name):
                const = value.id
            elif isinstance(value, ast.Attribute):
                const = value.attr  # manager.TELEMETRY style
            if const is not None and const in names:
                continue
            problems.append(
                f"{_rel(path)}:{value.lineno}: cmd payload value is not a "
                "registered verb constant or CONTROL_VERBS literal — verbs "
                "must be statically checkable (core/manager.py registry)"
            )
    return problems


def main(argv: List[str]) -> int:
    roots = [pathlib.Path(a) for a in argv[1:]] or [PKG]
    problems: List[str] = []
    found_wrapper = False
    found_hot_path = 0
    found_server = False
    found_ledger = False
    found_shm_ring = False
    found_tcp_van = False
    found_trace_gated = 0
    try:
        events = load_event_registry(PKG / FLIGHTREC_MODULE)
    except (OSError, ValueError) as e:
        print(f"check_wrappers: event registry unreadable: {e}", file=sys.stderr)
        return 1  # a moved/emptied registry must fail loudly, not pass
    absent = sorted(REQUIRED_EVENTS - events)
    if absent:
        print(
            f"check_wrappers: required event kinds missing from EVENTS: "
            f"{absent} — the device-plane apply taxonomy (ISSUE 12) must "
            "stay registered",
            file=sys.stderr,
        )
        return 1
    try:
        verbs, verb_names = load_verb_registry(PKG / MANAGER_MODULE)
    except (OSError, ValueError) as e:
        print(f"check_wrappers: verb registry unreadable: {e}", file=sys.stderr)
        return 1  # same loud-failure stance as the event registry
    for root in roots:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            try:
                rel = str(f.resolve().relative_to(PKG)).replace("\\", "/")
            except ValueError:
                rel = None
            if rel in NO_PICKLE_MODULES:
                found_hot_path += 1
                problems.extend(check_no_pickle(f))
            if rel == SERVER_MODULE:
                found_server = True
                problems.extend(check_push_ack_sync_free(f))
            if rel == LEDGER_MODULE:
                found_ledger = True
                problems.extend(
                    check_push_ack_sync_free(
                        f, LEDGER_SYNC_FREE_FUNCS, "LEDGER_SYNC_FREE_FUNCS"
                    )
                )
            if rel == SHM_RING_MODULE:
                found_shm_ring = True
                problems.extend(
                    check_copy_free(f, SHM_COPY_FREE_FUNCS, "SHM_COPY_FREE_FUNCS")
                )
            if rel == "core/tcp_van.py":
                found_tcp_van = True
                problems.extend(
                    check_copy_free(f, VAN_COPY_FREE_FUNCS, "VAN_COPY_FREE_FUNCS")
                )
            if rel in TRACE_GATED_FUNCS:
                found_trace_gated += 1
                problems.extend(check_trace_gated(f, TRACE_GATED_FUNCS[rel]))
            problems.extend(check_flightrec_calls(f, events))
            problems.extend(check_control_verbs(f, verbs, verb_names))
            text = f.read_text()
            if "VanWrapper" not in text:
                continue
            found_wrapper = True
            problems.extend(check_file(f))
    if not found_wrapper:
        print("check_wrappers: no VanWrapper subclasses found", file=sys.stderr)
        return 1  # a rename must fail loudly, not pass vacuously
    if roots == [PKG] and not found_server:
        # the sync-free push-ack contract must not pass vacuously if the
        # server module moves
        print(
            "check_wrappers: kv/server.py not found — update SERVER_MODULE",
            file=sys.stderr,
        )
        return 1
    if roots == [PKG] and not found_ledger:
        # same vacuous-pass guard for the ledger's sync-free submit side
        print(
            "check_wrappers: kv/ledger.py not found — update LEDGER_MODULE",
            file=sys.stderr,
        )
        return 1
    if roots == [PKG] and not (found_shm_ring and found_tcp_van):
        # the copy-free fast-path contract must not pass vacuously if
        # either transport module moves
        print(
            "check_wrappers: shm/tcp transport module not found — update "
            "SHM_RING_MODULE / the core/tcp_van.py hook",
            file=sys.stderr,
        )
        return 1
    if roots == [PKG] and found_trace_gated != len(TRACE_GATED_FUNCS):
        # the sampled-tracing gate contract must not pass vacuously if a
        # traced hot-path module moves
        print(
            "check_wrappers: only "
            f"{found_trace_gated}/{len(TRACE_GATED_FUNCS)} trace-gated "
            "modules found — update TRACE_GATED_FUNCS",
            file=sys.stderr,
        )
        return 1
    if roots == [PKG] and found_hot_path != len(NO_PICKLE_MODULES):
        # same loud-failure stance: a moved/renamed hot-path module must not
        # let the pickle ban pass vacuously
        print(
            "check_wrappers: only "
            f"{found_hot_path}/{len(NO_PICKLE_MODULES)} no-pickle hot-path "
            "modules found — update NO_PICKLE_MODULES",
            file=sys.stderr,
        )
        return 1
    for p in problems:
        print(p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
