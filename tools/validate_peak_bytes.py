"""Validate feasibility.py's peak_bytes model against the real allocator.

VERDICT r4 weak #7: ``parallel/feasibility.py``'s ``peak_bytes`` (arguments
+ temps + generated code + max(out − alias, 0)) is a hand-rolled model of
XLA's ``memory_analysis()`` that anchors the Llama-3-8B "FITS a v5e-16"
claim, but had never been cross-checked against a chip's actual high-water
mark.  This tool closes that: it AOT-compiles a mid-size single-chip body
step, reads the model's prediction, then MATERIALIZES the inputs, runs the
step for real, and compares against ``device.memory_stats()``'s
``peak_bytes_in_use``.

Run by the tunnel watcher when the axon TPU is healthy; ``--cpu`` exercises
the flow on the CPU backend (whose PJRT typically lacks memory_stats — the
tool then reports ``actual: unsupported`` and exits 0 so the CPU smoke
stays green).
"""

from __future__ import annotations

import json
import sys
import time

REPO = __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    cpu = "--cpu" in sys.argv[1:]
    if cpu:
        from parameter_server_tpu.utils.platform import force_cpu

        force_cpu()
    import jax
    import numpy as np

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel import mesh as mesh_lib
    from parameter_server_tpu.parallel.feasibility import (
        compile_body_step,
        peak_bytes_from_analysis,
    )

    backend = jax.default_backend()
    dev = jax.devices()[0]
    # mid-size so the number is well above allocator granularity but far
    # from OOM: ~110M body params, fp32, batch 8 x seq 1024
    cfg = tfm.TransformerConfig(
        vocab_size=32_768, n_layers=8, n_heads=16, n_kv_heads=8,
        d_model=1024, d_ff=4096, max_seq=1024,
        remat=True, scan_blocks=True,
    )
    mesh = mesh_lib.make_mesh((1, 1))
    t0 = time.perf_counter()
    compiled, inputs = compile_body_step(
        cfg, mesh, 8, 1024, loss_chunk=256, fsdp="none"
    )
    compile_s = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    predicted = peak_bytes_from_analysis(ma)

    def materialize(tree):
        return jax.tree.map(
            lambda s: jax.device_put(
                np.zeros(s.shape, s.dtype), s.sharding
            ),
            tree,
        )

    params, opt_state, emb, tokens = (materialize(t) for t in inputs)
    jax.block_until_ready((params, emb))

    def stats():
        try:
            return dict(dev.memory_stats() or {})
        except Exception:  # noqa: BLE001 — plugin may not implement it
            return {}

    before = stats()
    outs = compiled(params, opt_state, emb, tokens)
    jax.block_until_ready(outs)
    after = stats()

    record = {
        "metric": "peak_bytes_model_vs_allocator",
        "unit": "pct_delta",
        "backend": backend,
        "config": "8L/16H/1024d/4096ff vocab32k, batch8 seq1024, "
                  "scan+remat, loss_chunk 256, single device",
        "compile_s": round(compile_s, 1),
        "analysis": {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        },
        "predicted_peak_bytes": predicted,
    }
    peak = after.get("peak_bytes_in_use")
    if peak is None:
        record["value"] = None
        record["actual"] = "unsupported"
        record["note"] = (
            f"{backend} PJRT exposes no memory_stats peak; model run "
            "completed, no comparison possible"
        )
    else:
        record["actual_peak_bytes"] = int(peak)
        record["bytes_in_use_before_step"] = int(
            before.get("bytes_in_use", 0)
        )
        record["bytes_in_use_after_step"] = int(after.get("bytes_in_use", 0))
        record["value"] = round(100.0 * (peak - predicted) / predicted, 2)
        record["vs_baseline"] = None
    print(json.dumps(record))

    if backend == "tpu" and peak is not None:
        _record_baseline(record)
    return 0


def _record_baseline(record: dict) -> None:
    import bench

    stamp = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
    a = record["analysis"]
    body = (
        f"\nBackend `{record['backend']}`, {stamp}.  "
        f"Config: {record['config']}.\n\n"
        "| Item | bytes |\n|---|---|\n"
        f"| memory_analysis args | {a['argument_bytes']:,} |\n"
        f"| memory_analysis temps | {a['temp_bytes']:,} |\n"
        f"| memory_analysis codegen | {a['generated_code_bytes']:,} |\n"
        f"| **model predicted peak** | **{record['predicted_peak_bytes']:,}** |\n"
        f"| **allocator peak_bytes_in_use** | "
        f"**{record['actual_peak_bytes']:,}** |\n"
        f"| delta | {record['value']}% |\n\n"
        "A delta within ~±15% calibrates feasibility.py's `peak_bytes` "
        "formula (args + temps + codegen + max(out−alias, 0)) against the "
        "chip's real high-water mark — the calibration point VERDICT r4 "
        "weak #7 asked for under the 8B FITS claim.\n"
    )
    bench._splice_baseline(
        "<!-- BENCH-PEAKVAL:BEGIN -->",
        "<!-- BENCH-PEAKVAL:END -->",
        body,
        "## peak_bytes model vs real allocator "
        "(auto-recorded by tools/validate_peak_bytes.py)",
    )


if __name__ == "__main__":
    sys.exit(main())
