#!/usr/bin/env python
"""Decompose sampled request traces into per-plane critical-path segments.

Input is the same per-node flight-recorder bundles ``tools/postmortem.py``
merges (``flightrec_<node>.json``: an ``events`` list plus paired
``wall_anchor_s``/``mono_anchor_s`` anchors and the heartbeat-derived
``clock_offset_s``).  The tracing plane (ISSUE 18) journals a ``trace.*``
event at every hop of a sampled request — worker submit, per-conn wire
tx/rx, bundle fan-out, server dispatch, reply build, device apply,
ack-return closure — and this tool stitches each request's events back
into ONE timeline, then attributes its end-to-end latency across planes:

    serialize     ctx stamp -> span tree registered (worker-side prep;
                  the trace.submit event fires just before the wire submit)
    send_queue    span registered -> first request-direction wire tx
                  (send call + coalescing/flush delay)
    wire          wire tx -> LAST request leg received by a server
    server_queue  wire rx -> handler dispatch (server recv-thread queue)
    apply         dispatch -> reply built (table update + version stamp)
    ack_return    reply built -> worker closes the span tree (last ack)

Segments telescope: each boundary stamp is clamped monotone (running
max), so the six segments sum EXACTLY to ``t_ack - t0`` — the same
end-to-end latency the worker's ``trace.ack`` event records as
``e2e_ms``.  A stamp a plane never produced (loopback runs have no wire
tx/rx; fenced replies skip apply) contributes a zero-width segment and
its time is absorbed by the preceding plane — attribution degrades,
never double-counts.

Direction disambiguation: both request and reply legs journal wire
events with the same trace id.  ``origin = tid.split("/")[0]`` names the
submitting node, so request-direction tx events are those with
``recver != origin`` (earliest wins: the first byte leaving the worker)
and request-direction rx events are those with ``sender == origin``
(latest wins: the span tree is open until the last leg lands).

Clock rebase is identical to postmortem.py: ``wall + (t_mono - mono) -
clock_offset`` maps every node onto the shared scheduler reference
(exact in-process, RTT/2 accuracy across hosts — ``FleetMonitor.
clock_offset``).

Usage::

    python tools/critpath.py bundles/flightrec_*.json
    python tools/critpath.py --json --requests 0 bundles/*.json

The report prints a worked per-request transcript (``--requests`` many,
default 3) and a per-plane p50/p99 attribution table; ``--json`` emits
the same data machine-readable (``bench.py --traceplane`` and the e2e
tests consume it).  The live complements of this offline view are the
``trace.wire`` / ``trace.sq`` / ``trace.apply`` / ``trace.e2e``
telemetry digests (pstop's WIREus/SQus/APLY%% columns and the
``tracing_plane_specs`` SLO read those).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

#: plane name -> the request-record stamp that closes the segment, in
#: causal order.  Each segment is ``stamp - previous stamp`` after the
#: running-max clamp; the tuple order IS the critical path.
PLANES = (
    ("serialize", "t_send"),
    ("send_queue", "t_tx"),
    ("wire", "t_rx"),
    ("server_queue", "t_disp"),
    ("apply", "t_reply"),
    ("ack_return", "t_ack"),
)


def load_bundle(path: str) -> dict:
    """Read one per-node bundle; same shape/stance as postmortem.py."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc.get("events"), list):
        raise ValueError(f"{path}: not a flight-recorder bundle (no events)")
    doc.setdefault("node", os.path.splitext(os.path.basename(path))[0])
    return doc


def merge_events(paths: List[str]) -> List[dict]:
    """Load bundles and rebase every trace event onto the shared clock.

    Each event gains ``t_s`` (rebased wall-clock seconds); ``trace.submit``
    events additionally gain ``_t0_s`` — the context-stamp time rebased
    with the SAME bundle anchors (``t0_s`` is a raw monotonic value from
    the submitting node's clock).
    """
    events: List[dict] = []
    for path in paths:
        b = load_bundle(path)
        wall = float(b.get("wall_anchor_s") or 0.0)
        mono = float(b.get("mono_anchor_s") or 0.0)
        off = float(b.get("clock_offset_s") or 0.0)
        node = str(b["node"])
        for ev in b["events"]:
            if not isinstance(ev, dict):
                continue
            kind = ev.get("kind") or ""
            if not kind.startswith("trace."):
                continue
            ev = dict(ev)
            t_mono = float(ev.get("t_mono_s") or 0.0)
            ev["t_s"] = wall + (t_mono - mono) - off
            if kind == "trace.submit" and ev.get("t0_s") is not None:
                ev["_t0_s"] = wall + (float(ev["t0_s"]) - mono) - off
            ev.setdefault("node", node)
            events.append(ev)
    events.sort(key=lambda e: (e["t_s"], str(e["node"]), e.get("seq", 0)))
    return events


def _blank(tid: str) -> dict:
    return {
        "tid": tid,
        "origin": tid.split("/")[0],
        "op": None,
        "legs": None,
        "t0": None,
        "t_send": None,
        "t_tx": None,
        "t_rx": None,
        "t_disp": None,
        "t_reply": None,
        "t_ack": None,
        "e2e_ms": None,
        "fenced": False,
        "retransmits": 0,
        "device_ms": None,
    }


def requests(events: List[dict]) -> Dict[str, dict]:
    """Fold rebased trace events into per-request stamp records."""
    reqs: Dict[str, dict] = {}

    def rec(tid: str) -> dict:
        return reqs.setdefault(tid, _blank(tid))

    for ev in events:
        kind = ev["kind"]
        if kind == "trace.submit":
            q = rec(ev["tid"])
            q["t0"] = ev.get("_t0_s", ev["t_s"])
            q["t_send"] = ev["t_s"]
            q["op"] = ev.get("op")
            q["legs"] = ev.get("legs")
        elif kind == "trace.wire_tx":
            for tid in ev.get("tids") or []:
                q = rec(tid)
                if ev.get("recver") != q["origin"]:
                    t = ev["t_s"]
                    q["t_tx"] = t if q["t_tx"] is None else min(q["t_tx"], t)
        elif kind == "trace.wire_rx":
            for tid in ev.get("tids") or []:
                q = rec(tid)
                if ev.get("sender") == q["origin"]:
                    t = ev["t_s"]
                    q["t_rx"] = t if q["t_rx"] is None else max(q["t_rx"], t)
        elif kind == "trace.dispatch":
            q = rec(ev["tid"])
            t = ev["t_s"]
            q["t_disp"] = t if q["t_disp"] is None else max(q["t_disp"], t)
        elif kind == "trace.reply":
            q = rec(ev["tid"])
            t = ev["t_s"]
            q["t_reply"] = t if q["t_reply"] is None else max(q["t_reply"], t)
            if ev.get("verdict") == "fenced":
                q["fenced"] = True
        elif kind == "trace.apply":
            q = rec(ev["tid"])
            if ev.get("device_ms") is not None:
                q["device_ms"] = float(ev["device_ms"])
        elif kind == "trace.ack":
            q = rec(ev["tid"])
            q["t_ack"] = ev["t_s"]
            if ev.get("e2e_ms") is not None:
                q["e2e_ms"] = float(ev["e2e_ms"])
        elif kind == "trace.retransmit":
            for tid in ev.get("tids") or []:
                rec(tid)["retransmits"] += 1
    return reqs


def segments(q: dict) -> Optional[Dict[str, float]]:
    """Telescoping per-plane segments (seconds) for one request.

    ``None`` for incomplete span trees (no submit or no ack) — those are
    postmortem.py's orphan anchors, not attribution samples.  Boundary
    stamps are clamped to a running max so every segment is >= 0 and the
    sum is exactly ``max(stamps) - t0`` (== ``t_ack - t0`` whenever the
    ack is, as it must be, the last stamp).
    """
    if q["t0"] is None or q["t_ack"] is None:
        return None
    prev = q["t0"]
    out: Dict[str, float] = {}
    for name, key in PLANES:
        t = q[key]
        t = prev if t is None else max(prev, t)
        out[name] = t - prev
        prev = t
    out["e2e"] = prev - q["t0"]
    return out


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile on a sorted copy; 0.0 for empty input."""
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(len(vals) - 1, max(0, int(round(q * (len(vals) - 1)))))
    return vals[idx]


def attribution(reqs: Dict[str, dict]) -> dict:
    """Per-plane p50/p99 (ms) + mean share of e2e across complete requests."""
    samples: Dict[str, List[float]] = {name: [] for name, _ in PLANES}
    samples["e2e"] = []
    complete = 0
    for q in reqs.values():
        segs = segments(q)
        if segs is None:
            continue
        complete += 1
        for name, v in segs.items():
            samples[name].append(v)
    out = {"requests": len(reqs), "complete": complete, "planes": {}}
    e2e_total = sum(samples["e2e"]) or 1.0
    for name in list(samples):
        vals = samples[name]
        out["planes"][name] = {
            "p50_ms": round(percentile(vals, 0.50) * 1e3, 3),
            "p99_ms": round(percentile(vals, 0.99) * 1e3, 3),
            "share_pct": round(100.0 * sum(vals) / e2e_total, 1),
        }
    return out


def transcript(q: dict) -> List[str]:
    """Worked per-request lines: each plane's width and running total."""
    segs = segments(q)
    head = (
        f"request {q['tid']} op={q['op'] or '?'} legs={q['legs'] or '?'}"
        + (" FENCED" if q["fenced"] else "")
        + (f" retransmits={q['retransmits']}" if q["retransmits"] else "")
    )
    if segs is None:
        missing = "submit" if q["t0"] is None else "ack-return"
        return [head, f"  INCOMPLETE span tree (no {missing} span) — "
                      "postmortem.py anchors on this"]
    lines = [head]
    acc = 0.0
    for name, _ in PLANES:
        acc += segs[name]
        lines.append(
            f"  {name:<12s} {segs[name] * 1e6:10.1f}us"
            f"   (cum {acc * 1e6:10.1f}us)"
        )
    lines.append(
        f"  {'e2e':<12s} {segs['e2e'] * 1e6:10.1f}us"
        + (
            f"   (worker-measured {q['e2e_ms'] * 1e3:.1f}us)"
            if q["e2e_ms"] is not None else ""
        )
    )
    return lines


def render(reqs: Dict[str, dict], *, show: int = 3) -> List[str]:
    attr = attribution(reqs)
    lines = [
        f"critpath: {attr['requests']} sampled requests "
        f"({attr['complete']} complete span trees)"
    ]
    shown = 0
    for tid in sorted(reqs):
        if shown >= show:
            break
        lines.extend(transcript(reqs[tid]))
        shown += 1
    lines.append(f"{'plane':<14s} {'p50_ms':>10s} {'p99_ms':>10s} {'share%':>8s}")
    for name in [n for n, _ in PLANES] + ["e2e"]:
        p = attr["planes"][name]
        lines.append(
            f"{name:<14s} {p['p50_ms']:>10.3f} {p['p99_ms']:>10.3f} "
            f"{p['share_pct']:>8.1f}"
        )
    return lines


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="per-plane critical-path attribution of sampled requests"
    )
    ap.add_argument("bundles", nargs="+", help="flightrec_*.json bundle files")
    ap.add_argument(
        "--requests", type=int, default=3,
        help="per-request transcripts to print (default: %(default)s)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit machine-readable attribution + per-request segments",
    )
    args = ap.parse_args(argv)
    try:
        events = merge_events(args.bundles)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"critpath: {e}", file=sys.stderr)
        return 1
    reqs = requests(events)
    if args.json:
        doc = {
            "attribution": attribution(reqs),
            "requests": {
                tid: {
                    **{k: q[k] for k in ("op", "legs", "fenced",
                                         "retransmits", "e2e_ms")},
                    "segments_s": segments(q),
                }
                for tid, q in sorted(reqs.items())
            },
        }
        json.dump(doc, sys.stdout, indent=2)
        print()
    else:
        print("\n".join(render(reqs, show=args.requests)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
