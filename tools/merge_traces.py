#!/usr/bin/env python
"""Merge per-node chrome-trace dumps into one Perfetto timeline.

Each node of a cluster run dumps its own timeline
(``Tracer.dump_chrome_trace(path, process_name=node_id)``).  Loaded alone,
those files are N disconnected views of one distributed request; merged,
each node becomes a Perfetto *process* (pid = node index, named via
``process_name`` metadata events), and the worker-side ``kv.push`` span
lines up with the serving nodes' ``kv.server.push`` spans — both carry the
same stitched trace id in ``args.trace`` (stamped into
``Task.payload["__trace__"]`` by ``KVWorker._trace_ctx`` and echoed by
``KVServer.handle_request``), so clicking one end finds the other.

Clock alignment: every Tracer records span starts relative to its own
construction time.  ``dump_chrome_trace(..., process_name=...)`` embeds
that epoch (``metadata.clock_t0_s``, a ``perf_counter`` value), and the
merge rebases each file's events onto the shared clock — exact for
in-process clusters (one perf_counter domain), best-effort across OS
processes (as with any unsynchronized one-way timestamps).

Flight-recorder bundles (``tools/postmortem.py`` input — a JSON document
with an ``events`` list plus ``wall/mono_anchor_s`` and ``clock_offset_s``)
are accepted alongside trace files and bridged as Perfetto *instant*
events (``ph: "i"``), so the black-box journal's ``resend.retransmit`` /
``slo.breach`` markers land on the same timeline as the spans they
explain.  Each bundle event's monotonic stamp is rebased into the shared
scheduler clock domain by subtracting the bundle's ``clock_offset_s``
(the heartbeat min-RTT estimate), then shifted onto the merge's common
epoch exactly like span ``ts`` values.

Sampled request tracing (ISSUE 18) rides on both bridges.  After the
merge, every group of "X" spans sharing an ``args.trace`` id across
DIFFERENT pids is stitched with Perfetto *flow* events (``ph: "s"`` at
the upstream span, ``ph: "f"``/``bp: "e"`` at the downstream one, flow id
derived from the trace id) — one sampled request renders as a single
cross-node arrow chain from the worker's submit span through each
server's handler span.  Transport backpressure journal events
(``net.ring_full`` / ``net.writeq_full``) are bridged with ``cat:
"backpressure"`` so a stalled arrow can be read against the pressure
instants that explain it.

Usage::

    python tools/merge_traces.py -o merged.json trace_W0.json trace_S0.json ...
    python tools/merge_traces.py -o merged.json trace_W0.json flightrec_W0.json

Node names come from each file's ``metadata.node``, else the file stem.
The output is plain chrome-trace JSON ("traceEvents" array) — open with
https://ui.perfetto.dev or chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import zlib
from typing import Dict, List, Optional, Tuple

#: ph values this tool understands (complete spans, metadata, instants,
#: flow start/finish).
_KNOWN_PHASES = {"X", "M", "i", "s", "f"}

#: journal kinds bridged with ``cat: "backpressure"`` so transport-pressure
#: instants are filterable against the request flow arrows they explain.
_BACKPRESSURE_KINDS = {"net.ring_full", "net.writeq_full"}

#: valid instant-event scopes ("g"lobal, "p"rocess, "t"hread).
_INSTANT_SCOPES = {"g", "p", "t"}


def is_bundle(doc: dict) -> bool:
    """True for a flight-recorder bundle (postmortem.py's input shape)."""
    return isinstance(doc.get("events"), list) and "traceEvents" not in doc


def bundle_to_trace(doc: dict, fallback_node: str) -> Tuple[str, dict]:
    """Bridge a flight-recorder bundle into a chrome-trace-shaped document.

    Every journal event becomes an instant (``ph: "i"``, process scope)
    named by its kind, carrying the remaining journal fields in ``args``.
    The embedded epoch is the bundle's monotonic anchor REBASED into the
    scheduler clock domain (``mono_anchor_s - clock_offset_s``), and each
    event's ``ts`` is likewise offset-corrected — so once ``merge_traces``
    shifts all files onto the earliest epoch, bundle instants from
    different nodes line up to RTT/2 accuracy, and line up with tracer
    spans exactly for in-process clusters (one clock domain).
    """
    node = str(doc.get("node") or fallback_node)
    mono = float(doc.get("mono_anchor_s") or 0.0)
    off = float(doc.get("clock_offset_s") or 0.0)
    events: List[dict] = []
    for ev in doc["events"]:
        if not isinstance(ev, dict):
            continue
        t_mono = float(ev.get("t_mono_s") or 0.0)
        args = {
            k: v for k, v in ev.items()
            if k not in ("t_mono_s", "kind")
        }
        args.setdefault("node", node)
        kind = str(ev.get("kind") or "event")
        inst = {
            "name": kind,
            "ph": "i",
            "s": "p",
            "ts": (t_mono - mono) * 1e6,
            "tid": 0,
            "args": args,
        }
        if kind in _BACKPRESSURE_KINDS:
            inst["cat"] = "backpressure"
        events.append(inst)
    return node, {
        "traceEvents": events,
        "metadata": {"node": node, "clock_t0_s": mono - off},
    }


def load_trace(path: str) -> Tuple[str, dict]:
    """Read one per-node dump; returns (node_name, document).

    Flight-recorder bundles are detected by shape and bridged via
    :func:`bundle_to_trace`; chrome-trace files pass through unchanged.
    """
    with open(path) as f:
        doc = json.load(f)
    stem = os.path.splitext(os.path.basename(path))[0]
    if is_bundle(doc):
        return bundle_to_trace(doc, stem)
    meta = doc.get("metadata") or {}
    node = meta.get("node") or stem
    return str(node), doc


def merge_traces(
    paths: List[str], nodes: Optional[List[str]] = None
) -> dict:
    """Merge per-node chrome traces into one multi-process document.

    ``nodes``: optional explicit node names (parallel to ``paths``),
    overriding embedded/filename-derived names.  Input order fixes pid
    assignment (pid = 1 + index), so merges are deterministic.
    """
    events: List[dict] = []
    # rebase every file to the EARLIEST embedded clock epoch so merged ts
    # stay positive and relative offsets between nodes are preserved
    loaded = []
    t0s = []
    for i, path in enumerate(paths):
        node, doc = load_trace(path)
        if nodes is not None:
            node = nodes[i]
        t0 = (doc.get("metadata") or {}).get("clock_t0_s")
        loaded.append((node, doc, t0))
        if t0 is not None:
            t0s.append(t0)
    base_t0 = min(t0s) if t0s else None
    for pid, (node, doc, t0) in enumerate(loaded, start=1):
        shift_us = (
            (t0 - base_t0) * 1e6 if (t0 is not None and base_t0 is not None)
            else 0.0
        )
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": node},
            }
        )
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if ev.get("ph") == "M":
                continue  # per-file metadata is superseded by ours
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            events.append(ev)
    events.extend(_stitch_flows(events))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _stitch_flows(events: List[dict]) -> List[dict]:
    """Build Perfetto flow arrows between same-trace spans on different pids.

    Spans sharing an ``args.trace`` id are sorted by rebased ``ts``; each
    consecutive cross-pid pair gets a flow start (``ph: "s"``) bound to
    the upstream span and a flow finish (``ph: "f"``, ``bp: "e"`` so it
    binds to the ENCLOSING downstream slice) — rendering one sampled
    request as a single arrow chain across node processes.  Flow ids are
    ``crc32("<trace>:<hop>")``: deterministic, unique per hop, shared by
    exactly its s/f pair.  Same-pid neighbours are skipped (no wire hop).
    """
    by_trace: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        trace = (ev.get("args") or {}).get("trace")
        if trace:
            by_trace.setdefault(str(trace), []).append(ev)
    flows: List[dict] = []
    for trace, spans in sorted(by_trace.items()):
        spans.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
        hop = 0
        for up, down in zip(spans, spans[1:]):
            if up.get("pid") == down.get("pid"):
                continue
            fid = zlib.crc32(f"{trace}:{hop}".encode()) & 0xFFFFFFFF
            common = {"name": "req", "cat": "traceflow", "id": fid,
                      "args": {"trace": trace}}
            flows.append(dict(common, ph="s", pid=up["pid"],
                              tid=up.get("tid", 0), ts=up.get("ts", 0.0)))
            flows.append(dict(common, ph="f", bp="e", pid=down["pid"],
                              tid=down.get("tid", 0), ts=down.get("ts", 0.0)))
            hop += 1
    return flows


def validate_chrome_trace(doc: dict) -> List[str]:
    """Schema check: the invariants Perfetto's importer relies on.

    Returns a list of problems (empty = valid): a ``traceEvents`` array
    where every event has a string ``name`` and known ``ph``; complete
    ("X") events also need numeric ``ts`` + non-negative ``dur`` and
    integer ``pid``/``tid``; instants ("i", the bridged flight-recorder
    events) need numeric ``ts``, integer ``tid``, and a valid scope when
    ``s`` is present; flow events ("s"/"f", the cross-node request
    stitches) need numeric ``ts``, integer ``tid``, an ``id``, and — for
    finishes — ``bp`` restricted to the enclosing-slice binding ("e").
    """
    problems: List[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(events):
        where = f"event[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str):
            problems.append(f"{where}: name missing or not a string")
        ph = ev.get("ph")
        if ph not in _KNOWN_PHASES:
            problems.append(f"{where}: unknown ph {ph!r}")
            continue
        if not isinstance(ev.get("pid"), int):
            problems.append(f"{where}: pid missing or not an int")
        if ph == "X":
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"{where}: ts missing or not numeric")
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: dur missing/negative")
            if not isinstance(ev.get("tid"), int):
                problems.append(f"{where}: tid missing or not an int")
        if ph == "i":
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"{where}: ts missing or not numeric")
            if not isinstance(ev.get("tid"), int):
                problems.append(f"{where}: tid missing or not an int")
            if "s" in ev and ev["s"] not in _INSTANT_SCOPES:
                problems.append(f"{where}: instant scope {ev['s']!r} invalid")
        if ph in ("s", "f"):
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"{where}: ts missing or not numeric")
            if not isinstance(ev.get("tid"), int):
                problems.append(f"{where}: tid missing or not an int")
            if not isinstance(ev.get("id"), (int, str)):
                problems.append(f"{where}: flow event missing id")
            if ph == "f" and "bp" in ev and ev["bp"] != "e":
                problems.append(f"{where}: flow finish bp {ev['bp']!r} invalid")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"{where}: args not an object")
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-node chrome traces into one Perfetto timeline"
    )
    ap.add_argument("traces", nargs="+", help="per-node trace JSON files")
    ap.add_argument(
        "-o", "--output", default="merged_trace.json",
        help="merged output path (default: %(default)s)",
    )
    args = ap.parse_args(argv)
    merged = merge_traces(args.traces)
    problems = validate_chrome_trace(merged)
    if problems:
        for p in problems:
            print(f"merge_traces: {p}", file=sys.stderr)
        return 1
    with open(args.output, "w") as f:
        json.dump(merged, f)
    n_spans = sum(1 for e in merged["traceEvents"] if e.get("ph") == "X")
    n_inst = sum(1 for e in merged["traceEvents"] if e.get("ph") == "i")
    n_flows = sum(1 for e in merged["traceEvents"] if e.get("ph") == "s")
    print(
        f"merged {len(args.traces)} node traces ({n_spans} spans, "
        f"{n_inst} instants, {n_flows} flow arrows) -> {args.output}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
