#!/usr/bin/env python
"""bench_gate: the tier-1-adjacent perf-regression gate over BASELINE.md.

``bench.py``'s arms (``--wire``/``--obs``/``--apply``/``--devobs``/
``--serve``/``--compress``/``--hier``/``--ckpt``/``--transport``/
``--traceplane``/``--wargame``/``--consistency``) auto-record their
headline numbers into
marker blocks of
``BASELINE.md``; ``tools/benchdiff.py`` can diff two revisions of that
file cell-by-cell.  This tool closes the loop as a GATE a CI job (or a
pre-commit hook) runs after re-benching:

    python tools/bench_gate.py                 # HEAD vs working tree, 10%
    python tools/bench_gate.py --fail-over 25  # looser gate
    python tools/bench_gate.py --baseline v1.2 # gate against a tag

It extracts the BASELINE.md of ``--baseline`` (default ``HEAD``) via
``git show``, diffs it against the working-tree file with benchdiff's
direction-aware comparison, and exits 1 when any shared metric regressed
beyond ``--fail-over`` percent.

Escape hatch — intentional re-baselines:

Perf numbers legitimately move when the code means them to (a new arm, a
machine change, an optimization that trades one metric for another).  Two
sanctioned ways to pass the gate on purpose:

- set ``PS_BENCH_REBASE=1`` in the environment: the gate still PRINTS the
  full diff but exits 0, stamping ``REBASE`` so the CI log records that
  the move was deliberate;
- or simply commit the regenerated BASELINE.md first — the gate compares
  against the committed revision, so a committed re-baseline IS the new
  baseline.

Exit codes: 0 pass (or rebase), 1 regression, 2 usage/environment error
(missing file, not a git checkout, unknown revision).
"""

from __future__ import annotations

import argparse
import os
import pathlib
import subprocess
import sys
import tempfile
from typing import List, Optional

_REPO = pathlib.Path(__file__).resolve().parent.parent

sys.path.insert(0, str(_REPO / "tools"))
import benchdiff  # noqa: E402  (sibling tool, not a package)


def baseline_text(rev: str, repo: pathlib.Path) -> str:
    """BASELINE.md as of git revision ``rev`` (raises on unknown rev)."""
    return subprocess.run(
        ["git", "show", f"{rev}:BASELINE.md"],
        cwd=repo,
        check=True,
        capture_output=True,
        text=True,
    ).stdout


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="gate the working-tree BASELINE.md against a committed one"
    )
    ap.add_argument(
        "--baseline", default="HEAD",
        help="git revision holding the reference BASELINE.md "
        "(default: %(default)s)",
    )
    ap.add_argument(
        "--fail-over", type=float, default=10.0, metavar="PCT",
        help="regression tolerance in percent (default: %(default)s)",
    )
    ap.add_argument(
        "--file", default=None,
        help="candidate file (default: <repo>/BASELINE.md working tree)",
    )
    args = ap.parse_args(argv)
    cand = pathlib.Path(args.file) if args.file else _REPO / "BASELINE.md"
    if not cand.exists():
        print(f"bench_gate: {cand} not found", file=sys.stderr)
        return 2
    try:
        ref = baseline_text(args.baseline, _REPO)
    except (subprocess.CalledProcessError, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        print(f"bench_gate: git show {args.baseline}:BASELINE.md failed: "
              f"{detail.strip()}", file=sys.stderr)
        return 2
    # benchdiff consumes paths; give the committed text a real file
    with tempfile.NamedTemporaryFile(
        "w", suffix=".md", prefix="baseline_ref_", delete=False
    ) as tf:
        tf.write(ref)
        ref_path = tf.name
    try:
        rc = benchdiff.main(
            [ref_path, str(cand), "--fail-over", str(args.fail_over)]
        )
    finally:
        os.unlink(ref_path)
    if rc == 1 and os.environ.get("PS_BENCH_REBASE"):
        print(
            "bench_gate: REBASE — regressions accepted via PS_BENCH_REBASE=1"
        )
        return 0
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
