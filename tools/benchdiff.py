#!/usr/bin/env python
"""benchdiff: compare bench arms across runs, with a regression gate.

The bench trajectory is recorded (``BENCH_r*.json`` wrappers per round,
``BASELINE.md`` arm tables spliced by ``bench.py``) but until ISSUE 12
nothing DIFFED it — a regression between rounds surfaced only if someone
eyeballed the tables.  This tool makes the trajectory comparable:

- ``BENCH_*.json``: the driver wrapper ``{"n", "cmd", "rc", "tail",
  "parsed"}`` — ``parsed`` (when present) and every embedded
  ``{"metric": ...}`` JSON line in ``tail`` become one sample each, keyed
  by metric name;
- ``BASELINE.md``: every ``<!-- BENCH-<ARM>:BEGIN/END -->`` block's
  markdown tables become samples keyed ``<arm>/<row label>/<column>``, so
  two revisions of the file (e.g. ``git show HEAD~1:BASELINE.md`` vs the
  working tree) diff cell-by-cell across every recorded arm.

Usage::

    python tools/benchdiff.py OLD NEW [MORE...] [--fail-over PCT]

The FIRST path is the baseline; each later path diffs against it.  With
``--fail-over PCT`` the exit code is 1 when any shared metric REGRESSED by
more than PCT percent — direction is inferred from units/names
(throughput-like = higher is better, latency/overhead-like = lower is
better, unknown = any move beyond PCT fails), so the gate is usable from
CI without a per-metric config.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys
from typing import Dict, List, Optional, Tuple

#: metric-name/unit fragments marking higher-is-better series.
_HIGHER = ("throughput", "/s", "per_s", "speedup", "examples", "rows_per")
#: fragments marking lower-is-better series.  ``minutes``/``breach``/
#: ``migrated`` cover the war-game scorecard (SLO-breach-minutes,
#: bytes-migrated) — less downtime and less data moved are both wins.
_LOWER = ("ms", "us", "latency", "overhead", "pct", "%", "seconds", "bytes",
          "minutes", "breach", "migrated")

_MARKER = re.compile(r"<!--\s*BENCH-([A-Z0-9_]+):BEGIN\s*-->")
_NUM = re.compile(r"-?\d+(?:,\d{3})*(?:\.\d+)?")


def direction(metric: str, unit: str = "") -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 unknown."""
    probe = f"{metric} {unit}".lower()
    for frag in _HIGHER:
        if frag in probe:
            return 1
    for frag in _LOWER:
        if frag in probe:
            return -1
    return 0


def _metric_lines(text: str) -> List[dict]:
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and isinstance(rec.get("metric"), str):
            out.append(rec)
    return out


def load_bench_json(path: pathlib.Path) -> Dict[str, dict]:
    """Samples from one driver wrapper: ``{metric: {"value", "unit"}}``.

    ``parsed`` (the driver's own extraction) and every embedded metric
    line in ``tail`` contribute; on duplicates the LAST tail line wins —
    it is the most recent emission of that arm in the run.
    """
    blob = json.loads(path.read_text())
    out: Dict[str, dict] = {}
    recs: List[dict] = []
    if isinstance(blob, dict):
        if isinstance(blob.get("parsed"), dict):
            recs.append(blob["parsed"])
        recs.extend(_metric_lines(str(blob.get("tail") or "")))
    for rec in recs:
        v = rec.get("value")
        if isinstance(v, (int, float)):
            out[rec["metric"]] = {
                "value": float(v),
                "unit": str(rec.get("unit") or ""),
            }
    return out


def load_baseline_md(path: pathlib.Path) -> Dict[str, dict]:
    """Samples from BASELINE.md's spliced arm blocks.

    Keys are ``<arm>/<row label>/<column header>`` for every numeric cell
    of every markdown table inside a ``BENCH-<ARM>`` marker block (the
    leading number of a cell like ``20.6 us (62.4 GB/s)`` is the sample).
    Stable across re-splices: bench.py rewrites whole blocks, and the
    row/column labels are the arm's own vocabulary.
    """
    out: Dict[str, dict] = {}
    text = path.read_text()
    for m in _MARKER.finditer(text):
        arm = m.group(1).lower()
        end = text.find(f"<!-- BENCH-{m.group(1)}:END -->", m.end())
        block = text[m.end(): end if end != -1 else len(text)]
        header: List[str] = []
        for line in block.splitlines():
            line = line.strip()
            if not (line.startswith("|") and line.endswith("|")):
                continue
            cells = [c.strip() for c in line.strip("|").split("|")]
            if all(set(c) <= {"-", ":", " "} for c in cells):
                continue  # the |---|---| separator row
            if not header:
                header = cells
                continue
            label = cells[0]
            for col, cell in zip(header[1:], cells[1:]):
                num = _NUM.search(cell)
                if num is None:
                    continue
                out[f"{arm}/{label}/{col}"] = {
                    "value": float(num.group(0).replace(",", "")),
                    "unit": cell[num.end():].strip() or col,
                }
        # headline scalars outside tables: "Overhead: **-0.86%**" style
        for hm in re.finditer(
            r"(\w[\w -]*?):\s*\*\*(-?\d+(?:\.\d+)?)\s*([%a-zA-Z/]*)\*\*",
            block,
        ):
            out[f"{arm}/{hm.group(1).strip().lower()}"] = {
                "value": float(hm.group(2)),
                "unit": hm.group(3),
            }
    return out


def load(path_str: str) -> Dict[str, dict]:
    path = pathlib.Path(path_str)
    if path.suffix == ".json":
        return load_bench_json(path)
    return load_baseline_md(path)


def diff(
    old: Dict[str, dict], new: Dict[str, dict]
) -> List[Tuple[str, float, float, float, int]]:
    """Per shared metric: ``(name, old, new, delta_pct, direction)``."""
    rows = []
    for name in sorted(set(old) & set(new)):
        a, b = old[name]["value"], new[name]["value"]
        if a == 0:
            continue  # delta undefined; absolute values still printed
        pct = 100.0 * (b - a) / abs(a)
        rows.append((name, a, b, pct, direction(name, new[name]["unit"])))
    return rows


def regressions(
    rows: List[Tuple[str, float, float, float, int]], fail_over: float
) -> List[str]:
    """Metric names whose move counts as a regression beyond the gate."""
    out = []
    for name, _a, _b, pct, sign in rows:
        worse = (
            (sign > 0 and pct < -fail_over)       # throughput fell
            or (sign < 0 and pct > fail_over)     # latency/overhead rose
            or (sign == 0 and abs(pct) > fail_over)  # unknown: any move
        )
        if worse:
            out.append(name)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="diff bench arms across runs (BENCH_*.json / BASELINE.md)"
    )
    ap.add_argument("paths", nargs="+", help="baseline first, then candidates")
    ap.add_argument(
        "--fail-over", type=float, default=None, metavar="PCT",
        help="exit 1 when any shared metric regresses by more than PCT%%",
    )
    args = ap.parse_args(argv)
    if len(args.paths) < 2:
        print("benchdiff: need a baseline and at least one candidate",
              file=sys.stderr)
        return 2
    try:
        base = load(args.paths[0])
    except (OSError, json.JSONDecodeError) as e:
        print(f"benchdiff: {args.paths[0]}: {e}", file=sys.stderr)
        return 2
    if not base:
        print(f"benchdiff: no metrics found in {args.paths[0]}",
              file=sys.stderr)
        return 2
    failed: List[str] = []
    for cand in args.paths[1:]:
        try:
            cur = load(cand)
        except (OSError, json.JSONDecodeError) as e:
            print(f"benchdiff: {cand}: {e}", file=sys.stderr)
            return 2
        rows = diff(base, cur)
        print(f"== {args.paths[0]} -> {cand} "
              f"({len(rows)} shared metrics) ==")
        if not rows:
            print("  (nothing comparable)")
        width = max((len(r[0]) for r in rows), default=0)
        for name, a, b, pct, sign in rows:
            arrow = {1: "^ better", -1: "v better", 0: "?"}[sign]
            print(
                f"  {name:<{width}}  {a:>14.4g} -> {b:>14.4g}  "
                f"{pct:>+8.2f}%  [{arrow}]"
            )
        if args.fail_over is not None:
            bad = regressions(rows, args.fail_over)
            for name in bad:
                print(f"  REGRESSION beyond {args.fail_over}%: {name}")
            failed.extend(bad)
    if args.fail_over is not None and failed:
        print(f"benchdiff: FAIL — {len(failed)} regression(s) beyond "
              f"{args.fail_over}%", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
