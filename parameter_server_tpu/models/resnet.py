"""ResNet (v1.5 bottleneck) in flax — BASELINE config #2 (ResNet-50/ImageNet).

The reference has no CNN zoo (it predates them); the north star adds
"ResNet-50 async SGD" as a target workload, so the model is built TPU-first:
NHWC layout (TPU conv-native), flax BatchNorm whose batch statistics are
computed over the *global* (data-sharded) batch under jit/GSPMD — the
cross-replica sync that would be a NCCL allreduce elsewhere is just the
reduction XLA inserts.

ResNet-50 == ``ResNet(stage_sizes=[3, 4, 6, 3], bottleneck=True)``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="shortcut",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="shortcut",
            )(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    bottleneck: bool = True
    dtype: Any = jnp.float32
    #: small-image mode (CIFAR-style): 3x3 stem, no max-pool
    small_inputs: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        block = BottleneckBlock if self.bottleneck else BasicBlock

        if self.small_inputs:
            x = conv(self.width, (3, 3), name="stem")(x)
        else:
            x = conv(self.width, (7, 7), strides=(2, 2), name="stem")(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block(
                    self.width * 2**i, strides, conv=conv, norm=norm
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def resnet18(**kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], bottleneck=False, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], bottleneck=True, **kw)
