"""models subpackage."""
