"""DLRM / Wide&Deep — BASELINE config #3 (billion-row sparse embeddings).

Architecture (standard DLRM): dense features -> bottom MLP; categorical
features -> embedding rows from the PS table; pairwise dot-product feature
interactions; top MLP -> CTR logit.

The embedding table is the parameter-server table: row-sharded over the
``model`` mesh axis (the reference's key-range server partition — and the EP
analogue called out in SURVEY.md §2: embedding shards ARE the expert shards).
The train step differentiates w.r.t. the *gathered unique rows* — XLA's AD
turns the ``rows[inverse]`` indexing into the duplicate-combining segment-sum
(the reference's ParallelOrderedMatch merge) — and the row-wise ServerOptimizer
applies the sparse update, so per-step memory is O(batch), never O(table).
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.kv.optim import ServerOptimizer, make_optimizer
from parameter_server_tpu.models.linear import logloss
from parameter_server_tpu.ops import scatter
from parameter_server_tpu.parallel import mesh as mesh_lib
from parameter_server_tpu.utils.keys import HashLocalizer, localize_to_slots


class MLP(nn.Module):
    features: Sequence[int]
    final_activation: bool = True

    @nn.compact
    def __call__(self, x):
        for i, f in enumerate(self.features):
            x = nn.Dense(f)(x)
            if i < len(self.features) - 1 or self.final_activation:
                x = nn.relu(x)
        return x


class DLRM(nn.Module):
    """Dense part of DLRM: bottom MLP, interactions, top MLP.

    The embedding rows come in as an argument (they live in the PS table).
    """

    bottom_mlp: Sequence[int]
    top_mlp: Sequence[int]
    emb_dim: int

    @nn.compact
    def __call__(self, dense_feats: jax.Array, emb: jax.Array) -> jax.Array:
        """dense_feats [B, n_dense]; emb [B, n_sparse, emb_dim] -> logits [B]."""
        bottom = MLP(tuple(self.bottom_mlp) + (self.emb_dim,))(dense_feats)
        feats = jnp.concatenate([bottom[:, None, :], emb], axis=1)  # [B, F, D]
        inter = jnp.einsum(
            "bfd,bgd->bfg", feats, feats, preferred_element_type=jnp.float32
        )
        f = feats.shape[1]
        iu, ju = jnp.triu_indices(f, k=1)
        inter_flat = inter[:, iu, ju]  # [B, F*(F-1)/2]
        top_in = jnp.concatenate([bottom, inter_flat], axis=1)
        logits = MLP(tuple(self.top_mlp) + (1,), final_activation=False)(top_in)
        return logits[:, 0]


def make_dlrm_step(
    table_cfg: TableConfig,
    mesh: Mesh,
    model: DLRM,
    optimizer: ServerOptimizer,
    tx,
    n_sparse: int,
):
    """Build the jitted DLRM train step over a (data, model) mesh.

    Factored out of ``SpmdDLRMTrainer`` so the billion-row feasibility path
    (VERDICT r4 #3) can AOT-compile the REAL step from ShapeDtypeStructs —
    a 2^30-row table is never materialized on a dev box, exactly like the
    8B body in ``parallel/feasibility.py``.

    Returns ``(jitted_step, shardings)`` where shardings carry the input
    layout: table row-sharded over ``model`` (the reference's key-range
    server partition), MLP replicated, batch over ``data``, unique slot
    ids replicated.
    """
    t_shard = mesh_lib.table_sharding(mesh)
    repl = mesh_lib.replicated(mesh)
    batch2 = mesh_lib.batch_sharding(mesh, 2)
    batch1 = mesh_lib.batch_sharding(mesh, 1)
    state_keys = sorted(optimizer.state_shapes())
    trash = table_cfg.rows  # trash row id (pads live past it)

    def step_fn(
        emb_value, emb_state, mlp_params, opt_state,
        ids, inverse, dense_feats, labels,
    ):
        batch = labels.shape[0]
        v_rows = scatter.gather_rows(emb_value, ids)
        s_rows = {k: scatter.gather_rows(v, ids) for k, v in emb_state.items()}
        w_rows = optimizer.pull_weights(v_rows, s_rows)

        def loss_fn(mlp_p, rows):
            emb = rows[inverse].reshape(batch, n_sparse, -1)
            logits = model.apply({"params": mlp_p}, dense_feats, emb)
            return logloss(logits, labels)

        l, (g_mlp, g_rows) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            mlp_params, w_rows
        )
        updates, opt_state = tx.update(g_mlp, opt_state, mlp_params)
        mlp_params = optax.apply_updates(mlp_params, updates)
        new_v, new_s = optimizer.apply(v_rows, s_rows, g_rows)
        emb_value = scatter.scatter_update_rows_xla(emb_value, ids, new_v)
        emb_state = {
            k: scatter.scatter_update_rows_xla(emb_state[k], ids, new_s[k])
            for k in emb_state
        }
        # trash-row reset (PAD gradients)
        fills = optimizer.state_shapes()
        emb_value = emb_value.at[trash].set(0.0)
        emb_state = {k: emb_state[k].at[trash].set(fills[k]) for k in emb_state}
        return emb_value, emb_state, mlp_params, opt_state, l

    step = jax.jit(
        step_fn,
        in_shardings=(
            t_shard,
            {k: t_shard for k in state_keys},
            repl,
            repl,
            repl,  # ids: replicated unique slots
            repl,  # inverse
            batch2,
            batch1,
        ),
        out_shardings=(
            t_shard,
            {k: t_shard for k in state_keys},
            repl,
            repl,
            repl,
        ),
        donate_argnums=(0, 1, 2, 3),
    )
    shardings = {
        "table": t_shard, "replicated": repl,
        "batch2": batch2, "batch1": batch1,
    }
    return step, shardings


def init_sharded_table(
    table_cfg: TableConfig,
    mesh: Mesh,
    optimizer: ServerOptimizer,
    total_rows: int,
    key=None,
    kind: str = "normal",
):
    """Materialize (value, state) DIRECTLY into their row shards.

    ``jit`` with ``out_shardings`` makes GSPMD generate each device's rows
    in place (partitionable threefry), so peak per-device memory is the
    shard, never the full table — the only way a near-HBM-sized table can
    come up on real hardware, and what keeps the 2^28-row CPU-mesh proof
    inside host RAM.

    ``kind="zeros"`` skips the gaussian draw (memset-speed): cold-start
    embeddings at tens of GB, where RNG generation dominates bring-up —
    the row-sharded layout and the train step are identical either way.
    """
    if kind not in ("normal", "zeros"):
        raise ValueError(f"kind must be normal|zeros, got {kind!r}")
    if key is None:
        key = jax.random.PRNGKey(0)
    t_shard = mesh_lib.table_sharding(mesh)
    dim = table_cfg.dim
    fills = optimizer.state_shapes()

    @functools.partial(
        jax.jit,
        static_argnums=(1,),
        out_shardings=(t_shard, {k: t_shard for k in sorted(fills)}),
    )
    def build(key, kind_):
        if kind_ == "zeros":
            value = jnp.zeros((total_rows, dim), jnp.float32)
        else:
            value = (
                jax.random.normal(key, (total_rows, dim))
                * table_cfg.init_scale
            ).astype(jnp.float32)
            value = value.at[table_cfg.rows :].set(0.0)  # trash + pad rows
        state = {
            k: jnp.full((total_rows, dim), fill, jnp.float32)
            for k, fill in fills.items()
        }
        return value, state

    with mesh:
        return build(key, kind)


class SpmdDLRMTrainer:
    """DLRM over a (data, model) mesh: PS-sharded embeddings + DP dense part."""

    def __init__(
        self,
        table_cfg: TableConfig,
        mesh: Mesh,
        *,
        n_dense: int = 13,
        n_sparse: int = 26,
        bottom_mlp: Sequence[int] = (64, 32),
        top_mlp: Sequence[int] = (64, 32),
        learning_rate: float = 0.01,
        min_bucket: int = 1024,
        seed: int = 0,
        table_init: str = "normal",
        dashboard=None,
    ) -> None:
        from parameter_server_tpu.utils import metrics as metrics_lib

        self.cfg = table_cfg
        self.mesh = mesh
        self.n_sparse = n_sparse
        self.min_bucket = min_bucket
        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        self.step_count = 0
        self._flops_shape = None  # (n_slots, batch) the cost analysis is for
        self.optimizer: ServerOptimizer = make_optimizer(table_cfg.optimizer)
        self.localizer = HashLocalizer(table_cfg.rows, seed=seed)
        self.model = DLRM(
            bottom_mlp=bottom_mlp, top_mlp=top_mlp, emb_dim=table_cfg.dim
        )
        self.tx = optax.adam(learning_rate)

        repl = mesh_lib.replicated(mesh)
        n_model = mesh.shape[mesh_lib.MODEL_AXIS]
        self.total_rows = ((table_cfg.rows + 1 + n_model - 1) // n_model) * n_model

        k_table, k_mlp = jax.random.split(jax.random.PRNGKey(seed))
        self.emb_value, self.emb_state = init_sharded_table(
            table_cfg, mesh, self.optimizer, self.total_rows, key=k_table,
            kind=table_init,
        )
        dense0 = jnp.zeros((1, n_dense), jnp.float32)
        emb0 = jnp.zeros((1, n_sparse, table_cfg.dim), jnp.float32)
        self.mlp_params = jax.device_put(
            self.model.init(k_mlp, dense0, emb0)["params"], repl
        )
        self.opt_state = jax.device_put(self.tx.init(self.mlp_params), repl)

        self._step, _shardings = make_dlrm_step(
            table_cfg, mesh, self.model, self.optimizer, self.tx, n_sparse,
        )

    def step(
        self,
        keys: np.ndarray,
        dense_feats: np.ndarray,
        labels: np.ndarray,
    ) -> float:
        slots, inverse, _n = localize_to_slots(
            keys, self.localizer, min_bucket=self.min_bucket
        )
        # MFU wiring (VERDICT r3 weak #4): DLRM has no clean FLOPs closed
        # form (MLPs + interactions + sparse gathers), so the numerator is
        # XLA's own count of the full step, refreshed when the bucketed
        # unique-slot count changes shape.
        shape_key = (slots.shape[0], labels.shape[0])
        if shape_key != self._flops_shape:
            from parameter_server_tpu.utils import metrics as metrics_lib

            step_flops = metrics_lib.lowered_flops(
                self._step,
                self.emb_value,
                self.emb_state,
                self.mlp_params,
                self.opt_state,
                jax.ShapeDtypeStruct(slots.shape, jnp.int32),
                jax.ShapeDtypeStruct(inverse.shape, jnp.int32),
                jax.ShapeDtypeStruct(np.asarray(dense_feats).shape, jnp.float32),
                jax.ShapeDtypeStruct(np.asarray(labels).shape, jnp.float32),
            )
            self.dashboard.flops_per_example = step_flops / max(
                labels.shape[0], 1
            )
            self._flops_shape = shape_key
        (
            self.emb_value,
            self.emb_state,
            self.mlp_params,
            self.opt_state,
            loss,
        ) = self._step(
            self.emb_value,
            self.emb_state,
            self.mlp_params,
            self.opt_state,
            jnp.asarray(slots),
            jnp.asarray(inverse),
            jnp.asarray(dense_feats),
            jnp.asarray(labels),
        )
        loss_f = float(loss)
        self.step_count += 1
        self.dashboard.record(
            self.step_count, loss_f, examples=int(labels.shape[0])
        )
        return loss_f
