"""Factorization machine over the KV layer.

Reference analogue: ``src/app/factorization_machine/`` — the FM model served
from KV tables (SURVEY.md §2 #17 [U — reference mount empty, public layout]).
One table holds, per feature row, the linear weight AND the factor vector:
``dim = 1 + k`` (column 0 = w_i, columns 1..k = v_i), so a single Push/Pull
moves the whole per-feature parameter block — the reference's KV-layer usage,
and on TPU one gather instead of two.

With one-hot categorical inputs (x_i = 1 at the example's keys) the
second-order FM term reduces to

    1/2 * sum_f [ (sum_i v_if)^2 - sum_i v_if^2 ]

and the per-position gradients are dl/dw_i = r and
dl/dv_if = r * (S_f - v_if) with S_f = sum_j v_jf, r = dloss/dlogit.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from parameter_server_tpu.kv.optim import ServerOptimizer
from parameter_server_tpu.models.linear import logloss
from parameter_server_tpu.ops import scatter


def fm_logits(rows_pos: jax.Array, bias: jax.Array) -> jax.Array:
    """Per-example logits from per-position parameter rows ``[B, nnz, 1+k]``."""
    w_pos = rows_pos[..., 0]  # [B, nnz]
    v_pos = rows_pos[..., 1:]  # [B, nnz, k]
    s = jnp.sum(v_pos, axis=1)  # [B, k]
    pair = 0.5 * jnp.sum(s * s - jnp.sum(v_pos * v_pos, axis=1), axis=-1)
    return jnp.sum(w_pos, axis=-1) + pair + bias


def fm_grad_rows(
    rows_pos: jax.Array, labels: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Van-path worker compute: per-position gradient rows ``[B, nnz, 1+k]``.

    Returns ``(g_pos, bias_grad, loss)``; gradients are mean-loss scaled so
    the server applies them unmodified (matches ``linear.grad_rows`` usage).
    """
    batch = labels.shape[0]
    logits = fm_logits(rows_pos, 0.0)
    loss = logloss(logits, labels)
    r = (jax.nn.sigmoid(logits) - labels) / batch  # [B]
    v_pos = rows_pos[..., 1:]
    s = jnp.sum(v_pos, axis=1, keepdims=True)  # [B, 1, k]
    g_w = jnp.broadcast_to(r[:, None], rows_pos.shape[:2])[..., None]  # [B,nnz,1]
    g_v = r[:, None, None] * (s - v_pos)  # [B, nnz, k]
    return jnp.concatenate([g_w, g_v], axis=-1), jnp.sum(r), loss


@functools.partial(
    jax.jit,
    static_argnames=("optimizer", "num_rows"),
    donate_argnums=(0, 1, 2, 3),
)
def fused_train_step(
    value: jax.Array,
    state: Dict[str, jax.Array],
    bias: jax.Array,
    bias_state: Dict[str, jax.Array],
    ids: jax.Array,
    inverse: jax.Array,
    labels: jax.Array,
    optimizer: ServerOptimizer,
    num_rows: int,
):
    """One full FM step on the device-resident ``[rows+1, 1+k]`` table.

    Same structure as ``linear.fused_train_step`` (gather touched rows ->
    loss/grad -> duplicate pre-combine -> optimizer apply -> scatter back,
    one XLA program, donated buffers); only the model math differs.
    """
    batch = labels.shape[0]
    dim = value.shape[1]
    rows = optimizer.pull_weights(
        scatter.gather_rows(value, ids),
        {k: scatter.gather_rows(v, ids) for k, v in state.items()},
    )  # [num_rows, 1+k]
    rows_pos = rows[inverse].reshape(batch, -1, dim)
    bias_w = optimizer.pull_weights(bias, bias_state)
    logits = fm_logits(rows_pos, bias_w[0, 0])
    loss = logloss(logits, labels)
    r = (jax.nn.sigmoid(logits) - labels) / batch
    v_pos = rows_pos[..., 1:]
    s = jnp.sum(v_pos, axis=1, keepdims=True)
    g_w = jnp.broadcast_to(r[:, None], rows_pos.shape[:2])[..., None]
    g_v = r[:, None, None] * (s - v_pos)
    g_pos = jnp.concatenate([g_w, g_v], axis=-1).reshape(-1, dim)
    combined = scatter.segment_combine(g_pos, inverse, num_rows)
    v_rows = scatter.gather_rows(value, ids)
    s_rows = {k: scatter.gather_rows(v, ids) for k, v in state.items()}
    new_v, new_s = optimizer.apply(v_rows, s_rows, combined)
    value = scatter.scatter_update_rows_xla(value, ids, new_v)
    state = {k: scatter.scatter_update_rows_xla(state[k], ids, new_s[k]) for k in state}
    fills = optimizer.state_shapes()
    value = value.at[-1].set(0.0)
    state = {k: state[k].at[-1].set(fills[k]) for k in state}
    g_bias = jnp.sum(r)[None, None]
    new_b, new_bs = optimizer.apply(bias, bias_state, g_bias)
    return value, state, new_b, new_bs, loss


def eval_logits_np(table_rows, bias, slots_pos):
    """Offline scoring from a host-side weight table (model evaluation path).

    ``table_rows``: full ``[rows, 1+k]`` numpy array (e.g. from
    ``checkpoint.load_global_weights``); ``slots_pos``: ``[B, nnz]`` row ids.
    """
    import numpy as np

    rows_pos = table_rows[slots_pos]  # [B, nnz, 1+k]
    w_pos = rows_pos[..., 0]
    v_pos = rows_pos[..., 1:]
    s = np.sum(v_pos, axis=1)
    pair = 0.5 * np.sum(s * s - np.sum(v_pos * v_pos, axis=1), axis=-1)
    return np.sum(w_pos, axis=-1) + pair + bias
