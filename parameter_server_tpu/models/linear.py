"""Sparse logistic regression — the reference's flagship linear method.

(Reference: ``src/app/linear_method/`` — logit loss, L1/L2 penalties, AdaGrad
async SGD workers [U]; BASELINE config #1: Criteo sparse LR.)

Two execution paths over the same math:

- :func:`grad_rows` — the *Van path*: the worker pulls per-position weights,
  computes per-position gradient values, pushes them back (classic PS loop).
- :func:`fused_train_step` — the *single-device fast path*: pull (gather),
  loss/grad, duplicate pre-combine, optimizer apply, and scatter-back compiled
  into ONE XLA program over the HBM-resident table; buffers donated.  This is
  what the north-star examples/sec/chip metric measures, and the body that
  ``parallel/`` later wraps in shard_map (psum of combined grads over the DP
  axis before the apply == NCCL-pre-reduction replacement).

With one-hot categorical features the per-example logit is the sum of the
weights at the example's keys plus bias, and d(loss)/d(w_k) = (p - y) for
each position holding key k.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from parameter_server_tpu.kv.optim import ServerOptimizer
from parameter_server_tpu.ops import scatter


def predict_logits(w_pos: jax.Array, bias: jax.Array) -> jax.Array:
    """Per-example logits from per-position weights ``[B, nnz]``."""
    return jnp.sum(w_pos, axis=-1) + bias


def logloss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean binary cross-entropy from logits (numerically stable)."""
    return jnp.mean(
        jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )


def grad_rows(
    w_pos: jax.Array, labels: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Van-path worker compute: per-position gradient values.

    Returns ``(per_position_grads [B, nnz], bias_grad [], loss [])``.
    """
    logits = predict_logits(w_pos, 0.0)
    p = jax.nn.sigmoid(logits)
    residual = p - labels  # [B]
    g = jnp.broadcast_to(residual[:, None], w_pos.shape)
    return g, jnp.mean(residual), logloss(logits, labels)


@functools.partial(
    jax.jit,
    static_argnames=("optimizer", "num_rows"),
    donate_argnums=(0, 1, 2, 3),
)
def fused_train_step(
    value: jax.Array,
    state: Dict[str, jax.Array],
    bias: jax.Array,
    bias_state: Dict[str, jax.Array],
    ids: jax.Array,
    inverse: jax.Array,
    labels: jax.Array,
    optimizer: ServerOptimizer,
    num_rows: int,
):
    """One full LR step on the device-resident table.

    Args:
      value/state: the table arrays (donated, updated in place).
      bias/bias_state: scalar bias row ``[1, 1]`` and its optimizer state.
      ids: unique row slots ``[num_rows]`` (bucket-padded, pads -> trash row).
      inverse: position -> slot-row map ``[B * nnz]``.
      labels: ``[B]``.

    Returns ``(value, state, bias, bias_state, loss)``.
    """
    batch = labels.shape[0]
    w_rows = optimizer.pull_weights(
        scatter.gather_rows(value, ids),
        {k: scatter.gather_rows(v, ids) for k, v in state.items()},
    )  # [num_rows, 1]
    w_pos = w_rows[inverse, 0].reshape(batch, -1)  # [B, nnz]
    # bias goes through the same lazy-weight transform (FTRL stores z here)
    bias_w = optimizer.pull_weights(bias, bias_state)
    logits = predict_logits(w_pos, bias_w[0, 0])
    loss = logloss(logits, labels)
    residual = (jax.nn.sigmoid(logits) - labels) / batch  # mean-loss scaling
    g_pos = jnp.broadcast_to(residual[:, None], w_pos.shape).reshape(-1, 1)
    combined = scatter.segment_combine(g_pos, inverse, num_rows)  # [num_rows, 1]
    # optimizer apply on touched rows, scatter back
    v_rows = scatter.gather_rows(value, ids)
    s_rows = {k: scatter.gather_rows(v, ids) for k, v in state.items()}
    new_v, new_s = optimizer.apply(v_rows, s_rows, combined)
    value = scatter.scatter_update_rows_xla(value, ids, new_v)
    state = {k: scatter.scatter_update_rows_xla(state[k], ids, new_s[k]) for k in state}
    # re-zero the trash row (last): PAD_KEY positions route gradients there
    fills = optimizer.state_shapes()
    value = value.at[-1].set(0.0)
    state = {k: state[k].at[-1].set(fills[k]) for k in state}
    # bias via the same optimizer rule on its 1x1 "table"
    g_bias = jnp.sum(residual)[None, None]
    new_b, new_bs = optimizer.apply(bias, bias_state, g_bias)
    return value, state, new_b, new_bs, loss


def dense_fused_impl(
    value: jax.Array,
    state: Dict[str, jax.Array],
    bias: jax.Array,
    bias_state: Dict[str, jax.Array],
    slots_pos: jax.Array,
    labels: jax.Array,
    optimizer: ServerOptimizer,
    trash_row: int = -1,
):
    """Dense-apply LR step: no host dedup, no row gather/scatter of updates.

    The TPU-native formulation of the server update: per-position hashed row
    slots ``[B, nnz]`` index the table directly; duplicate slots are combined
    by the scatter-add into a full-size gradient buffer, and the optimizer
    applies *elementwise over the whole table*.  For rows with zero gradient
    the update is exactly zero under SGD/AdaGrad/FTRL (their state updates
    are also zero at g=0), so this matches the sparse row-apply semantics
    while avoiding the per-batch ``np.unique`` host bottleneck entirely.

    Caveats (callers must enforce): requires ``l1 == l2 == 0`` — penalties
    make the update nonzero at g=0 rows (l2 decays every row; AdaGrad's prox
    with sum_sq=0 would zero untouched weights) — and an optimizer whose
    state update is zero at g=0 (true for SGD/AdaGrad/FTRL; NOT Adam, whose
    moments decay).  Otherwise use the row-apply :func:`fused_train_step`.

    HBM traffic per step is O(table size); right for tables up to a few GB
    (Criteo LR at 2^25 rows x 4B = 128 MB -> ~0.2 ms at v5e bandwidth).
    """
    batch = labels.shape[0]
    w_table = optimizer.pull_weights(value, state)  # elementwise transform
    w_pos = w_table[slots_pos.reshape(-1), 0].reshape(batch, -1)
    bias_w = optimizer.pull_weights(bias, bias_state)
    logits = predict_logits(w_pos, bias_w[0, 0])
    loss = logloss(logits, labels)
    residual = (jax.nn.sigmoid(logits) - labels) / batch
    g_pos = jnp.broadcast_to(residual[:, None], w_pos.shape).reshape(-1)
    grad_buf = jnp.zeros_like(value).at[slots_pos.reshape(-1), 0].add(g_pos)
    # drop PAD contributions; trash_row is the PAD slot of the localizer
    # (== capacity); -1 only coincides with it for unpadded [rows+1] tables
    grad_buf = grad_buf.at[trash_row].set(0.0)
    value, state = optimizer.apply(value, state, grad_buf)
    g_bias = jnp.sum(residual)[None, None]
    new_b, new_bs = optimizer.apply(bias, bias_state, g_bias)
    return value, state, new_b, new_bs, loss


dense_fused_train_step = functools.partial(
    jax.jit,
    static_argnames=("optimizer", "trash_row"),
    donate_argnums=(0, 1, 2, 3),
)(dense_fused_impl)


def mix32_jax(x: jax.Array, seed: int = 0) -> jax.Array:
    """murmur3 fmix32 on device (uint32) — twin of ``utils.keys.mix32``.

    TPUs have no native uint64, so device-side hashing uses the 32-bit
    avalanche; ``HashLocalizer(hash_bits=32)`` reproduces it on the host.
    The constants are shared with the host twin so they cannot diverge.
    """
    from parameter_server_tpu.utils.keys import MIX32_A, MIX32_B

    x = x.astype(jnp.uint32) ^ jnp.uint32(seed)
    x ^= x >> 16
    x = x * jnp.uint32(MIX32_A)
    x ^= x >> 13
    x = x * jnp.uint32(MIX32_B)
    x ^= x >> 16
    return x


@functools.partial(
    jax.jit,
    static_argnames=("optimizer", "num_rows", "seed"),
    donate_argnums=(0, 1, 2, 3),
)
def dense_scan_train_step(
    value: jax.Array,
    state: Dict[str, jax.Array],
    bias: jax.Array,
    bias_state: Dict[str, jax.Array],
    keys_block: jax.Array,
    labels_block: jax.Array,
    optimizer: ServerOptimizer,
    num_rows: int,
    seed: int = 0,
):
    """K dense-apply LR steps in ONE XLA program (``lax.scan`` over steps).

    The tunnel/PCIe-bound single-chip path: raw uint32 keys ``[K, B, nnz]``
    ship in one transfer (half the bytes of int32 slot ids computed on host,
    and K× fewer dispatches), the hashing trick runs on device via
    :func:`mix32_jax`, and each scan iteration is the ``dense_fused_impl``
    update.  PAD positions (key == ``0xFFFFFFFF``, the uint32 image of
    ``PAD_KEY``) route to the table's trash row like the host path; real keys
    must therefore be < 2**32 - 1.  Returns
    ``(value, state, bias, bias_state, losses [K])``.
    """

    def body(carry, xs):
        value, state, bias, bias_state = carry
        keys, labels = xs
        slots = jnp.where(
            keys == jnp.uint32(0xFFFF_FFFF),
            jnp.int32(num_rows),  # trash row of the [rows + 1] table
            (mix32_jax(keys, seed) % jnp.uint32(num_rows)).astype(jnp.int32),
        )
        value, state, bias, bias_state, loss = dense_fused_impl(
            value, state, bias, bias_state, slots, labels, optimizer
        )
        return (value, state, bias, bias_state), loss

    (value, state, bias, bias_state), losses = jax.lax.scan(
        body, (value, state, bias, bias_state), (keys_block, labels_block)
    )
    return value, state, bias, bias_state, losses


def eval_logits(
    value: jax.Array,
    state: Dict[str, jax.Array],
    bias: jax.Array,
    bias_state: Dict[str, jax.Array],
    ids: jax.Array,
    inverse: jax.Array,
    batch: int,
    optimizer: ServerOptimizer,
) -> jax.Array:
    """Forward-only logits for evaluation batches."""
    w_rows = optimizer.pull_weights(
        scatter.gather_rows(value, ids),
        {k: scatter.gather_rows(v, ids) for k, v in state.items()},
    )
    w_pos = w_rows[inverse, 0].reshape(batch, -1)
    bias_w = optimizer.pull_weights(bias, bias_state)
    return predict_logits(w_pos, bias_w[0, 0])
