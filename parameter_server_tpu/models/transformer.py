"""Transformer family: one configurable module covering BERT and Llama.

BASELINE configs #4 (BERT-base MLM) and #5 (Llama-3-8B hybrid).  The
reference predates transformers; the north star adds them, with the Llama
hybrid defined as "PS-sharded embeddings + XLA allreduce for transformer
blocks": here the embedding table is row-sharded over the ``model`` mesh axis
(exactly the KV table partition scheme) while attention/MLP weights use
tensor-parallel sharding rules (``parallel/tp.py``) whose collectives XLA
emits over ICI.

Implementation notes (TPU-first):
- all projections keep explicit head axes so GSPMD can shard heads;
- rotary embeddings computed in f32 regardless of activation dtype;
- GQA: n_kv_heads <= n_heads with head-group repetition;
- no data-dependent control flow; causal masking via static tril.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int
    n_layers: int
    n_heads: int
    d_model: int
    d_ff: int
    n_kv_heads: Optional[int] = None  # None -> == n_heads (MHA)
    max_seq: int = 2048
    causal: bool = True
    positional: str = "rotary"  # "rotary" | "learned"
    norm: str = "rms"  # "rms" | "ln"
    activation: str = "swiglu"  # "swiglu" | "gelu"
    tie_embeddings: bool = False
    dtype: Any = jnp.float32
    rope_theta: float = 500_000.0
    #: rematerialize each block on backward (jax.checkpoint): the bwd pass
    #: then saves only the O(B*S*d) block inputs instead of every attention
    #: score / d_ff intermediate — the HBM-for-FLOPs trade that makes the
    #: 8B config fit a v5e-16 (SURVEY §7 step 7).
    remat: bool = False
    #: run the block stack as ONE lax.scan over stacked per-layer params
    #: instead of a Python-unrolled loop.  Param tree changes shape: all
    #: blocks live under ``blocks/block/...`` with a leading layer axis.
    #: This is the at-scale layout: compile time is O(1) in depth, and
    #: XLA's buffer liveness (and therefore remat's memory win) is explicit
    #: — measured on the 8B feasibility path, unrolled remat saves ~nothing
    #: while scan+remat cuts temp memory several-fold.
    scan_blocks: bool = False
    #: attention implementation: "dense" (full scores matrix), "ring"
    #: (sequence-parallel exact attention via ppermute over the ``sp_axis``
    #: mesh axis — ONLY valid inside a shard_map that carries that axis;
    #: ``parallel/sp_lm.py`` is the trainer that sets this up), "ulysses"
    #: (same contract as "ring"), or "ring_spmd" (the ring wrapped in a
    #: PARTIAL shard_map — callable from ordinary GSPMD code on global
    #: views, composing with TP/FSDP shardings on the other mesh axes;
    #: requires ``spmd_mesh``; ``parallel/sp_fsdp.py`` is the trainer).
    #: The param tree is identical in every case, so dense-initialized
    #: checkpoints load into ring models and vice versa.
    attn_impl: str = "dense"
    sp_axis: str = "sp"
    #: concrete mesh for "ring_spmd" (the partial shard_map must name it)
    spmd_mesh: Any = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads


def bert_base(vocab_size: int = 30522, **kw) -> "TransformerConfig":
    """BERT-base: 12L, 12H, 768d, bidirectional, learned pos, LN, GELU."""
    return TransformerConfig(
        vocab_size=vocab_size, n_layers=12, n_heads=12, d_model=768,
        d_ff=3072, max_seq=512, causal=False, positional="learned",
        norm="ln", activation="gelu", tie_embeddings=True, **kw,
    )


def llama3_8b(vocab_size: int = 128_256, **kw) -> "TransformerConfig":
    """Llama-3-8B: 32L, 32H/8KV, 4096d, 14336ff, rotary, RMS, SwiGLU."""
    return TransformerConfig(
        vocab_size=vocab_size, n_layers=32, n_heads=32, n_kv_heads=8,
        d_model=4096, d_ff=14336, max_seq=8192, **kw,
    )


def tiny_config(causal: bool = True, **kw) -> TransformerConfig:
    """Small config for tests: same code paths, toy sizes."""
    defaults = dict(
        vocab_size=256, n_layers=2, n_heads=4, n_kv_heads=2, d_model=64,
        d_ff=128, max_seq=64, causal=causal,
    )
    if not causal:
        defaults.update(positional="learned", norm="ln", activation="gelu",
                        n_kv_heads=4, tie_embeddings=True)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def _rotary(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Apply rotary embedding over the last (head_dim) axis. x: [B,S,H,D]."""
    d = x.shape[-1]
    freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, :, None].astype(jnp.float32) * freq  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    out = jnp.stack([out1, out2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class Norm(nn.Module):
    kind: str
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.kind == "rms":
            scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
            var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
            return (x * jax.lax.rsqrt(var + 1e-6)).astype(self.dtype) * scale
        return nn.LayerNorm(dtype=self.dtype)(x)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, attn_mask=None):
        cfg = self.cfg
        B, S, _ = x.shape
        H, KV, D = cfg.n_heads, cfg.kv_heads, cfg.head_dim
        dense = lambda heads, name: nn.DenseGeneral(  # noqa: E731
            (heads, D), axis=-1, use_bias=cfg.norm == "ln", name=name,
            dtype=cfg.dtype,
        )
        q = dense(H, "q")(x)  # [B,S,H,D]
        k = dense(KV, "k")(x)
        v = dense(KV, "v")(x)
        if cfg.positional == "rotary":
            q = _rotary(q, positions, cfg.rope_theta)
            k = _rotary(k, positions, cfg.rope_theta)
        if KV != H:
            rep = H // KV
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if cfg.attn_impl in ("ring", "ulysses", "ring_spmd"):
            if attn_mask is not None:
                raise ValueError(
                    "sequence-parallel attention does not support attn_mask "
                    "(padding masks are a dense-impl feature)"
                )
            if cfg.attn_impl == "ring_spmd":
                from parameter_server_tpu.ops.ring_attention import (
                    ring_attention_spmd,
                )

                if cfg.spmd_mesh is None:
                    raise ValueError(
                        "attn_impl='ring_spmd' needs cfg.spmd_mesh (the "
                        "partial shard_map must name a concrete mesh)"
                    )
                out = ring_attention_spmd(
                    q, k, v, mesh=cfg.spmd_mesh, sp_axis=cfg.sp_axis,
                    causal=cfg.causal,
                ).astype(cfg.dtype)
            elif cfg.attn_impl == "ring":
                from parameter_server_tpu.ops.ring_attention import (
                    ring_attention,
                )

                out = ring_attention(
                    q, k, v, axis_name=cfg.sp_axis, causal=cfg.causal
                ).astype(cfg.dtype)
            else:
                from parameter_server_tpu.ops.ulysses import ulysses_attention

                out = ulysses_attention(
                    q, k, v, axis_name=cfg.sp_axis, causal=cfg.causal
                ).astype(cfg.dtype)
        else:
            scores = jnp.einsum(
                "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
            ) / np.sqrt(D)
            if cfg.causal:
                causal = jnp.tril(jnp.ones((S, S), bool))
                scores = jnp.where(causal[None, None], scores, -1e30)
            if attn_mask is not None:  # [B, S] True = attend
                scores = jnp.where(attn_mask[:, None, None, :], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            out = jnp.einsum(
                "bhst,bthd->bshd", probs, v,
                preferred_element_type=jnp.float32,
            ).astype(cfg.dtype)
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), use_bias=cfg.norm == "ln", name="o",
            dtype=cfg.dtype,
        )(out)


class MLPBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        bias = cfg.norm == "ln"
        if cfg.activation == "swiglu":
            gate = nn.Dense(cfg.d_ff, use_bias=bias, name="gate", dtype=cfg.dtype)(x)
            up = nn.Dense(cfg.d_ff, use_bias=bias, name="up", dtype=cfg.dtype)(x)
            h = nn.silu(gate) * up
        else:
            h = nn.gelu(
                nn.Dense(cfg.d_ff, use_bias=bias, name="up", dtype=cfg.dtype)(x)
            )
        return nn.Dense(cfg.d_model, use_bias=bias, name="down", dtype=cfg.dtype)(h)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, attn_mask=None):
        cfg = self.cfg
        h = Norm(cfg.norm, cfg.dtype, name="attn_norm")(x)
        x = x + Attention(cfg, name="attn")(h, positions, attn_mask)
        h = Norm(cfg.norm, cfg.dtype, name="mlp_norm")(x)
        return x + MLPBlock(cfg, name="mlp")(h)


class _ScanBlock(nn.Module):
    """Scan-body adapter: Block with the (carry, ys) return nn.scan wants."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, attn_mask=None):
        return Block(self.cfg, name="block")(x, positions, attn_mask), ()


def _apply_body(mod: nn.Module, cfg: TransformerConfig, x, attn_mask,
                positions=None):
    """Shared block stack: pos-emb + layers + final norm (no head).

    Called from inside a module's ``@nn.compact`` ``__call__``; submodules
    and params attach to the CALLER's scope with identical names, so
    :class:`Transformer` and :class:`TransformerBody` stay one
    implementation with interchangeable param trees.

    ``positions``: GLOBAL token positions ``[B, S]`` — pass them when ``x``
    is a sequence SHARD (SP: rotary phases and learned pos-emb rows must
    use global offsets, not the local 0..S_local range).
    """
    B, S, _ = x.shape
    x = x.astype(cfg.dtype)
    if positions is None:
        if cfg.positional == "learned" and S > cfg.max_seq:
            # the old slice failed loudly here; the gather below would
            # silently clamp out-of-range rows instead — keep it loud
            raise ValueError(
                f"sequence {S} exceeds learned-positional max_seq "
                f"{cfg.max_seq}"
            )
        positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
    if cfg.positional == "learned":
        pos_emb = mod.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (cfg.max_seq, cfg.d_model),
        )
        x = x + jnp.take(pos_emb, positions, axis=0).astype(cfg.dtype)
    if cfg.scan_blocks:
        body_cls = nn.remat(_ScanBlock) if cfg.remat else _ScanBlock
        scanned = nn.scan(
            body_cls,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=cfg.n_layers,
            in_axes=(nn.broadcast, nn.broadcast),
        )
        x, _ = scanned(cfg, name="blocks")(x, positions, attn_mask)
    else:
        block_cls = nn.remat(Block) if cfg.remat else Block
        for i in range(cfg.n_layers):
            x = block_cls(cfg, name=f"layer_{i}")(x, positions, attn_mask)
    return Norm(cfg.norm, cfg.dtype, name="final_norm")(x)


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, attn_mask=None):
        """tokens [B, S] int32 -> logits [B, S, vocab]."""
        cfg = self.cfg
        emb = self.param(
            "embedding",
            nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.d_model),
        )
        x = _apply_body(self, cfg, emb[tokens], attn_mask)
        if cfg.tie_embeddings:
            logits = jnp.einsum(
                "bsd,vd->bsv", x, emb.astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            )
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, name="lm_head",
                dtype=cfg.dtype,
            )(x)
        return logits.astype(jnp.float32)


class TransformerTrunk(nn.Module):
    """Block stack + final norm WITHOUT the lm_head: hidden states out.

    Param names match :class:`TransformerBody` minus ``lm_head`` (both call
    :func:`_apply_body` in their own scope), so a body param tree minus its
    ``lm_head`` entry applies directly — the seam the memory-bounded chunked
    loss needs (head matmul fused into the loss, logits never materialized).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, attn_mask=None, positions=None):
        return _apply_body(self, self.cfg, x, attn_mask, positions)


class TransformerBody(nn.Module):
    """The dense half of the PS hybrid (BASELINE config #5): blocks + final
    norm + untied lm_head, taking PRE-COMPUTED input embeddings.

    The embedding table itself lives in a KVServer (async Push/Pull over the
    Van, row-partitioned by token id — the reference's key-range scheme),
    while this body trains synchronously under GSPMD: batch sharded over
    ``data``, params TP-sharded per ``parallel/tp.py``, XLA emitting the
    allreduce.  ``learner/hybrid.py`` glues the two halves.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, attn_mask=None):
        """x [B, S, d_model] input embeddings -> logits [B, S, vocab]."""
        cfg = self.cfg
        x = _apply_body(self, cfg, x, attn_mask)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, name="lm_head", dtype=cfg.dtype
        )(x)
        return logits.astype(jnp.float32)


# -- losses -----------------------------------------------------------------


def causal_lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token CE: predict tokens[:, 1:] from logits[:, :-1]."""
    logp = jax.nn.log_softmax(logits[:, :-1])
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def chunked_causal_lm_loss(
    hidden: jax.Array,
    head_kernel: jax.Array,
    tokens: jax.Array,
    chunk: int = 1024,
) -> jax.Array:
    """Next-token CE with the head matmul fused into the loss, by chunks.

    ``causal_lm_loss`` needs the full f32 ``[B, S, vocab]`` logits live (and
    AD saves more copies for backward) — at Llama-3-8B scale (vocab 128k)
    that one tensor dominates the step's memory.  Here the lm_head matmul
    runs per sequence-chunk inside a rematerialized scan body: only one
    ``[B, chunk, vocab]`` slab exists at a time and backward recomputes it,
    so peak memory is O(S/chunk smaller) for ~one extra head matmul of
    FLOPs.  Numerically identical to
    ``causal_lm_loss(hidden @ head_kernel, tokens)`` up to summation order.
    """
    B, S, _d = hidden.shape
    n = S - 1
    xs = hidden[:, :-1]
    tg = tokens[:, 1:]
    chunk = min(chunk, n)
    pad = (-n) % chunk
    if pad:
        xs = jnp.pad(xs, ((0, 0), (0, pad), (0, 0)))
        tg = jnp.pad(tg, ((0, 0), (0, pad)))
    valid = (jnp.arange(n + pad) < n)[None, :]
    n_chunks = (n + pad) // chunk
    xs = xs.reshape(B, n_chunks, chunk, -1).transpose(1, 0, 2, 3)
    tg = tg.reshape(B, n_chunks, chunk).transpose(1, 0, 2)
    mk = (
        jnp.broadcast_to(valid, (B, n + pad))
        .reshape(B, n_chunks, chunk)
        .transpose(1, 0, 2)
    )

    @jax.checkpoint
    def chunk_nll(xc, tc, mc):
        logits = jnp.einsum(
            "bcd,dv->bcv", xc, head_kernel,
            preferred_element_type=jnp.float32,
        )
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * mc)

    def body(acc, args):
        xc, tc, mc = args
        return acc + chunk_nll(xc, tc, mc), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xs, tg, mk))
    return total / (B * n)


def mlm_loss(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    """Masked-LM CE over masked positions only (mask True = predict)."""
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(nll * mask) / denom
