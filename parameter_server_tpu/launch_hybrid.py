"""Dual-plane config #5 launch: TcpVan embedding plane + jax.distributed body.

The deployment shape BASELINE config #5 actually describes (SURVEY.md §5
two-plane design; the composition VERDICT r3 flagged as never-run): KVServers
serving the embedding table live in their OWN OS processes on the native
TcpVan (wire filters on), while the transformer body runs as a
``jax.distributed`` GSPMD job across N more processes — two independent
communication planes crossing real process boundaries:

- **embedding plane (DCN analogue)**: every body process registers as a Van
  worker and pulls/pushes ONLY its ``local_batch_slice`` of every global
  batch over real sockets (key-cached, int8-quantized, zlib-compressed);
- **dense plane (ICI analogue)**: the body processes form one global mesh;
  XLA/Gloo inserts the gradient allreduce inside the jit step.

Consistency across the plane: ``--bsp`` (default) drains every push and
barriers the body processes (``sync_global_devices``) each step, so all
pushes land before anyone's next pull — the cross-process run then matches
the in-process hybrid loss-for-loss (with an ``sgd`` embedding optimizer the
two-halves-pushed-separately update equals the one-push update up to float
summation order).  ``--no-bsp`` enables the production overlap instead:
``max_delay`` pushes in flight, prefetched pulls — bounded staleness, no
parity guarantee (the reference's SSP regime).

Roles mirror ``launch.py`` (scheduler H / servers S* / bodies W*); the
scheduler is the same Manager barrier host.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Optional

from parameter_server_tpu.core.filters import DEFAULT_SPEC

from parameter_server_tpu.launch import (
    _build_cluster,
    _free_port,
    _log,
    run_scheduler,
)


def _tfm_cfg(args):
    from parameter_server_tpu.models import transformer as tfm

    return tfm.TransformerConfig(
        vocab_size=args.vocab,
        n_layers=args.layers,
        n_heads=args.heads,
        d_model=args.d_model,
        d_ff=args.d_ff,
        max_seq=args.seq,
        causal=True,
        tie_embeddings=False,
    )


def _table_cfgs(args):
    from parameter_server_tpu.learner import hybrid

    return {
        "emb": hybrid.embedding_table_cfg(
            _tfm_cfg(args),
            learning_rate=args.emb_lr,
            optimizer=args.emb_optimizer,
        )
    }


def run_server(args) -> int:
    """One embedding KVServer shard in its own process (TcpVan, filters)."""
    from parameter_server_tpu.kv.server import KVServer

    index = int(args.node_id[1:])
    van, post, mgr, _server = _build_cluster(
        args,
        0,
        setup=lambda post: KVServer(
            post, _table_cfgs(args), index, args.num_servers
        ),
    )
    try:
        _log(args, "emb shard serving; waiting on shutdown barrier")
        n_nodes = args.num_workers + args.num_servers
        ok = mgr.barrier("shutdown", n_nodes + 1, timeout=args.run_timeout)
        _log(args, f"shutdown barrier -> {ok}")
        return 0
    finally:
        van.close()


def run_body(args) -> int:
    """One GSPMD body process: mesh member AND Van embedding worker."""
    from parameter_server_tpu.parallel import distributed

    proc_id = int(args.node_id[1:])
    # dense plane first: jax.distributed must initialize before any backend
    # use; the Van attaches afterwards (independent plane)
    distributed.initialize(
        args.coordinator, args.num_workers, proc_id,
        cpu_devices=args.cpu_devices,
    )
    import numpy as np
    from jax.experimental import multihost_utils

    from parameter_server_tpu.kv.worker import KVWorker
    from parameter_server_tpu.learner import hybrid

    cfg = _tfm_cfg(args)
    mesh = distributed.global_mesh()
    van, post, mgr, _ = _build_cluster(args, 0)
    try:
        worker = KVWorker(
            post,
            _table_cfgs(args),
            args.num_servers,
            localizers=hybrid.embedding_localizers(cfg),
        )
        tr = hybrid.HybridLMTrainer(
            cfg,
            mesh,
            worker,
            learning_rate=args.lr,
            max_delay=0 if args.bsp else args.max_delay,
            seed=args.seed,
        )
        # deterministic global batch stream, identical on every body process
        # (the reference's coordination-free WorkloadPool determinism)
        rng = np.random.default_rng(args.seed + 1)
        batches = [
            rng.integers(
                0, cfg.vocab_size, size=(args.global_batch, args.seq)
            ).astype(np.int32)
            for _ in range(args.steps + 1)
        ]
        _log(args, f"training on mesh {dict(mesh.shape)}")
        losses = []
        for s in range(args.steps):
            nxt = None if args.bsp else batches[s + 1]
            loss = tr.step(batches[s], next_tokens=nxt)
            if args.bsp:
                # BSP across the embedding plane: all pushes applied (drain
                # acks) on every process before anyone's next pull
                tr.drain()
                multihost_utils.sync_global_devices(f"emb-step{s}")
            losses.append(loss)
        tr.drain()
        if args.outdir:
            chain = getattr(van, "filter_chain", None)
            out = os.path.join(args.outdir, f"{args.node_id}.json")
            with open(out, "w") as f:
                json.dump(
                    {
                        "node": args.node_id,
                        "losses": losses,
                        # socket + colocated-shm-ring bytes: the cross-
                        # process traffic proof must not read zero just
                        # because colocated links negotiated the fast path
                        "wire_sent": van.payload_bytes_sent(),
                        "wire_recv": van.payload_bytes_recv(),
                        "filter_overhead": (
                            chain.overhead() if chain is not None else None
                        ),
                    },
                    f,
                )
        n_nodes = args.num_workers + args.num_servers
        ok = mgr.barrier("shutdown", n_nodes + 1, timeout=args.run_timeout)
        _log(args, f"shutdown barrier -> {ok}")
        return 0
    finally:
        van.close()


def launch_hybrid(
    *,
    num_body: int = 2,
    cpu_devices: int = 4,
    num_servers: int = 2,
    steps: int = 4,
    vocab: int = 256,
    layers: int = 2,
    heads: int = 2,
    d_model: int = 32,
    d_ff: int = 64,
    seq: int = 16,
    global_batch: int = 8,
    lr: float = 1e-3,
    emb_lr: float = 0.05,
    emb_optimizer: str = "adagrad",
    bsp: bool = True,
    max_delay: int = 2,
    seed: int = 0,
    filters: str = DEFAULT_SPEC,
    run_timeout: float = 300.0,
    python: str = sys.executable,
) -> dict:
    """Spawn the dual-plane job: scheduler + emb servers + GSPMD bodies.

    Returns per-body losses and true socket byte counters (the evidence
    that embedding traffic crossed process boundaries).
    """
    from parameter_server_tpu.core.filters import make_chain

    make_chain(filters)  # validate the spec HERE, not in five children
    sched_port = _free_port()
    coord_port = _free_port()
    outdir = tempfile.mkdtemp(prefix="psx_hybrid_")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pypath = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ,
        PYTHONPATH=f"{repo_root}:{pypath}" if pypath else repo_root,
    )

    def spawn(role: str, node_id: str) -> subprocess.Popen:
        cmd = [
            python, "-m", "parameter_server_tpu.launch_hybrid",
            "--role", role, "--node-id", node_id,
            "--scheduler-port", str(sched_port),
            "--coordinator", f"127.0.0.1:{coord_port}",
            "--num-body", str(num_body),
            "--cpu-devices", str(cpu_devices),
            "--num-servers", str(num_servers),
            "--steps", str(steps),
            "--vocab", str(vocab), "--layers", str(layers),
            "--heads", str(heads), "--d-model", str(d_model),
            "--d-ff", str(d_ff), "--seq", str(seq),
            "--global-batch", str(global_batch),
            "--lr", str(lr), "--emb-lr", str(emb_lr),
            "--emb-optimizer", emb_optimizer,
            "--max-delay", str(max_delay),
            "--seed", str(seed),
            "--filters", filters,
            "--outdir", outdir,
            "--run-timeout", str(run_timeout),
        ] + (["--bsp"] if bsp else ["--no-bsp"])
        return subprocess.Popen(cmd, env=env)

    procs = [spawn("scheduler", "H")]
    time.sleep(0.3)  # scheduler binds its fixed port first
    procs += [spawn("server", f"S{i}") for i in range(num_servers)]
    procs += [spawn("body", f"W{i}") for i in range(num_body)]

    deadline = time.monotonic() + run_timeout
    rcs = []
    try:
        for p in procs:
            try:
                rcs.append(
                    p.wait(timeout=max(deadline - time.monotonic(), 1.0))
                )
            except subprocess.TimeoutExpired:
                rcs.append(None)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
    rcs = [p.poll() if rc is None else rc for rc, p in zip(rcs, procs)]
    losses = {}
    wire = {}
    overheads = {}
    for i in range(num_body):
        path = os.path.join(outdir, f"W{i}.json")
        if os.path.exists(path):
            with open(path) as f:
                rec = json.load(f)
            losses[i] = rec["losses"]
            wire[i] = {
                "sent": rec["wire_sent"], "recv": rec["wire_recv"],
            }
            overheads[i] = rec.get("filter_overhead")
    shutil.rmtree(outdir, ignore_errors=True)
    return {
        "returncodes": rcs,
        "losses": losses,
        "wire": wire,
        "filter_overhead": overheads,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--role", required=True,
                   choices=["scheduler", "server", "body"])
    p.add_argument("--node-id", required=True)
    p.add_argument("--scheduler-port", type=int, required=True)
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num-body", type=int, default=2)
    p.add_argument("--cpu-devices", type=int, default=4)
    p.add_argument("--num-servers", type=int, default=2)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=2)
    p.add_argument("--d-model", type=int, default=32)
    p.add_argument("--d-ff", type=int, default=64)
    p.add_argument("--seq", type=int, default=16)
    p.add_argument("--global-batch", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--emb-lr", type=float, default=0.05)
    p.add_argument("--emb-optimizer", default="adagrad")
    p.add_argument("--bsp", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--max-delay", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--filters", default=DEFAULT_SPEC)
    p.add_argument("--outdir", default=None)
    p.add_argument("--heartbeat-timeout", type=float, default=30.0)
    p.add_argument("--run-timeout", type=float, default=300.0)
    args = p.parse_args(argv)
    # Manager/launch code sizes barriers by num_workers: the bodies ARE the
    # workers of this topology
    args.num_workers = args.num_body
    if args.role != "body":
        # host-side roles must never touch the chip (or jax.distributed)
        from parameter_server_tpu.utils.platform import force_cpu

        force_cpu()
    return {
        "scheduler": run_scheduler,
        "server": run_server,
        "body": run_body,
    }[args.role](args)


if __name__ == "__main__":
    sys.exit(main())
