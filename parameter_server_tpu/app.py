"""App factory: registry + config-file driven app construction.

Reference analogue: ``src/system/app.h/.cc`` — ``App::Create(conf)`` reads the
text-proto config, looks up the app class by its config type, and the
scheduler calls ``app->Run()`` (SURVEY.md §2 #7 [U — reference mount empty,
public layout]).  Here the registry is keyed by a string ``app:`` field in a
yaml/json config file, apps are callables returning a result dict, and the
same config vocabulary (data / optimizer / penalty / consistency) carries
over via the dataclasses in ``config.py``.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, Optional

from parameter_server_tpu.config import (
    ConsistencyConfig,
    ConsistencyMode,
    OptimizerConfig,
    TableConfig,
    TopologyConfig,
)


@dataclasses.dataclass
class DataConfig:
    """Input source: synthetic CTR stream or an on-disk text dataset."""

    kind: str = "synthetic"  # synthetic | libsvm | criteo
    path: Optional[str] = None
    batch_size: int = 1024
    #: synthetic stream parameters (ignored for file inputs)
    key_space: int = 1 << 22
    nnz: int = 39
    seed: int = 0
    #: > 0 enables count-min tail filtering on the key stream: keys whose
    #: estimated frequency is below the threshold mask to the trash row
    #: (the reference's DARLIN preprocessing countmin filter, on the
    #: production input path — VERDICT r3 #4).
    tail_threshold: int = 0


@dataclasses.dataclass
class AppConfig:
    """One training/eval job — the reference's app-level text proto."""

    app: str
    table: TableConfig
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    consistency: ConsistencyConfig = dataclasses.field(
        default_factory=ConsistencyConfig
    )
    topology: TopologyConfig = dataclasses.field(default_factory=TopologyConfig)
    steps: int = 100
    eval_batches: int = 0
    ckpt_root: Optional[str] = None
    ckpt_every: int = 0


_REGISTRY: Dict[str, Callable[[AppConfig], Callable[[], dict]]] = {}


def register_app(name: str):
    """Decorator: register an app builder under ``name``.

    A builder takes the :class:`AppConfig` and returns a zero-arg ``run``
    callable producing a result dict (losses, metrics, ...).
    """

    def deco(builder):
        if name in _REGISTRY:
            raise ValueError(f"app {name!r} already registered")
        _REGISTRY[name] = builder
        return builder

    return deco


def registered_apps() -> list[str]:
    return sorted(_REGISTRY)


def create(cfg: AppConfig) -> Callable[[], dict]:
    """The ``App::Create`` seam: config -> runnable app."""
    try:
        builder = _REGISTRY[cfg.app]
    except KeyError:
        raise ValueError(
            f"unknown app {cfg.app!r}; registered: {registered_apps()}"
        ) from None
    return builder(cfg)


# --------------------------------------------------------------- config IO --


def _hydrate(cls, obj: Any):
    """Recursively build a dataclass from a plain dict (yaml/json)."""
    if obj is None or not dataclasses.is_dataclass(cls):
        return obj
    if not isinstance(obj, dict):
        raise TypeError(f"expected mapping for {cls.__name__}, got {type(obj)}")
    kwargs = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for k, v in obj.items():
        if k not in fields:
            raise ValueError(f"unknown field {k!r} for {cls.__name__}")
        ftype = fields[k].type
        target = _FIELD_TYPES.get((cls.__name__, k))
        if target is not None:
            v = _hydrate(target, v) if isinstance(v, dict) else target(v)
        kwargs[k] = v
        del ftype
    return cls(**kwargs)


#: nested dataclass/enum fields (dataclass field types are strings under
#: ``from __future__ import annotations``, so map them explicitly)
_FIELD_TYPES = {
    ("AppConfig", "table"): TableConfig,
    ("AppConfig", "data"): DataConfig,
    ("AppConfig", "consistency"): ConsistencyConfig,
    ("AppConfig", "topology"): TopologyConfig,
    ("TableConfig", "optimizer"): OptimizerConfig,
    ("ConsistencyConfig", "mode"): ConsistencyMode,
}


def load_config(path: str) -> AppConfig:
    """Read a yaml/json app config file into an :class:`AppConfig`."""
    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        raw = json.loads(text)
    else:
        import yaml

        raw = yaml.safe_load(text)
    if not isinstance(raw, dict) or "app" not in raw:
        raise ValueError(f"{path}: config must be a mapping with an 'app' key")
    return _hydrate(AppConfig, raw)


# ------------------------------------------------------------ built-in apps --


def _tail_wrap(batch_fn, data: DataConfig):
    """Apply the count-min tail filter when configured (else pass through)."""
    if data.tail_threshold <= 0:
        return batch_fn
    from parameter_server_tpu.data.tailfilter import TailFilteredStream

    return TailFilteredStream(batch_fn, data.tail_threshold)


def _tail_stats(batch_fn) -> dict:
    """Result-dict stats for a tail-filtered batch source (empty if none)."""
    frac = getattr(batch_fn, "masked_fraction", None)
    if frac is None:
        return {}
    return {
        "tail_masked_fraction": round(float(frac), 6),
        "tail_seen_positions": int(batch_fn.seen),
    }


def _make_batch_fn(data: DataConfig):
    if data.kind == "synthetic":
        from parameter_server_tpu.data.synthetic import SyntheticCTR

        stream = SyntheticCTR(
            key_space=data.key_space,
            nnz=data.nnz,
            batch_size=data.batch_size,
            seed=data.seed,
        )
        return _tail_wrap(stream.next_batch, data)
    if data.kind in ("libsvm", "criteo"):
        from parameter_server_tpu.data import fs
        from parameter_server_tpu.data.reader import StreamReader

        if not data.path:
            raise ValueError(f"data.kind={data.kind!r} requires data.path")
        # the path may be a glob and/or a psfs:// url — shard expansion and
        # remote streaming both go through the fs layer (file.h/HDFS role).
        # An empty expansion is a config error NOW, not a FileNotFoundError
        # three layers deep at the first batch — unless the "glob" is really
        # a literal filename containing metacharacters (day[1].csv) that
        # exists on disk, which must keep working.
        import os as os_lib

        files = fs.list_files(data.path)
        if not files:
            literal = (
                data.path[len("file://") :]
                if data.path.startswith("file://")
                else data.path
            )
            if not data.path.startswith("psfs://") and os_lib.path.exists(literal):
                files = [data.path]
            else:
                raise FileNotFoundError(
                    f"data.path {data.path!r} matched no files"
                )
        reader = StreamReader(
            files, data.batch_size, format=data.kind, epochs=None
        )
        it = iter(reader)

        def next_batch():
            keys, _vals, labels = next(it)
            return keys, labels

        return _tail_wrap(next_batch, data)
    raise ValueError(f"unknown data kind {data.kind!r}")


@register_app("sparse_lr")
def _build_sparse_lr(cfg: AppConfig) -> Callable[[], dict]:
    """Single-device fused sparse LR (BASELINE config #1 shape)."""
    from parameter_server_tpu.learner.sgd import LocalLRTrainer

    def run() -> dict:
        trainer = LocalLRTrainer(cfg.table)
        batch_fn = _make_batch_fn(cfg.data)
        losses = [trainer.step(*batch_fn()) for _ in range(cfg.steps)]
        out = {"losses": losses, "steps": cfg.steps, **_tail_stats(batch_fn)}
        if cfg.eval_batches:
            out["auc"] = trainer.eval_auc(batch_fn, cfg.eval_batches)
        return out

    return run


@register_app("fm")
def _build_fm(cfg: AppConfig) -> Callable[[], dict]:
    """Single-device fused factorization machine (table dim = 1 + k)."""
    from parameter_server_tpu.learner.fm import LocalFMTrainer

    def run() -> dict:
        trainer = LocalFMTrainer(cfg.table)
        batch_fn = _make_batch_fn(cfg.data)
        losses = [trainer.step(*batch_fn()) for _ in range(cfg.steps)]
        out = {"losses": losses, "steps": cfg.steps, **_tail_stats(batch_fn)}
        if cfg.eval_batches:
            out["auc"] = trainer.eval_auc(batch_fn, cfg.eval_batches)
        return out

    return run


@register_app("llama_hybrid")
def _build_llama_hybrid(cfg: AppConfig) -> Callable[[], dict]:
    """BASELINE config #5: PS-served embedding table over the Van + sync
    GSPMD transformer body (``learner/hybrid.py``).  ``cfg.table.optimizer``
    is the embedding optimizer; the vocab is ``data.key_space`` (kept tiny
    by default so the app runs anywhere); ``consistency.max_delay`` bounds
    in-flight embedding pushes (SSP)."""

    def run() -> dict:
        import numpy as np

        from parameter_server_tpu.core.postoffice import Postoffice
        from parameter_server_tpu.core.van import LoopbackVan
        from parameter_server_tpu.kv.server import KVServer
        from parameter_server_tpu.kv.worker import KVWorker
        from parameter_server_tpu.learner import hybrid
        from parameter_server_tpu.models import transformer as tfm
        from parameter_server_tpu.parallel import mesh as mesh_lib

        ns = cfg.topology.num_servers
        model_cfg = tfm.tiny_config(
            causal=True, tie_embeddings=False,
            vocab_size=min(cfg.data.key_space, 1 << 16),
        )
        van = LoopbackVan()
        try:
            table = dataclasses.replace(
                hybrid.embedding_table_cfg(model_cfg),
                optimizer=cfg.table.optimizer,
            )
            tables = {"emb": table}
            _servers = [
                KVServer(Postoffice(f"S{i}", van), tables, i, ns)
                for i in range(ns)
            ]
            worker = KVWorker(
                Postoffice("W0", van), tables, ns,
                localizers=hybrid.embedding_localizers(model_cfg),
            )
            import jax

            n_dev = len(jax.devices())
            trainer = hybrid.HybridLMTrainer(
                model_cfg,
                mesh_lib.make_mesh((n_dev, 1)),
                worker,
                max_delay=cfg.consistency.max_delay,
            )
            rng = np.random.default_rng(cfg.data.seed)
            B, S = 2 * n_dev, 32  # batch divisible by the data axis
            losses = []
            for _ in range(cfg.steps):
                base = rng.integers(0, model_cfg.vocab_size, size=(B, 1))
                tokens = (base + np.arange(S)[None]) % model_cfg.vocab_size
                losses.append(trainer.step(tokens.astype(np.int32)))
            trainer.drain()
            return {"losses": losses, "steps": cfg.steps}
        finally:
            van.close()

    return run


def _sp_app_knobs(cfg: AppConfig, round_to: int):
    """Shared knobs of the long-context apps (sp_lm / sptp_lm).

    One source for the model config, sequence length (``data.nnz * 64``
    rounded up to ``round_to`` — nnz reused as a length knob so the app
    config stays one schema), batch rows, and the synthetic token stream.
    """
    import numpy as np

    from parameter_server_tpu.models import transformer as tfm

    model_cfg = tfm.tiny_config(
        causal=True, tie_embeddings=False,
        vocab_size=min(cfg.data.key_space, 1 << 16),
        max_seq=1 << 16,
    )
    seq = max(cfg.data.nnz, 1) * 64
    seq = ((seq + round_to - 1) // round_to) * round_to
    B = max(cfg.data.batch_size // 256, 1)
    rng = np.random.default_rng(cfg.data.seed)

    def next_tokens() -> np.ndarray:
        base = rng.integers(0, model_cfg.vocab_size, size=(B, 1))
        return (
            (base + np.arange(seq)[None]) % model_cfg.vocab_size
        ).astype(np.int32)

    return model_cfg, seq, next_tokens


@register_app("sp_lm")
def _build_sp_lm(cfg: AppConfig) -> Callable[[], dict]:
    """Long-context causal LM: the sequence axis sharded over EVERY device
    (``parallel/sp_lm.py``), ring attention inside the transformer.  The
    vocab is ``data.key_space`` (kept small by default); ``data.batch_size``
    is the batch; seq-length knob per ``_sp_app_knobs``."""

    def run() -> dict:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from parameter_server_tpu.parallel.sp_lm import SpLMTrainer

        devices = jax.devices()
        model_cfg, seq, next_tokens = _sp_app_knobs(cfg, len(devices))
        mesh = Mesh(np.asarray(devices), ("sp",))
        trainer = SpLMTrainer(model_cfg, mesh, learning_rate=3e-3)
        losses = [trainer.step(next_tokens()) for _ in range(cfg.steps)]
        return {"losses": losses, "steps": cfg.steps, "seq": seq}

    return run


@register_app("sptp_lm")
def _build_sptp_lm(cfg: AppConfig) -> Callable[[], dict]:
    """The COMPOSED long-context causal LM (``parallel/sp_fsdp.py``): ring
    attention over an ``sp`` axis x tensor parallelism over ``model`` x
    adamw moments FSDP over ``sp``, one GSPMD program.  Mesh shape comes
    from ``topology.mesh_shape`` (data, model) reinterpreted as
    (sp, model) — ``None`` (the schema default, "unset") falls back to
    all-devices-on-sp x model 1, while an EXPLICIT shape — (1, 1)
    included — is validated against the available devices (ADVICE r5 #4).
    Sequence length knob as in the ``sp_lm`` app (``data.nnz * 64``,
    rounded to a multiple of sp)."""

    def run() -> dict:
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from parameter_server_tpu.parallel.sp_fsdp import SpTpLMTrainer

        devices = jax.devices()
        n_dev = len(devices)
        mesh_cfg = (
            None
            if cfg.topology.mesh_shape is None
            else tuple(cfg.topology.mesh_shape)
        )
        if mesh_cfg is None:  # unset: all devices on sp, no TP
            sp_n, tp_n = n_dev, 1
        elif len(mesh_cfg) == 2 and mesh_cfg[0] * mesh_cfg[1] == n_dev:
            sp_n, tp_n = mesh_cfg
        else:
            # a silently-substituted mesh would run the "composed SP x TP"
            # app with no TP at all; fail the misconfiguration loudly
            raise ValueError(
                f"topology.mesh_shape {mesh_cfg} does not factor the "
                f"{n_dev} available devices into (sp, model)"
            )
        model_cfg, seq, next_tokens = _sp_app_knobs(cfg, sp_n)
        mesh = Mesh(
            np.asarray(devices).reshape(sp_n, tp_n), ("sp", "model")
        )
        trainer = SpTpLMTrainer(
            model_cfg, mesh, learning_rate=3e-3, fsdp="state",
            loss_chunk=max(seq // (4 * sp_n), 8),
        )
        losses = [trainer.step(next_tokens()) for _ in range(cfg.steps)]
        return {
            "losses": losses, "steps": cfg.steps, "seq": seq,
            "mesh": {"sp": sp_n, "model": tp_n},
        }

    return run


@register_app("async_lr")
def _build_async_lr(cfg: AppConfig) -> Callable[[], dict]:
    """Classic PS topology on one host: scheduler + servers + worker threads
    over the LoopbackVan with BSP/SSP/ASP gating and elastic workloads."""

    def run() -> dict:
        import numpy as np

        from parameter_server_tpu.core.fleet import FleetMonitor
        from parameter_server_tpu.core.manager import launch_local_cluster
        from parameter_server_tpu.core.messages import server_id, worker_id
        from parameter_server_tpu.core.netmon import MeteredVan
        from parameter_server_tpu.core.van import LoopbackVan
        from parameter_server_tpu.kv.server import KVServer
        from parameter_server_tpu.kv.worker import KVWorker
        from parameter_server_tpu.learner.elastic import ElasticTrainer
        from parameter_server_tpu.utils.keys import HashLocalizer
        from parameter_server_tpu.utils.metrics import transport_counters

        nw, ns = cfg.topology.num_workers, cfg.topology.num_servers
        # metered outermost: per-link wire accounting on every logical
        # message; heartbeats carry the digests to the scheduler's fleet
        # monitor (SURVEY §5 observability plane)
        van = MeteredVan(LoopbackVan())
        try:
            sched, managers, posts = launch_local_cluster(
                van, num_workers=nw, num_servers=ns
            )
            sched.fleet = FleetMonitor()
            tables = {cfg.table.name: cfg.table}
            loc = {cfg.table.name: HashLocalizer(cfg.table.rows)}
            _servers = {
                server_id(i): KVServer(posts[server_id(i)], tables, i, ns)
                for i in range(ns)
            }
            workers = {
                worker_id(i): KVWorker(
                    posts[worker_id(i)], tables, ns, localizers=loc
                )
                for i in range(nw)
            }
            batch_fn = _make_batch_fn(cfg.data)
            batches_per_shard = 4
            n_shards = max(1, cfg.steps // batches_per_shard)
            shards = [
                [batch_fn() for _ in range(batches_per_shard)]
                for _ in range(n_shards)
            ]
            trainer = ElasticTrainer(
                workers,
                sched,
                shards,
                cfg.consistency,
                managers=managers,
                table=cfg.table.name,
                ckpt_root=cfg.ckpt_root,
                ckpt_every=cfg.ckpt_every,
            )
            losses = trainer.run()
            return {
                "losses": losses,
                "steps": len(losses),
                "mean_loss_tail": float(np.mean(losses[-10:])),
                "last_ckpt_step": trainer.last_ckpt_step,
                "net": transport_counters(van),
                "fleet": sched.fleet.snapshot(),
                "stragglers": sched.fleet.stragglers(),
            }
        finally:
            van.close()

    return run
