"""Multi-process cluster launch over the native TCP Van.

Reference analogue: ``script/local.sh`` — spawn scheduler + N servers + M
workers as separate OS processes with role/topology from the environment
(SURVEY.md §2 #23, §4 [U]).  The transport is the real DCN-plane
``TcpVan`` on loopback, so this is also the multi-process integration test
of the whole stack (the role loopback-ZMQ played for the reference): same
code runs unmodified with remote addresses across hosts.

Flow: the launcher picks a free port, spawns every role via
``python -m parameter_server_tpu.launch --role ...``; nodes register with
the scheduler carrying their Van address; the node-table broadcast gives
every process routes to every other; workers train async-SGD sparse LR
against the servers, synchronize on a Manager barrier, worker 0 saves the
model, and each worker writes its losses to ``--outdir`` for the launcher
to aggregate.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from typing import Optional

import numpy as np

from parameter_server_tpu.core.filters import DEFAULT_SPEC


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _build_cluster(args, role_port: int, setup=None):
    """Common per-process setup: Van, Postoffice, Manager, registration.

    ``setup(post)`` runs BEFORE registration — servers must bind their
    KVServer customer first, because the moment the table broadcast lands,
    workers may start sending Push/Pull at them.
    """
    from parameter_server_tpu.core.filters import make_chain
    from parameter_server_tpu.core.manager import Manager
    from parameter_server_tpu.core.postoffice import Postoffice
    from parameter_server_tpu.core.tcp_van import TcpVan

    van = TcpVan(
        port=role_port,
        filter_chain=make_chain(getattr(args, "filters", "none")),
    )
    if args.node_id != "H":
        van.add_route("H", ("127.0.0.1", args.scheduler_port))
    post = Postoffice(args.node_id, van)
    mgr = Manager(
        post,
        num_workers=args.num_workers,
        num_servers=args.num_servers,
        advertise=van.address,
        heartbeat_timeout=args.heartbeat_timeout,
    )
    result = setup(post) if setup is not None else None
    if args.node_id != "H":
        if not mgr.register_with_scheduler(timeout=60):
            raise TimeoutError(f"{args.node_id}: node table never arrived")
    else:
        if not mgr.wait_ready(timeout=60):
            raise TimeoutError("scheduler: not all nodes registered")
    return van, post, mgr, result


def _table_cfgs(args):
    from parameter_server_tpu.config import OptimizerConfig, TableConfig

    return {
        "w": TableConfig(
            name="w",
            rows=args.rows,
            dim=1,
            optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.1),
        )
    }


def run_scheduler(args) -> int:
    van, post, mgr, _ = _build_cluster(args, args.scheduler_port)
    try:
        _log(args, "ready; waiting on shutdown barrier")
        # stay up until every node passed the final barrier
        n_nodes = args.num_workers + args.num_servers
        ok = mgr.barrier("shutdown", n_nodes + 1, timeout=args.run_timeout)
        _log(args, f"shutdown barrier -> {ok}")
        # Last-observer protocol: the scheduler must outlive every participant
        # still polling the barrier, or their next poll hits a closed van and
        # spuriously returns False.  barrier() acks on success; drain all
        # n_nodes + 1 acks (incl. our own) before tearing the van down.
        if ok:
            drained = mgr.barrier_drain(
                "shutdown", n_nodes + 1, timeout=min(args.run_timeout, 60.0)
            )
            _log(args, f"shutdown barrier drained -> {drained}")
        return 0
    finally:
        van.close()


def run_server(args) -> int:
    from parameter_server_tpu.kv.server import KVServer

    index = int(args.node_id[1:])
    van, post, mgr, _server = _build_cluster(
        args,
        0,
        setup=lambda post: KVServer(
            post, _table_cfgs(args), index, args.num_servers
        ),
    )
    try:
        _log(args, "serving; waiting on shutdown barrier")
        n_nodes = args.num_workers + args.num_servers
        ok = mgr.barrier("shutdown", n_nodes + 1, timeout=args.run_timeout)
        _log(args, f"shutdown barrier -> {ok}")
        return 0
    finally:
        van.close()
        _log(args, "van closed")


def _log(args, msg: str) -> None:
    print(
        f"[launch {args.node_id} {time.strftime('%H:%M:%S')}] {msg}",
        file=sys.stderr,
        flush=True,
    )


def run_worker(args) -> int:
    import jax.numpy as jnp

    from parameter_server_tpu.data.synthetic import SyntheticCTR
    from parameter_server_tpu.kv.worker import KVWorker
    from parameter_server_tpu.models import linear

    van, post, mgr, _ = _build_cluster(args, 0)
    try:
        index = int(args.node_id[1:])
        worker = KVWorker(post, _table_cfgs(args), args.num_servers)
        data = SyntheticCTR(
            key_space=4 * args.rows,
            nnz=args.nnz,
            batch_size=args.batch_size,
            seed=100 + index,
        )
        _log(args, "training")
        losses = []
        for _ in range(args.steps):
            keys, labels = data.next_batch()
            w_pos = worker.pull_sync("w", keys, timeout=60)
            g, _gb, loss = linear.grad_rows(jnp.asarray(w_pos), jnp.asarray(labels))
            ts = worker.push("w", keys, np.asarray(g) / labels.shape[0])
            if not worker.wait(ts, timeout=60):
                raise TimeoutError("push not acked")
            losses.append(float(loss))
        _log(args, "trained; entering trained barrier")
        # all workers done training before anyone saves (BSP-style epoch end)
        if not mgr.barrier("trained", args.num_workers, timeout=args.run_timeout):
            raise TimeoutError("trained barrier timed out")
        _log(args, "trained barrier passed")
        if index == 0 and args.ckpt_root:
            worker.save_model(args.ckpt_root, step=args.steps)
        if args.outdir:
            # wire byte accounting (reference network_usage.h role; VERDICT
            # r2 weak #4): the van counts ACTUAL frame bytes handed to the
            # transport — headers, pickled scales and all, whether they hit
            # the socket or a colocated shm ring — so comparing runs with
            # and without --filters measures the true reduction, not a
            # codec's self-reported ratio.
            out = os.path.join(args.outdir, f"{args.node_id}.json")
            chain = getattr(van, "filter_chain", None)
            with open(out, "w") as f:
                json.dump(
                    {
                        "node": args.node_id,
                        "losses": losses,
                        "wire_sent": van.payload_bytes_sent(),
                        "wire_recv": van.payload_bytes_recv(),
                        # per-message codec cost, so the default-on filter
                        # stack is justified by measurement (VERDICT r3 #7)
                        "filter_overhead": (
                            chain.overhead() if chain is not None else None
                        ),
                    },
                    f,
                )
        n_nodes = args.num_workers + args.num_servers
        ok = mgr.barrier("shutdown", n_nodes + 1, timeout=args.run_timeout)
        _log(args, f"shutdown barrier -> {ok}")
        return 0
    finally:
        van.close()


def launch(
    *,
    num_workers: int = 2,
    num_servers: int = 2,
    steps: int = 20,
    rows: int = 1 << 14,
    batch_size: int = 256,
    nnz: int = 8,
    ckpt_root: Optional[str] = None,
    filters: str = DEFAULT_SPEC,
    run_timeout: float = 300.0,
    python: str = sys.executable,
) -> dict:
    """Spawn the full cluster as OS processes; returns aggregated results."""
    from parameter_server_tpu.core.filters import make_chain

    make_chain(filters)  # validate the spec HERE, not in five children
    port = _free_port()
    outdir = tempfile.mkdtemp(prefix="psx_launch_")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pypath = os.environ.get("PYTHONPATH", "")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=f"{repo_root}:{pypath}" if pypath else repo_root,
    )

    def spawn(role: str, node_id: str) -> subprocess.Popen:
        cmd = [
            python, "-m", "parameter_server_tpu.launch",
            "--role", role, "--node-id", node_id,
            "--scheduler-port", str(port),
            "--num-workers", str(num_workers),
            "--num-servers", str(num_servers),
            "--steps", str(steps), "--rows", str(rows),
            "--batch-size", str(batch_size), "--nnz", str(nnz),
            "--outdir", outdir,
            "--run-timeout", str(run_timeout),
            "--filters", filters,
        ]
        if ckpt_root:
            cmd += ["--ckpt-root", ckpt_root]
        return subprocess.Popen(cmd, env=env)

    procs = [spawn("scheduler", "H")]
    time.sleep(0.3)  # let the scheduler bind its fixed port first
    procs += [spawn("server", f"S{i}") for i in range(num_servers)]
    procs += [spawn("worker", f"W{i}") for i in range(num_workers)]

    deadline = time.monotonic() + run_timeout
    rcs = []
    try:
        for p in procs:
            left = max(deadline - time.monotonic(), 1.0)
            rcs.append(p.wait(timeout=left))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    losses = []
    per_worker = {}
    wire_sent = wire_recv = 0
    overheads = []
    for i in range(num_workers):
        path = os.path.join(outdir, f"W{i}.json")
        if os.path.exists(path):
            with open(path) as f:
                row = json.load(f)
            per_worker[row["node"]] = row["losses"]
            losses.extend(row["losses"])
            wire_sent += row.get("wire_sent", 0)
            wire_recv += row.get("wire_recv", 0)
            if row.get("filter_overhead"):
                overheads.append(row["filter_overhead"])
    overhead = None
    if overheads:
        overhead = {
            "encode_us_per_msg": round(
                float(np.mean([o["encode_us_per_msg"] for o in overheads])), 2
            ),
            "decode_us_per_msg": round(
                float(np.mean([o["decode_us_per_msg"] for o in overheads])), 2
            ),
            "messages": int(sum(o["encode_calls"] for o in overheads)),
        }
    return {
        "returncodes": rcs,
        "workers_reported": sorted(per_worker),
        "steps_total": len(losses),
        "first_loss": float(np.mean(losses[:5])) if losses else None,
        "final_loss": float(np.mean(losses[-5:])) if losses else None,
        "wire_sent": wire_sent,
        "wire_recv": wire_recv,
        "filter_overhead": overhead,
    }


def main(argv=None) -> int:
    # cluster roles are host-side: never let the axon plugin grab the chip
    # (its init can also block when the device relay is busy)
    from parameter_server_tpu.utils.platform import force_cpu

    force_cpu()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--role", required=True,
                   choices=["scheduler", "server", "worker"])
    p.add_argument("--node-id", required=True)
    p.add_argument("--scheduler-port", type=int, required=True)
    p.add_argument("--num-workers", type=int, required=True)
    p.add_argument("--num-servers", type=int, required=True)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--rows", type=int, default=1 << 14)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--nnz", type=int, default=8)
    p.add_argument("--outdir", default=None)
    p.add_argument("--ckpt-root", default=None)
    p.add_argument(
        "--filters", default=DEFAULT_SPEC,
        help="wire filter stack on the TcpVan: 'none', 'lossless' "
        "(=key_caching+zlib, the default — bit-exact wire), 'full' "
        "(adds the LOSSY int8 quantizer; explicit opt-in), or a "
        "'+'-separated pipeline over {key_caching, int8, zlib, noise}",
    )
    p.add_argument("--heartbeat-timeout", type=float, default=30.0)
    p.add_argument("--run-timeout", type=float, default=300.0)
    args = p.parse_args(argv)
    return {"scheduler": run_scheduler, "server": run_server,
            "worker": run_worker}[args.role](args)


if __name__ == "__main__":
    sys.exit(main())
