"""parameter_server_tpu — a TPU-native parameter-server training framework.

A from-scratch rebuild of the capabilities of the classic parameter server
(reference: ``pserver/parameter_server``, the Li et al. OSDI'14 system) designed
idiomatically for TPU:

- **KV layer** (``kv/``): range-partitioned ``KVServer`` tables living in TPU
  HBM as (optionally mesh-sharded) ``jax.Array``s, updated by jit-compiled
  optimizer steps; ``KVWorker`` keeps the classic ``push/pull -> timestamp`` /
  ``wait(ts)`` API.  (Reference: ``src/parameter/parameter.h``,
  ``kv_vector.h``, ``kv_map.h`` [U — reference mount empty, public layout].)
- **Core** (``core/``): Message/Task model with integer timestamps, a
  BSP/SSP/ASP consistency controller (vector clocks replacing the reference's
  ``Task.time``/``wait_time`` DAG in ``src/system/executor.h`` [U]), and a
  Van/Postoffice transport layer whose in-process ``LoopbackVan`` doubles as
  the deterministic test seam.
- **Ops** (``ops/``): device-side sparse gather / scatter-add (XLA and Pallas
  paths), segment pre-combine for duplicate keys, ring attention and Ulysses
  sequence parallelism, quantization codecs for the DCN plane.
- **Parallel** (``parallel/``): mesh construction, GSPMD sharding rules,
  psum-over-ICI gradient pre-reduction (replacing NCCL intra-node
  pre-reduction per the north star).
- **Models / learner / data**: Criteo sparse LR, ResNet-50, DLRM, BERT, Llama
  hybrid; SGD + BCD/DARLIN scaffolds; Criteo/libsvm data pipeline.

See ``SURVEY.md`` at the repo root for the full blueprint and the provenance
caveat on reference citations ([U] = unverified public-repo layout).  The
package is built up milestone by milestone — consult the module list (or
``git log``) rather than this overview for what exists at any given commit.
"""

__version__ = "0.1.0"

from parameter_server_tpu.config import (  # noqa: F401
    ConsistencyConfig,
    ConsistencyMode,
    OptimizerConfig,
    TableConfig,
    TopologyConfig,
    TraceConfig,
)
