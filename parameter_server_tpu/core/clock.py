"""Consistency controller: the BSP/SSP/ASP spectrum as vector clocks.

The reference encodes consistency as dependency edges in the Executor's task
DAG (``Task.time``/``wait_time``; ``src/system/executor.h`` [U]): BSP depends
on all prior iterations, SSP on iteration ``t - max_delay``, ASP on nothing.
XLA execution is synchronous SPMD, so asynchrony lives on the host: this
controller holds the vector of per-worker clocks and gates *dispatch* of
already-compiled device steps (SURVEY.md §7 design stance).

Semantics (matching SSP literature and the reference's bounded delay):
a worker may *start* iteration ``t`` only when every worker has *completed*
iteration ``t - 1 - bound`` — i.e. the fastest worker leads the slowest by at
most ``bound`` iterations.  ``bound=0`` is BSP lockstep; ``bound=None`` is ASP.
"""

from __future__ import annotations

import threading
from typing import Optional

from parameter_server_tpu.config import ConsistencyConfig


class VectorClock:
    """Thread-safe per-worker completed-iteration counters."""

    def __init__(self, num_workers: int) -> None:
        self._clocks = [0] * num_workers
        self._cond = threading.Condition()

    def __getitem__(self, w: int) -> int:
        with self._cond:
            return self._clocks[w]

    def min(self) -> int:
        with self._cond:
            return min(self._clocks)

    def snapshot(self) -> list[int]:
        with self._cond:
            return list(self._clocks)

    def advance(self, w: int) -> int:
        """Mark one more completed iteration for worker ``w``."""
        with self._cond:
            self._clocks[w] += 1
            self._cond.notify_all()
            return self._clocks[w]

    def wait_until_min(self, t: int, timeout: Optional[float] = None) -> bool:
        """Block until ``min(clocks) >= t``.  Returns False on timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: min(self._clocks) >= t, timeout)


class ConsistencyController:
    """Gate worker iteration dispatch per the configured consistency mode.

    Replaces the reference Executor's dependency check loop: instead of
    parking messages, the host thread parks *before dispatching* the next
    jit-compiled step, which keeps the device queue free of stale work.
    """

    def __init__(self, cfg: ConsistencyConfig, num_workers: int) -> None:
        self.cfg = cfg
        self.clock = VectorClock(num_workers)
        self._dead: set[int] = set()
        self._dead_lock = threading.Lock()

    def wait_turn(self, worker: int, t: int, timeout: Optional[float] = None) -> bool:
        """Block until worker ``worker`` may start iteration ``t``.

        Returns False if the bound could not be satisfied within ``timeout``
        (callers treat that as a straggler signal, not an error).
        """
        bound = self.cfg.bound
        if bound is None:  # ASP
            return True
        need = t - bound  # all workers must have completed >= t - bound
        if need <= 0:
            return True
        return self._wait_min_alive(need, timeout)

    def _wait_min_alive(self, t: int, timeout: Optional[float]) -> bool:
        # Dead workers are excluded from the bound (elasticity: a lost worker
        # must not stall SSP forever; its shard is reassigned by the
        # WorkloadPool — reference Executor::ReplaceNode behavior [U]).
        cond = self.clock._cond
        with cond:
            return cond.wait_for(
                lambda: min(self._alive_clocks()) >= t, timeout
            )

    def _alive_clocks(self) -> list[int]:
        clocks = self.clock._clocks
        with self._dead_lock:
            alive = [c for w, c in enumerate(clocks) if w not in self._dead]
        return alive or [2**62]  # all workers dead: nothing to wait for

    def finish_iteration(self, worker: int) -> int:
        return self.clock.advance(worker)

    def mark_dead(self, worker: int) -> None:
        with self._dead_lock:
            self._dead.add(worker)
        with self.clock._cond:
            self.clock._cond.notify_all()

    def mark_alive(self, worker: int) -> None:
        with self._dead_lock:
            self._dead.discard(worker)

    # -- reference API parity: Task.wait_time computation ------------------
    def wait_time_for(self, t: int) -> int:
        """The ``Task.wait_time`` dependency the reference would emit."""
        bound = self.cfg.bound
        if bound is None:
            return -1
        return t - 1 - bound
