"""Node management: registration, membership, heartbeats, elasticity.

Reference analogue (``src/system/manager.h/.cc`` + ``assigner.h`` +
``heartbeat_info.h`` [U — reference mount empty, public layout]): the
scheduler node collects REGISTER messages from launching workers/servers,
assigns node ids and server key ranges (NodeAssigner), and broadcasts
ADD_NODE with the full node table; afterwards it watches heartbeats and
broadcasts REMOVE_NODE when a node misses its window.

Here the same protocol runs over any :class:`~parameter_server_tpu.core.van.Van`
as CONTROL messages, so it works identically on the in-process LoopbackVan
(tests / single host) and a future DCN Van.  On a TPU pod the *static* mesh is
the normal case — `jax.distributed` already provides coordinated startup — so
this layer's value is (a) API parity, (b) the *elastic* paths: dead-worker
detection feeding :class:`~parameter_server_tpu.core.clock.ConsistencyController`
and the WorkloadPool, which XLA/jax.distributed does not give you.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from parameter_server_tpu.core.messages import (
    SCHEDULER,
    Message,
    NodeRole,
    Task,
    TaskKind,
    node_role,
    server_id,
    worker_id,
)
from parameter_server_tpu.core.postoffice import Customer, Postoffice

#: CONTROL payload "cmd" values — the reference's Control proto verbs.
REGISTER = "register"
ADD_NODE = "add_node"
REMOVE_NODE = "remove_node"
HEARTBEAT = "heartbeat"
BARRIER = "barrier"
PING = "ping"
#: PR-6 routing-table broadcast: the scheduler owns the authoritative
#: epoch-versioned RoutingTable and pushes new generations to the fleet.
ROUTING = "routing"
#: ISSUE-10 live telemetry: delta-encoded per-node frames riding the
#: heartbeat cadence; the scheduler folds them into its TelemetryAggregator.
TELEMETRY = "telemetry"

#: The closed CONTROL-verb registry.  MUST stay a literal frozenset of
#: plain strings — ``tools/check_wrappers.py`` parses this set out of the
#: AST (no import) and verifies every ``{"cmd": ...}`` payload literal in
#: the package names a registered verb.  Add new verbs here AND as a
#: module constant above.
CONTROL_VERBS = frozenset({
    "register",
    "add_node",
    "remove_node",
    "heartbeat",
    "barrier",
    "ping",
    "routing",
    "telemetry",
})
# import-time sync check: a verb constant that drifts from the registry
# fails the import, not just the AST pass
assert CONTROL_VERBS == frozenset({
    REGISTER, ADD_NODE, REMOVE_NODE, HEARTBEAT, BARRIER, PING, ROUTING,
    TELEMETRY,
}), "CONTROL_VERBS out of sync with the verb constants"


@dataclasses.dataclass
class NodeInfo:
    """One row of the scheduler's node table."""

    node_id: str
    role: NodeRole
    #: server key range [begin, end) over the global row space (servers only).
    range_begin: int = 0
    range_end: int = 0
    #: wall time of the last heartbeat seen by the scheduler.
    last_seen: float = 0.0
    alive: bool = True
    #: restart epoch of this node id (scheduler-assigned; bumped on every
    #: re-registration under the same id).  Broadcast with the table so
    #: every transport endpoint can fence frames from stale incarnations —
    #: see ``core/resender.py``.
    incarnation: int = 0
    #: (host, port) the node's Van listens on (multi-process TcpVan runs;
    #: None on an in-process LoopbackVan).  Broadcast with the table so
    #: every process can route to every other.
    address: Optional[list] = None


class NodeAssigner:
    """Even key-range split over servers (``src/system/assigner.h`` [U]).

    The range here is an abstract [0, key_space) row space; concrete tables
    scale it to their own row counts via
    :class:`~parameter_server_tpu.kv.partition.RangePartition`, which uses the
    same even-contiguous-split rule, so both layers agree on shard boundaries.
    """

    def __init__(self, key_space: int) -> None:
        self.key_space = key_space

    def ranges(self, num_servers: int) -> List[tuple[int, int]]:
        from parameter_server_tpu.kv.partition import RangePartition

        off = RangePartition(self.key_space, num_servers).offsets
        return [(int(off[s]), int(off[s + 1])) for s in range(num_servers)]


class Manager(Customer):
    """Membership manager; scheduler-role instances own the node table.

    Every process creates one Manager on its Postoffice.  Non-scheduler nodes
    call :meth:`register_with_scheduler` at startup and then send periodic
    heartbeats; the scheduler replies to REGISTER once all expected nodes have
    arrived, broadcasting the complete table (one-shot batch ADD_NODE, which
    is the reference's startup behavior).
    """

    CUSTOMER_NAME = "manager"

    def __init__(
        self,
        post: Postoffice,
        *,
        num_workers: int,
        num_servers: int,
        key_space: int = 1 << 20,
        heartbeat_timeout: float = 5.0,
        advertise: Optional[tuple] = None,
    ) -> None:
        """``advertise``: this node's Van (host, port) for multi-process
        clusters — carried in REGISTER and broadcast with the node table so
        peers can ``van.add_route`` to each other."""
        super().__init__(self.CUSTOMER_NAME, post)
        self.advertise = advertise
        self.role = node_role(post.node_id)
        self.num_workers = num_workers
        self.num_servers = num_servers
        self.assigner = NodeAssigner(key_space)
        self.heartbeat_timeout = heartbeat_timeout
        self._table: Dict[str, NodeInfo] = {}
        self._barriers: Dict[str, set] = {}
        self._barrier_acks: Dict[str, set] = {}
        self._table_lock = threading.Lock()
        self._ready = threading.Event()
        #: elasticity callbacks: fn(node_id) on death / (re)join.
        self.on_node_dead: List[Callable[[str], None]] = []
        self.on_node_added: List[Callable[[str], None]] = []
        #: latest RoutingTable seen (scheduler: the authoritative copy set by
        #: set_routing; others: the last ROUTING broadcast adopted).
        self.routing = None
        #: fn(RoutingTable) fired on every newly-adopted broadcast — wire a
        #: worker's ``adopt_routing`` here for eager (non-fence) convergence.
        self.on_routing: List[Callable] = []
        self._monitor_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: scheduler-side sink for heartbeat stats (attach a
        #: ``core.fleet.FleetMonitor``); None = stats dropped as before.
        self.fleet = None
        #: scheduler-side sink for TELEMETRY frames (attach a
        #: ``core.telemetry.TelemetryAggregator``); None = frames dropped.
        self.telemetry = None
        #: node-side frame builder (attach a
        #: ``core.telemetry.TelemetryPublisher``); when set,
        #: ``send_heartbeat`` auto-publishes a frame after each beat.
        self.telemetry_pub = None
        #: clock offset vs the scheduler (local minus scheduler monotonic,
        #: seconds) + the RTT of the winning sample — set by sync_clock().
        self.clock_offset: Optional[float] = None
        self.clock_rtt: Optional[float] = None
        if self.role == NodeRole.SCHEDULER:
            self._register_self()

    # -- startup -------------------------------------------------------------
    def _register_self(self) -> None:
        with self._table_lock:
            self._table[self.post.node_id] = NodeInfo(
                self.post.node_id, self.role, last_seen=time.monotonic()
            )

    def register_with_scheduler(
        self, timeout: Optional[float] = 30.0, *, wait: bool = True
    ) -> bool:
        """Send REGISTER; optionally block until the table broadcast arrives.

        ``wait=False`` returns immediately (callers that launch many nodes
        from one thread register them all first, then ``wait_ready`` each —
        otherwise node k would block on nodes k+1.. ever registering).
        """
        payload = {"cmd": REGISTER, "role": self.role.value}
        if self.advertise is not None:
            payload["address"] = list(self.advertise)
        self.submit(
            [
                Message(
                    task=Task(TaskKind.CONTROL, self.name, payload=payload),
                    recver=SCHEDULER,
                )
            ]
        )
        if not wait:
            return True
        return self._ready.wait(timeout)

    def wait_ready(self, timeout: Optional[float] = 30.0) -> bool:
        """Scheduler: block until all expected nodes have registered."""
        return self._ready.wait(timeout)

    # -- table access --------------------------------------------------------
    def nodes(self, role: Optional[NodeRole] = None, alive_only: bool = False):
        with self._table_lock:
            rows = [
                n
                for n in self._table.values()
                if (role is None or n.role == role)
                and (not alive_only or n.alive)
            ]
        return sorted(rows, key=lambda n: n.node_id)

    def server_range(self, sid: str) -> tuple[int, int]:
        with self._table_lock:
            n = self._table[sid]
            return (n.range_begin, n.range_end)

    def is_alive(self, node_id: str) -> bool:
        with self._table_lock:
            n = self._table.get(node_id)
            return bool(n and n.alive)

    # -- message handling ----------------------------------------------------
    def handle_request(self, msg: Message) -> Optional[Message]:
        cmd = msg.task.payload.get("cmd")
        if cmd == REGISTER:
            self._on_register(msg)
        elif cmd == ADD_NODE:
            self._on_add_node(msg)
        elif cmd == REMOVE_NODE:
            self._on_remove_node(msg)
        elif cmd == HEARTBEAT:
            self._on_heartbeat(msg)
        elif cmd == BARRIER:
            return self._on_barrier(msg)
        elif cmd == PING:
            return self._on_ping(msg)
        elif cmd == ROUTING:
            self._on_routing(msg)
        elif cmd == TELEMETRY:
            self._on_telemetry(msg)
        return msg.reply()

    # -- routing-table broadcast (PR 6) --------------------------------------
    def set_routing(self, routing) -> None:
        """Scheduler: adopt ``routing`` as authoritative and broadcast it.

        One CONTROL message per alive node; delivery is per-node atomic (a
        node sees the old table or the new one, never a blend) and stragglers
        self-heal off server fences, so no global barrier is needed.
        """
        assert self.role == NodeRole.SCHEDULER, "set_routing on non-scheduler"
        self.routing = routing
        with self._table_lock:
            targets = [
                n.node_id
                for n in self._table.values()
                if n.alive and n.node_id != self.post.node_id
            ]
        msgs = [
            Message(
                task=Task(
                    TaskKind.CONTROL,
                    self.name,
                    payload={"cmd": ROUTING, "routing": routing.to_payload()},
                ),
                recver=t,
            )
            for t in targets
        ]
        if msgs:
            self.submit(msgs)

    def _on_routing(self, msg: Message) -> None:
        from parameter_server_tpu.kv.routing import RoutingTable

        routing = RoutingTable.from_payload(msg.task.payload["routing"])
        # highest epoch wins — broadcasts can arrive out of order across
        # migrations, and a stale one must not roll a node's view back
        if self.routing is not None and routing.epoch <= self.routing.epoch:
            return
        self.routing = routing
        for cb in self.on_routing:
            try:
                cb(routing)
            except Exception:  # noqa: BLE001 — one bad sink must not block
                logging.getLogger(__name__).exception(
                    "on_routing callback failed on %s", self.post.node_id
                )

    # -- clock sync (heartbeat-RTT/2 offset estimation) ----------------------
    def _on_ping(self, msg: Message) -> Message:
        import numpy as np

        # reply carries the scheduler's monotonic clock reading; the pinger
        # timestamps both legs locally and estimates its offset NTP-style
        return msg.reply(
            values=[np.asarray([time.monotonic()], np.float64)]
        )

    def sync_clock(
        self, samples: int = 5, *, timeout: Optional[float] = 10.0
    ) -> Optional[float]:
        """Estimate this node's clock offset vs the scheduler (seconds).

        Sends ``samples`` PINGs, timestamps both legs locally, and keeps the
        minimum-RTT sample (least queueing noise): with the scheduler's
        reading assumed to land mid-flight, ``offset = midpoint - sched``,
        i.e. LOCAL minus SCHEDULER monotonic time.  The estimate (and the
        winning RTT) ride subsequent heartbeats under ``stats["clock"]`` so
        the fleet monitor (``core/fleet.py``) can correct cross-host
        deliver-latency attribution from ``core/netmon.py`` — node-local
        ``time.monotonic`` clocks share no epoch across processes, so raw
        one-way latencies off loopback are meaningless without this.

        Returns the offset, or None if every ping timed out (the previous
        estimate, if any, is kept).
        """
        best: Optional[tuple[float, float]] = None  # (rtt, offset)
        for _ in range(max(1, samples)):
            t0 = time.monotonic()
            ts = self.submit(
                [
                    Message(
                        task=Task(
                            TaskKind.CONTROL, self.name, payload={"cmd": PING}
                        ),
                        recver=SCHEDULER,
                    )
                ],
                keep_responses=True,
            )
            ok = self.wait(ts, timeout=timeout)
            if not ok:
                self.cancel(ts, "clock ping deadline")
            responses = self.take_responses(ts)
            if not ok or not responses or not responses[0].values:
                continue
            t1 = time.monotonic()
            sched = float(responses[0].values[0][0])
            rtt = t1 - t0
            offset = (t0 + t1) / 2.0 - sched
            if best is None or rtt < best[0]:
                best = (rtt, offset)
        if best is None:
            return None
        self.clock_rtt, self.clock_offset = best
        return self.clock_offset

    # -- barrier (poll-based; replies carry the arrival count) ---------------
    def _on_barrier(self, msg: Message) -> Message:
        import numpy as np

        name = msg.task.payload["name"]
        with self._table_lock:
            arrivals = self._barriers.setdefault(name, set())
            if msg.task.payload.get("enter"):
                arrivals.add(msg.sender)
            if msg.task.payload.get("ack"):
                self._barrier_acks.setdefault(name, set()).add(msg.sender)
            count = len(arrivals)
        return msg.reply(values=[np.asarray([count], np.int64)])

    def barrier(
        self,
        name: str,
        expected: int,
        *,
        timeout: Optional[float] = 60.0,
        poll: float = 0.05,
    ) -> bool:
        """Block until ``expected`` distinct nodes entered barrier ``name``.

        Poll-based (the scheduler cannot defer replies), so it works across
        processes over any Van.  Returns False on timeout.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        enter = True
        while deadline is None or time.monotonic() < deadline:
            ts = self.submit(
                [
                    Message(
                        task=Task(
                            TaskKind.CONTROL,
                            self.name,
                            payload={"cmd": BARRIER, "name": name, "enter": enter},
                        ),
                        recver=SCHEDULER,
                    )
                ],
                keep_responses=True,
            )
            left = None if deadline is None else max(deadline - time.monotonic(), 0.1)
            ok = self.wait(ts, timeout=left)
            if not ok:
                # deadline while the scheduler is unreachable: finalize the
                # task so _pending/_responses don't leak one entry per
                # timed-out barrier round
                self.cancel(ts, "barrier poll deadline")
            responses = self.take_responses(ts)
            if not ok or not responses:
                return False
            enter = False  # entered; subsequent rounds just poll
            if int(responses[0].values[0][0]) >= expected:
                # fire-and-forget ack so the scheduler can barrier_drain:
                # it must outlive every participant still polling
                self.submit(
                    [
                        Message(
                            task=Task(
                                TaskKind.CONTROL,
                                self.name,
                                payload={"cmd": BARRIER, "name": name, "ack": True},
                            ),
                            recver=SCHEDULER,
                        )
                    ]
                )
                return True
            time.sleep(poll)
        return False

    def barrier_drain(
        self,
        name: str,
        expected: int,
        *,
        timeout: Optional[float] = 60.0,
        poll: float = 0.05,
    ) -> bool:
        """Scheduler: block until ``expected`` nodes ACKED barrier ``name``.

        Call after :meth:`barrier` and before process exit — otherwise the
        scheduler can die while a slow participant is still polling, and
        that participant hangs until its own timeout (the classic
        last-observer race).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        while deadline is None or time.monotonic() < deadline:
            with self._table_lock:
                n = len(self._barrier_acks.get(name, ()))
            if n >= expected:
                return True
            time.sleep(poll)
        return False

    def _on_register(self, msg: Message) -> None:
        assert self.role == NodeRole.SCHEDULER, "REGISTER sent to non-scheduler"
        addr = msg.task.payload.get("address")
        if addr and hasattr(self.post.van, "add_route"):
            self.post.van.add_route(msg.sender, tuple(addr))
        rejoin_row = None
        with self._table_lock:
            existing = self._table.get(msg.sender)
            if existing is not None:
                # Same-id restart: the scheduler is the incarnation
                # authority.  Bump the epoch, keep the assigned key range
                # (a restarted server still owns its shard), mark alive.
                existing.incarnation += 1
                existing.alive = True
                existing.last_seen = time.monotonic()
                if addr:
                    existing.address = list(addr)
                rejoin_row = dataclasses.asdict(existing)
                table_rows = [
                    dataclasses.asdict(n) for n in self._table.values()
                ]
                peers = [
                    n.node_id
                    for n in self._table.values()
                    if n.alive
                    and n.node_id not in (self.post.node_id, msg.sender)
                ]
            else:
                info = NodeInfo(
                    msg.sender, NodeRole(msg.task.payload["role"]),
                    last_seen=time.monotonic(),
                    address=addr,
                )
                self._table[msg.sender] = info
                workers = sum(
                    1 for n in self._table.values() if n.role == NodeRole.WORKER
                )
                servers = sum(
                    1 for n in self._table.values() if n.role == NodeRole.SERVER
                )
                complete = (
                    workers >= self.num_workers and servers >= self.num_servers
                )
                if complete:
                    ranges = self.assigner.ranges(self.num_servers)
                    sids = sorted(
                        n.node_id
                        for n in self._table.values()
                        if n.role == NodeRole.SERVER
                    )
                    for sid, (b, e) in zip(sids, ranges):
                        self._table[sid].range_begin = b
                        self._table[sid].range_end = e
                table_rows = [
                    dataclasses.asdict(n) for n in self._table.values()
                ]
        if rejoin_row is not None:
            # Fence first (locally), so any zombie frames still in flight
            # under the old incarnation die at this endpoint too; then tell
            # the fleet: peers get the one changed row, the restarted node
            # gets the full table (it lost its copy with its memory).
            self._learn_incarnation(msg.sender, rejoin_row["incarnation"])
            self._broadcast_table(table_rows, [msg.sender])
            if peers:
                self._broadcast_table([rejoin_row], peers)
            for cb in self.on_node_added:
                cb(msg.sender)
            return
        if complete:
            self._broadcast_table(table_rows)
            self._ready.set()

    def _learn_incarnation(self, node_id: str, incarnation: int) -> None:
        """Teach the local transport stack a node's incarnation.

        Hasattr-guarded: delegates down the Van decorator chain to
        ``ReliableVan.set_incarnation`` when one is present (a bare
        LoopbackVan stack simply has no fencing to update).  Idempotent —
        the registry only ever advances.
        """
        if incarnation and hasattr(self.post.van, "set_incarnation"):
            self.post.van.set_incarnation(node_id, incarnation)

    def _broadcast_table(
        self, rows: list[dict], targets: Optional[list[str]] = None
    ) -> None:
        if targets is None:
            targets = [r["node_id"] for r in rows if r["node_id"] != SCHEDULER]
        msgs = [
            Message(
                task=Task(
                    TaskKind.CONTROL,
                    self.name,
                    payload={"cmd": ADD_NODE, "table": rows},
                ),
                recver=t,
            )
            for t in targets
        ]
        if msgs:
            self.submit(msgs)

    def _on_add_node(self, msg: Message) -> None:
        learned: list[tuple[str, int]] = []
        with self._table_lock:
            for row in msg.task.payload["table"]:
                row = dict(row)
                row["role"] = NodeRole(row["role"])
                info = NodeInfo(**row)
                self._table[info.node_id] = info
                if info.incarnation:
                    learned.append((info.node_id, info.incarnation))
                # multi-process: learn routes to every peer from the table
                if (
                    info.address
                    and info.node_id != self.post.node_id
                    and hasattr(self.post.van, "add_route")
                ):
                    self.post.van.add_route(info.node_id, tuple(info.address))
        # outside the table lock: fence stale incarnations at this endpoint
        # (and arm this node's own stamp if the row is about itself)
        for node_id, inc in learned:
            self._learn_incarnation(node_id, inc)
        for cb in self.on_node_added:
            for row in msg.task.payload["table"]:
                cb(row["node_id"] if isinstance(row, dict) else row.node_id)
        self._ready.set()

    def _on_remove_node(self, msg: Message) -> None:
        dead = msg.task.payload["node_id"]
        with self._table_lock:
            if dead in self._table:
                self._table[dead].alive = False
        for cb in self.on_node_dead:
            cb(dead)

    def _on_heartbeat(self, msg: Message) -> None:
        fleet = self.fleet
        if fleet is not None:
            try:
                fleet.observe(msg.sender, msg.task.payload.get("stats") or {})
            except Exception:  # noqa: BLE001 — monitoring must never break
                # liveness handling (a malformed stats dict is not a death)
                logging.getLogger(__name__).exception(
                    "fleet: bad heartbeat stats from %s", msg.sender
                )
        recovered = None
        with self._table_lock:
            n = self._table.get(msg.sender)
            if n is not None:
                n.last_seen = time.monotonic()
                if not n.alive:
                    n.alive = True
                    recovered = dataclasses.asdict(n)
        if recovered is not None and self.role == NodeRole.SCHEDULER:
            # Re-join: peers learned REMOVE_NODE, so re-broadcast the row to
            # everyone and fire the add callbacks (ADD_NODE-on-recovery).
            with self._table_lock:
                targets = [
                    n.node_id
                    for n in self._table.values()
                    if n.alive and n.node_id != self.post.node_id
                ]
            self._broadcast_table([recovered], targets)
            for cb in self.on_node_added:
                cb(msg.sender)

    # -- live telemetry (ISSUE 10) -------------------------------------------
    def _on_telemetry(self, msg: Message) -> None:
        """Scheduler: fold one TELEMETRY frame into the aggregator.

        Guarded like ``_on_heartbeat`` — a malformed frame must never break
        the CONTROL plane.  The reply (sent by ``handle_request`` after this
        returns) therefore doubles as an ingest ack: a publisher that
        ``wait()``s on its TELEMETRY ts knows the scheduler has evaluated.
        """
        agg = self.telemetry
        if agg is None:
            return
        try:
            agg.ingest(msg.sender, msg.task.payload.get("frame") or {})
        except Exception:  # noqa: BLE001 — telemetry must never break CONTROL
            logging.getLogger(__name__).exception(
                "telemetry: bad frame from %s", msg.sender
            )

    def publish_telemetry(self) -> Optional[int]:
        """Non-scheduler: build and send one telemetry frame.

        Returns the submit ts (``wait()`` on it to block until the
        scheduler has ingested + evaluated), or None when no publisher is
        attached or frame construction failed — telemetry never raises into
        the training loop.
        """
        pub = self.telemetry_pub
        if pub is None:
            return None
        try:
            frame = pub.frame()
        except Exception:  # noqa: BLE001 — a broken stat source must not
            # cost the caller (frame building walks user-attached sources)
            logging.getLogger(__name__).exception(
                "telemetry: frame build failed on %s", self.post.node_id
            )
            return None
        return self.submit(
            [
                Message(
                    task=Task(
                        TaskKind.CONTROL,
                        self.name,
                        payload={"cmd": TELEMETRY, "frame": frame},
                    ),
                    recver=SCHEDULER,
                )
            ]
        )

    # -- heartbeats / failure detection --------------------------------------
    def send_heartbeat(
        self, stats: Optional[dict] = None, *, auto: bool = True
    ) -> int:
        """Non-scheduler: report liveness + observability stats.

        ``auto=True`` (default) attaches what the reference carried in
        ``heartbeat_info.h`` [U] and what the scheduler's
        :class:`~parameter_server_tpu.core.fleet.FleetMonitor` consumes:
        ``resource`` (:func:`~parameter_server_tpu.utils.trace.resource_usage`),
        ``net`` (cumulative :func:`~parameter_server_tpu.utils.metrics.transport_counters`
        of this node's Van stack), and ``links`` (per-link wire digests from
        a :class:`~parameter_server_tpu.core.netmon.MeteredVan`, when one is
        in the stack).  Caller-provided ``stats`` keys win (``setdefault``);
        ``auto=False`` sends a bare liveness ping.  Stat collection failures
        are swallowed — metrics must never cost a heartbeat.
        """
        payload_stats = dict(stats or {})
        if auto:
            try:
                from parameter_server_tpu.core.netmon import find_metered
                from parameter_server_tpu.utils.metrics import (
                    transport_counters,
                )
                from parameter_server_tpu.utils.trace import resource_usage

                payload_stats.setdefault("resource", resource_usage())
                payload_stats.setdefault(
                    "net", transport_counters(self.post.van)
                )
                if self.clock_offset is not None:
                    payload_stats.setdefault(
                        "clock",
                        {
                            "offset_s": self.clock_offset,
                            "rtt_s": self.clock_rtt,
                        },
                    )
                metered = find_metered(self.post.van)
                if metered is not None:
                    payload_stats.setdefault(
                        "links", metered.node_digests(self.post.node_id)
                    )
            except Exception:  # noqa: BLE001 — liveness > observability
                logging.getLogger(__name__).exception(
                    "heartbeat: stat collection failed on %s",
                    self.post.node_id,
                )
        ts = self.submit(
            [
                Message(
                    task=Task(
                        TaskKind.CONTROL,
                        self.name,
                        payload={"cmd": HEARTBEAT, "stats": payload_stats},
                    ),
                    recver=SCHEDULER,
                )
            ]
        )
        # telemetry rides the heartbeat cadence: the beat is submitted first
        # so the scheduler's FleetMonitor has seen this node (clock offset,
        # straggler state) before the frame is rebased against it
        if self.telemetry_pub is not None:
            self.publish_telemetry()
        return ts

    def check_heartbeats(self) -> List[str]:
        """Scheduler: mark nodes silent past the timeout dead; broadcast.

        Returns newly dead node ids.  Called from the monitor thread or
        directly by tests (deterministic failure injection).
        """
        now = time.monotonic()
        newly_dead: List[str] = []
        with self._table_lock:
            for n in self._table.values():
                if n.node_id == self.post.node_id or not n.alive:
                    continue
                if now - n.last_seen > self.heartbeat_timeout:
                    n.alive = False
                    newly_dead.append(n.node_id)
            live_targets = [
                n.node_id
                for n in self._table.values()
                if n.alive and n.node_id != self.post.node_id
            ]
        for dead in newly_dead:
            for cb in self.on_node_dead:
                cb(dead)
            msgs = [
                Message(
                    task=Task(
                        TaskKind.CONTROL,
                        self.name,
                        payload={"cmd": REMOVE_NODE, "node_id": dead},
                    ),
                    recver=t,
                )
                for t in live_targets
            ]
            if msgs:
                self.submit(msgs)
        return newly_dead

    def start_monitor(self, interval: float = 1.0) -> None:
        """Scheduler: poll heartbeats in a daemon thread."""
        self._stop.clear()  # allow start after a previous stop_monitor

        def loop() -> None:
            while not self._stop.wait(interval):
                self.check_heartbeats()

        self._monitor_thread = threading.Thread(
            target=loop, name="manager-monitor", daemon=True
        )
        self._monitor_thread.start()

    def stop_monitor(self) -> None:
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5)
            self._monitor_thread = None


def launch_local_cluster(
    van,
    *,
    num_workers: int,
    num_servers: int,
    key_space: int = 1 << 20,
    heartbeat_timeout: float = 5.0,
) -> tuple[Manager, Dict[str, Manager], Dict[str, Postoffice]]:
    """Spin up scheduler + N servers + M workers on one Van (local sim).

    This is the ``script/local.sh`` analogue for in-process tests: every node
    gets its own Postoffice + Manager, workers/servers register, and the call
    returns once the scheduler has broadcast the node table.
    """
    posts: Dict[str, Postoffice] = {}
    managers: Dict[str, Manager] = {}

    def make(node_id: str) -> Manager:
        post = Postoffice(node_id, van)
        posts[node_id] = post
        mgr = Manager(
            post,
            num_workers=num_workers,
            num_servers=num_servers,
            key_space=key_space,
            heartbeat_timeout=heartbeat_timeout,
        )
        managers[node_id] = mgr
        return mgr

    sched = make(SCHEDULER)
    for i in range(num_servers):
        make(server_id(i))
    for i in range(num_workers):
        make(worker_id(i))
    for nid, mgr in managers.items():
        if nid != SCHEDULER:
            mgr.register_with_scheduler(wait=False)
    for nid, mgr in managers.items():
        if not mgr.wait_ready(timeout=30):
            raise TimeoutError(f"node {nid} never saw the table broadcast")
    return sched, managers, posts
