"""TcpVan: the DCN-plane transport over native TCP sockets.

Reference analogue: ``src/system/van.h/.cc`` — ZeroMQ sockets, a node table,
and a receive thread [U] (SURVEY.md #2).  The socket/framing/thread core is
native C++ (``native/src/tcpvan.cc``, loaded via ctypes); this module owns
what the reference kept in C++ around protobuf: routing (node id -> address),
message serialization, per-link filter chains, and handler dispatch.

Design notes:

- One ``TcpVan`` per *process*; multiple logical nodes (scheduler + servers +
  workers colocated on a host) may bind on it, exactly like LoopbackVan.
- Wire format per frame: the flat self-describing layout of
  ``core/frame.py`` — 52-byte fixed header (magic/version/kind/flags,
  seq/incarnation/epoch stamps, plane+meta CRC32s, section lengths), a
  tag-encoded
  binary meta section (NO pickle anywhere on this path), then the raw
  contiguous key/value planes.  Arrays ride as raw bytes both ways (the
  SArray zero-copy role: sends read array buffers directly, receives take
  ``frombuffer`` views of the received buffer), and malformed or corrupted
  frames are rejected with a typed ``FrameError`` off the header alone.
- Filters (key caching / compression / quantization — core/filters.py) apply
  per link on the encoded Message before serialization, matching the
  reference's RemoteNode filter stacks.
- Unreachable/unknown destinations drop the message and return False — same
  contract as LoopbackVan, which the failure-detection layer builds on.
"""

from __future__ import annotations

import ctypes
import logging
import socket
import threading
from typing import Callable, Dict, Optional, Tuple

from parameter_server_tpu import native
from parameter_server_tpu.core import flightrec, frame
from parameter_server_tpu.core.frame import FrameError
from parameter_server_tpu.core.messages import Message
from parameter_server_tpu.core.van import Van, _Endpoint

_u8p = ctypes.POINTER(ctypes.c_uint8)


def _lib() -> ctypes.CDLL:
    lib = native.load("tcpvan", required=True)
    if not getattr(lib, "_ps_sigs", False):
        lib.ps_van_new.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
        ]
        lib.ps_van_new.restype = ctypes.c_void_p
        lib.ps_van_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
        lib.ps_van_send.argtypes = [ctypes.c_void_p, ctypes.c_int, _u8p, ctypes.c_int64]
        lib.ps_van_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_double, ctypes.POINTER(_u8p),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.ps_van_recv.restype = ctypes.c_int64
        lib.ps_van_free.argtypes = [_u8p]
        lib.ps_van_disconnect.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.ps_van_close.argtypes = [ctypes.c_void_p]
        lib.ps_van_port.argtypes = [ctypes.c_void_p]
        lib.ps_van_bytes_sent.argtypes = [ctypes.c_void_p]
        lib.ps_van_bytes_sent.restype = ctypes.c_int64
        lib.ps_van_bytes_recv.argtypes = [ctypes.c_void_p]
        lib.ps_van_bytes_recv.restype = ctypes.c_int64
        lib._ps_sigs = True
    return lib


# ------------------------------------------------------------ serialization


def serialize_message(msg: Message) -> bytes:
    """Message -> flat frame bytes (``core/frame.py``).  One join over the
    header, the binary meta section, and the arrays' own buffers — no
    ``tobytes()`` intermediates, no pickle."""
    return frame.encode(msg)


def deserialize_message(buf) -> Message:
    """Flat frame bytes -> Message; arrays are zero-copy ``frombuffer``
    views.  Raises :class:`~parameter_server_tpu.core.frame.FrameError`
    (typed) on truncated/garbled/corrupt frames — including a plane CRC
    check made in one pass over the raw buffer before any reconstruction."""
    return frame.decode(buf)


def _resolve(host: str) -> str:
    """inet_addr in the native core needs a numeric IPv4."""
    return socket.gethostbyname(host)


# ------------------------------------------------------------------- TcpVan


class TcpVan(Van):
    """Cross-host Van over the native TCP core.

    Usage::

        van = TcpVan()                      # binds an ephemeral port
        van.bind("S0", server_handler)      # local node(s)
        van.add_route("W0", ("10.0.0.2", 9001))
        van.send(msg)                       # routes local or remote
    """

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        *,
        filter_chain=None,
        advertise_host: Optional[str] = None,
    ) -> None:
        self._lib = _lib()
        actual = ctypes.c_int()
        self._van = self._lib.ps_van_new(
            host.encode(), port, ctypes.byref(actual)
        )
        if not self._van:
            raise OSError(f"TcpVan: cannot bind {host}:{port}")
        self.port = actual.value
        self.advertise_host = advertise_host or "127.0.0.1"
        self.filter_chain = filter_chain
        self._stateless_chain = None  # lazily-built reply-path subchain
        #: bound local nodes: per-node inbox + single handler thread, exactly
        #: like LoopbackVan — KVServer table mutation relies on each node's
        #: handler being single-threaded by construction.
        self._endpoints: Dict[str, _Endpoint] = {}
        self._routes: Dict[str, Tuple[str, int]] = {}
        self._conns: Dict[Tuple[str, int], int] = {}
        #: sender node id -> native conn the last inbound frame arrived on.
        #: Replies ride the requester's own connection (the ZMQ ROUTER
        #: identity pattern), so a server can answer peers it has no route
        #: for yet — e.g. a pull racing ahead of the node-table broadcast.
        self._peer_conns: Dict[str, int] = {}
        self._link_locks: Dict[tuple, threading.Lock] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self.sent_messages = 0
        self.dropped_messages = 0
        self.frame_rejects = 0
        self._dispatch = threading.Thread(
            target=self._dispatch_loop, name=f"tcpvan-dispatch-{self.port}",
            daemon=True,
        )
        self._dispatch.start()

    @property
    def address(self) -> Tuple[str, int]:
        return (self.advertise_host, self.port)

    # -- routing -------------------------------------------------------------
    def add_route(self, node_id: str, address: Tuple[str, int]) -> None:
        with self._lock:
            self._routes[node_id] = address

    def routes(self) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            return dict(self._routes)

    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        with self._lock:
            if node_id in self._endpoints:
                raise ValueError(f"node {node_id!r} already bound")
            self._endpoints[node_id] = _Endpoint(node_id, handler)

    def unbind(self, node_id: str) -> None:
        """Tear down a node's endpoint (see LoopbackVan.unbind)."""
        with self._lock:
            ep = self._endpoints.pop(node_id, None)
        if ep is not None:
            ep.stop()

    # -- send ----------------------------------------------------------------
    def send(self, msg: Message) -> bool:
        if self._closed.is_set():
            with self._lock:
                self.dropped_messages += 1
            return False
        with self._lock:
            local = self._endpoints.get(msg.recver)
        if local is not None:
            # same-process fast path: no serialization; the endpoint's own
            # thread runs the handler (single-threaded per node)
            with self._lock:
                self.sent_messages += 1
            local.inbox.put(msg)
            return True
        with self._lock:
            addr = self._routes.get(msg.recver)
        if addr is None:
            return self._send_via_peer_conn(msg)
        if self.filter_chain is not None:
            # Stateful filters (key caching) need wire-FIFO per link: hold the
            # link lock across encode AND the socket write so a later encode
            # cannot overtake an earlier frame onto the wire (LoopbackVan
            # documents the same invariant).
            with self._lock:
                ll = self._link_locks.setdefault(
                    (msg.sender, msg.recver), threading.Lock()
                )
            with ll:
                orig = msg
                msg = self.filter_chain.encode(msg)
                ok = self._send_wire(serialize_message(msg), addr)
                if not ok:
                    # the receiver never saw this frame — stateful filters
                    # (key caching) must roll back or the link poisons, and
                    # byte counters must un-commit (ADVICE r3)
                    self.filter_chain.on_send_failed(orig, msg)
                return ok
        return self._send_wire(serialize_message(msg), addr)

    def _send_via_peer_conn(self, msg: Message) -> bool:
        """No route: answer over the connection the peer last spoke on."""
        with self._lock:
            conn = self._peer_conns.get(msg.recver)
        if conn is None or self._van is None:
            with self._lock:
                self.dropped_messages += 1
            return False
        # STATELESS filters only on this path (compression/quantization):
        # per-link state (key caching) is keyed by the route-table identity
        # we lack here, but the codec filters are marker-driven — the
        # requester's full chain decodes them fine.  Pull replies are the
        # bulk of DCN bytes, so skipping them entirely (as before) forfeited
        # most of the compression win.
        orig = msg
        sub = None
        if self.filter_chain is not None:
            sub = self._stateless_chain
            if sub is None:
                sub = self._stateless_chain = self.filter_chain.stateless_subchain()
            msg = sub.encode(msg)
        data = serialize_message(msg)
        buf = ctypes.cast(ctypes.c_char_p(data), _u8p)
        rc = self._lib.ps_van_send(self._van, conn, buf, len(data))
        with self._lock:
            if rc == 0:
                self.sent_messages += 1
            else:
                self.dropped_messages += 1
                if self._peer_conns.get(msg.recver) == conn:
                    self._peer_conns.pop(msg.recver, None)  # stale conn
        if rc != 0 and sub is not None:
            # un-commit codec byte counters for a frame that never hit the
            # wire (same rollback as the routed path; pull replies are the
            # bulk of DCN bytes, so this path overstated worst)
            sub.on_send_failed(orig, msg)
        return rc == 0

    def _send_wire(self, data: bytes, addr: Tuple[str, int]) -> bool:
        if self._closed.is_set() or self._van is None:
            with self._lock:
                self.dropped_messages += 1
            return False
        conn = self._get_conn(addr)
        if conn is None:
            with self._lock:
                self.dropped_messages += 1
            return False
        # zero-copy: point at the bytes' buffer (send only reads it)
        buf = ctypes.cast(ctypes.c_char_p(data), _u8p)
        rc = self._lib.ps_van_send(self._van, conn, buf, len(data))
        with self._lock:
            if rc == 0:
                self.sent_messages += 1
            else:
                self.dropped_messages += 1
                # force reconnect next time; release the native fd + thread
                if self._conns.get(addr) == conn:
                    self._conns.pop(addr, None)
        if rc != 0:
            self._lib.ps_van_disconnect(self._van, conn)
        return rc == 0

    def _get_conn(self, addr: Tuple[str, int]) -> Optional[int]:
        with self._lock:
            conn = self._conns.get(addr)
        if conn is not None:
            return conn
        try:
            ip = _resolve(addr[0])
        except OSError:
            return None
        conn = self._lib.ps_van_connect(self._van, ip.encode(), addr[1])
        if conn < 0:
            return None
        with self._lock:
            # lost race: keep the first connection
            existing = self._conns.setdefault(addr, conn)
        if existing != conn:
            # release the abandoned duplicate (fd + native recv thread)
            self._lib.ps_van_disconnect(self._van, conn)
        return existing

    # -- receive -------------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while not self._closed.is_set():
            data = _u8p()
            conn = ctypes.c_int()
            n = self._lib.ps_van_recv(
                self._van, 0.2, ctypes.byref(data), ctypes.byref(conn)
            )
            if n == -1:
                continue  # timeout tick: re-check closed flag
            if n == -3:
                return
            if n == -2:
                continue  # peer closed; routes stay (reconnect on send)
            try:
                raw = ctypes.string_at(data, n) if n else b""
            finally:
                self._lib.ps_van_free(data)
            try:
                msg = deserialize_message(memoryview(raw))
            except FrameError as e:
                # typed rejection (bad magic/version, header/meta/plane CRC
                # mismatch, truncation): count it and keep the recv thread
                # alive — wire noise reads as loss, repaired by the
                # resender's retransmit, never as a dead transport
                with self._lock:
                    self.frame_rejects += 1
                    self.dropped_messages += 1
                flightrec.record(
                    "frame.reject", reason="decode", nbytes=n,
                    error=str(e)[:120],
                )
                logging.getLogger(__name__).debug(
                    "tcpvan: rejecting %d-byte frame: %s", n, e
                )
                continue
            except Exception:  # noqa: BLE001 — the codec's contract is that
                # every decode failure is a FrameError, but this thread is a
                # process-wide singleton: an exception type the codec missed
                # must still read as one dropped frame, not dead reception
                # for every node in the process
                with self._lock:
                    self.frame_rejects += 1
                    self.dropped_messages += 1
                flightrec.record(
                    "frame.reject", reason="codec-bug", nbytes=n,
                )
                logging.getLogger(__name__).exception(
                    "tcpvan: untyped decode failure on %d-byte frame "
                    "(codec bug — dropping frame)", n
                )
                continue
            if msg.sender:
                with self._lock:
                    self._peer_conns[msg.sender] = conn.value
            try:
                if self.filter_chain is not None:
                    with self._lock:
                        ll = self._link_locks.setdefault(
                            (msg.sender, msg.recver), threading.Lock()
                        )
                    with ll:
                        msg = self.filter_chain.decode(msg)
            except Exception:  # noqa: BLE001 — one bad message must not kill
                # the single dispatch thread (that would silently disable all
                # reception for every node in this process)
                logging.getLogger(__name__).exception(
                    "tcpvan: dropping message for %r after filter-decode error",
                    msg.recver,
                )
                with self._lock:
                    self.dropped_messages += 1
                continue
            with self._lock:
                ep = self._endpoints.get(msg.recver)
            if ep is not None:
                ep.inbox.put(msg)  # handler runs on the endpoint's own thread

    # -- stats / lifecycle ---------------------------------------------------
    def counters(self) -> dict:
        with self._lock:
            return {
                "sent": self.sent_messages,
                "dropped": self.dropped_messages,
                "frame_rejects": self.frame_rejects,
                "bytes_sent": self.bytes_sent(),
                "bytes_recv": self.bytes_recv(),
            }

    def bytes_sent(self) -> int:
        van = self._van
        return int(self._lib.ps_van_bytes_sent(van)) if van else 0

    def bytes_recv(self) -> int:
        van = self._van
        return int(self._lib.ps_van_bytes_recv(van)) if van else 0

    def close(self) -> None:
        if self._closed.is_set():
            return
        # dispatch thread exits on its next timeout tick BEFORE the native
        # handle is destroyed (it dereferences the handle in ps_van_recv)
        self._closed.set()
        self._dispatch.join(timeout=30)
        with self._lock:
            endpoints = list(self._endpoints.values())
        for ep in endpoints:
            ep.stop()
        if self._dispatch.is_alive():
            # The dispatch thread is wedged (>30s).  Freeing the native van
            # now would be a use-after-free in that thread; leak the handle
            # instead — the process is tearing down anyway.
            logging.getLogger(__name__).error(
                "tcpvan: dispatch thread did not exit; leaking native handle"
            )
            return
        self._lib.ps_van_close(self._van)
        self._van = None
