"""TcpVan: the DCN-plane transport over native TCP sockets + shm rings.

Reference analogue: ``src/system/van.h/.cc`` — ZeroMQ sockets, a node table,
and a receive thread [U] (SURVEY.md #2).  The socket/framing/thread core is
native C++ (loaded via ctypes); this module owns what the reference kept in
C++ around protobuf: routing (node id -> address), message serialization,
per-link filter chains, and handler dispatch.

Transport v2 (ISSUE 17) — two planes behind the same Van contract:

- **Wire backend**: ``native/src/epollvan.cc`` (default) multiplexes every
  connection on ONE event-loop thread with non-blocking vectored ``writev``
  sends and bounded per-connection write queues; ``native/src/tcpvan.cc``
  (``PS_WIRE=threaded`` or ``TransportConfig(wire="threaded")``) is the
  PR 6 thread-per-connection core.  Either way the wire format is the flat
  frame of ``core/frame.py`` inside ``[u32 magic][u64 len]`` framing, and
  the receive path hands Python a BORROWED native buffer decoded zero-copy
  (``np.frombuffer`` views) and freed only when the last view dies — no
  ``ctypes.string_at`` copy on either backend.
- **Shared-memory fast path**: links whose peers share a kernel boot id
  negotiate a pair of SPSC mmap rings (``core/shm_ring.py``) over the TCP
  connection; data frames then bypass TCP entirely, decoded zero-copy
  straight off the ring.  TCP stays attached as the control/fallback
  plane: a full ring degrades that one frame to TCP (counted
  ``ring_full``), and any conn death tears the rings down, so chaos,
  migration, and restart paths behave exactly as before.  Old peers never
  answer the offer — the link silently stays pure TCP (MIGRATION.md
  rolling-upgrade note).

Shm negotiation and the FIFO cutover.  The handshake rides the TCP conn it
upgrades (``__shmneg__`` control frames, never delivered to endpoints)::

    offer(boot, path)     initiator created ring R_i (it will WRITE R_i)
    accept(boot, path)    acceptor attached R_i as a gated reader and
                          created R_a; its own tx stays OFF
    cutover               each side, at the instant it enables its tx
    confirm(ok)           initiator attached R_a; acceptor enables its tx

Per-link FIFO survives the transition because every data send for a conn —
ring or TCP — runs under that conn's send lock, the ``cutover`` marker is
written to the TCP stream under the SAME lock in the same act that enables
the ring, and the receiver's ring reader is GATED until the dispatch thread
(which enqueues TCP frames in stream order) has processed the marker.  So
every TCP frame sent before the flip is in its endpoint inbox before the
first ring frame is, and no data frame ever follows the marker on TCP.

Ring-full backpressure is the one place the two planes can reorder: the
degraded frame rides TCP behind ring frames already in flight.  Links with
no stateful filters tolerate that (the reliable layer dedups and the stack
already absorbs ChaosVan's reorder injection), so they degrade per frame;
links running a stateful chain (key caching needs exact wire FIFO) DROP the
frame instead — ``on_send_failed`` rolls the codec back and the resender
retransmits — trading one retransmit for cache integrity.

Design notes:

- One ``TcpVan`` per *process*; multiple logical nodes (scheduler + servers +
  workers colocated on a host) may bind on it, exactly like LoopbackVan.
- Filters (key caching / compression / quantization — core/filters.py) apply
  per link on the encoded Message before serialization, matching the
  reference's RemoteNode filter stacks; which plane the frame then rides is
  decided below the filters, so they see one logical link either way.
- Unreachable/unknown destinations drop the message and return False — same
  contract as LoopbackVan, which the failure-detection layer builds on.
"""

from __future__ import annotations

import ctypes
import logging
import os
import socket
import threading
import time
import weakref
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from parameter_server_tpu import native
from parameter_server_tpu.config import TransportConfig
from parameter_server_tpu.core import flightrec, frame, shm_ring
from parameter_server_tpu.core.frame import FrameError
from parameter_server_tpu.core.tracectx import TRACE_KEY, trace_ids
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.van import Van, _Endpoint

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u8pp = ctypes.POINTER(_u8p)

#: internal handshake customer — intercepted by the dispatch loop, never
#: delivered to endpoints.  Old peers (pre-v2) drop these frames on the
#: floor (no endpoint named ``__shmneg__``), which IS the negotiation
#: failure path: silence leaves the link pure TCP.
SHMNEG_CUSTOMER = "__shmneg__"

#: env overrides (see :class:`~parameter_server_tpu.config.TransportConfig`)
WIRE_ENV = "PS_WIRE"
NO_SHM_ENV = "PS_NO_SHM"

#: native iovec cap of the epoll backend (kMaxIov in epollvan.cc); frames
#: with more segments take the joined single-buffer path.
_MAX_IOV = 64

# _send_on_conn return codes (superset of the native ps_van_send contract)
_SEND_OK = 0
_SEND_DEAD = -1        # conn dead: drop conn, tear down shm, reconnect later
_SEND_WRITEQ_FULL = -2  # epoll write queue refused the frame; conn is fine
_SEND_RING_DROP = -4   # ring full on a stateful-filtered link: frame dropped


def _setup_sigs(lib: ctypes.CDLL) -> ctypes.CDLL:
    if getattr(lib, "_ps_sigs", False):
        return lib
    lib.ps_van_new.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int)
    ]
    lib.ps_van_new.restype = ctypes.c_void_p
    lib.ps_van_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.ps_van_send.argtypes = [ctypes.c_void_p, ctypes.c_int, _u8p, ctypes.c_int64]
    lib.ps_van_recv.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.POINTER(_u8p),
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.ps_van_recv.restype = ctypes.c_int64
    lib.ps_van_free.argtypes = [_u8p]
    lib.ps_van_disconnect.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ps_van_close.argtypes = [ctypes.c_void_p]
    lib.ps_van_port.argtypes = [ctypes.c_void_p]
    lib.ps_van_bytes_sent.argtypes = [ctypes.c_void_p]
    lib.ps_van_bytes_sent.restype = ctypes.c_int64
    lib.ps_van_bytes_recv.argtypes = [ctypes.c_void_p]
    lib.ps_van_bytes_recv.restype = ctypes.c_int64
    try:
        # epoll backend only: vectored send + typed write-queue counter
        lib.ps_van_send_vec.argtypes = [
            ctypes.c_void_p, ctypes.c_int, _u8pp,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.ps_van_writeq_full.argtypes = [ctypes.c_void_p]
        lib.ps_van_writeq_full.restype = ctypes.c_int64
    except AttributeError:
        pass
    lib._ps_sigs = True
    return lib


def _lib() -> ctypes.CDLL:
    """Legacy threaded backend (kept for ``PS_WIRE=threaded`` and callers
    that import this directly)."""
    return _setup_sigs(native.load("tcpvan", required=True))


def _load_wire(wire: str) -> Tuple[ctypes.CDLL, str]:
    """Resolve the wire backend: requested (env beats config), with a quiet
    fallback from epoll to threaded when the epoll core fails to build."""
    wire = os.environ.get(WIRE_ENV, wire)
    if wire == "epoll":
        lib = native.load("epollvan")
        if lib is not None:
            return _setup_sigs(lib), "epoll"
        logging.getLogger(__name__).warning(
            "tcpvan: epoll backend unavailable; falling back to threaded"
        )
    return _lib(), "threaded"


# ------------------------------------------------------------ serialization


def serialize_message(msg: Message) -> bytes:
    """Message -> flat frame bytes (``core/frame.py``).  One join over the
    header, the binary meta section, and the arrays' own buffers — no
    ``tobytes()`` intermediates, no pickle."""
    return frame.encode(msg)


def deserialize_message(buf) -> Message:
    """Flat frame buffer -> Message; arrays are zero-copy ``frombuffer``
    views.  Raises :class:`~parameter_server_tpu.core.frame.FrameError`
    (typed) on truncated/garbled/corrupt frames — including a plane CRC
    check made in one pass over the raw buffer before any reconstruction."""
    return frame.decode(buf)


# DNS memoization (ISSUE 17 satellite): gethostbyname runs once per host,
# not on every cold connect; a failed connect invalidates the entry so a
# migrated/re-addressed host re-resolves on the retry.
_DNS_LOCK = threading.Lock()
_DNS_CACHE: Dict[str, str] = {}


def _resolve(host: str) -> str:
    """inet_addr in the native core needs a numeric IPv4 (memoized)."""
    with _DNS_LOCK:
        ip = _DNS_CACHE.get(host)
    if ip is not None:
        return ip
    ip = socket.gethostbyname(host)
    with _DNS_LOCK:
        _DNS_CACHE[host] = ip
    return ip


def _dns_invalidate(host: str) -> None:
    with _DNS_LOCK:
        _DNS_CACHE.pop(host, None)


def _free_native(lib: ctypes.CDLL, addr: int) -> None:
    """weakref.finalize target: release a borrowed native recv buffer once
    the last decoded view over it has died."""
    lib.ps_van_free(ctypes.cast(addr, _u8p))


class _ShmLink:
    """One colocated link in (or past) negotiation: the ring we write
    (``tx``), the ring we read (``rx`` + its gated reader thread), and the
    TCP conn that anchors the link's liveness (conn death tears it down)."""

    __slots__ = ("conn", "addr", "tx", "rx", "reader", "gate")

    def __init__(self, conn: int, addr: Optional[Tuple[str, int]] = None) -> None:
        self.conn = conn
        self.addr = addr  # set on the initiator side only
        self.tx: Optional[shm_ring.ShmRing] = None
        self.rx: Optional[shm_ring.ShmRing] = None
        self.reader: Optional[threading.Thread] = None
        #: opened by the peer's ``cutover`` marker: until then the reader
        #: must not deliver (FIFO vs TCP frames still in the dispatch queue)
        self.gate = threading.Event()


# ------------------------------------------------------------------- TcpVan


class TcpVan(Van):
    """Cross-host Van over the native wire core + colocated shm rings.

    Usage::

        van = TcpVan()                      # binds an ephemeral port
        van.bind("S0", server_handler)      # local node(s)
        van.add_route("W0", ("10.0.0.2", 9001))
        van.send(msg)                       # routes local or remote
    """

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        *,
        filter_chain=None,
        advertise_host: Optional[str] = None,
        transport: Optional[TransportConfig] = None,
    ) -> None:
        self.transport = transport or TransportConfig()
        self._lib, self.wire_backend = _load_wire(self.transport.wire)
        self._send_vec = getattr(self._lib, "ps_van_send_vec", None)
        actual = ctypes.c_int()
        self._van = self._lib.ps_van_new(
            host.encode(), port, ctypes.byref(actual)
        )
        if not self._van:
            raise OSError(f"TcpVan: cannot bind {host}:{port}")
        self.port = actual.value
        self.advertise_host = advertise_host or "127.0.0.1"
        self.filter_chain = filter_chain
        self._stateless_chain = None  # lazily-built reply-path subchain
        #: bound local nodes: per-node inbox + single handler thread, exactly
        #: like LoopbackVan — KVServer table mutation relies on each node's
        #: handler being single-threaded by construction.
        self._endpoints: Dict[str, _Endpoint] = {}
        self._routes: Dict[str, Tuple[str, int]] = {}
        self._conns: Dict[Tuple[str, int], int] = {}
        #: sender node id -> native conn the last inbound frame arrived on.
        #: Replies ride the requester's own connection (the ZMQ ROUTER
        #: identity pattern), so a server can answer peers it has no route
        #: for yet — e.g. a pull racing ahead of the node-table broadcast.
        self._peer_conns: Dict[str, int] = {}
        self._link_locks: Dict[tuple, threading.Lock] = {}
        #: per-conn send locks: the ring-vs-TCP choice, the write itself,
        #: and the shm cutover are atomic per conn (the FIFO story above)
        self._conn_locks: Dict[int, threading.Lock] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self.sent_messages = 0
        self.dropped_messages = 0
        self.frame_rejects = 0
        # -- shm fast path state ------------------------------------------
        self.shm_enabled = (
            self.transport.shm and not os.environ.get(NO_SHM_ENV)
        )
        self._boot_id = shm_ring.boot_id()
        #: conn id -> link state (from first offer until teardown)
        self._shm_links: Dict[int, _ShmLink] = {}
        #: conn id -> LIVE tx ring (the flip _send_on_conn checks);
        #: entered only under the conn's send lock, with the cutover marker
        self._shm_tx_live: Dict[int, shm_ring.ShmRing] = {}
        self.shm_frames_sent = 0
        self.shm_bytes_sent = 0
        self.shm_frames_recv = 0
        self.shm_bytes_recv = 0
        self.ring_fulls = 0    # frames hitting a full ring (degraded/dropped)
        self.writeq_fulls = 0  # vectored sends refused by the write queue
        self._dispatch = threading.Thread(
            target=self._dispatch_loop, name=f"tcpvan-dispatch-{self.port}",
            daemon=True,
        )
        self._dispatch.start()

    @property
    def address(self) -> Tuple[str, int]:
        return (self.advertise_host, self.port)

    # -- routing -------------------------------------------------------------
    def add_route(self, node_id: str, address: Tuple[str, int]) -> None:
        with self._lock:
            self._routes[node_id] = address

    def routes(self) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            return dict(self._routes)

    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        with self._lock:
            if node_id in self._endpoints:
                raise ValueError(f"node {node_id!r} already bound")
            self._endpoints[node_id] = _Endpoint(node_id, handler)

    def unbind(self, node_id: str) -> None:
        """Tear down a node's endpoint (see LoopbackVan.unbind)."""
        with self._lock:
            ep = self._endpoints.pop(node_id, None)
        if ep is not None:
            ep.stop()

    # -- send ----------------------------------------------------------------
    def send(self, msg: Message) -> bool:
        if self._closed.is_set():
            with self._lock:
                self.dropped_messages += 1
            return False
        with self._lock:
            local = self._endpoints.get(msg.recver)
        if local is not None:
            # same-process fast path: no serialization; the endpoint's own
            # thread runs the handler (single-threaded per node)
            with self._lock:
                self.sent_messages += 1
            local.inbox.put(msg)
            return True
        with self._lock:
            addr = self._routes.get(msg.recver)
        if addr is None:
            return self._send_via_peer_conn(msg)
        if self.filter_chain is not None:
            # Stateful filters (key caching) need wire-FIFO per link: hold the
            # link lock across encode AND the transport write so a later
            # encode cannot overtake an earlier frame onto the wire/ring
            # (LoopbackVan documents the same invariant).
            with self._lock:
                ll = self._link_locks.setdefault(
                    (msg.sender, msg.recver), threading.Lock()
                )
            with ll:
                orig = msg
                msg = self.filter_chain.encode(msg)
                ok = self._send_wire(msg, addr, stateful=True)
                if not ok:
                    # the receiver never saw this frame — stateful filters
                    # (key caching) must roll back or the link poisons, and
                    # byte counters must un-commit (ADVICE r3)
                    self.filter_chain.on_send_failed(orig, msg)
                return ok
        return self._send_wire(msg, addr)

    def _send_via_peer_conn(self, msg: Message) -> bool:
        """No route: answer over the connection the peer last spoke on."""
        with self._lock:
            conn = self._peer_conns.get(msg.recver)
        if conn is None or self._van is None:
            with self._lock:
                self.dropped_messages += 1
            return False
        # STATELESS filters only on this path (compression/quantization):
        # per-link state (key caching) is keyed by the route-table identity
        # we lack here, but the codec filters are marker-driven — the
        # requester's full chain decodes them fine.  Pull replies are the
        # bulk of DCN bytes, so skipping them entirely (as before) forfeited
        # most of the compression win.
        orig = msg
        sub = None
        if self.filter_chain is not None:
            sub = self._stateless_chain
            if sub is None:
                sub = self._stateless_chain = self.filter_chain.stateless_subchain()
            msg = sub.encode(msg)
        rc = self._send_on_conn(conn, msg)
        with self._lock:
            if rc == _SEND_OK:
                self.sent_messages += 1
            else:
                self.dropped_messages += 1
                if rc == _SEND_DEAD and self._peer_conns.get(msg.recver) == conn:
                    self._peer_conns.pop(msg.recver, None)  # stale conn
        if rc != _SEND_OK and sub is not None:
            # un-commit codec byte counters for a frame that never hit the
            # wire (same rollback as the routed path; pull replies are the
            # bulk of DCN bytes, so this path overstated worst)
            sub.on_send_failed(orig, msg)
        if rc == _SEND_DEAD:
            self._teardown_shm(conn)
        return rc == _SEND_OK

    def _send_wire(
        self, msg: Message, addr: Tuple[str, int], *, stateful: bool = False
    ) -> bool:
        if self._closed.is_set() or self._van is None:
            with self._lock:
                self.dropped_messages += 1
            return False
        conn = self._get_conn(addr)
        if conn is None:
            with self._lock:
                self.dropped_messages += 1
            return False
        rc = self._send_on_conn(conn, msg, stateful=stateful)
        with self._lock:
            if rc == _SEND_OK:
                self.sent_messages += 1
            else:
                self.dropped_messages += 1
                # a dead conn forces a reconnect next time; write-queue/ring
                # backpressure keeps the conn: the frame is dropped for the
                # resender to retransmit, nothing below is broken
                if rc == _SEND_DEAD and self._conns.get(addr) == conn:
                    self._conns.pop(addr, None)
        if rc == _SEND_DEAD:
            self._teardown_shm(conn)
            self._lib.ps_van_disconnect(self._van, conn)
        return rc == _SEND_OK

    def _conn_lock(self, conn: int) -> threading.Lock:
        with self._lock:
            return self._conn_locks.setdefault(conn, threading.Lock())

    def _send_on_conn(
        self, conn: int, msg: Message, *, stateful: bool = False
    ) -> int:
        """The per-conn choke point: ring if live, else TCP, atomically.

        Returns ``_SEND_OK``/``_SEND_DEAD``/``_SEND_WRITEQ_FULL``/
        ``_SEND_RING_DROP``.  ``stateful`` marks frames from a stateful
        filter chain: on ring-full those DROP (caller rolls the codec back,
        resender retransmits) instead of degrading to TCP, because the
        degraded frame would arrive out of order and poison key-cache state.
        """
        payload = msg.task.payload
        if isinstance(payload, dict) and TRACE_KEY in payload:
            # sampled request tracing (ISSUE 18): this is the per-conn
            # choke point every outbound frame — ring OR TCP — passes, so
            # one gated record covers both wire planes.  Unsampled frames
            # (no trace key) cost the dict membership test only.
            flightrec.record(
                "trace.wire_tx",
                tids=trace_ids(payload),
                recver=msg.recver,
                conn=conn,
            )
        with self._conn_lock(conn):
            ring = self._shm_tx_live.get(conn)
            if ring is not None and not ring.closed:
                segs, total = frame.encode_vec(msg)
                if ring.write(segs, total, timeout=self.transport.ring_wait_s):
                    with self._lock:
                        self.shm_frames_sent += 1
                        self.shm_bytes_sent += total
                    return _SEND_OK
                with self._lock:
                    self.ring_fulls += 1
                flightrec.record(
                    "net.ring_full", recver=msg.recver, nbytes=total,
                )
                if stateful:
                    return _SEND_RING_DROP
                return self._wire_send_segs(conn, segs, total)
            return self._wire_send_msg(conn, msg)

    def _wire_send_msg(self, conn: int, msg: Message) -> int:
        if self._send_vec is None:
            data = serialize_message(msg)
            buf = ctypes.cast(ctypes.c_char_p(data), _u8p)
            return self._lib.ps_van_send(self._van, conn, buf, len(data))
        segs, total = frame.encode_vec(msg)
        return self._wire_send_segs(conn, segs, total)

    def _wire_send_segs(self, conn: int, segs: list, total: int) -> int:
        """Vectored send on the epoll backend: a coalesced bundle's header
        and member planes ride one ``writev`` without ever concatenating
        host-side.  Frames over the native iovec cap (or on the threaded
        backend) take the joined single-buffer path."""
        if self._send_vec is not None and len(segs) < _MAX_IOV:
            n = len(segs)
            bufs = (_u8p * n)()
            lens = (ctypes.c_int64 * n)()
            # uint8 views resolve each segment (bytes / bytearray / plane
            # memoryview) to a stable pointer without copying; `holders`
            # pins the buffers for the duration of the call (the native
            # side copies any unsent tail before returning).
            holders = []
            for i, s in enumerate(segs):
                a = np.frombuffer(s, dtype=np.uint8)
                holders.append(a)
                bufs[i] = a.ctypes.data_as(_u8p)
                lens[i] = a.nbytes
            rc = self._lib.ps_van_send_vec(self._van, conn, bufs, lens, n)
            del holders
            if rc == _SEND_WRITEQ_FULL:
                with self._lock:
                    self.writeq_fulls += 1
                flightrec.record("net.writeq_full", conn=conn, nbytes=total)
            if rc != -3:  # -3: over the native seg cap — join instead
                return rc
        data = b"".join(bytes(s) if not isinstance(s, bytes) else s
                        for s in segs)
        buf = ctypes.cast(ctypes.c_char_p(data), _u8p)
        return self._lib.ps_van_send(self._van, conn, buf, len(data))

    def _get_conn(self, addr: Tuple[str, int]) -> Optional[int]:
        with self._lock:
            conn = self._conns.get(addr)
        if conn is not None:
            return conn
        try:
            ip = _resolve(addr[0])
        except OSError:
            return None
        conn = self._lib.ps_van_connect(self._van, ip.encode(), addr[1])
        if conn < 0:
            # the cached resolution may be stale (host re-addressed after a
            # migration): drop it so the retry resolves fresh
            _dns_invalidate(addr[0])
            return None
        with self._lock:
            # lost race: keep the first connection
            existing = self._conns.setdefault(addr, conn)
        if existing != conn:
            # release the abandoned duplicate (fd + native recv state)
            self._lib.ps_van_disconnect(self._van, conn)
        elif self.shm_enabled:
            self._shm_offer(conn, addr)
        return existing

    # -- shm negotiation -----------------------------------------------------
    def _neg_send(self, conn: int, op: str, **fields) -> None:
        payload = {"op": op, "boot": self._boot_id, **fields}
        m = Message(
            task=Task(TaskKind.CONTROL, SHMNEG_CUSTOMER, payload=payload),
            sender="", recver="",
        )
        data = frame.encode(m)
        buf = ctypes.cast(ctypes.c_char_p(data), _u8p)
        self._lib.ps_van_send(self._van, conn, buf, len(data))

    def _shm_offer(self, conn: int, addr: Tuple[str, int]) -> None:
        """Initiator: create our tx ring for this link and offer it."""
        try:
            ring = shm_ring.ShmRing.create(self.transport.ring_capacity)
        except OSError:
            return
        link = _ShmLink(conn, addr)
        link.tx = ring  # created, but OFF until the peer's accept
        with self._lock:
            self._shm_links[conn] = link
        self._neg_send(conn, "offer", path=ring.path)

    def _shm_on_offer(self, conn: int, payload: dict) -> None:
        if (
            not self.shm_enabled
            or payload.get("boot") != self._boot_id
            or not isinstance(payload.get("path"), str)
        ):
            self._neg_send(conn, "nak")
            return
        try:
            rx = shm_ring.ShmRing.attach(payload["path"])
            tx = shm_ring.ShmRing.create(self.transport.ring_capacity)
        except (OSError, shm_ring.ShmRingError):
            self._neg_send(conn, "nak")
            return
        link = _ShmLink(conn)
        link.rx = rx
        link.tx = tx  # OFF until the initiator's confirm
        with self._lock:
            self._shm_links[conn] = link
        self._start_reader(link)  # gated: waits for the initiator's cutover
        self._neg_send(conn, "accept", path=tx.path)

    def _shm_on_accept(self, conn: int, payload: dict) -> None:
        with self._lock:
            link = self._shm_links.get(conn)
        if (
            link is None or link.addr is None or link.rx is not None
            or payload.get("boot") != self._boot_id
            or not isinstance(payload.get("path"), str)
        ):
            return  # not ours / stale / duplicate accept: ignore
        try:
            rx = shm_ring.ShmRing.attach(payload["path"])
        except (OSError, shm_ring.ShmRingError):
            self._neg_send(conn, "confirm", ok=False)
            self._teardown_shm(conn)
            return
        link.rx = rx
        self._start_reader(link)  # gated: waits for the acceptor's cutover
        self._flip_tx_live(conn, link.tx)
        self._neg_send(conn, "confirm", ok=True)

    def _shm_on_confirm(self, conn: int, payload: dict) -> None:
        with self._lock:
            link = self._shm_links.get(conn)
        if link is None or link.addr is not None or link.rx is None:
            return  # not an acceptor-side link: ignore
        if not payload.get("ok"):
            self._teardown_shm(conn)
            return
        self._flip_tx_live(conn, link.tx)

    def _flip_tx_live(self, conn: int, ring: shm_ring.ShmRing) -> None:
        """Enable the ring for sends AND put the cutover marker on the TCP
        stream in one atomic act (vs this conn's data sends): after this, no
        data frame follows the marker on TCP, so the peer's gated reader
        starting at the marker preserves per-link FIFO exactly."""
        with self._conn_lock(conn):
            self._shm_tx_live[conn] = ring
            self._neg_send(conn, "cutover")

    def _start_reader(self, link: _ShmLink) -> None:
        t = threading.Thread(
            target=self._shm_reader, args=(link,),
            name=f"shm-reader-{self.port}-{link.conn}", daemon=True,
        )
        link.reader = t
        t.start()

    def _shm_reader(self, link: _ShmLink) -> None:
        """Drain one rx ring: zero-copy decode + the same dispatch path TCP
        frames take.  Gated until the peer's cutover marker has passed the
        dispatch thread; exits when the ring closes or the van shuts down."""
        ring = link.rx
        while not link.gate.is_set():
            if self._closed.is_set() or ring.closed:
                return
            link.gate.wait(0.1)
        while not self._closed.is_set():
            if not ring.poll(0.1):
                if ring.closed:
                    return
                continue
            rec = ring.read()
            if rec is None:
                # poll() reports ready on a CLOSED ring too; a drained +
                # closed ring means the peer is gone — exit (don't spin)
                # so teardown's join() succeeds before it unmaps the ring.
                if ring.closed:
                    return
                continue
            idx, view = rec
            # GC-anchored reclamation: every decoded array's base chain
            # roots at this wrapper; the ring slot frees when the LAST view
            # (numpy or CPU-jax alias) dies — see core/shm_ring.py.
            wrapper = np.frombuffer(view, dtype=np.uint8)
            weakref.finalize(wrapper, ring.release, idx)
            with self._lock:
                self.shm_frames_recv += 1
                self.shm_bytes_recv += len(view)
            self._dispatch_frame(wrapper, len(view), link.conn)
            del wrapper, view, rec

    def _teardown_shm(self, conn: int) -> None:
        """Conn died (or negotiation failed): close both rings, stop the
        reader, fall back to pure TCP.  Re-negotiated on reconnect."""
        with self._lock:
            link = self._shm_links.pop(conn, None)
        if link is None:
            return
        with self._conn_lock(conn):
            self._shm_tx_live.pop(conn, None)
        with self._lock:
            self._conn_locks.pop(conn, None)
        for ring in (link.tx, link.rx):
            if ring is not None:
                ring.mark_closed()
        link.gate.set()  # unblock a reader still waiting on the cutover
        if link.reader is not None and link.reader is not threading.current_thread():
            link.reader.join(timeout=5)
        for ring in (link.tx, link.rx):
            if ring is not None:
                ring.close()

    def drop_shm_links(self, *, disable: bool = False) -> int:
        """Chaos/test hook: tear down every negotiated shm link (traffic
        falls back to TCP mid-run, the same path a dying peer triggers).
        ``disable=True`` also stops future negotiation, pinning the van to
        pure TCP."""
        if disable:
            self.shm_enabled = False
        with self._lock:
            conns = list(self._shm_links)
        for conn in conns:
            self._teardown_shm(conn)
        return len(conns)

    # -- receive -------------------------------------------------------------
    def _dispatch_loop(self) -> None:
        while not self._closed.is_set():
            data = _u8p()
            conn = ctypes.c_int()
            n = self._lib.ps_van_recv(
                self._van, 0.2, ctypes.byref(data), ctypes.byref(conn)
            )
            if n == -1:
                continue  # timeout tick: re-check closed flag
            if n == -3:
                return
            if n == -2:
                # peer closed; routes stay (reconnect on send), but any shm
                # link anchored to the conn dies with it — that is the
                # fallback path chaos/migration/restart rely on
                self._teardown_shm(conn.value)
                continue
            # Borrowed-buffer decode (no string_at copy): wrap the native
            # malloc'd buffer, decode zero-copy views over it, and free it
            # only when the last view dies (weakref.finalize -> ps_van_free).
            addr = ctypes.cast(data, ctypes.c_void_p).value
            carr = (ctypes.c_ubyte * n).from_address(addr)
            wrapper = np.frombuffer(carr, dtype=np.uint8)
            weakref.finalize(wrapper, _free_native, self._lib, addr)
            self._dispatch_frame(wrapper, n, conn.value)
            del wrapper, carr

    def _dispatch_frame(self, buf, n: int, conn: Optional[int]) -> None:
        """Decode one inbound frame and route it to its endpoint — shared by
        the TCP dispatch loop and every shm ring reader."""
        try:
            msg = deserialize_message(buf)
        except FrameError as e:
            # typed rejection (bad magic/version, header/meta/plane CRC
            # mismatch, truncation): count it and keep the recv thread
            # alive — wire noise reads as loss, repaired by the
            # resender's retransmit, never as a dead transport
            with self._lock:
                self.frame_rejects += 1
                self.dropped_messages += 1
            flightrec.record(
                "frame.reject", reason="decode", nbytes=n,
                error=str(e)[:120],
            )
            logging.getLogger(__name__).debug(
                "tcpvan: rejecting %d-byte frame: %s", n, e
            )
            return
        except Exception:  # noqa: BLE001 — the codec's contract is that
            # every decode failure is a FrameError, but this thread is a
            # process-wide singleton: an exception type the codec missed
            # must still read as one dropped frame, not dead reception
            # for every node in the process
            with self._lock:
                self.frame_rejects += 1
                self.dropped_messages += 1
            flightrec.record("frame.reject", reason="codec-bug", nbytes=n)
            logging.getLogger(__name__).exception(
                "tcpvan: untyped decode failure on %d-byte frame "
                "(codec bug — dropping frame)", n
            )
            return
        if msg.task.customer == SHMNEG_CUSTOMER:
            payload = msg.task.payload
            op = payload.get("op") if isinstance(payload, dict) else None
            if conn is not None:
                self._shm_neg_dispatch(conn, op, payload)
            return  # handshake traffic never reaches endpoints
        if msg.sender and conn is not None:
            with self._lock:
                self._peer_conns[msg.sender] = conn
        try:
            if self.filter_chain is not None:
                with self._lock:
                    ll = self._link_locks.setdefault(
                        (msg.sender, msg.recver), threading.Lock()
                    )
                with ll:
                    msg = self.filter_chain.decode(msg)
        except Exception:  # noqa: BLE001 — one bad message must not kill
            # the single dispatch thread (that would silently disable all
            # reception for every node in this process)
            logging.getLogger(__name__).exception(
                "tcpvan: dropping message for %r after filter-decode error",
                msg.recver,
            )
            with self._lock:
                self.dropped_messages += 1
            return
        payload = msg.task.payload
        if isinstance(payload, dict):
            tctx = payload.get(TRACE_KEY)
            if isinstance(tctx, dict):
                # sampled request tracing (ISSUE 18): stamp the receive
                # time INTO the context — safe exactly here because this
                # payload dict was freshly decoded off the wire (TCP and
                # shm reader alike), never shared with a sender.  The
                # server's queue attribution (trace.sq) is dispatch - rx.
                tctx["rx"] = time.monotonic()
                flightrec.record(
                    "trace.wire_rx",
                    tids=trace_ids(payload),
                    sender=msg.sender,
                    nbytes=n,
                )
        with self._lock:
            ep = self._endpoints.get(msg.recver)
        if ep is not None:
            ep.inbox.put(msg)  # handler runs on the endpoint's own thread

    def _shm_neg_dispatch(self, conn: int, op, payload) -> None:
        if op == "offer":
            self._shm_on_offer(conn, payload)
        elif op == "accept":
            self._shm_on_accept(conn, payload)
        elif op == "confirm":
            self._shm_on_confirm(conn, payload)
        elif op == "cutover":
            with self._lock:
                link = self._shm_links.get(conn)
            if link is not None:
                link.gate.set()
        elif op == "nak":
            self._teardown_shm(conn)

    # -- stats / lifecycle ---------------------------------------------------
    def counters(self) -> dict:
        with self._lock:
            tx_rings = [
                l.tx for l in self._shm_links.values() if l.tx is not None
            ]
            c = {
                "sent": self.sent_messages,
                "dropped": self.dropped_messages,
                "frame_rejects": self.frame_rejects,
                "bytes_sent": self.bytes_sent(),
                "bytes_recv": self.bytes_recv(),
                "shm_links": len(self._shm_tx_live),
                "shm_frames_sent": self.shm_frames_sent,
                "shm_bytes_sent": self.shm_bytes_sent,
                "shm_frames_recv": self.shm_frames_recv,
                "shm_bytes_recv": self.shm_bytes_recv,
                "ring_full": self.ring_fulls,
                "writeq_full": self.writeq_fulls,
            }
        for tx in tx_rings:
            c["ring_full"] += tx.ring_full
        if self._send_vec is not None and self._van:
            c["writeq_full_native"] = int(
                self._lib.ps_van_writeq_full(self._van)
            )
        return c

    def bytes_sent(self) -> int:
        van = self._van
        return int(self._lib.ps_van_bytes_sent(van)) if van else 0

    def bytes_recv(self) -> int:
        van = self._van
        return int(self._lib.ps_van_bytes_recv(van)) if van else 0

    # Payload egress/ingress regardless of medium: socket bytes PLUS frames
    # that rode a colocated shm ring.  Byte-accounting flows (launch result
    # JSON, bench plane-overlap arm) must use these — with shm negotiated,
    # bytes_sent() alone reads near zero because data frames bypass the
    # socket entirely, while wire filters still compress ring frames.
    def payload_bytes_sent(self) -> int:
        with self._lock:
            return self.bytes_sent() + self.shm_bytes_sent

    def payload_bytes_recv(self) -> int:
        with self._lock:
            return self.bytes_recv() + self.shm_bytes_recv

    def close(self) -> None:
        if self._closed.is_set():
            return
        # dispatch thread exits on its next timeout tick BEFORE the native
        # handle is destroyed (it dereferences the handle in ps_van_recv);
        # shm readers exit on the same flag / their rings' closed marks
        self._closed.set()
        with self._lock:
            conns = list(self._shm_links)
        for conn in conns:
            self._teardown_shm(conn)
        self._dispatch.join(timeout=30)
        with self._lock:
            endpoints = list(self._endpoints.values())
        for ep in endpoints:
            ep.stop()
        if self._dispatch.is_alive():
            # The dispatch thread is wedged (>30s).  Freeing the native van
            # now would be a use-after-free in that thread; leak the handle
            # instead — the process is tearing down anyway.
            logging.getLogger(__name__).error(
                "tcpvan: dispatch thread did not exit; leaking native handle"
            )
            return
        self._lib.ps_van_close(self._van)
        self._van = None
