"""FleetMonitor: scheduler-side node time series + straggler detection.

Reference analogue: ``heartbeat_info.h`` -> ``monitor.h`` -> ``dashboard.h``
[U] — worker/server heartbeats carried CPU and network usage, the scheduler
kept per-node rows and printed the fleet table.  Our Manager accepted those
``stats`` payloads and dropped them; this module is where they land.

The interesting detector is the GRAY-FAILURE one (ROADMAP names it as
unmodeled).  A slow-but-alive node heartbeats on time, so the liveness
sweep (``Manager.check_heartbeats``) never fires; what gives it away is
latency: every link INTO it runs k× slower than the fleet.  Heartbeats
auto-attach per-link deliver-latency digests
(:meth:`~parameter_server_tpu.core.netmon.MeteredVan.node_digests`);
FleetMonitor merges them into a per-node INBOUND histogram and flags nodes
whose push p99 exceeds k× the fleet median — with an absolute floor so
microsecond-scale jitter inside a uniformly healthy fleet can never trip
it.  Heartbeat-GAP straggling (a node that reports, but late) is flagged
the same relative way against the fleet's median beat interval.

Wall-clock discipline: every entry point takes an explicit ``now``
(``time.monotonic()`` domain) so tests drive synthetic clocks and the
detector is deterministic under load.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
import threading
import time
from typing import IO, Dict, List, Optional

from parameter_server_tpu.utils.trace import LatencyHistogram


class RotatingJsonlWriter:
    """Size-rotated JSONL sink writing WHOLE lines only.

    Each :meth:`write_line` is one ``write()`` call of a complete
    ``...\\n``-terminated line followed by ``flush()``, and rotation happens
    BETWEEN lines (the current file is renamed to ``<path>.<n>`` and a fresh
    one opened), so no reader — and no postmortem bundle — can ever capture
    a truncated last line.  :meth:`sync` adds an fsync for the dump path.
    """

    def __init__(self, path: str, *, rotate_bytes: int = 0) -> None:
        self.path = path
        self.rotate_bytes = rotate_bytes
        self._lock = threading.Lock()
        self._rotations = 0
        self._f = open(path, "a")
        self._size = self._f.tell()

    def write_line(self, line: str) -> None:
        if not line.endswith("\n"):
            line += "\n"
        with self._lock:
            if (
                self.rotate_bytes > 0
                and self._size > 0
                and self._size + len(line) > self.rotate_bytes
            ):
                self._rotate_locked()
            self._f.write(line)
            self._f.flush()
            self._size += len(line)

    def _rotate_locked(self) -> None:
        self._f.close()
        self._rotations += 1
        os.replace(self.path, f"{self.path}.{self._rotations}")
        self._f = open(self.path, "a")
        self._size = 0

    @property
    def rotations(self) -> int:
        with self._lock:
            return self._rotations

    def sync(self) -> None:
        """Flush + fsync (the flush-on-dump guarantee for bundles)."""
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            self._f.close()


@dataclasses.dataclass(frozen=True)
class StragglerPolicy:
    """Thresholds for the two detectors.  Both are RELATIVE (k× the fleet
    median) with ABSOLUTE floors: relative-only would flag one node of a
    uniformly fast fleet over microseconds of noise; absolute-only would
    need retuning per deployment."""

    #: flag when a node's stat exceeds k× the fleet median of that stat.
    k: float = 4.0
    #: inbound push p99 must also exceed this to flag (absolute floor).
    p99_floor_ms: float = 10.0
    #: heartbeat gap must also exceed this to flag (absolute floor).
    gap_floor_s: float = 1.0
    #: minimum inbound deliver samples before the latency detector speaks.
    min_latency_count: int = 4
    #: minimum heartbeats per node before the gap detector speaks.
    min_heartbeats: int = 2


class _NodeSeries:
    """Retained per-node state: beat times + latest cumulative stats."""

    __slots__ = (
        "beats", "resource", "prev_resource", "net", "prev_net", "clock",
    )

    def __init__(self, window: int) -> None:
        import collections

        self.beats: "collections.deque[float]" = collections.deque(
            maxlen=window
        )
        self.resource: dict = {}
        self.prev_resource: dict = {}
        self.net: dict = {}
        self.prev_net: dict = {}
        #: latest clock-sync estimate from Manager.sync_clock:
        #: {"offset_s": local-minus-scheduler, "rtt_s": winning RTT}.
        self.clock: dict = {}


class FleetMonitor:
    """Aggregates heartbeat stats into per-node series + straggler flags.

    Attach to the scheduler's Manager (``sched.fleet = FleetMonitor()``);
    ``Manager._on_heartbeat`` then feeds every beat's stats here.  Pass a
    ``jsonl`` stream and each :meth:`write_jsonl` call appends one fleet
    snapshot line (the ``fleet`` JSONL artifact — field meanings in the
    README Observability section).
    """

    def __init__(
        self,
        *,
        policy: Optional[StragglerPolicy] = None,
        window: int = 256,
        jsonl: Optional[IO[str]] = None,
        jsonl_path: Optional[str] = None,
        rotate_bytes: int = 0,
    ) -> None:
        """``jsonl``: an open text stream (legacy form, no rotation), or
        ``jsonl_path``: a file path managed through a
        :class:`RotatingJsonlWriter` with ``rotate_bytes`` size rotation
        (0 = never rotate).  Mutually exclusive."""
        if jsonl is not None and jsonl_path is not None:
            raise ValueError("pass jsonl OR jsonl_path, not both")
        self.policy = policy or StragglerPolicy()
        self.jsonl = jsonl
        self.jsonl_writer: Optional[RotatingJsonlWriter] = (
            RotatingJsonlWriter(jsonl_path, rotate_bytes=rotate_bytes)
            if jsonl_path is not None
            else None
        )
        self._window = window
        self._lock = threading.Lock()
        self._series: Dict[str, _NodeSeries] = {}
        #: latest CUMULATIVE per-link digest, keyed "sender->recver".
        #: Cumulative digests are REPLACED, never re-merged — merging two
        #: snapshots of the same counter would double-count every sample.
        self._links: Dict[str, dict] = {}

    # -- ingest --------------------------------------------------------------
    def observe(
        self, node_id: str, stats: dict, now: Optional[float] = None
    ) -> None:
        """Record one heartbeat's stats payload from ``node_id``."""
        now = time.monotonic() if now is None else now
        stats = stats or {}
        with self._lock:
            s = self._series.get(node_id)
            if s is None:
                s = self._series[node_id] = _NodeSeries(self._window)
            s.beats.append(now)
            if stats.get("resource"):
                s.prev_resource, s.resource = s.resource, dict(stats["resource"])
            if stats.get("net"):
                s.prev_net, s.net = s.net, dict(stats["net"])
            if stats.get("clock"):
                s.clock = dict(stats["clock"])
            for link, digest in (stats.get("links") or {}).items():
                self._links[link] = digest

    # -- clock offsets (cross-host latency attribution) ----------------------
    def clock_offset(self, node_id: str) -> Optional[float]:
        """``node_id``'s monotonic clock minus the scheduler's (seconds),
        as last reported over heartbeat; None before its first sync.  The
        scheduler itself is the reference: offset 0 by definition."""
        with self._lock:
            s = self._series.get(node_id)
            if s is not None and "offset_s" in s.clock:
                return float(s.clock["offset_s"])
        return None

    def relative_offset(self, a: str, b: str) -> Optional[float]:
        """Clock of node ``a`` minus clock of node ``b`` (seconds).

        This is the number a receiver needs to correct one-way deliver
        latencies measured from ``__mts__`` stamps
        (:class:`~parameter_server_tpu.core.netmon.MeteredVan.set_clock_offset`):
        node-local monotonic clocks share no epoch across hosts, so the raw
        ``recv_local - send_remote`` difference is offset + latency until
        corrected.  None until BOTH nodes have synced (the scheduler counts
        as always synced at 0).
        """
        from parameter_server_tpu.core.messages import SCHEDULER

        off_a = 0.0 if a == SCHEDULER else self.clock_offset(a)
        off_b = 0.0 if b == SCHEDULER else self.clock_offset(b)
        if off_a is None or off_b is None:
            return None
        return off_a - off_b

    def nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    # -- derived stats -------------------------------------------------------
    @staticmethod
    def _inbound_hist(links: Dict[str, dict], node_id: str) -> LatencyHistogram:
        """Merged deliver-latency histogram of every link INTO a node.

        Safe to merge: each link digest appears exactly once in ``links``
        (latest snapshot), and distinct links are independent streams.
        """
        h = LatencyHistogram()
        for link, digest in links.items():
            if link.endswith(f"->{node_id}") and digest.get("deliver"):
                h.merge(LatencyHistogram.from_dict(digest["deliver"]))
        return h

    def inbound_totals(self) -> Dict[str, dict]:
        """Cumulative inbound wire load per node:
        ``{node: {bytes, msgs, verbs}}``.

        Summed over the latest per-link digests of every link INTO each
        node — the load-ranking signal the PR-6 rebalancer consumes
        (``learner/elastic.py::RebalancePolicy``).  Cumulative by design:
        the policy differences successive calls to get rates, so one missed
        heartbeat cannot fake a load drop.

        ``verbs`` splits the totals per request verb
        (``{"PUSH": {"msgs", "bytes"}, ...}``, from MeteredVan's per-link
        verb counters) so the hierarchical-push reduction (ISSUE 15) — and
        the Zipfian rebalance bench's before/after — can report inbound
        request COUNT, not just bytes.  Empty for digests from pre-verb
        publishers (old snapshots merge cleanly).
        """
        with self._lock:
            links = dict(self._links)
        out: Dict[str, dict] = {}
        for link, digest in links.items():
            _, _, recver = link.partition("->")
            if not recver:
                continue
            row = out.setdefault(recver, {"bytes": 0, "msgs": 0, "verbs": {}})
            row["bytes"] += int(digest.get("bytes", 0))
            row["msgs"] += int(digest.get("msgs", 0))
            for verb, vd in (digest.get("verbs") or {}).items():
                vrow = row["verbs"].setdefault(verb, {"msgs": 0, "bytes": 0})
                vrow["msgs"] += int(vd.get("msgs", 0))
                vrow["bytes"] += int(vd.get("bytes", 0))
        return out

    def snapshot(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-node derived rows: beat cadence, rates, inbound latency."""
        now = time.monotonic() if now is None else now
        with self._lock:
            series = dict(self._series)
            links = dict(self._links)
        out: Dict[str, dict] = {}
        for node_id, s in series.items():
            beats = list(s.beats)
            row: dict = {
                "heartbeats": len(beats),
                "last_seen_s": round(now - beats[-1], 3) if beats else None,
            }
            if len(beats) >= 2:
                gaps = [b - a for a, b in zip(beats, beats[1:])]
                row["beat_interval_s"] = round(statistics.median(gaps), 3)
            res, prev = s.resource, s.prev_resource
            if res:
                if "rss_mb" in res:
                    row["rss_mb"] = round(res["rss_mb"], 1)
                dt = res.get("time", 0.0) - prev.get("time", 0.0)
                if prev and dt > 0 and "cpu_user_s" in res:
                    busy = (
                        res.get("cpu_user_s", 0.0) + res.get("cpu_sys_s", 0.0)
                        - prev.get("cpu_user_s", 0.0) - prev.get("cpu_sys_s", 0.0)
                    )
                    row["cpu_pct"] = round(100.0 * busy / dt, 1)
            net, pnet = s.net, s.prev_net
            if net and pnet and len(beats) >= 2:
                dt = beats[-1] - beats[-2]
                if dt > 0 and "wire_bytes" in net:
                    row["wire_bytes_per_s"] = round(
                        (net["wire_bytes"] - pnet.get("wire_bytes", 0)) / dt, 1
                    )
            if "offset_s" in s.clock:
                row["clock_offset_ms"] = round(1e3 * s.clock["offset_s"], 3)
                if s.clock.get("rtt_s") is not None:
                    row["clock_rtt_ms"] = round(1e3 * s.clock["rtt_s"], 3)
            h = self._inbound_hist(links, node_id)
            if h.count:
                row["push_p99_ms"] = round(1e3 * h.percentile(0.99), 3)
                row["push_p50_ms"] = round(1e3 * h.percentile(0.50), 3)
                row["inbound_count"] = h.count
            out[node_id] = row
        return out

    # -- detection -----------------------------------------------------------
    def stragglers(self, now: Optional[float] = None) -> Dict[str, List[str]]:
        """Nodes currently flagged, with human-readable reasons.

        Empty dict = healthy fleet.  Needs >= 2 reporting nodes — "k× the
        fleet median" is meaningless for a fleet of one.
        """
        now = time.monotonic() if now is None else now
        pol = self.policy
        flags: Dict[str, List[str]] = {}
        with self._lock:
            series = dict(self._series)
            links = dict(self._links)
        if len(series) < 2:
            return flags

        # gray failures: inbound push p99 vs fleet median
        p99s = {}
        for node_id in series:
            h = self._inbound_hist(links, node_id)
            if h.count >= pol.min_latency_count:
                p99s[node_id] = h.percentile(0.99)
        if len(p99s) >= 2:
            med = statistics.median(p99s.values())
            for node_id, p99 in p99s.items():
                if p99 > pol.k * med and p99 * 1e3 > pol.p99_floor_ms:
                    flags.setdefault(node_id, []).append(
                        f"inbound push p99 {p99 * 1e3:.1f}ms > "
                        f"{pol.k:g}x fleet median {med * 1e3:.1f}ms"
                    )

        # heartbeat-gap stragglers: silence vs fleet median beat interval
        intervals = {}
        for node_id, s in series.items():
            beats = list(s.beats)
            if len(beats) >= pol.min_heartbeats:
                gaps = [b - a for a, b in zip(beats, beats[1:])]
                if gaps:
                    intervals[node_id] = statistics.median(gaps)
        if len(intervals) >= 2:
            med = statistics.median(intervals.values())
            for node_id, s in series.items():
                if node_id not in intervals or not s.beats:
                    continue
                gap = now - s.beats[-1]
                if gap > pol.k * max(med, 1e-9) and gap > pol.gap_floor_s:
                    flags.setdefault(node_id, []).append(
                        f"heartbeat silent {gap:.2f}s > {pol.k:g}x fleet "
                        f"median interval {med:.2f}s"
                    )
        return flags

    # -- JSONL sink ----------------------------------------------------------
    def write_jsonl(
        self, now: Optional[float] = None, *, wall: Optional[float] = None
    ) -> Optional[dict]:
        """Append one fleet snapshot line to the attached ``jsonl`` stream.

        Returns the row (or None without a sink).  Call per monitor sweep;
        one line = one fleet-wide observation, replayable offline.
        ``wall``: the tick's shared wall-clock stamp — pass the same value
        the co-running ``Dashboard.record(now=...)`` uses so a slow dump
        cannot skew the two sinks' rate denominators apart.
        """
        if self.jsonl is None and self.jsonl_writer is None:
            return None
        now = time.monotonic() if now is None else now
        row = {
            "t": time.time() if wall is None else wall,
            "nodes": self.snapshot(now),
            "stragglers": self.stragglers(now),
        }
        line = json.dumps(row) + "\n"
        if self.jsonl_writer is not None:
            self.jsonl_writer.write_line(line)
        else:
            self.jsonl.write(line)
            self.jsonl.flush()
        return row

    def flush_jsonl(self) -> None:
        """Durably flush the JSONL sink (called by ``flightrec`` bundle
        dumps — the no-truncated-last-line guarantee)."""
        if self.jsonl_writer is not None:
            self.jsonl_writer.sync()
        elif self.jsonl is not None:
            self.jsonl.flush()
            fileno = getattr(self.jsonl, "fileno", None)
            if fileno is not None:
                try:
                    os.fsync(fileno())
                except (OSError, ValueError):
                    pass  # StringIO and friends have no real fd
