"""Flat self-describing wire frames: the zero-copy binary codec.

Replaces the pickle framing of ``core/tcp_van.py`` (ISSUE 7 tentpole).  A
frame is::

    [52-byte fixed header][meta section][key/value planes, back to back]

- **Fixed header** (little-endian, :data:`HEADER` layout): magic, version,
  Task kind, flags, array count, the transport stamps that every receiver
  wants *before* it touches the body — per-link sequence (``__rseq__``),
  sender incarnation (``__rinc__``), routing epoch (``__repoch__``), the
  resender's end-to-end payload CRC (``__rcrc__``) — plus the plane CRC32,
  the meta CRC32, the meta/plane section lengths, and a CRC32 over the
  header bytes themselves.  Dedup, incarnation fencing, and corruption
  rejection can all be decided from fixed offsets without decoding the
  meta section.
- **Meta section**: a compact tag-based binary encoding (``_enc_obj`` /
  ``_dec_obj`` — NO pickle on this path, enforced by
  ``tools/check_wrappers.py``) of the Task strings and payload dict,
  followed by a fixed binary manifest block (dtype string + shape per
  plane — known layout, no tag machinery).  Numpy scalars and enums decay
  to their Python values on the wire (receivers re-wrap, e.g.
  ``NodeRole(row["role"])``); unsupported types are a typed encode error,
  never a silent pickle fallback.
- **Planes**: each array's raw contiguous bytes, written straight from
  ``memoryview(a).cast("B")`` (zero ``tobytes()`` copies on send) and read
  back as ``np.frombuffer`` views over the received buffer (zero copies on
  receive — the SArray role end to end).

CRC layering: every frame section has its own check.  ``header_crc``
covers the fixed header bytes; ``meta_crc`` covers the meta section (Task
strings, payload dict, plane manifests — verified in :func:`decode` before
any meta parsing, so a flipped meta bit is a typed reject, never a garbled
payload delivered upstream or an untyped parse error on the recv thread);
``plane_crc`` covers the frame's plane bytes AS ENCODED (post-filter),
computed incrementally over the plane memoryviews during the same pass
that writes them and verified in one pass over the raw buffer before any
numpy reconstruction.  None of these is the resender's ``__rcrc__`` stamp
— that one is computed ABOVE the base van's filter chain
(pre-compression/quantization) and stays the end-to-end integrity check;
the header/meta/plane CRCs catch wire-level corruption at the transport
boundary, typed (:class:`FrameError`) instead of a recv-thread exception.

Stamp lifting is loss-free: :func:`encode` pops the stamp keys out of the
payload into header fields, :func:`decode` reinstates them, so every layer
above the codec (resender dedup/fencing, routing fences, migration) sees
bitwise-identical messages.  A stamp that is absent — or not a fixed-width
int — simply stays in the meta section (flag unset).

Sampled request tracing (ISSUE 18): a sampled request's trace context
(``core/tracectx.py``, payload key ``__trace__``) is ordinary meta — a
small dict of strings/floats the tag codec carries like any other payload
entry, decoded into a FRESH dict on every receive (which is what lets the
receiving van stamp its ``rx`` time into it without aliasing the sender's
object).  Unsampled requests omit the key entirely: their frames are
byte-identical to a tracing-off build (``frame_nbytes`` proves this in
tests), and an all-int payload stays eligible for ``_fast_encode``'s
cached-template path.  Old peers that predate the key simply decode and
ignore it — plain meta, no version gate (MIGRATION.md).
"""

from __future__ import annotations

import dataclasses
import enum
import struct
import zlib
from typing import Any, Callable, Optional, Tuple

import numpy as np

try:  # registers bfloat16/fp8 extension dtypes with numpy (ships with jax)
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover - jax env always has it
    ml_dtypes = None

from parameter_server_tpu.core.messages import (
    INCARNATION_KEY,
    Message,
    Task,
    TaskKind,
)
from parameter_server_tpu.core.van import Van, VanWrapper

#: transport stamp keys lifted into the fixed header (payload-borne above
#: the codec, header-borne on the wire).  SEQ/CRC are owned by
#: ``core/resender.py``, the epoch by ``kv/routing.py``; the literals are
#: repeated here (asserted equal in tests/test_frame.py) because importing
#: resender would put the stamp/verify module on this module's import path.
SEQ_KEY = "__rseq__"
CRC_KEY = "__rcrc__"
ROUTING_EPOCH_KEY = "__repoch__"

MAGIC = b"PF"
VERSION = 1

#: fixed header layout (52 bytes, little-endian).
HEADER = struct.Struct(
    "<2s"  # magic
    "B"    # version
    "B"    # Task kind (index into _KINDS)
    "H"    # flags
    "H"    # n_arrays (keys, when present, is plane 0)
    "q"    # seq        (valid iff FLAG_SEQ)
    "i"    # incarnation(valid iff FLAG_INC)
    "i"    # epoch      (valid iff FLAG_EPOCH)
    "I"    # e2e_crc    (valid iff FLAG_E2E_CRC — the resender's __rcrc__)
    "I"    # plane_crc32 over the plane bytes as framed
    "I"    # meta_crc32 over the meta section bytes
    "I"    # meta_len
    "Q"    # planes_len
    "I"    # header_crc32 over the 48 bytes above
)
HEADER_SIZE = HEADER.size  # 52

FLAG_REQUEST = 1 << 0
FLAG_HAS_KEYS = 1 << 1
FLAG_SEQ = 1 << 2
FLAG_INC = 1 << 3
FLAG_EPOCH = 1 << 4
FLAG_E2E_CRC = 1 << 5
#: one or more value planes are lossily quantized (ISSUE 14): the payload
#: carries a ``COMPRESSED_KEY`` marker describing per-plane codec/scale,
#: and receivers dequantize off the frombuffer plane view before H2D.
#: Purely informational at the frame layer (decode is marker-driven);
#: exists so wire captures / foreign receivers can tell a compressed
#: plane from a raw one without parsing the meta section.
FLAG_COMPRESSED = 1 << 6

#: payload key the quantizing codec stamps (``core/filters.py``); frames
#: whose payload carries it get ``FLAG_COMPRESSED`` set in the header.
COMPRESSED_KEY = "wc_meta"

_KINDS = (TaskKind.PUSH, TaskKind.PULL, TaskKind.CONTROL)
_KIND_INDEX = {k: i for i, k in enumerate(_KINDS)}

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1
_I32_MIN, _I32_MAX = -(1 << 31), (1 << 31) - 1


def plane_view(a: np.ndarray) -> memoryview:
    """Zero-copy byte view of a contiguous array.

    ``memoryview(a).cast("B")`` for native dtypes; extension dtypes
    (bfloat16/fp8 — no buffer-protocol format) go through a ``uint8`` view
    instead.  Either way: no ``tobytes()`` copy.
    """
    if not a.ndim:
        a = a.reshape(1)
    try:
        return memoryview(a).cast("B")
    except (ValueError, TypeError):
        return memoryview(a.view(np.uint8).reshape(-1))


class FrameError(ValueError):
    """Typed rejection of a malformed/truncated/corrupted frame.

    Receivers (``TcpVan._dispatch_loop``) catch exactly this, count the
    drop, and keep the recv thread alive — wire noise must read as loss
    (repaired by the resender), never as a dead transport.
    """


# ------------------------------------------------------------- meta codec
#
# Tag-based binary object encoding for the meta section.  Covers every
# payload shape the codebase puts on the wire (None/bool/int/float/str/
# bytes/list/tuple/dict/np scalar/np ndarray — e.g. routing tables, q8
# scale arrays, trace contexts, bundle indexes).  Tuples and lists keep
# their identity (filters compare payload dicts bitwise).

_T_NONE = 0
_T_TRUE = 1
_T_FALSE = 2
_T_INT64 = 3
_T_BIGINT = 4
_T_FLOAT = 5
_T_STR = 6
_T_BYTES = 7
_T_LIST = 8
_T_TUPLE = 9
_T_DICT = 10
_T_NDARRAY = 11

_pack_q = struct.Struct("<q").pack
_pack_d = struct.Struct("<d").pack
_pack_I = struct.Struct("<I").pack
_unpack_q = struct.Struct("<q").unpack_from
_unpack_d = struct.Struct("<d").unpack_from
_unpack_I = struct.Struct("<I").unpack_from
_pack_I_into = struct.Struct("<I").pack_into

#: dtype <-> canonical string caches.  ``str(np.dtype)`` walks numpy's
#: Python-level name machinery (~2us) and ``np.dtype(str)`` re-parses it;
#: the working set is a handful of dtypes per process, so both directions
#: memoize (hot enough to show up at the top of an encode profile).
_DTYPE_TO_STR: dict = {}
_STR_TO_DTYPE: dict = {}


def _dtype_str(dt) -> str:
    s = _DTYPE_TO_STR.get(dt)
    if s is None:
        s = _DTYPE_TO_STR[dt] = str(dt)
    return s


def _str_dtype(s: str) -> np.dtype:
    dt = _STR_TO_DTYPE.get(s)
    if dt is None:
        dt = _STR_TO_DTYPE[s] = np.dtype(s)
    return dt


#: per-ndim shape (de)serializers: one C pack/unpack call for the whole
#: shape tuple instead of a Python loop per dimension.
_SHAPE_STRUCTS: dict = {}


def _shape_struct(ndim: int) -> struct.Struct:
    st = _SHAPE_STRUCTS.get(ndim)
    if st is None:
        st = _SHAPE_STRUCTS[ndim] = struct.Struct(f"<{ndim}q")
    return st


def _contig(a: np.ndarray) -> np.ndarray:
    """ascontiguousarray without its call overhead for the common case.

    Keeps ascontiguousarray's ndmin=1 promotion (0-d frames as shape (1,),
    the seed codec's behavior) — 0-d arrays are contiguous, so the fast
    path must not keep them."""
    if type(a) is np.ndarray and a.ndim and a.flags.c_contiguous:
        return a
    return np.ascontiguousarray(a)


# per-type encoders dispatched on ``type(obj)`` — one dict lookup replaces
# the isinstance chain on the hottest path in ``encode`` (payload dicts).


def _enc_none(obj, out):
    out.append(_T_NONE)


def _enc_bool(obj, out):
    out.append(_T_TRUE if obj else _T_FALSE)


def _enc_int(obj, out):
    if _I64_MIN <= obj <= _I64_MAX:
        out.append(_T_INT64)
        out += _pack_q(obj)
    else:
        raw = obj.to_bytes((obj.bit_length() + 8) // 8, "little", signed=True)
        out.append(_T_BIGINT)
        out += _pack_I(len(raw))
        out += raw


def _enc_float(obj, out):
    out.append(_T_FLOAT)
    out += _pack_d(obj)


def _enc_str(obj, out):
    raw = obj.encode("utf-8")
    out.append(_T_STR)
    out += _pack_I(len(raw))
    out += raw


#: encoded-record memo for the identity strings every frame carries
#: (customer, sender, recver) — node ids and customer names form a small
#: fixed set per process, so their tag+len+utf8 records are precomputable.
#: Bounded: an unbounded payload string must never grow it.
_NAME_ENC_CACHE: dict = {}


def _enc_name(obj, out):
    rec = _NAME_ENC_CACHE.get(obj)
    if rec is None:
        raw = obj.encode("utf-8")
        rec = bytes((_T_STR,)) + _pack_I(len(raw)) + raw
        if len(_NAME_ENC_CACHE) < 4096:
            _NAME_ENC_CACHE[obj] = rec
    out += rec


def _enc_bytes(obj, out):
    out.append(_T_BYTES)
    out += _pack_I(len(obj))
    out += obj


def _enc_list(obj, out):
    out.append(_T_LIST)
    out += _pack_I(len(obj))
    for item in obj:
        _enc_obj(item, out)


def _enc_tuple(obj, out):
    out.append(_T_TUPLE)
    out += _pack_I(len(obj))
    for item in obj:
        _enc_obj(item, out)


def _enc_dict(obj, out):
    out.append(_T_DICT)
    out += _pack_I(len(obj))
    for k, v in obj.items():
        _enc_obj(k, out)
        _enc_obj(v, out)


def _enc_ndarray(obj, out):
    a = _contig(obj)
    dt = _dtype_str(a.dtype).encode("ascii")
    out.append(_T_NDARRAY)
    out.append(len(dt))
    out += dt
    out.append(a.ndim)
    if a.ndim:
        out += _shape_struct(a.ndim).pack(*a.shape)
    out += plane_view(a)


_ENC_DISPATCH: dict = {
    type(None): _enc_none,
    bool: _enc_bool,
    int: _enc_int,
    float: _enc_float,
    str: _enc_str,
    bytes: _enc_bytes,
    list: _enc_list,
    tuple: _enc_tuple,
    dict: _enc_dict,
    np.ndarray: _enc_ndarray,
}


def _enc_obj(obj: Any, out: bytearray) -> None:
    enc = _ENC_DISPATCH.get(type(obj))
    if enc is not None:
        enc(obj, out)
    elif isinstance(obj, np.ndarray):
        _enc_ndarray(obj, out)
    elif isinstance(obj, (np.bool_, np.integer, np.floating)):
        # numpy scalars decay to their Python value (payloads compare
        # equal; nothing round-trips scalar *types* on the wire)
        _enc_obj(obj.item(), out)
    elif isinstance(obj, enum.Enum):
        # enums (TaskKind, NodeRole, ...) decay to .value — NOT str(obj),
        # which is the qualified name on 3.10 and breaks receivers that
        # re-wrap, e.g. NodeRole(row["role"]) in core/manager.py
        _enc_obj(obj.value, out)
    elif isinstance(obj, int):  # bool handled above; int subclasses decay
        _enc_int(int(obj), out)
    elif isinstance(obj, str):
        _enc_str(str(obj), out)
    else:
        raise FrameError(
            f"meta codec cannot encode {type(obj).__name__!r} "
            "(wire payloads are plain data: None/bool/int/float/str/bytes/"
            "list/tuple/dict/ndarray)"
        )


def _dec_obj(buf, pos: int) -> Tuple[Any, int]:
    try:
        tag = buf[pos]
        pos += 1
        if tag == _T_NONE:
            return None, pos
        if tag == _T_TRUE:
            return True, pos
        if tag == _T_FALSE:
            return False, pos
        if tag == _T_INT64:
            return _unpack_q(buf, pos)[0], pos + 8
        if tag == _T_BIGINT:
            n = _unpack_I(buf, pos)[0]
            pos += 4
            raw = bytes(buf[pos : pos + n])
            if len(raw) != n:
                raise FrameError("meta truncated inside bigint")
            return int.from_bytes(raw, "little", signed=True), pos + n
        if tag == _T_FLOAT:
            return _unpack_d(buf, pos)[0], pos + 8
        if tag == _T_STR:
            n = _unpack_I(buf, pos)[0]
            pos += 4
            raw = bytes(buf[pos : pos + n])
            if len(raw) != n:
                raise FrameError("meta truncated inside str")
            return raw.decode("utf-8"), pos + n
        if tag == _T_BYTES:
            n = _unpack_I(buf, pos)[0]
            pos += 4
            raw = bytes(buf[pos : pos + n])
            if len(raw) != n:
                raise FrameError("meta truncated inside bytes")
            return raw, pos + n
        if tag in (_T_LIST, _T_TUPLE):
            n = _unpack_I(buf, pos)[0]
            pos += 4
            items = []
            for _ in range(n):
                item, pos = _dec_obj(buf, pos)
                items.append(item)
            return (tuple(items) if tag == _T_TUPLE else items), pos
        if tag == _T_DICT:
            n = _unpack_I(buf, pos)[0]
            pos += 4
            d = {}
            for _ in range(n):
                k, pos = _dec_obj(buf, pos)
                v, pos = _dec_obj(buf, pos)
                d[k] = v
            return d, pos
        if tag == _T_NDARRAY:
            dlen = buf[pos]
            pos += 1
            dt = _str_dtype(bytes(buf[pos : pos + dlen]).decode("ascii"))
            pos += dlen
            ndim = buf[pos]
            pos += 1
            shape = _shape_struct(ndim).unpack_from(buf, pos) if ndim else ()
            pos += 8 * ndim
            n = 1
            for d in shape:
                if d < 0:
                    # a negative dim makes the truncation check below pass
                    # (negative nbytes), frombuffer read to the buffer end,
                    # and pos move BACKWARDS — silent mis-parse, not reject
                    raise FrameError(f"negative ndarray dim {d} in meta")
                n *= d
            nbytes = n * dt.itemsize
            if pos + nbytes > len(buf):
                raise FrameError("meta truncated inside ndarray")
            arr = np.frombuffer(buf, dtype=dt, count=n, offset=pos)
            return arr.reshape(shape), pos + nbytes
        raise FrameError(f"unknown meta tag {tag}")
    except FrameError:
        raise
    except (IndexError, struct.error, UnicodeDecodeError, TypeError,
            ValueError, OverflowError) as e:
        # garbled bytes surface as many exception types (np.dtype parse,
        # frombuffer size math, int-to-ssize_t overflow, ...); ALL of them
        # must become the one typed reject the recv thread catches
        raise FrameError(f"garbled meta section: {e}") from e


# ------------------------------------------------------------ frame codec


#: stamp key -> the header-field range ``encode`` lifts it within; values
#: outside (or non-int) ride the meta section instead (flag unset).
#: ``frame_nbytes`` filters by the SAME ranges so its estimate stays exact
#: for out-of-range stamp values.
_STAMP_RANGES = {
    SEQ_KEY: (_I64_MIN, _I64_MAX),
    INCARNATION_KEY: (_I32_MIN, _I32_MAX),
    ROUTING_EPOCH_KEY: (_I32_MIN, _I32_MAX),
    CRC_KEY: (0, 0xFFFFFFFF),
}


def _lift_int(payload: dict, key: str, lo: int, hi: int):
    """Pop ``payload[key]`` when it is a header-width int, else leave it."""
    v = payload.get(key)
    if type(v) is int and lo <= v <= hi:
        del payload[key]
        return v
    return None


# ---------------------------------------------------- control-frame fast path
#
# No-plane control frames (resender ACKs above all: every data frame costs
# one) have META-STABLE layouts: the same (kind, customer, sender, recver,
# payload-key) signature encodes to the same bytes except for a handful of
# 8-byte int slots (time, wait_time, the meta-resident payload ints) and
# the header stamps.  ``_fast_encode`` caches the fully-encoded template
# per signature and per call only copies it, patches the int slots, and
# re-CRCs — skipping the whole meta codec walk.  Output is BYTE-IDENTICAL
# to the slow path (the payload dict is never mutated); anything outside
# the eligible shape (planes, non-int values, out-of-range stamps, non-str
# names/keys) falls through to the general encoder.

_pack_q_into = struct.Struct("<q").pack_into

_FAST_CACHE_CAP = 1024
_FAST_ENC_CACHE: dict = {}


class _FastEntry:
    __slots__ = ("buf", "slots", "dispo", "kind_idx")

    def __init__(self, buf, slots, dispo, kind_idx):
        self.buf = buf          # header placeholder + meta template bytes
        self.slots = slots      # buf offsets of the 8-byte int patch slots
        self.dispo = dispo      # [(payload key, stamp key | None), ...]
        self.kind_idx = kind_idx


def _build_fast_entry(msg: Message):
    task = msg.task
    payload = task.payload
    kind_idx = _KIND_INDEX.get(task.kind)
    if kind_idx is None:
        return None
    dispo = []
    for k, v in payload.items():
        if type(k) is not str:
            return None
        if k in _STAMP_RANGES:
            dispo.append((k, k))
        else:
            if type(v) is not int:
                return None
            dispo.append((k, None))
    meta = bytearray()
    for name in (task.customer, msg.sender, msg.recver):
        _enc_name(name, meta)
    slots = []
    for _ in range(2):  # time, wait_time
        slots.append(HEADER_SIZE + len(meta) + 1)
        meta.append(_T_INT64)
        meta += _pack_q(0)
    meta.append(_T_DICT)
    meta += _pack_I(sum(1 for _, s in dispo if s is None))
    for k, stamp in dispo:
        if stamp is None:
            _enc_name(k, meta)  # same record _enc_str writes for dict keys
            slots.append(HEADER_SIZE + len(meta) + 1)
            meta.append(_T_INT64)
            meta += _pack_q(0)
    return _FastEntry(
        bytes(HEADER_SIZE) + bytes(meta), tuple(slots), tuple(dispo), kind_idx
    )


def _fast_encode(msg: Message) -> Optional[bytes]:
    """Encode an eligible no-plane control frame off the template cache;
    None = not eligible (caller runs the general path)."""
    task = msg.task
    payload = task.payload
    if (
        type(payload) is not dict
        or type(task.customer) is not str
        or type(msg.sender) is not str
        or type(msg.recver) is not str
        or type(task.time) is not int
        or type(task.wait_time) is not int
        or not _I64_MIN <= task.time <= _I64_MAX
        or not _I64_MIN <= task.wait_time <= _I64_MAX
    ):
        return None
    key = (task.kind, task.customer, msg.sender, msg.recver, tuple(payload))
    entry = _FAST_ENC_CACHE.get(key)
    if entry is None:
        entry = _build_fast_entry(msg)
        if entry is None:
            return None
        if len(_FAST_ENC_CACHE) < _FAST_CACHE_CAP:
            _FAST_ENC_CACHE[key] = entry
    vals = [task.time, task.wait_time]
    seq = inc = epoch = e2e = None
    for k, stamp in entry.dispo:
        v = payload[k]
        if type(v) is not int:
            return None
        if stamp is None:
            if not _I64_MIN <= v <= _I64_MAX:
                return None
            vals.append(v)
        else:
            lo, hi = _STAMP_RANGES[stamp]
            if not lo <= v <= hi:
                return None  # out-of-range stamp rides meta: general path
            if stamp == SEQ_KEY:
                seq = v
            elif stamp == INCARNATION_KEY:
                inc = v
            elif stamp == ROUTING_EPOCH_KEY:
                epoch = v
            else:
                e2e = v
    buf = bytearray(entry.buf)
    for off, v in zip(entry.slots, vals):
        _pack_q_into(buf, off, v)
    flags = FLAG_REQUEST if msg.is_request else 0
    if seq is not None:
        flags |= FLAG_SEQ
    if inc is not None:
        flags |= FLAG_INC
    if epoch is not None:
        flags |= FLAG_EPOCH
    if e2e is not None:
        flags |= FLAG_E2E_CRC
    mv = memoryview(buf)
    HEADER.pack_into(
        buf, 0,
        MAGIC,
        VERSION,
        entry.kind_idx,
        flags,
        0,
        seq if seq is not None else 0,
        inc if inc is not None else 0,
        epoch if epoch is not None else 0,
        e2e if e2e is not None else 0,
        0,  # plane crc of zero planes
        zlib.crc32(mv[HEADER_SIZE:]),
        len(buf) - HEADER_SIZE,
        0,
        0,  # header crc placeholder
    )
    _pack_I_into(buf, HEADER_SIZE - 4, zlib.crc32(mv[: HEADER_SIZE - 4]))
    return bytes(buf)


def encode(msg: Message) -> bytes:
    """Message -> flat frame bytes.  One output allocation (``b"".join``);
    array planes are read straight through their buffers — no ``tobytes()``
    intermediates on the send side.  No-plane control frames (ACKs) take
    the cached-template fast path when eligible — byte-identical output."""
    if msg.keys is None and not msg.values:
        fast = _fast_encode(msg)
        if fast is not None:
            return fast
    head, meta, planes, _planes_len = _encode_parts(msg)
    return b"".join([head, meta] + planes)


def encode_vec(msg: Message) -> Tuple[list, int]:
    """Message -> ``(segments, total_len)`` for vectored (``writev``/shm)
    sends: byte-identical to :func:`encode` when the segments are laid end
    to end, but the value planes stay SEPARATE zero-copy views over the
    original array buffers — a coalesced bundle's member gradients go from
    their source buffers to the wire without ever concatenating host-side.
    The first segment is the fixed header + meta section (one small
    bytearray); every following segment is a plane ``memoryview``."""
    if msg.keys is None and not msg.values:
        fast = _fast_encode(msg)
        if fast is not None:
            return [fast], len(fast)
    head, meta, planes, planes_len = _encode_parts(msg)
    head += meta  # bytearray extend: header+meta ride one iovec slot
    return [head] + planes, len(head) + planes_len


def _encode_parts(msg: Message) -> Tuple[bytearray, bytearray, list, int]:
    """Shared general-path body of :func:`encode`/:func:`encode_vec`:
    ``(header, meta, plane_views, planes_len)``."""
    arrays = []
    for a in ([msg.keys] if msg.keys is not None else []) + list(msg.values):
        arrays.append(_contig(a))

    payload = msg.task.payload
    flags = FLAG_REQUEST if msg.is_request else 0
    if msg.keys is not None:
        flags |= FLAG_HAS_KEYS
    seq = inc = epoch = e2e = None
    if isinstance(payload, dict) and payload:
        lifted = {
            k: v
            for k, v in payload.items()
            # only int values of header width lift; anything else rides meta
        }
        seq = _lift_int(lifted, SEQ_KEY, *_STAMP_RANGES[SEQ_KEY])
        inc = _lift_int(lifted, INCARNATION_KEY,
                        *_STAMP_RANGES[INCARNATION_KEY])
        epoch = _lift_int(lifted, ROUTING_EPOCH_KEY,
                          *_STAMP_RANGES[ROUTING_EPOCH_KEY])
        e2e = _lift_int(lifted, CRC_KEY, *_STAMP_RANGES[CRC_KEY])
        payload = lifted
    if seq is not None:
        flags |= FLAG_SEQ
    if inc is not None:
        flags |= FLAG_INC
    if epoch is not None:
        flags |= FLAG_EPOCH
    if e2e is not None:
        flags |= FLAG_E2E_CRC
    if isinstance(payload, dict) and COMPRESSED_KEY in payload:
        # lossy-quantized plane(s) aboard: decode stays marker-driven, the
        # header bit is for captures/foreign receivers (and MIGRATION.md)
        flags |= FLAG_COMPRESSED

    meta = bytearray()
    for name in (msg.task.customer, msg.sender, msg.recver):
        (_enc_name if type(name) is str else _enc_obj)(name, meta)
    _enc_obj(msg.task.time, meta)
    _enc_obj(msg.task.wait_time, meta)
    _enc_obj(payload, meta)
    # manifest block: a fixed binary record per plane (dtype str, shape) —
    # NOT the generic object codec; this is every frame's hottest meta and
    # its layout is known, so it skips the tag machinery entirely
    plane_crc = 0
    planes = []
    planes_len = 0
    for a in arrays:
        dt = _dtype_str(a.dtype).encode("ascii")
        meta.append(len(dt))
        meta += dt
        meta.append(a.ndim)
        if a.ndim:
            meta += _shape_struct(a.ndim).pack(*a.shape)
        mv = plane_view(a)
        plane_crc = zlib.crc32(mv, plane_crc)
        planes.append(mv)
        planes_len += len(mv)

    if len(arrays) > 0xFFFF:
        raise FrameError(
            f"{len(arrays)} planes exceed the u16 n_arrays field "
            "(split the bundle)"
        )
    if len(meta) > 0xFFFFFFFF:
        raise FrameError(
            f"{len(meta)}-byte meta section exceeds the u32 meta_len field"
        )
    head = bytearray(HEADER_SIZE)
    HEADER.pack_into(
        head, 0,
        MAGIC,
        VERSION,
        _KIND_INDEX[msg.task.kind],
        flags,
        len(arrays),
        seq if seq is not None else 0,
        inc if inc is not None else 0,
        epoch if epoch is not None else 0,
        e2e if e2e is not None else 0,
        plane_crc & 0xFFFFFFFF,
        zlib.crc32(meta),
        len(meta),
        planes_len,
        0,  # header crc placeholder
    )
    _pack_I_into(head, HEADER_SIZE - 4,
                 zlib.crc32(memoryview(head)[: HEADER_SIZE - 4]))
    return head, meta, planes, planes_len


@dataclasses.dataclass(frozen=True)
class FrameInfo:
    """Decoded fixed header — everything dedup/fencing/accounting needs
    without touching the meta section or planes."""

    version: int
    kind: TaskKind
    flags: int
    n_arrays: int
    seq: Optional[int]
    incarnation: Optional[int]
    epoch: Optional[int]
    e2e_crc: Optional[int]
    plane_crc: int
    meta_crc: int
    meta_len: int
    planes_len: int

    @property
    def is_request(self) -> bool:
        return bool(self.flags & FLAG_REQUEST)

    @property
    def overhead(self) -> int:
        """Non-plane frame bytes: fixed header + meta section."""
        return HEADER_SIZE + self.meta_len


def peek(buf) -> FrameInfo:
    """Validate and read the fixed header alone (no meta/plane decode).

    Raises :class:`FrameError` on anything short of a well-formed header
    over a complete frame: truncation, bad magic/version, a header CRC
    mismatch (garbled headers are *typed* rejects, not struct errors
    escaping on the recv thread), or section lengths past the buffer.
    """
    if len(buf) < HEADER_SIZE:
        raise FrameError(
            f"truncated frame: {len(buf)} bytes < {HEADER_SIZE}-byte header"
        )
    (
        magic, version, kind_i, flags, n_arrays,
        seq, inc, epoch, e2e, plane_crc, meta_crc, meta_len, planes_len,
        hcrc,
    ) = HEADER.unpack_from(buf, 0)
    mv = memoryview(buf) if not isinstance(buf, memoryview) else buf
    if zlib.crc32(mv[: HEADER_SIZE - 4]) != hcrc:
        raise FrameError("header CRC mismatch (garbled header)")
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r}")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if kind_i >= len(_KINDS):
        raise FrameError(f"bad task kind {kind_i}")
    if HEADER_SIZE + meta_len + planes_len != len(buf):
        raise FrameError(
            f"frame length mismatch: header says "
            f"{HEADER_SIZE}+{meta_len}+{planes_len}, buffer has {len(buf)}"
        )
    return FrameInfo(
        version=version,
        kind=_KINDS[kind_i],
        flags=flags,
        n_arrays=n_arrays,
        seq=seq if flags & FLAG_SEQ else None,
        incarnation=inc if flags & FLAG_INC else None,
        epoch=epoch if flags & FLAG_EPOCH else None,
        e2e_crc=e2e if flags & FLAG_E2E_CRC else None,
        plane_crc=plane_crc,
        meta_crc=meta_crc,
        meta_len=meta_len,
        planes_len=planes_len,
    )


def verify_planes(buf, info: Optional[FrameInfo] = None) -> bool:
    """One-pass plane CRC check over the raw buffer — zero numpy work."""
    if info is None:
        info = peek(buf)
    mv = memoryview(buf) if not isinstance(buf, memoryview) else buf
    start = HEADER_SIZE + info.meta_len
    crc = zlib.crc32(mv[start : start + info.planes_len])
    return crc == info.plane_crc


def decode(buf, *, verify: bool = True) -> Message:
    """Flat frame bytes -> Message; arrays are zero-copy views over ``buf``.

    ``verify=True`` (the wire path) CRC-checks the plane bytes in one pass
    over the raw buffer and raises :class:`FrameError` on mismatch —
    BEFORE any meta decode or array reconstruction.  ``verify=False`` is
    for callers that intentionally decode damaged planes (ChaosVan's
    bit-flip injection, which relies on the resender's end-to-end CRC to
    catch the corruption downstream).  The meta CRC is checked on BOTH
    paths: a garbled meta section cannot be parsed meaningfully, only
    rejected (ChaosVan flips plane bytes exclusively, so this never fires
    on its injection path).
    """
    # header handling is inlined (same checks, same order, same typed
    # rejects as peek()) rather than routed through peek(): this is the
    # per-frame hot path of every wire AND shm receive, and building a
    # frozen FrameInfo per frame costs more than the whole plane CRC
    if len(buf) < HEADER_SIZE:
        raise FrameError(
            f"truncated frame: {len(buf)} bytes < {HEADER_SIZE}-byte header"
        )
    (
        magic, version, kind_i, flags, n_arrays,
        seq, inc, epoch, e2e, plane_crc, meta_crc, meta_len, planes_len,
        hcrc,
    ) = HEADER.unpack_from(buf, 0)
    mv = memoryview(buf) if not isinstance(buf, memoryview) else buf
    if zlib.crc32(mv[: HEADER_SIZE - 4]) != hcrc:
        raise FrameError("header CRC mismatch (garbled header)")
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r}")
    if version != VERSION:
        raise FrameError(f"unsupported frame version {version}")
    if kind_i >= len(_KINDS):
        raise FrameError(f"bad task kind {kind_i}")
    meta_end = HEADER_SIZE + meta_len
    if meta_end + planes_len != len(buf):
        raise FrameError(
            f"frame length mismatch: header says "
            f"{HEADER_SIZE}+{meta_len}+{planes_len}, buffer has {len(buf)}"
        )
    if verify and zlib.crc32(mv[meta_end : meta_end + planes_len]) != plane_crc:
        raise FrameError("plane CRC mismatch (corrupt frame body)")
    meta = mv[HEADER_SIZE:meta_end]
    if zlib.crc32(meta) != meta_crc:
        raise FrameError("meta CRC mismatch (corrupt meta section)")
    customer, p = _dec_obj(meta, 0)
    sender, p = _dec_obj(meta, p)
    recver, p = _dec_obj(meta, p)
    time_, p = _dec_obj(meta, p)
    wait_time, p = _dec_obj(meta, p)
    payload, p = _dec_obj(meta, p)
    if not isinstance(payload, dict):
        raise FrameError("meta section inconsistent with header")
    # reinstate the lifted stamps: layers above the codec see the payload
    # dict bitwise as the sender's stack stamped it
    if flags & FLAG_SEQ:
        payload[SEQ_KEY] = seq
    if flags & FLAG_INC:
        payload[INCARNATION_KEY] = inc
    if flags & FLAG_EPOCH:
        payload[ROUTING_EPOCH_KEY] = epoch
    if flags & FLAG_E2E_CRC:
        payload[CRC_KEY] = e2e
    # manifest block (fixed binary records, one per plane — see encode)
    # fused with plane reconstruction: one pass, no intermediate tuples
    arrays = []
    off = meta_end
    try:
        for _ in range(n_arrays):
            dlen = meta[p]
            p += 1
            dt = _str_dtype(bytes(meta[p : p + dlen]).decode("ascii"))
            p += dlen
            ndim = meta[p]
            p += 1
            if ndim:
                shape = _shape_struct(ndim).unpack_from(meta, p)
                p += 8 * ndim
                n = 1
                for d in shape:
                    if d < 0:
                        raise FrameError(
                            f"negative plane dim in manifest: {shape}"
                        )
                    n *= d
            else:
                shape = ()
                n = 1
            arrays.append(
                np.frombuffer(mv, dtype=dt, count=n, offset=off).reshape(shape)
            )
            off += n * dt.itemsize
    except FrameError:
        raise
    except (IndexError, struct.error, UnicodeDecodeError, TypeError,
            ValueError, OverflowError) as e:
        # same contract as _dec_obj: EVERY decode failure mode is the one
        # typed reject — nothing escapes to kill the recv thread
        raise FrameError(f"garbled manifest block: {e}") from e
    keys = arrays.pop(0) if flags & FLAG_HAS_KEYS else None
    return Message(
        task=Task(
            kind=_KINDS[kind_i], customer=customer, time=time_,
            wait_time=wait_time, payload=payload,
        ),
        sender=sender,
        recver=recver,
        keys=keys,
        values=arrays,
        is_request=bool(flags & FLAG_REQUEST),
    )


def frame_nbytes(msg: Message) -> Tuple[int, int]:
    """(total frame bytes, non-plane overhead bytes) for ``msg`` as the
    codec would put it on the wire — exact, without building the frame.

    Plane sizes come from ``nbytes`` attributes (no device sync for
    ``jax.Array`` values); the overhead is the fixed header plus the meta
    section actually encoded (stamps lifted into the header contribute
    zero variable bytes, so the estimate is invariant to resender/metering
    stamps by construction).
    """
    planes = int(getattr(msg.keys, "nbytes", 0) or 0)
    manifest_len = 0
    if msg.keys is not None:
        # max(ndim, 1): the codec frames 0-d planes as shape (1,)
        manifest_len += (
            2 + len(_dtype_str(msg.keys.dtype)) + 8 * max(msg.keys.ndim, 1)
        )
    for v in msg.values:
        nb = getattr(v, "nbytes", None)
        if nb is None:
            v = np.asarray(v)
            nb = v.nbytes
        planes += int(nb)
        manifest_len += 2 + len(_dtype_str(v.dtype)) + 8 * max(v.ndim, 1)
    payload = msg.task.payload
    if isinstance(payload, dict) and payload:
        # drop exactly the stamps encode would lift: int-typed AND within
        # the header field's range — an out-of-range stamp rides the meta
        # section in the real frame, so it must stay in the estimate too
        payload = {
            k: v
            for k, v in payload.items()
            if (r := _STAMP_RANGES.get(k)) is None
            or type(v) is not int
            or not r[0] <= v <= r[1]
        }
    meta = bytearray()
    for name in (msg.task.customer, msg.sender, msg.recver):
        (_enc_name if type(name) is str else _enc_obj)(name, meta)
    _enc_obj(msg.task.time, meta)
    _enc_obj(msg.task.wait_time, meta)
    _enc_obj(payload, meta)
    overhead = HEADER_SIZE + len(meta) + manifest_len
    return overhead + planes, overhead


class FrameCodecVan(VanWrapper):
    """Force every message through the flat wire representation.

    In-process stacks (LoopbackVan) normally deliver Message objects by
    reference; wrapping the base van in a ``FrameCodecVan`` makes them ride
    the exact bytes a TcpVan would put on the wire — encode, then decode
    into frombuffer views — so parity/chaos tests exercise the production
    frame path without sockets.  Non-codable messages (device-resident
    values) pass through unframed, counted in ``frame_passthrough``.
    """

    def __init__(self, inner: Van) -> None:
        super().__init__(inner)
        self.frames = 0
        self.frame_bytes = 0
        self.frame_overhead_bytes = 0
        self.frame_passthrough = 0
        self.frame_rejects = 0

    def send(self, msg: Message) -> bool:
        try:
            data = encode(msg)
        except FrameError:
            self.frame_passthrough += 1
            return self.inner.send(msg)
        try:
            out = decode(data)
        except FrameError:
            self.frame_rejects += 1
            return True  # accepted by the "wire", lost to corruption
        self.frames += 1
        self.frame_bytes += len(data)
        self.frame_overhead_bytes += peek(data).overhead
        return self.inner.send(out)

    def counters(self) -> dict:
        return {
            "frames": self.frames,
            "frame_bytes": self.frame_bytes,
            "frame_overhead_bytes": self.frame_overhead_bytes,
            "frame_passthrough": self.frame_passthrough,
            "frame_rejects": self.frame_rejects,
        }
