"""ChaosVan: seeded, deterministic fault injection for any Van.

The reference tolerated lossy asynchronous networks but never shipped a way
to *prove* it: ``script/local.sh`` integration runs exercised the happy path
only (SURVEY.md §4 "opportunity").  This module is the missing harness — a
Van decorator that injects in-flight faults between ``send`` and delivery:

- **drop**: the message is silently lost (the sender still sees ``True`` —
  a real network cannot tell you at send time, which is exactly the failure
  mode the fire-and-forget Van could not express before: ``disconnect`` is
  rejected-at-send, drop is lost-in-flight);
- **latency**: fixed delay plus uniform jitter, delivered via a timer wheel
  so in-order timestamps keep per-link FIFO and jitter breaks it;
- **duplicate**: the message is delivered twice (what a retransmitting
  sender looks like from the receiver's side);
- **reorder**: an extra delay penalty that lets the next message on the
  link overtake this one;
- **partition**: per-link blackholes, asymmetric by default (A can reach B
  while B cannot reach A — the split-brain shape ``disconnect`` cannot
  model);
- **slow** (gray failure): a fixed extra delivery delay, per link
  (``ChaosConfig.slow_ms``) or per NODE (:meth:`ChaosVan.slow_node` slows
  every link INTO the node) — the slow-but-alive shape the ROADMAP names
  as unmodeled.  A slowed node still heartbeats on time, so liveness
  sweeps never fire; only per-link latency attribution
  (``core/netmon.py`` -> ``core/fleet.py``) can see it.  Inbound-only by
  design: a gray node's observable symptom is work queueing at ITS door,
  and metering attributes deliver latency to the destination, so the
  detector's signal lands on the right node.
- **corrupt**: one payload bit flipped in flight, in a COPY of one
  keys/values array (the sender's buffer is a retransmit source and is
  never mutated).  Caught end-to-end by the CRC32 integrity stamp in
  ``core/resender.py`` (``rejected_corrupt``); the dropped ACK makes the
  sender retransmit the pristine original, so recovery is automatic;
- **bandwidth** (``ChaosConfig.bandwidth_bps``): a per-link deterministic
  token bucket over payload bytes — each delivery waits for the link's
  virtual transmit clock, modeling a capped pipe without any RNG draws.

Determinism: every decision comes from a per-link ``random.Random`` keyed
by ``(seed, sender, recver)`` via crc32, and exactly four uniforms are
drawn per message regardless of config, so a fixed seed plus a fixed
per-link send order yields the identical fault sequence run over run.
(Per-link send order is single-threaded everywhere in this codebase —
submitting threads on the requester side, the endpoint recv thread on the
responder side — so seeded chaos tests are reproducible; see
tests/test_chaos.py.)

Pair with :class:`~parameter_server_tpu.core.resender.ReliableVan` *above*
this wrapper (``ReliableVan(ChaosVan(LoopbackVan()))``) to prove exactly-
once delivery under loss.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import logging
import random
import threading
import time
import zlib
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from parameter_server_tpu.core import flightrec, frame
from parameter_server_tpu.core.messages import Message
from parameter_server_tpu.core.van import Van, VanWrapper


def payload_nbytes(msg: Message) -> int:
    """Wire size of a message's bulk payload (keys + values), in bytes.

    Only objects exposing ``nbytes`` count (numpy / device arrays); the
    small dict payload is control-plane noise next to them and is ignored,
    which keeps the bandwidth model focused on the data plane.
    """
    size = int(getattr(msg.keys, "nbytes", 0) or 0)
    for v in msg.values:
        size += int(getattr(v, "nbytes", 0) or 0)
    return size


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Per-link fault rates.  All probabilities in [0, 1]; delays in sec."""

    #: P(message silently lost in flight).
    drop: float = 0.0
    #: P(message delivered twice).
    duplicate: float = 0.0
    #: P(message delayed past its successor on the link).
    reorder: float = 0.0
    #: fixed added latency.
    delay: float = 0.0
    #: uniform extra latency in [0, jitter).
    jitter: float = 0.0
    #: penalty added on a reorder hit (must exceed the link's typical
    #: inter-send gap to actually swap adjacent messages).
    reorder_delay: float = 0.01
    #: gray failure: fixed extra delivery delay (milliseconds) on this
    #: link.  Deterministic — no RNG draw — so a slowed link never shifts
    #: the fault sequence of drop/dup/reorder decisions.
    slow_ms: float = 0.0
    #: P(one payload bit flipped in flight).  Draws come from a SEPARATE
    #: per-link RNG stream (keyed ``corrupt:``), so enabling corruption
    #: never shifts the seeded drop/dup/reorder schedule of this or any
    #: other link.  The flip lands in a COPY of one key/value array — the
    #: sender's buffer (a retransmit source) is never touched.
    corrupt: float = 0.0
    #: per-link bandwidth cap in bytes/sec (0 = uncapped): a deterministic
    #: token bucket over payload bytes delays each delivery until the
    #: link's virtual transmit clock frees up.  Zero RNG draws, so seeded
    #: fault schedules are unperturbed; FIFO is preserved (delays are
    #: monotone along a link).
    bandwidth_bps: float = 0.0

    @property
    def randomized(self) -> bool:
        """Any stochastic fault enabled — exactly these configs consume the
        four per-message RNG draws, so adding ``slow_ms`` to a link can
        never shift the seeded fault sequence of any other fault."""
        return not (
            self.drop == 0.0
            and self.duplicate == 0.0
            and self.reorder == 0.0
            and self.delay == 0.0
            and self.jitter == 0.0
        )

    @property
    def inert(self) -> bool:
        return (
            not self.randomized
            and self.slow_ms == 0.0
            and self.corrupt == 0.0
            and self.bandwidth_bps == 0.0
        )


class TimerWheel:
    """Deferred executor: ``schedule(delay, fn)`` runs ``fn`` on one wheel
    thread at ``now + delay``, ordered by (due time, enqueue order) — equal
    delays therefore preserve enqueue order (per-link FIFO under fixed
    latency), while jittered delays reorder, which is the point."""

    def __init__(self, name: str = "chaos-wheel") -> None:
        self._heap: list = []
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    def schedule(self, delay: float, fn: Callable[[], None]) -> None:
        due = time.monotonic() + max(delay, 0.0)
        with self._cond:
            if self._stopped:
                return
            heapq.heappush(self._heap, (due, next(self._seq), fn))
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._stopped:
                    if not self._heap:
                        self._cond.wait()
                        continue
                    wait = self._heap[0][0] - time.monotonic()
                    if wait <= 0:
                        break
                    self._cond.wait(wait)
                if self._stopped:
                    return
                _due, _n, fn = heapq.heappop(self._heap)
            try:
                fn()
            except Exception:  # noqa: BLE001 — a bad delivery must not kill
                # the only wheel thread (all later delayed messages would
                # silently never fire)
                logging.getLogger(__name__).exception(
                    "chaos: deferred delivery failed"
                )

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify()
        self._thread.join(timeout=5)


class ChaosVan(VanWrapper):
    """Fault-injecting Van decorator.  See module docstring.

    ``send`` always returns True (unless the van is closed): the chaos
    layer models a network that *accepted* the frame — whether it arrives
    is decided in flight.  Inner-van send failures (unbound receiver) are
    swallowed and counted in ``unreachable_drops``, so a dead node looks
    like loss, which is what retransmission layers must survive.
    """

    def __init__(
        self,
        inner: Van,
        *,
        seed: int = 0,
        default: Optional[ChaosConfig] = None,
        links: Optional[Dict[Tuple[str, str], ChaosConfig]] = None,
        drop: float = 0.0,
        duplicate: float = 0.0,
        reorder: float = 0.0,
        delay: float = 0.0,
        jitter: float = 0.0,
        corrupt: float = 0.0,
        bandwidth_bps: float = 0.0,
    ) -> None:
        super().__init__(inner)
        if default is None:
            default = ChaosConfig(
                drop=drop, duplicate=duplicate, reorder=reorder,
                delay=delay, jitter=jitter, corrupt=corrupt,
                bandwidth_bps=bandwidth_bps,
            )
        self.seed = seed
        self.default = default
        self.links: Dict[Tuple[str, str], ChaosConfig] = dict(links or {})
        self._rngs: Dict[Tuple[str, str], random.Random] = {}
        self._partitions: set[Tuple[str, str]] = set()
        self._lock = threading.Lock()
        self._wheel: Optional[TimerWheel] = None
        self._closed = False
        #: injection counters (asserted by the chaos test suite).
        self.injected_drops = 0
        self.injected_dups = 0
        self.injected_reorders = 0
        self.injected_slow = 0
        self.injected_corrupt = 0
        self.bandwidth_delays = 0
        self.partition_drops = 0
        self.unreachable_drops = 0
        self.forwarded = 0
        #: gray failures: node id -> extra inbound delivery delay (seconds).
        self._slow: Dict[str, float] = {}
        #: corruption RNGs live in a SEPARATE per-link stream (keyed
        #: ``corrupt:``) so enabling bit-flips never shifts the seeded
        #: drop/dup/reorder schedule drawn from ``_rng``.
        self._corrupt_rngs: Dict[Tuple[str, str], random.Random] = {}
        #: token bucket: link -> monotonic time its virtual transmit clock
        #: frees up (bandwidth_bps caps).  Deterministic, draw-free.
        self._bw_free: Dict[Tuple[str, str], float] = {}

    # -- configuration -------------------------------------------------------
    def set_link(self, sender: str, recver: str, cfg: ChaosConfig) -> None:
        """Override the fault config for one directed link."""
        with self._lock:
            self.links[(sender, recver)] = cfg

    def config_for(self, link: Tuple[str, str]) -> ChaosConfig:
        with self._lock:
            return self.links.get(link, self.default)

    # -- partitions (asymmetric per directed link) ---------------------------
    def partition(self, a: str, b: str, *, symmetric: bool = False) -> None:
        """Blackhole traffic a -> b (and b -> a when ``symmetric``)."""
        with self._lock:
            self._partitions.add((a, b))
            if symmetric:
                self._partitions.add((b, a))

    def heal(self, a: Optional[str] = None, b: Optional[str] = None) -> None:
        """Heal one directed link, or every partition when called bare."""
        with self._lock:
            if a is None:
                self._partitions.clear()
            else:
                self._partitions.discard((a, b))

    # -- gray failures (slow-but-alive nodes) --------------------------------
    def slow_node(self, node_id: str, slow_ms: float) -> None:
        """Make ``node_id`` a gray failure: every delivery INTO it gains a
        fixed ``slow_ms`` delay (0 heals).  Deterministic — no RNG draws —
        so the seeded fault sequence of every other injector is unchanged.
        The node itself stays alive and heartbeating; only the fleet
        monitor's latency attribution can tell it apart from a healthy one.
        """
        with self._lock:
            if slow_ms <= 0.0:
                self._slow.pop(node_id, None)
            else:
                self._slow[node_id] = slow_ms / 1e3

    # -- send path -----------------------------------------------------------
    def _rng(self, link: Tuple[str, str]) -> random.Random:
        r = self._rngs.get(link)
        if r is None:
            key = zlib.crc32(f"{self.seed}:{link[0]}->{link[1]}".encode())
            r = self._rngs[link] = random.Random(key)
        return r

    def _corrupt_rng(self, link: Tuple[str, str]) -> random.Random:
        r = self._corrupt_rngs.get(link)
        if r is None:
            key = zlib.crc32(
                f"{self.seed}:corrupt:{link[0]}->{link[1]}".encode()
            )
            r = self._corrupt_rngs[link] = random.Random(key)
        return r

    @staticmethod
    def _flip_bit(msg: Message, rng: random.Random) -> Optional[Message]:
        """Return a copy of ``msg`` with one in-flight payload bit flipped.

        The flip operates on the FLAT WIRE BUFFER: the message is encoded
        into its ``core/frame.py`` frame, one bit of the key/value plane
        region is flipped (a uniformly random plane byte — exactly what
        wire corruption does to the bytes a TcpVan carries), and the frame
        is decoded back with ``verify=False`` (a real receiver's header
        plane-CRC would reject the frame at the transport; ChaosVan models
        the residual case that slips past it, which the resender's
        end-to-end ``__rcrc__`` stamp must still catch).  The original
        message object is never touched: it is a retransmit source held by
        the sender's ReliableVan, so in-place mutation would poison every
        future retransmit and make recovery impossible.

        Device-resident (non-numpy) values never ride a wire buffer in
        this stack (they are delivered by reference), so such messages
        fall back to the legacy direct array-copy flip — matching the CRC
        stamp's type-based coverage in ``core/resender.py``.  Returns None
        when nothing is corruptible.
        """
        if (msg.keys is None or isinstance(msg.keys, np.ndarray)) and all(
            isinstance(v, np.ndarray) for v in msg.values
        ):
            try:
                data = frame.encode(msg)
            except frame.FrameError:
                data = None  # uncodable payload object: legacy flip below
            if data is not None:
                info = frame.peek(data)
                if info.planes_len <= 0:
                    return None  # no plane bytes — nothing corruptible
                buf = bytearray(data)
                off = (
                    frame.HEADER_SIZE
                    + info.meta_len
                    + rng.randrange(info.planes_len)
                )
                buf[off] ^= 1 << rng.randrange(8)
                out = frame.decode(bytes(buf), verify=False)
                # decoded arrays are read-only frombuffer views; deliver
                # owned writable copies like any chaos-free receive path
                if out.keys is not None:
                    out.keys = np.array(out.keys)
                out.values = [np.array(v) for v in out.values]
                return out
        candidates = []
        if isinstance(msg.keys, np.ndarray) and msg.keys.nbytes > 0:
            candidates.append(("keys", None))
        for i, v in enumerate(msg.values):
            if isinstance(v, np.ndarray) and v.nbytes > 0:
                candidates.append(("values", i))
        if not candidates:
            return None
        where, idx = candidates[rng.randrange(len(candidates))]
        target = msg.keys if where == "keys" else msg.values[idx]
        corrupted = target.copy()
        flat = corrupted.view(np.uint8).reshape(-1)
        flat[rng.randrange(flat.size)] ^= np.uint8(1 << rng.randrange(8))
        if where == "keys":
            return dataclasses.replace(msg, keys=corrupted)
        values = list(msg.values)
        values[idx] = corrupted
        return dataclasses.replace(msg, values=values)

    def send(self, msg: Message) -> bool:
        if self._closed:
            return False
        link = (msg.sender, msg.recver)
        with self._lock:
            if link in self._partitions:
                self.partition_drops += 1
                return True  # swallowed in flight
            cfg = self.links.get(link, self.default)
            # gray-failure delay: per-node (slow_node) + per-link config;
            # deterministic, consumes no draws
            slow = self._slow.get(msg.recver, 0.0) + cfg.slow_ms / 1e3
            randomized = cfg.randomized
            if randomized:
                # exactly four draws per message, config-independent, so a
                # config tweak cannot shift the fault sequence of later sends
                rng = self._rng(link)
                u_drop = rng.random()
                u_dup = rng.random()
                u_jit = rng.random()
                u_reord = rng.random()
            # corruption draws from its own stream — isolated from the four
            # draws above, so flipping cfg.corrupt on cannot shift the
            # seeded drop/dup/reorder schedule of this or any other link
            corrupt_hit = False
            if cfg.corrupt > 0.0:
                crng = self._corrupt_rng(link)
                corrupt_hit = crng.random() < cfg.corrupt
            # bandwidth cap: deterministic token bucket on payload bytes;
            # delays are monotone along a link (the bucket's free time only
            # advances), so FIFO through the wheel is preserved
            bw_delay = 0.0
            if cfg.bandwidth_bps > 0.0:
                now = time.monotonic()
                start = max(now, self._bw_free.get(link, now))
                done = start + payload_nbytes(msg) / cfg.bandwidth_bps
                self._bw_free[link] = done
                bw_delay = done - now
                if bw_delay > 0.0:
                    self.bandwidth_delays += 1
        if (
            not randomized
            and slow == 0.0
            and not corrupt_hit
            and bw_delay <= 0.0
        ):
            ok = self.inner.send(msg)
            with self._lock:
                if ok:
                    self.forwarded += 1
                else:
                    self.unreachable_drops += 1
            return True
        copies = 1
        latency = slow + bw_delay
        if randomized:
            if u_drop < cfg.drop:
                with self._lock:
                    self.injected_drops += 1
                flightrec.record(
                    "chaos.inject", fault="drop",
                    node=msg.sender, recver=msg.recver,
                )
                return True
            if u_dup < cfg.duplicate:
                copies = 2
                with self._lock:
                    self.injected_dups += 1
                flightrec.record(
                    "chaos.inject", fault="dup",
                    node=msg.sender, recver=msg.recver,
                )
            latency += cfg.delay + u_jit * cfg.jitter
            if u_reord < cfg.reorder:
                latency += cfg.reorder_delay
                with self._lock:
                    self.injected_reorders += 1
                flightrec.record(
                    "chaos.inject", fault="reorder",
                    node=msg.sender, recver=msg.recver,
                )
        if slow > 0.0:
            with self._lock:
                self.injected_slow += 1
        if corrupt_hit:
            flipped = self._flip_bit(msg, crng)
            if flipped is not None:
                msg = flipped
                with self._lock:
                    self.injected_corrupt += 1
                flightrec.record(
                    "chaos.inject", fault="corrupt",
                    node=msg.sender, recver=msg.recver,
                )
        if latency <= 0.0:
            # synchronous path: per-link FIFO preserved exactly (duplicates
            # arrive back to back, like an eager retransmitter)
            for _ in range(copies):
                self._deliver(msg)
            return True
        wheel = self._ensure_wheel()
        for _ in range(copies):
            wheel.schedule(latency, lambda m=msg: self._deliver(m))
        return True

    def _deliver(self, msg: Message) -> None:
        ok = self.inner.send(msg)
        with self._lock:
            if ok:
                self.forwarded += 1
            else:
                self.unreachable_drops += 1

    def _ensure_wheel(self) -> TimerWheel:
        with self._lock:
            if self._wheel is None:
                self._wheel = TimerWheel()
            return self._wheel

    # -- stats / lifecycle ---------------------------------------------------
    def counters(self) -> dict:
        with self._lock:
            return {
                "chaos_drops": self.injected_drops,
                "chaos_dups": self.injected_dups,
                "chaos_reorders": self.injected_reorders,
                "chaos_slow": self.injected_slow,
                "chaos_corrupt": self.injected_corrupt,
                "chaos_bw_delays": self.bandwidth_delays,
                "chaos_partition_drops": self.partition_drops,
                "chaos_unreachable": self.unreachable_drops,
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            wheel = self._wheel
            self._wheel = None
        if wheel is not None:
            wheel.stop()
        self.inner.close()
