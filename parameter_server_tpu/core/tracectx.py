"""Trace-context plumbing for the sampled request-tracing plane.

A *trace context* is a tiny dict stamped into a sampled request's payload
under :data:`TRACE_KEY` by the worker at submit time.  It rides the frame
meta plane end to end — through :class:`~.coalesce.CoalescingVan` bundling,
:class:`~.resender.ReliableVan` retransmit/dedup, both wire backends
(TCP/epoll and the shm ring), hierarchical-push leader hops — and is echoed
back on acks/pull replies by the server's copy-on-write reply stamping, so
the worker can close the span tree.

Shape (all keys optional except ``tid``)::

    {"tid": "<origin>/<customer>/<seq>",   # globally unique trace id
     "origin": "<node>", "customer": "<name>",
     "t": <monotonic submit time on the origin node>,
     "rx": <monotonic receive time, stamped by the receiving van>,
     "t_disp": <server dispatch>, "t_reply": <server reply built>}

Sampling is *deterministic and seeded*: whether a given ``tid`` is traced
depends only on ``(tid, seed, sample_every)``, so replays of a seeded run
sample the same requests and two nodes never disagree about a request's
sampling decision.  Unsampled requests carry **no** trace key at all —
zero bytes on the wire, and the int-only fast meta codec stays eligible.

Old peers simply ignore the key (it is plain frame meta), which is what
makes any-order rolling upgrades safe — see MIGRATION.md.
"""

from __future__ import annotations

import zlib
from typing import Any, List, Mapping, Optional

#: Payload key the trace context rides under.  PR 3 introduced the key for
#: loopback-only stitching; the modern plane keeps it for compatibility.
TRACE_KEY = "__trace__"


def sampled(tid: str, seed: int, sample_every: int) -> bool:
    """Deterministic hash-sampling decision for ``tid``.

    ``sample_every <= 0`` disables sampling entirely; ``1`` samples every
    request.  The decision is a pure function of the arguments so every
    node (and every replay of a seeded run) agrees on it.
    """
    if sample_every <= 0:
        return False
    if sample_every == 1:
        return True
    return zlib.crc32(f"{tid}:{seed}".encode()) % sample_every == 0


def trace_ids(payload: Optional[Mapping[str, Any]]) -> List[str]:
    """All sampled trace ids carried by ``payload`` (empty when unsampled).

    Handles both the single-request form (``{"tid": ...}``) and the bundle
    aggregate form (``{"tids": [...]}``) that ``CoalescingVan`` stamps on a
    packed frame.
    """
    if not payload:
        return []
    ctx = payload.get(TRACE_KEY)
    if not isinstance(ctx, dict):
        return []
    tid = ctx.get("tid")
    if tid is not None:
        return [tid]
    tids = ctx.get("tids")
    if isinstance(tids, (list, tuple)):
        return [t for t in tids if t is not None]
    return []
