"""MeteredVan: per-link wire accounting for any Van stack.

Reference analogue: ``system/network_usage.h`` feeding ``monitor.h`` [U] —
the per-node send/recv byte counters the scheduler dashboard aggregated.
Here the accounting is a Van decorator, so it meters whatever stack it
wraps: per directed link (sender -> recver) it records message counts,
payload bytes (keys + values nbytes), and two latency distributions in
mergeable :class:`~parameter_server_tpu.utils.trace.LatencyHistogram`\\ s:

- **send**: the wall time of the inner ``send`` call (serialization,
  filter passes, queue handoff — what the sending thread pays);
- **deliver**: send-stamp to receive-side delivery, measured by stamping
  ``time.monotonic()`` into ``Task.payload`` on the way out and reading it
  in a receive wrapper on the way in (the ``__rseq__`` pattern of
  ``core/resender.py``).  Over an in-process Van both ends share a clock,
  so this is true one-way latency; cross-host the raw difference embeds
  clock skew — feed :meth:`MeteredVan.set_clock_offset` with the
  heartbeat-RTT/2 estimates from ``Manager.sync_clock`` /
  ``FleetMonitor.relative_offset`` to correct it.

Stack position: OUTERMOST — ``MeteredVan(ReliableVan(ChaosVan(base)))`` —
so each LOGICAL message is counted exactly once (retransmits, ACKs, and
coalesced bundle frames happen in the layers below) and deliver latency
includes everything the stack added: chaos delays, retransmit waits,
bundle flushes.  That end-to-end per-link signal is what the
``core/fleet.py`` straggler detector consumes: a gray-failing node shows
up as elevated deliver latency on every link INTO it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from parameter_server_tpu.core import flightrec, frame
from parameter_server_tpu.core.messages import Message, Task
from parameter_server_tpu.core.van import Van, VanWrapper
from parameter_server_tpu.utils.trace import LatencyHistogram

#: payload key carrying the send-side monotonic stamp (stripped on receive).
STAMP_KEY = "__mts__"


def payload_nbytes(msg: Message) -> int:
    """Payload bytes of one message: keys nbytes + each value's nbytes.

    ``nbytes`` is read straight off array values (numpy and jax.Array both
    expose it — no device sync); anything else is sized via ``np.asarray``.
    Task metadata (pickle overhead, payload dict) is intentionally NOT
    counted: the meter reports the tensor traffic the PS exists to move,
    which is what ``bytes_per_example`` should be built from.
    """
    total = 0
    if msg.keys is not None:
        total += int(msg.keys.nbytes)
    for v in msg.values:
        nb = getattr(v, "nbytes", None)
        if nb is None:
            nb = np.asarray(v).nbytes
        total += int(nb)
    return total


class _LinkStats:
    """Counters + histograms for one directed link."""

    __slots__ = ("msgs", "bytes", "raw_bytes", "frame_bytes",
                 "overhead_bytes", "verbs", "send", "deliver")

    def __init__(self) -> None:
        self.msgs = 0
        self.bytes = 0
        #: per-verb split of msgs/bytes (``{"PUSH": [msgs, bytes], ...}``):
        #: the request-COUNT-by-verb signal the hierarchical-push bench
        #: (ISSUE 15) reads to show inbound PUSH requests dropping with
        #: group size, and ``fleet.inbound_totals`` aggregates per node.
        self.verbs: Dict[str, list] = {}
        #: pre-compression payload bytes: ``bytes`` plus whatever the lossy
        #: wire codec saved (its payload marker's ``saved`` total).  Equal
        #: to ``bytes`` on uncompressed links; the per-link compression
        #: ratio is ``bytes / raw_bytes`` with no filter instrumentation.
        self.raw_bytes = 0
        #: exact flat-frame wire size (``core/frame.py``): payload planes
        #: PLUS the 52-byte fixed header and the encoded meta section —
        #: per-message framing tax, measured rather than modeled.
        self.frame_bytes = 0
        #: the non-plane share of ``frame_bytes`` (header + meta).
        self.overhead_bytes = 0
        self.send = LatencyHistogram()
        self.deliver = LatencyHistogram()


class MeteredVan(VanWrapper):
    """Wire-accounting Van decorator.  See module docstring.

    ``stamp=False`` disables the payload timestamp (and with it deliver
    latency) for stacks whose messages must round-trip byte-identical.
    """

    def __init__(self, inner: Van, *, stamp: bool = True) -> None:
        super().__init__(inner)
        self._stamp = stamp
        self._lock = threading.Lock()
        self._links: Dict[Tuple[str, str], _LinkStats] = {}
        self.undeliverable = 0
        #: per-sender clock correction (seconds): sender's monotonic clock
        #: minus the local receiver's, added to raw deliver latencies.
        self._clock_offsets: Dict[str, float] = {}

    def set_clock_offset(self, sender: str, offset_s: float) -> None:
        """Correct deliver latencies for frames FROM ``sender``.

        ``offset_s`` is the sender's monotonic clock minus this process's
        (i.e. :meth:`~parameter_server_tpu.core.fleet.FleetMonitor.relative_offset`
        of (sender, local node)).  Cross-host, ``recv_local - send_remote``
        embeds that offset; adding it back yields true one-way latency, so
        the gray-failure detector keeps working off loopback.  In-process
        stacks share one clock and never need this (offset 0).
        """
        with self._lock:
            if offset_s == 0.0:
                self._clock_offsets.pop(sender, None)
            else:
                self._clock_offsets[sender] = offset_s

    def _link(self, sender: str, recver: str) -> _LinkStats:
        st = self._links.get((sender, recver))
        if st is None:
            st = self._links[(sender, recver)] = _LinkStats()
        return st

    # -- send path -----------------------------------------------------------
    def send(self, msg: Message) -> bool:
        nbytes = payload_nbytes(msg)
        saved = 0
        p = msg.task.payload
        if isinstance(p, dict):
            wc = p.get(frame.COMPRESSED_KEY)
            if isinstance(wc, dict):
                saved = int(wc.get("saved", 0))
        out = msg
        if self._stamp:
            # direct constructors, not dataclasses.replace: replace() pays
            # ~7 us of field introspection per call pair, and this is the
            # per-message hot path the --obs overhead guard holds to <= 3%
            t = msg.task
            out = Message(
                task=Task(
                    kind=t.kind, customer=t.customer, time=t.time,
                    wait_time=t.wait_time,
                    payload={**t.payload, STAMP_KEY: time.monotonic()},
                ),
                sender=msg.sender, recver=msg.recver, keys=msg.keys,
                values=msg.values, is_request=msg.is_request,
            )
        # exact wire framing for this message as sent (incl. the __mts__
        # stamp just added): plane bytes + 52-byte header + meta section.
        # ``frame_nbytes`` sizes the meta without building the frame and
        # without touching device values; resender stamps added below ride
        # the fixed header (lifted), so they contribute zero meta bytes and
        # the per-layer accounting composes exactly.
        try:
            fbytes, obytes = frame.frame_nbytes(out)
        except frame.FrameError:  # uncodable payload object (in-proc only)
            fbytes, obytes = nbytes + frame.HEADER_SIZE, frame.HEADER_SIZE
        t0 = time.perf_counter()
        ok = self.inner.send(out)
        dt = time.perf_counter() - t0
        verb = msg.task.kind.name
        with self._lock:
            st = self._link(msg.sender, msg.recver)
            st.msgs += 1
            st.bytes += nbytes
            st.raw_bytes += nbytes + saved
            st.frame_bytes += fbytes
            st.overhead_bytes += obytes
            vb = st.verbs.get(verb)
            if vb is None:
                vb = st.verbs[verb] = [0, 0]
            vb[0] += 1
            vb[1] += nbytes
            st.send.record(dt)
            if not ok:
                self.undeliverable += 1
        flightrec.record(
            "frame.send", node=msg.sender, recver=msg.recver,
            verb=verb, bytes=nbytes, ok=ok,
        )
        return ok

    # -- receive path --------------------------------------------------------
    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        def metered(msg: Message) -> None:
            payload = msg.task.payload
            ts = payload.get(STAMP_KEY) if isinstance(payload, dict) else None
            if ts is not None:
                # strip the stamp before delivery: replies share the Task
                # (msg.reply()), so a leaked stamp would time-travel into
                # the response leg and read as a negative latency.  Direct
                # constructors for the same hot-path reason as send().
                t = msg.task
                stripped = dict(payload)
                del stripped[STAMP_KEY]
                msg = Message(
                    task=Task(
                        kind=t.kind, customer=t.customer, time=t.time,
                        wait_time=t.wait_time, payload=stripped,
                    ),
                    sender=msg.sender, recver=msg.recver, keys=msg.keys,
                    values=msg.values, is_request=msg.is_request,
                )
                with self._lock:
                    correction = self._clock_offsets.get(msg.sender, 0.0)
                    lat = time.monotonic() - ts + correction
                    self._link(msg.sender, msg.recver).deliver.record(lat)
                flightrec.record(
                    "frame.recv", node=msg.recver, sender=msg.sender,
                    verb=msg.task.kind.name, deliver_ms=round(1e3 * lat, 3),
                )
            handler(msg)

        self.inner.bind(node_id, metered)

    # -- accounting ----------------------------------------------------------
    def counters(self) -> dict:
        """Numeric totals for the ``transport_counters`` merge walk."""
        with self._lock:
            return {
                "wire_msgs": sum(st.msgs for st in self._links.values()),
                "wire_bytes": sum(st.bytes for st in self._links.values()),
                "wire_raw_bytes": sum(
                    st.raw_bytes for st in self._links.values()
                ),
                "wire_frame_bytes": sum(
                    st.frame_bytes for st in self._links.values()
                ),
                "wire_overhead_bytes": sum(
                    st.overhead_bytes for st in self._links.values()
                ),
                "wire_links": len(self._links),
                "wire_undeliverable": self.undeliverable,
            }

    def links(self) -> Dict[str, dict]:
        """Per-link digests keyed ``"sender->recver"`` (JSON-safe)."""
        with self._lock:
            return {
                f"{s}->{r}": {
                    "msgs": st.msgs,
                    "bytes": st.bytes,
                    "raw_bytes": st.raw_bytes,
                    "frame_bytes": st.frame_bytes,
                    "overhead_bytes": st.overhead_bytes,
                    "verbs": {
                        v: {"msgs": c[0], "bytes": c[1]}
                        for v, c in st.verbs.items()
                    },
                    "send": st.send.to_dict(),
                    "deliver": st.deliver.to_dict(),
                }
                for (s, r), st in self._links.items()
            }

    def node_digests(self, node_id: str) -> Dict[str, dict]:
        """The links ``node_id`` originated — its heartbeat contribution.

        Each node reports only what IT sent; deliver histograms for those
        links (recorded receive-side) ride along, so the fleet monitor can
        attribute inbound latency to each link's DESTINATION without any
        node reporting twice.
        """
        with self._lock:
            return {
                f"{s}->{r}": {
                    "msgs": st.msgs,
                    "bytes": st.bytes,
                    "raw_bytes": st.raw_bytes,
                    "frame_bytes": st.frame_bytes,
                    "overhead_bytes": st.overhead_bytes,
                    "verbs": {
                        v: {"msgs": c[0], "bytes": c[1]}
                        for v, c in st.verbs.items()
                    },
                    "send": st.send.to_dict(),
                    "deliver": st.deliver.to_dict(),
                }
                for (s, r), st in self._links.items()
                if s == node_id
            }


def find_metered(van) -> Optional[MeteredVan]:
    """First MeteredVan in a wrapper stack (``.inner`` walk), or None."""
    seen = set()
    v = van
    while v is not None and id(v) not in seen:
        seen.add(id(v))
        if isinstance(v, MeteredVan):
            return v
        v = getattr(v, "inner", None)
    return None
