"""TelemetryBus: live, delta-encoded per-node telemetry -> scheduler ring.

PR 8 closed the *postmortem* half of observability (flight recorder,
bundles, SLO verdicts), but every consumer was pull-at-dump-time:
``SloEngine`` saw fleet state only when something ingested it, and nothing
streamed per-node series while a run was healthy.  This module is the live
half — the layer the ROADMAP's read-heavy serving plane reads its
``SloEngine.healthy()`` admission signal from.

Two halves, one wire verb:

- :class:`TelemetryPublisher` runs on every node.  Each call to
  :meth:`~TelemetryPublisher.frame` produces one **delta-encoded** frame —
  transport-counter deltas (cumulative counters differenced against the
  previous frame), per-link :class:`~parameter_server_tpu.utils.trace.LatencyHistogram`
  *bucket* deltas, a flight-recorder event-rate summary (kind -> count of
  events journaled since the last frame, tracked by recorder ``seq``
  watermark), and any named digest series from attached sources (the
  KVWorker staleness histograms).  Delta framing keeps the wire cost
  proportional to what CHANGED since the last heartbeat, not to run length.
- :class:`TelemetryAggregator` runs on the scheduler.  It deduplicates by
  per-node frame ``seq``, rebases node-monotonic stamps into the scheduler
  clock domain via ``FleetMonitor.clock_offset``, reconstructs cumulative
  counters/histograms from the deltas, appends one derived row per frame to
  a bounded per-node ring (JSONL-spillable through
  :class:`~parameter_server_tpu.core.fleet.RotatingJsonlWriter`), and runs
  ``SloEngine.evaluate()`` on every arrival — so ``healthy(node)`` is
  always current and ``slo.breach`` / ``slo.clear`` fire in real time, not
  at dump time.

Transport: frames ride the ``TELEMETRY`` CONTROL verb
(``core/manager.py``), published at heartbeat cadence by
``Manager.send_heartbeat`` when a publisher is attached
(``mgr.telemetry_pub = TelemetryPublisher(...)``); the scheduler ingests in
``Manager._on_telemetry`` when an aggregator is attached
(``sched.telemetry = TelemetryAggregator(...)``).  ``tools/pstop.py``
renders the aggregator's ring (or its JSONL spill) as a live fleet console.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.fleet import RotatingJsonlWriter
from parameter_server_tpu.utils.trace import LatencyHistogram

#: frame format version (bumped on incompatible changes).
FRAME_VERSION = 1


def delta_digest(prev: Optional[dict], cur: Optional[dict]) -> Optional[dict]:
    """Sparse bucket delta between two CUMULATIVE histogram digests.

    Returns a digest dict (``LatencyHistogram.to_dict`` shape) holding only
    the samples recorded between ``prev`` and ``cur``, or None when nothing
    new was recorded.  A reset (any count moving backwards — recorder
    restarted) falls back to the full current digest rather than inventing
    negative mass; the aggregator's cumulative reconstruction then
    over-counts that one boundary, which is the standard delta-encoding
    trade for restart tolerance.
    """
    if not cur or not cur.get("count"):
        return None
    if not prev or not prev.get("count"):
        return dict(cur)
    if cur["count"] < prev["count"]:
        return dict(cur)  # reset fallback
    buckets: Dict[str, int] = {}
    prev_b = prev.get("b") or {}
    for i, c in (cur.get("b") or {}).items():
        d = int(c) - int(prev_b.get(i, 0))
        if d < 0:
            return dict(cur)  # reset fallback
        if d:
            buckets[i] = d
    count = int(cur["count"]) - int(prev["count"])
    if count <= 0:
        return None
    return {
        "count": count,
        "sum_s": round(max(float(cur.get("sum_s", 0.0)) - float(prev.get("sum_s", 0.0)), 0.0), 9),
        # upper bound: the exact inter-frame max is not tracked, and the
        # cumulative max is what percentile() clamps against anyway
        "max_s": cur.get("max_s", 0.0),
        "b": buckets,
    }


class TelemetryPublisher:
    """Node-side frame builder.  One instance per logical node.

    ``van``: this node's Van stack — its ``.inner`` chain is walked for
    layer ``counters()`` and the first MeteredVan's per-link digests
    (``node_digests``: only links this node ORIGINATED, so no link is
    reported twice fleet-wide).  ``sources``: extra objects contributing
    ``counters()`` dicts and/or ``staleness_digests()`` named cumulative
    histogram series (e.g. a :class:`~parameter_server_tpu.kv.worker.KVWorker`).
    ``recorder``: flight recorder to summarize (default: the process-wide
    one); only events stamped ``node=<this node>`` are counted, so the
    shared in-process ring is attributed, not multiply reported.
    ``verdicts``: optional zero-arg callable returning a JSON-safe local
    SLO verdict blob to ride along (a node running its own engine).
    """

    def __init__(
        self,
        node_id: str,
        van=None,
        *,
        recorder: Optional[flightrec.FlightRecorder] = None,
        sources: tuple = (),
        verdicts: Optional[Callable[[], dict]] = None,
    ) -> None:
        self.node_id = node_id
        self.van = van
        self._recorder = recorder
        self.sources: List[object] = list(sources)
        self.verdicts_fn = verdicts
        self._lock = threading.Lock()
        self._seq = 0
        self._prev_counters: Dict[str, float] = {}
        self._prev_links: Dict[str, dict] = {}
        self._prev_series: Dict[str, dict] = {}
        self._prev_latency: Dict[str, dict] = {}
        #: flight-recorder seq watermark: events <= this are already reported.
        self._ev_seq = -1

    def add_source(self, *sources) -> "TelemetryPublisher":
        with self._lock:
            self.sources.extend(sources)
        return self

    def _cumulative_counters(self) -> Dict[str, float]:
        cur: Dict[str, float] = {}
        if self.van is not None:
            cur.update(flightrec._walk_counters(self.van))
        for src in self.sources:
            get = getattr(src, "counters", None)
            if not callable(get):
                continue
            try:
                for k, v in get().items():
                    if isinstance(v, (int, float)):
                        cur[k] = cur.get(k, 0) + v
            except Exception:  # pragma: no cover — telemetry never crashes
                pass  # the node it observes
        return cur

    def frame(self, now: Optional[float] = None) -> dict:
        """Build the next delta frame (thread-safe, advances the seq)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._seq += 1
            out: dict = {
                "v": FRAME_VERSION,
                "node": self.node_id,
                "seq": self._seq,
                "t_mono_s": now,
            }
            # -- transport + source counter deltas ---------------------------
            cur = self._cumulative_counters()
            deltas: Dict[str, float] = {}
            for k, v in cur.items():
                d = v - self._prev_counters.get(k, 0)
                if d:
                    deltas[k] = round(d, 6) if isinstance(d, float) else d
            self._prev_counters = cur
            if deltas:
                out["counters"] = deltas
            # -- per-link wire digest deltas ---------------------------------
            metered = (
                flightrec._find_metered(self.van)
                if self.van is not None else None
            )
            if metered is not None:
                links: Dict[str, dict] = {}
                digs = metered.node_digests(self.node_id)
                for link, d in digs.items():
                    prev = self._prev_links.get(link) or {}
                    row: Dict[str, object] = {}
                    for k in ("msgs", "bytes", "frame_bytes", "overhead_bytes"):
                        dv = int(d.get(k, 0)) - int(prev.get(k, 0))
                        if dv:
                            row[k] = dv
                    for k in ("send", "deliver"):
                        dd = delta_digest(prev.get(k), d.get(k))
                        if dd:
                            row[k] = dd
                    if row:
                        links[link] = row
                self._prev_links = digs
                if links:
                    out["links"] = links
            # -- flight-recorder event-rate summary --------------------------
            rec = self._recorder if self._recorder is not None else flightrec.get()
            counts: Dict[str, int] = {}
            for ev in rec.events_since(self._ev_seq):
                if ev["seq"] > self._ev_seq:
                    self._ev_seq = ev["seq"]
                if ev.get("node") != self.node_id:
                    continue  # shared per-process ring: attribute, don't echo
                kind = ev.get("kind")
                counts[kind] = counts.get(kind, 0) + 1
            if counts:
                out["events"] = counts
            # -- named cumulative digest series (staleness) ------------------
            series: Dict[str, dict] = {}
            for src in self.sources:
                get = getattr(src, "staleness_digests", None)
                if not callable(get):
                    continue
                try:
                    digests = get()
                except Exception:  # pragma: no cover — telemetry never crashes
                    continue
                for name, dig in digests.items():
                    dd = delta_digest(self._prev_series.get(name), dig)
                    self._prev_series[name] = dig
                    if dd:
                        series[name] = dd
            if series:
                out["staleness"] = series
            # -- device-plane latency digest series (ISSUE 12) ---------------
            # Same delta framing, separate frame field: staleness rides a
            # unitless axis (pstop's STALE column takes the max-p99 across
            # the field), while these are seconds-axis apply attributions
            # (apply.<t> + host/h2d/dev splits from the ApplyLedger).
            lat: Dict[str, dict] = {}
            for src in self.sources:
                get = getattr(src, "latency_digests", None)
                if not callable(get):
                    continue
                try:
                    digests = get()
                except Exception:  # pragma: no cover — telemetry never crashes
                    continue
                for name, dig in digests.items():
                    dd = delta_digest(self._prev_latency.get(name), dig)
                    self._prev_latency[name] = dig
                    if dd:
                        lat[name] = dd
            if lat:
                out["digests"] = lat
            # -- local SLO verdicts ------------------------------------------
            if self.verdicts_fn is not None:
                try:
                    v = self.verdicts_fn()
                    if v:
                        out["verdicts"] = v
                except Exception:  # pragma: no cover — telemetry never crashes
                    pass
            seq_out = self._seq
        # journaled AFTER the watermark advanced, so the publish marker of
        # frame N is reported by frame N+1, never by itself
        flightrec.record("telemetry.publish", node=self.node_id, seq=seq_out)
        return out


class TelemetryAggregator:
    """Scheduler-side windowed per-node time-series ring.

    Attach to the scheduler's Manager (``sched.telemetry = aggregator``);
    every TELEMETRY frame then lands in :meth:`ingest`, which:

    1. drops duplicate/stale frames by per-node ``seq`` (journaled as
       ``telemetry.drop`` — a replayed frame must not double-count deltas);
    2. rebases the sender's monotonic stamp into the scheduler clock domain
       (``t_sched = t_node - clock_offset(node)``) when a ``fleet`` monitor
       is attached;
    3. folds counter/histogram deltas back into per-node cumulative state;
    4. feeds the attached :class:`~parameter_server_tpu.utils.slo.SloEngine`
       (cumulative counters for gauge/rate specs, cumulative digests for
       p99 specs) and calls ``evaluate()`` — breach/clear fire on ARRIVAL;
    5. appends one derived row (rates, staleness quantiles, health) to a
       bounded per-node ring and the optional JSONL spill.

    Memory is bounded: ``window`` rows per node in the ring, plus one
    cumulative counter dict / histogram per (node, series).
    """

    def __init__(
        self,
        *,
        window: int = 256,
        slo=None,
        fleet=None,
        jsonl_path: Optional[str] = None,
        rotate_bytes: int = 0,
        config=None,
        evaluate_scope: str = "fleet",
    ) -> None:
        if evaluate_scope not in ("fleet", "node"):
            raise ValueError(
                f"evaluate_scope must be 'fleet' or 'node', "
                f"got {evaluate_scope!r}"
            )
        from parameter_server_tpu.config import TelemetryConfig

        self.slo = slo
        self.fleet = fleet
        # ring sizing scales with fleet size (ISSUE 19): ``config`` is the
        # knob; a bare ``window=`` call synthesizes one that keeps the
        # legacy fixed-window behaviour for small fleets but still bounds
        # total retained rows once hundreds of publishers appear.
        self.config = config if config is not None else TelemetryConfig(
            window=window,
            ring_budget_rows=max(8192, window),
            min_window=min(8, window),
        )
        self.window = self.config.window
        #: "fleet" re-evaluates every node per ingest (breach/clear edges
        #: fire on ANY frame arrival — the live-cluster default); "node"
        #: evaluates only the frame's sender, for 200-publisher fleets
        #: where a per-ingest fleet sweep is O(fleet^2) per beat — the
        #: war-game runner pairs it with one full sweep per tick.
        self._evaluate_scope = evaluate_scope
        #: current scenario phase (war-game plane); None outside a run.
        self._phase: Optional[str] = None
        self._lock = threading.Lock()
        self._rings: Dict[str, collections.deque] = {}
        self._max_seq: Dict[str, int] = {}
        #: last frame timestamp per node, in the SENDER's clock (rate dt).
        self._last_t: Dict[str, float] = {}
        self._cum_counters: Dict[str, Dict[str, float]] = {}
        self._cum_series: Dict[Tuple[str, str], LatencyHistogram] = {}
        self._ev_totals: Dict[str, Dict[str, int]] = {}
        self._verdicts: Dict[str, dict] = {}
        self.frames = 0
        self.duplicates = 0
        self.late = 0
        #: per-node duplicate/stale-frame drops (control-plane self-metric:
        #: ROADMAP names ring sizing a scaling risk — drops were journaled
        #: but never surfaced per node until ISSUE 12).
        self._drops: Dict[str, int] = {}
        self.writer: Optional[RotatingJsonlWriter] = (
            RotatingJsonlWriter(jsonl_path, rotate_bytes=rotate_bytes)
            if jsonl_path is not None
            else None
        )

    # -- ingest ---------------------------------------------------------------
    def ingest(self, node: str, frame: dict, now: Optional[float] = None) -> bool:
        """Fold one frame in; returns False for dropped (duplicate) frames."""
        now = time.monotonic() if now is None else now
        seq = int(frame.get("seq") or 0)
        with self._lock:
            have = self._max_seq.get(node, 0)
            if seq <= have:
                self.duplicates += 1
                self._drops[node] = self._drops.get(node, 0) + 1
                flightrec.record(
                    "telemetry.drop", node=node, seq=seq, have=have
                )
                return False
            self._max_seq[node] = seq
            t_node = float(frame.get("t_mono_s") or now)
            offset = None
            if self.fleet is not None:
                try:
                    offset = self.fleet.clock_offset(node)
                except Exception:  # pragma: no cover — a malformed clock row
                    offset = None  # must not drop the frame
            t_sched = t_node - (offset or 0.0)
            prev_t = self._last_t.get(node)
            dt = (t_node - prev_t) if prev_t is not None else None
            if dt is not None and dt < 0:
                # newer seq with an older stamp (clock step on the node):
                # keep the frame, but rates for this hop are meaningless
                self.late += 1
                dt = None
            self._last_t[node] = max(t_node, prev_t or t_node)
            # cumulative reconstruction
            cum = self._cum_counters.setdefault(node, {})
            for k, d in (frame.get("counters") or {}).items():
                if isinstance(d, (int, float)):
                    cum[k] = cum.get(k, 0) + d
            ev_tot = self._ev_totals.setdefault(node, {})
            for kind, c in (frame.get("events") or {}).items():
                ev_tot[kind] = ev_tot.get(kind, 0) + int(c)
            stale_stats: Dict[str, dict] = {}
            slo_digests: Dict[str, dict] = {}
            # only series a p99 spec reads need the full digest re-exported
            want_digest: frozenset = frozenset()
            if self.slo is not None:
                want_digest = frozenset(
                    s.metric
                    for s in getattr(self.slo, "specs", ())
                    if getattr(s, "source", "") == "p99"
                )
            for name, dd in (frame.get("staleness") or {}).items():
                h = self._cum_series.get((node, name))
                if h is None:
                    h = self._cum_series[(node, name)] = LatencyHistogram()
                try:
                    h.merge_dict(dd)
                except Exception:
                    continue  # a malformed series must not drop the frame
                stale_stats[name] = {
                    "count": h.count,
                    "p50": round(h.percentile(0.50), 6),
                    "p99": round(h.percentile(0.99), 6),
                }
                if name in want_digest:
                    slo_digests[name] = h.to_dict()
            # device-plane latency series: same cumulative fold, own frame
            # field + row field (seconds axis — consumers scale to ms)
            lat_stats: Dict[str, dict] = {}
            for name, dd in (frame.get("digests") or {}).items():
                h = self._cum_series.get((node, name))
                if h is None:
                    h = self._cum_series[(node, name)] = LatencyHistogram()
                try:
                    h.merge_dict(dd)
                except Exception:
                    continue  # a malformed series must not drop the frame
                lat_stats[name] = {
                    "count": h.count,
                    "p50": round(h.percentile(0.50), 6),
                    "p99": round(h.percentile(0.99), 6),
                }
                if name in want_digest:
                    slo_digests[name] = h.to_dict()
            d_msgs = d_bytes = 0
            deliver = LatencyHistogram()
            for row in (frame.get("links") or {}).values():
                d_msgs += int(row.get("msgs") or 0)
                d_bytes += int(row.get("bytes") or 0)
                if row.get("deliver"):
                    try:
                        deliver.merge(
                            LatencyHistogram.from_dict(row["deliver"])
                        )
                    except Exception:
                        pass
            if frame.get("verdicts") is not None:
                self._verdicts[node] = frame["verdicts"]
            mig = (
                ev_tot.get("migrate.begin", 0)
                - ev_tot.get("migrate.commit", 0)
                - ev_tot.get("migrate.abort", 0)
            )
            cum_snapshot = dict(cum)
            self.frames += 1
        # continuous evaluation (outside the ring lock: SloEngine has its
        # own state, and recorder hooks must not run under our lock)
        healthy = None
        breaches: List[str] = []
        if self.slo is not None:
            self.slo.ingest_counters(node, cum_snapshot, t_sched)
            for name, dig in slo_digests.items():
                self.slo.observe(node, name, dig, t_sched)
            if self._evaluate_scope == "node":
                try:
                    self.slo.evaluate(now, nodes=[node])
                except TypeError:  # engine predates the nodes= restriction
                    self.slo.evaluate(now)
            else:
                self.slo.evaluate(now)
            healthy = self.slo.healthy(node)
            breaches = sorted(
                name for (name, n), hit in self.slo._breached.items()
                if hit and n == node
            )
        flags: List[str] = []
        if self.fleet is not None:
            try:
                flags = self.fleet.stragglers(now).get(node, [])
            except Exception:  # pragma: no cover — detector must not drop
                flags = []  # the frame
        row: dict = {
            "node": node,
            "seq": seq,
            "t": round(t_sched, 6),
            "t_ingest": round(now, 6),
        }
        if dt is not None and dt > 0:
            row["dt_s"] = round(dt, 6)
            row["msgs_per_s"] = round(d_msgs / dt, 2)
            row["bytes_per_s"] = round(d_bytes / dt, 1)
            n_ev = sum((frame.get("events") or {}).values())
            row["events_per_s"] = round(n_ev / dt, 2)
            # serving plane (ISSUE 13): per-beat read/shed rates off the
            # frame's counter DELTAS (sparse: only nodes that serve)
            fc = frame.get("counters") or {}
            d_ro = fc.get("ro_pulls")
            if d_ro:
                row["ro_per_s"] = round(d_ro / dt, 2)
            d_shed = fc.get("serve_shed")
            if d_shed:
                row["shed_per_s"] = round(d_shed / dt, 2)
        # serving plane: lifetime cache hit ratio off the CUMULATIVE
        # counters (a rate would thrash at low traffic)
        looked = cum_snapshot.get("cache_hits", 0) + cum_snapshot.get(
            "cache_misses", 0
        )
        if looked:
            row["cache_hit_pct"] = round(
                100.0 * cum_snapshot.get("cache_hits", 0) / looked, 2
            )
        # quantized wire plane (ISSUE 14): lifetime compressed-vs-raw ratio
        # off the CUMULATIVE MeteredVan byte counters (same reasoning)
        raw = cum_snapshot.get("wire_raw_bytes", 0)
        if raw and raw != cum_snapshot.get("wire_bytes", 0):
            row["cmpr_pct"] = round(
                100.0 * cum_snapshot.get("wire_bytes", 0) / raw, 2
            )
        # hierarchical push (ISSUE 15): group-reduced PUSH fan-in — the
        # wire applies a server saw as a % of the raw member pushes they
        # stand for (100 = no pre-reduction, 25 = 4-member groups fully
        # merged).  Off the server's CUMULATIVE group counters.
        graw = cum_snapshot.get("group_members", 0)
        if graw:
            row["grp_pct"] = round(
                100.0 * cum_snapshot.get("group_pushes", 0) / graw, 2
            )
        # durability plane (ISSUE 16): snapshot staleness as a first-class
        # derived field.  The server reports ckpt_age_s as a GAUGE (seconds
        # since last snap_commit/restore), so the reconstructed cumulative
        # value IS the age — surface it for pstop's CKPT column and the
        # ckpt-age SLO without any extra plumbing.
        if "ckpt_age_s" in cum_snapshot:
            row["ckpt_age_s"] = round(float(cum_snapshot["ckpt_age_s"]), 3)
        # consistency plane (ISSUE 20): the server's mode/bound ride the
        # counter channel as GAUGES (delta-framed like ckpt_age_s), so the
        # reconstructed cumulative value IS the current setting — surface
        # them for pstop's MODE/BOUND columns and the live-retune audit.
        if "consist_mode" in cum_snapshot:
            row["consist_mode"] = int(cum_snapshot["consist_mode"])
            row["consist_bound"] = int(cum_snapshot.get("consist_bound", 0))
        if deliver.count:
            row["deliver_p99_ms"] = round(1e3 * deliver.percentile(0.99), 3)
            row["deliver_p50_ms"] = round(1e3 * deliver.percentile(0.50), 3)
        if stale_stats:
            row["staleness"] = stale_stats
        if lat_stats:
            row["digests"] = lat_stats
        if frame.get("events"):
            row["events"] = dict(frame["events"])
        if mig > 0:
            row["migrations_active"] = mig
        if healthy is not None:
            row["healthy"] = healthy
            if breaches:
                row["breaches"] = breaches
        if flags:
            row["straggler"] = flags
        row["counters"] = cum_snapshot
        with self._lock:
            ring = self._rings.get(node)
            if ring is None:
                # a NEW publisher re-derives the fleet-wide per-node cap
                # and re-caps existing rings in place, so the total stays
                # near ``config.ring_budget_rows`` at any fleet size.
                cap = self.config.node_window(len(self._rings) + 1)
                if self._rings and next(iter(self._rings.values())).maxlen != cap:
                    for n, r in self._rings.items():
                        self._rings[n] = collections.deque(r, maxlen=cap)
                ring = self._rings[node] = collections.deque(maxlen=cap)
            ring.append(row)
            # control-plane self-metrics (ISSUE 12): the aggregator's own
            # state rides every derived row, so ring pressure and dedup
            # drops are visible downstream (pstop DRP column) without a
            # side channel.  Occupancy is post-append: cap hit => eviction.
            row["ctl"] = {
                "ring": len(ring),
                "ring_cap": ring.maxlen,
                "drops": self._drops.get(node, 0),
            }
            # war-game extras ride only when the planes exist, so the ctl
            # dict stays exactly the ISSUE-12 triple everywhere else.
            if self._phase is not None:
                row["ctl"]["phase"] = self._phase
            if self.slo is not None and hasattr(self.slo, "breach_seconds"):
                row["ctl"]["breach_min"] = round(
                    self.slo.breach_seconds() / 60.0, 4
                )
        if self.writer is not None:
            self.writer.write_line(json.dumps(row))
        return True

    # -- war-game plane (ISSUE 19) --------------------------------------------
    def set_phase(self, phase: Optional[str]) -> None:
        """Stamp the live scenario phase onto subsequent ctl blocks (and
        pstop's fleet footer).  ``None`` ends the run — ctl reverts to the
        bare ISSUE-12 triple."""
        self._phase = phase

    @property
    def phase(self) -> Optional[str]:
        return self._phase

    def breach_minutes(self) -> float:
        """Running fleet-wide SLO-breach-minutes off the attached engine
        (0.0 when no engine — or a pre-ISSUE-19 one — is attached)."""
        if self.slo is None or not hasattr(self.slo, "breach_seconds"):
            return 0.0
        return self.slo.breach_seconds() / 60.0

    # -- reads ----------------------------------------------------------------
    def nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def rows(self, node: str) -> List[dict]:
        """This node's retained derived rows, oldest first."""
        with self._lock:
            return list(self._rings.get(node, ()))

    def latest(self) -> Dict[str, dict]:
        """Most recent derived row per node — what ``pstop`` renders."""
        with self._lock:
            return {n: r[-1] for n, r in self._rings.items() if r}

    def staleness_quantile(self, node: str, series: str, q: float) -> float:
        """Quantile of a node's cumulative staleness series (0.0 if unseen)."""
        with self._lock:
            h = self._cum_series.get((node, series))
            return h.percentile(q) if h is not None and h.count else 0.0

    def event_totals(self, node: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._ev_totals.get(node, {}))

    def counters(self) -> dict:
        """Dashboard-mergeable ingest counters."""
        with self._lock:
            return {
                "telemetry_frames": self.frames,
                "telemetry_dup_frames": self.duplicates,
                "telemetry_late_frames": self.late,
            }

    def drops(self, node: str) -> int:
        """Cumulative duplicate/stale-frame drops for ``node``."""
        with self._lock:
            return self._drops.get(node, 0)

    def flush_jsonl(self) -> None:
        if self.writer is not None:
            self.writer.sync()

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
