"""Message / Task model.

The reference's wire unit is ``Message{Task, SArray keys, SArray[] values}``
with ``Task.time`` (the integer timestamp returned by Push/Pull) and
``Task.wait_time`` (the dependency edge that encodes BSP/SSP/ASP in the
Executor DAG).  (Reference: ``src/system/message.h`` +
``src/system/proto/task.proto`` [U — reference mount empty, public layout].)

Here a Message is a plain dataclass carrying numpy arrays — zero-copy views
of host staging buffers (the SArray role).  On the ICI data plane messages
never exist (collectives move the data); Messages travel only on the control
plane and the DCN plane, so protobuf + filters are replaced by simple
dataclasses plus optional codec hooks (``parameter_server_tpu.ops.quantize``).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
from typing import Any, Optional

import numpy as np


class NodeRole(str, enum.Enum):
    SCHEDULER = "scheduler"
    SERVER = "server"
    WORKER = "worker"


#: Node-id conventions of the reference: scheduler "H", servers "S<i>",
#: workers "W<i>", plus group aliases usable as Message.recver.
SCHEDULER = "H"
SERVER_GROUP = "server_group"
WORKER_GROUP = "worker_group"
ALL_GROUP = "all_group"


def server_id(i: int) -> str:
    return f"S{i}"


def worker_id(i: int) -> str:
    return f"W{i}"


def node_role(node_id: str) -> NodeRole:
    if node_id == SCHEDULER:
        return NodeRole.SCHEDULER
    if node_id.startswith("S"):
        return NodeRole.SERVER
    if node_id.startswith("W"):
        return NodeRole.WORKER
    raise ValueError(f"unknown node id {node_id!r}")


class TaskKind(str, enum.Enum):
    PUSH = "push"
    PULL = "pull"
    CONTROL = "control"  # membership, heartbeats, workload assignment


@dataclasses.dataclass
class Task:
    kind: TaskKind
    customer: str
    #: logical timestamp assigned by the submitting Customer; the public async
    #: handle (``wait(ts)``).
    time: int = -1
    #: dependency: the receiver must have executed this customer's tasks up to
    #: ``wait_time`` before running this one (-1 = no dependency).  BSP sets
    #: it to ``time - 1``; SSP to ``time - 1 - max_delay``; ASP leaves -1.
    wait_time: int = -1
    #: free-form control payload (registration info, workload ids, ...).
    payload: dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Message:
    task: Task
    sender: str = ""
    recver: str = ""
    #: sorted unique key array for PUSH/PULL (may be row ids once localized).
    keys: Optional[np.ndarray] = None
    #: value arrays (gradients, weights, optimizer rows).
    values: list[np.ndarray] = dataclasses.field(default_factory=list)
    #: request vs response leg of an RPC pair.
    is_request: bool = True

    def reply(self, values: Optional[list[np.ndarray]] = None) -> "Message":
        """Build the response leg for this request."""
        return Message(
            task=self.task,
            sender=self.recver,
            recver=self.sender,
            keys=self.keys,
            values=values or [],
            is_request=False,
        )


class TimestampGenerator:
    """Thread-safe monotonically increasing timestamps (per customer)."""

    def __init__(self) -> None:
        self._counter = itertools.count()
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            return next(self._counter)


#: payload key carrying the sender's incarnation (restart epoch) number.
#: Stamped next to the per-link sequence (``core/resender.py``) so a node's
#: transport identity is ``(node_id, incarnation, seq)``: a process that
#: crashes and restarts under the SAME node id gets a higher incarnation,
#: receivers reset their dedup windows for it, and frames from the dead
#: pre-crash process (a "zombie") are fenced instead of corrupting state.
INCARNATION_KEY = "__rinc__"


class IncarnationRegistry:
    """Thread-safe ``node_id -> incarnation`` table.

    The scheduler (``core/manager.py``) is the authority that ASSIGNS
    incarnations (re-registration under an existing id bumps it); every
    transport endpoint keeps a registry like this as its local view — used
    both to stamp outgoing frames from local nodes and to fence inbound
    frames from stale incarnations of a peer.  Incarnations only ever
    advance: ``learn`` ignores regressions (a delayed broadcast must never
    re-open the fence).
    """

    def __init__(self) -> None:
        self._inc: dict[str, int] = {}
        self._lock = threading.Lock()

    def get(self, node_id: str) -> int:
        with self._lock:
            return self._inc.get(node_id, 0)

    def learn(self, node_id: str, incarnation: int) -> bool:
        """Record ``incarnation`` for ``node_id``; True iff it advanced."""
        with self._lock:
            if incarnation <= self._inc.get(node_id, 0):
                return False
            self._inc[node_id] = incarnation
            return True

    def bump(self, node_id: str) -> int:
        """Advance ``node_id``'s incarnation by one and return it."""
        with self._lock:
            inc = self._inc.get(node_id, 0) + 1
            self._inc[node_id] = inc
            return inc

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._inc)
