"""Core: messages, consistency clocks, Van/Postoffice, filters, membership.

The process-level runtime of the PS (SURVEY.md L1-L3).  Tensor traffic on
ICI never touches this layer (XLA collectives move it); these objects carry
control-plane and DCN-plane traffic.
"""

from parameter_server_tpu.core.chaos import ChaosConfig, ChaosVan
from parameter_server_tpu.core.coalesce import CoalescingVan
from parameter_server_tpu.core.fleet import FleetMonitor, StragglerPolicy
from parameter_server_tpu.core.messages import (
    Message,
    NodeRole,
    Task,
    TaskKind,
    server_id,
    worker_id,
)
from parameter_server_tpu.core.netmon import MeteredVan
from parameter_server_tpu.core.resender import ReliableVan
from parameter_server_tpu.core.van import LoopbackVan, Van, VanWrapper

__all__ = [
    "ChaosConfig",
    "ChaosVan",
    "CoalescingVan",
    "FleetMonitor",
    "LoopbackVan",
    "Message",
    "MeteredVan",
    "NodeRole",
    "ReliableVan",
    "StragglerPolicy",
    "Task",
    "TaskKind",
    "Van",
    "VanWrapper",
    "server_id",
    "worker_id",
]


def __getattr__(name):
    # TcpVan requires the native toolchain; import lazily so toolchain-less
    # hosts can still use the rest of core.
    if name == "TcpVan":
        from parameter_server_tpu.core.tcp_van import TcpVan

        return TcpVan
    raise AttributeError(name)
