"""core subpackage."""
