"""Wire filters: symmetric per-link message codecs (DCN plane).

Reference component #13 (``src/filter/*`` [U]): each RemoteNode link applies
a filter chain on send and the inverse chain on receive — key-list caching
(skip resending identical key arrays), compression (LZ4 there, zlib here —
stdlib, no vendored deps), and float->int fixed-point (int8 quantization,
``ops/quantize.py``).  ICI traffic never sees these; they exist for the DCN
Van and are exercised in-process through the LoopbackVan for tests and byte
accounting (the reference's network_usage.h role).

Filters mutate copies of the Message and must satisfy
``decode(encode(msg)) == msg`` (up to quantization error for FixingFloat).
"""

from __future__ import annotations

import hashlib
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from parameter_server_tpu.config import WireCompressionConfig
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.frame import COMPRESSED_KEY
from parameter_server_tpu.core.messages import Message, TaskKind
from parameter_server_tpu.ops.quantize import (
    dequantize_fp8,
    dequantize_int8,
    quantize_fp8,
    quantize_int8,
)

# Bundle frame constants, mirrored from core/coalesce.py (importing it here
# would cycle through core/van.py); test_compress asserts they stay equal.
_BUNDLE_CUSTOMER = "__bundle__"
_BUNDLE_KEY = "__subs__"
# Hierarchical-push group stamp, mirrored from kv/routing.py::GROUP_KEY
# (same cycle argument); test_group asserts they stay equal.  A PUSH whose
# stamp says ``ef: "bypass"`` skips the quantizer entirely: under rotating
# leader election the error-feedback residual owner would change every
# step, so compression is DISABLED for group frames rather than replaying
# another member's carried error (``ef: "leader"`` — fixed election — keeps
# quantizing; the pinned leader's (sender, table) store owns the group's
# residual).  See config.GroupConfig.
_GROUP_KEY = "__grp__"


def _group_bypass(payload) -> bool:
    """True when a PUSH payload's group stamp opts out of quantization."""
    grp = payload.get(_GROUP_KEY) if isinstance(payload, dict) else None
    return grp is not None and grp.get("ef") == "bypass"


def _msg_copy(msg: Message) -> Message:
    import dataclasses

    # copy the Task too: filters rewrite payload, and the sender's Message
    # object must stay untouched (Customer bookkeeping aliases it).
    task = dataclasses.replace(msg.task, payload=dict(msg.task.payload))
    return Message(
        task=task,
        sender=msg.sender,
        recver=msg.recver,
        keys=msg.keys,
        values=list(msg.values),
        is_request=msg.is_request,
    )


class Filter:
    """Filters with mutable per-link state guard it themselves (``_lock``);
    the Van applies chains concurrently from many sender threads."""

    name = "base"
    #: True when encode/decode need no per-link shared state, so the codec
    #: may run on paths without a route-table identity (e.g. TcpVan replies
    #: over the requester's connection).  KeyCaching is the stateful one.
    stateless = True

    def encode(self, msg: Message) -> Message:
        return msg

    def decode(self, msg: Message) -> Message:
        return msg

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        """Hook: the wire write for an encoded ``msg`` did not happen.

        Filters that committed per-link state during encode must roll it
        back here, or the link state desynchronizes from what the receiver
        actually saw.  ``encoded`` (when the Van has it) is the post-chain
        message, for filters whose rollback needs the encoded sizes.
        """


class KeyCachingFilter(Filter):
    """Drop the key array when the receiver has seen it (hash match).

    The reference caches key lists per link with a checksum
    (``src/filter/key_caching.h`` [U]); repeated pulls/pushes over the same
    key set (block iterations) then ship only the hash.
    """

    name = "key_caching"
    stateless = False

    def __init__(self) -> None:
        self._send_cache: Dict[tuple, Tuple[int, np.ndarray]] = {}
        self._recv_cache: Dict[tuple, Tuple[int, np.ndarray]] = {}
        self._lock = threading.Lock()
        self.hits = 0

    @staticmethod
    def _link(msg: Message) -> tuple:
        return (msg.sender, msg.recver, msg.task.customer, msg.task.kind)

    @staticmethod
    def _hash(keys: np.ndarray) -> int:
        # Order- and multiplicity-sensitive: hash the raw bytes (a permuted
        # key array must NOT hash-match, or values silently misalign).
        a = np.ascontiguousarray(keys)
        d = hashlib.blake2b(
            a.tobytes(), digest_size=8, person=a.dtype.str.encode()
        )
        return int.from_bytes(d.digest(), "little")

    def encode(self, msg: Message) -> Message:
        if msg.keys is None:
            return msg
        link = self._link(msg)
        h = self._hash(msg.keys)
        out = _msg_copy(msg)
        out.task.payload = dict(msg.task.payload, key_hash=h)
        with self._lock:
            cached = self._send_cache.get(link)
            if cached is not None and cached[0] == h:
                out.keys = None  # receiver restores from its cache
                self.hits += 1
            else:
                self._send_cache[link] = (h, msg.keys)
        return out

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        # The receiver never saw this frame: drop the link's send cache so
        # the next send re-ships the key list instead of a hash the peer
        # cannot resolve (which would poison every later hit on this set).
        with self._lock:
            self._send_cache.pop(self._link(msg), None)

    def decode(self, msg: Message) -> Message:
        h = msg.task.payload.get("key_hash")
        if h is None:
            return msg
        link = self._link(msg)
        out = _msg_copy(msg)
        with self._lock:
            if out.keys is None:
                cached = self._recv_cache.get(link)
                if cached is None or cached[0] != h:
                    raise RuntimeError(
                        f"key-cache miss on {link}: receiver lost the key list"
                    )
                out.keys = cached[1]
            else:
                self._recv_cache[link] = (h, out.keys)
        out.task.payload = {
            k: v for k, v in out.task.payload.items() if k != "key_hash"
        }
        return out


class CompressingFilter(Filter):
    """zlib-compress value AND key arrays (the reference's LZ4 role).

    Keys matter as much as values on this wire: pull requests are nothing
    but keys, and the sorted unique row ids the worker ships compress far
    better than random bytes.
    """

    name = "compressing"

    def __init__(self, level: int = 1) -> None:
        self.level = level
        self.bytes_in = 0
        self.bytes_out = 0
        self._lock = threading.Lock()  # counters only; codec is stateless

    def _compress(self, arr: np.ndarray) -> np.ndarray:
        raw = np.ascontiguousarray(arr).tobytes()
        comp = zlib.compress(raw, self.level)
        with self._lock:
            self.bytes_in += len(raw)
            self.bytes_out += len(comp)
        return np.frombuffer(comp, np.uint8)

    def encode(self, msg: Message) -> Message:
        out = _msg_copy(msg)
        blobs = []
        meta = []
        for v in msg.values:
            v = np.asarray(v)
            blobs.append(self._compress(v))
            meta.append((v.dtype.str, v.shape))
        out.values = blobs
        payload = dict(msg.task.payload, zlib_meta=meta)
        if msg.keys is not None:
            k = np.asarray(msg.keys)
            out.keys = self._compress(k)
            payload["zlib_keys"] = (k.dtype.str, k.shape)
        out.task.payload = payload
        return out

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        # Undo the byte accounting: encode committed bytes_in/bytes_out, but
        # the frame never hit the wire, so compressed_bytes()/wire totals
        # would overstate traffic on lossy links (ADVICE r3).  The encoded
        # message carries everything needed: blob sizes are the uint8 arrays
        # themselves, raw sizes reconstruct from the zlib_meta dtypes/shapes.
        if encoded is None:
            return
        meta = encoded.task.payload.get("zlib_meta")
        if meta is None:
            return
        raw = sum(
            int(np.dtype(dt).itemsize * np.prod(shape, dtype=np.int64))
            for dt, shape in meta
        )
        comp = sum(np.asarray(b).nbytes for b in encoded.values)
        kmeta = encoded.task.payload.get("zlib_keys")
        if kmeta is not None and encoded.keys is not None:
            dt, shape = kmeta
            raw += int(np.dtype(dt).itemsize * np.prod(shape, dtype=np.int64))
            comp += np.asarray(encoded.keys).nbytes
        with self._lock:
            self.bytes_in -= raw
            self.bytes_out -= comp

    def decode(self, msg: Message) -> Message:
        meta = msg.task.payload.get("zlib_meta")
        if meta is None:
            return msg
        out = _msg_copy(msg)
        out.values = [
            np.frombuffer(
                zlib.decompress(np.asarray(b).tobytes()), np.dtype(dt)
            ).reshape(shape)
            for b, (dt, shape) in zip(msg.values, meta)
        ]
        kmeta = msg.task.payload.get("zlib_keys")
        if kmeta is not None and msg.keys is not None:
            dt, shape = kmeta
            out.keys = np.frombuffer(
                zlib.decompress(np.asarray(msg.keys).tobytes()), np.dtype(dt)
            ).reshape(shape)
        out.task.payload = {
            k: v
            for k, v in out.task.payload.items()
            if k not in ("zlib_meta", "zlib_keys")
        }
        return out


def _resolve_per_row(per_row, v: np.ndarray) -> bool:
    """Resolve a ``per_row`` config (True | False | "auto") for one array.

    "auto" keeps the measured heuristic: per-row scales only pay off for
    wide rows — each costs 4 B of (uncompressed, header-borne) f32, so on
    narrow arrays (the dim=1 LR tables) they would rival the int8 payload
    itself and INFLATE wire bytes.
    """
    if per_row == "auto":
        return v.ndim >= 2 and v.shape[-1] >= 16
    return bool(per_row)


class FixingFloatFilter(Filter):
    """float32 -> int8 + scale per value array (fixing_float analogue).

    ``config`` (a :class:`WireCompressionConfig`) makes the scale layout
    and rounding explicit; legacy kwargs remain for the spec-string path.
    """

    name = "fixing_float"

    def __init__(
        self,
        stochastic: bool = False,
        seed: int = 0,
        config: Optional[WireCompressionConfig] = None,
    ) -> None:
        if config is not None:
            stochastic = stochastic or config.rounding == "stochastic"
            seed = config.seed if seed == 0 else seed
        self.per_row = config.per_row if config is not None else "auto"
        self.stochastic = stochastic
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()  # the RNG is not thread-safe

    def encode(self, msg: Message) -> Message:
        out = _msg_copy(msg)
        vals = []
        scales = []
        quantized = []
        for v in msg.values:
            v = np.asarray(v)
            if v.dtype == np.float32 and v.size:
                per_row = _resolve_per_row(self.per_row, v)
                if self.stochastic:  # only the RNG path needs the lock
                    with self._lock:
                        q, s = quantize_int8(
                            v, per_row=per_row, stochastic=True,
                            rng=self._rng,
                        )
                else:
                    q, s = quantize_int8(v, per_row=per_row)
                vals.append(q)
                scales.append(s)
                quantized.append(True)
            else:
                vals.append(v)
                scales.append(None)
                quantized.append(False)
        out.values = vals
        out.task.payload = dict(
            msg.task.payload, q8_scales=scales, q8_mask=quantized
        )
        return out

    def decode(self, msg: Message) -> Message:
        mask = msg.task.payload.get("q8_mask")
        if mask is None:
            return msg
        scales = msg.task.payload["q8_scales"]
        out = _msg_copy(msg)
        out.values = [
            dequantize_int8(v, s) if is_q else v
            for v, s, is_q in zip(msg.values, scales, mask)
        ]
        out.task.payload = {
            k: v
            for k, v in msg.task.payload.items()
            if k not in ("q8_scales", "q8_mask")
        }
        return out


#: residual stores flip from sorted-sparse to dense slot-indexed arrays once
#: they hold this many keys (and the dense array stays under the byte cap):
#: past that point the per-push sorted merge costs more than the scatter.
_DENSE_PROMOTE_KEYS = 16384
_DENSE_MAX_BYTES = 64 << 20


class QuantizingFilter(Filter):
    """Error-feedback lossy codec for the PUSH value plane (ISSUE 14).

    Composed UNDER :class:`~parameter_server_tpu.core.coalesce.CoalescingVan`
    (its ``codec=`` slot), so it runs ONCE per outgoing frame over the
    already-bundled value plane — member arrays are planes of the one
    bundle frame, quantized in a single pass with no re-encode.  Only PUSH
    *requests* are touched; PULL replies (the serving plane) stay bit-exact.

    Per ``(sender, table)`` the filter keeps a sorted-key residual store:
    the quantization error of each push is re-injected into the NEXT push
    for the same keys (gather by ``searchsorted``, commit by union merge)
    instead of lost — the EQuARX error-feedback scheme that makes lossy
    compression converge like the uncompressed run.  Residuals are keyed by
    sender because loopback test clusters share ONE van (and thus one codec
    instance) across every node.  EF is skipped (plain quantize) for planes
    whose key array is not strictly increasing: duplicate keys would make
    the residual scatter ambiguous.

    Lifecycle: :meth:`reset_residuals` drops stores on ``adopt_routing``
    (routing-epoch advance — key ranges moved), on a peer incarnation
    advance or same-id restart (``ReliableVan.on_incarnation_advance``),
    and on a failed wire write (``on_send_failed`` — the push never arrived
    and the app-level retry must not double-count carried error).

    Wire marker: payload ``COMPRESSED_KEY`` -> ``{"v": [entry|None per
    plane], "saved": bytes}`` where entry is ``(codec, fmt, dtype, shape,
    scale)``; the frame layer sets ``FLAG_COMPRESSED`` on it and MeteredVan
    uses ``saved`` to account raw vs wire bytes per link.  Decode is one
    table-gather/multiply per plane, straight off a read-only frombuffer
    view — no receive-side state.
    """

    name = "quantizing"
    stateless = True  # decode is marker-driven; residual state is keyed by
    # message content (sender/table), not by link identity

    def __init__(
        self,
        default: Optional[WireCompressionConfig] = None,
        per_table: Optional[Dict[str, WireCompressionConfig]] = None,
    ) -> None:
        self.default = default if default is not None else WireCompressionConfig()
        self.per_table = dict(per_table or {})
        self._lock = threading.Lock()
        self._rng = np.random.default_rng(self.default.seed)
        #: (sender, table) -> {"keys": int64[n] sorted, "vals": f32[n, ...],
        #: "sq": float running sum of squared residuals}
        self._residuals: Dict[Tuple[str, str], dict] = {}
        self.raw_bytes = 0
        self.wire_bytes = 0
        self.resets = 0

    # -- config -------------------------------------------------------------
    def _cfg(self, table: Optional[str]) -> WireCompressionConfig:
        cfg = self.per_table.get(table) if table is not None else None
        return cfg if cfg is not None else self.default

    # -- quantize core (callers hold self._lock: the RNG is not thread-safe)
    def _quantize_plane(self, cfg: WireCompressionConfig, g: np.ndarray):
        per_row = _resolve_per_row(cfg.per_row, g)
        stoch = cfg.rounding == "stochastic"
        rng = self._rng if stoch else None
        if cfg.codec == "int8":
            q, s = quantize_int8(g, per_row=per_row, stochastic=stoch, rng=rng)
            dq = dequantize_int8(q, s)
        else:
            q, s = quantize_fp8(
                g, fmt=cfg.fp8_format, per_row=per_row, stochastic=stoch,
                rng=rng,
            )
            dq = dequantize_fp8(q, s, fmt=cfg.fp8_format)
        return q, s, dq

    def _encode_value(
        self,
        cfg: WireCompressionConfig,
        sender: str,
        table: Optional[str],
        keys: Optional[np.ndarray],
        v: np.ndarray,
    ):
        """Quantize one plane, with error feedback when keys align with rows.

        Eligible key planes are the worker push layout: sorted unique slot
        ids, optionally padded to a power-of-two bucket with a constant
        trash-row tail (``utils.keys.localize_to_slots``).  EF covers the
        strictly-increasing real prefix; pad rows are zeros and quantize
        exactly, so skipping them loses nothing.
        """
        k = None
        n_real = 0
        if cfg.error_feedback and table is not None and keys is not None:
            ka = np.asarray(keys)
            if ka.ndim == 1 and v.ndim >= 1 and ka.shape[0] == v.shape[0]:
                if ka.size < 2 or bool(np.all(ka[1:] > ka[:-1])):
                    n_real = ka.size
                else:
                    # padded bucket: real slots strictly increase, then a
                    # constant run of the localizer's trash row
                    p = int(np.searchsorted(ka, ka[-1], side="left"))
                    if (
                        p >= 1
                        and bool(np.all(ka[p:] == ka[-1]))
                        and bool(np.all(ka[1:p] > ka[: p - 1]))
                    ):
                        n_real = p
                if n_real:
                    k = ka[:n_real].astype(np.int64, copy=False).reshape(-1)
        if k is None:
            q, s, _dq = self._quantize_plane(cfg, v)
            return q, s
        st = self._residuals.get((sender, table))
        if st is not None and st["vals"].shape[1:] != v.shape[1:]:
            st = None  # table reshaped underneath us: the store is stale
        if st is not None and st.get("dense"):
            return self._ef_dense(cfg, st, k, n_real, v)
        pos = hit = None
        r = None
        if st is not None and len(st["keys"]):
            pos = np.minimum(
                np.searchsorted(st["keys"], k), len(st["keys"]) - 1
            )
            hit = st["keys"][pos] == k
            if hit.any():
                r = np.zeros_like(v, dtype=np.float32)
                r[:n_real][hit] = st["vals"][pos[hit]]
        g = v if r is None else v + r
        q, s, dq = self._quantize_plane(cfg, g)
        err = np.ascontiguousarray((g - dq)[:n_real], dtype=np.float32)
        if st is None:
            st = {"keys": k.copy(), "vals": err, "sq": float((err * err).sum())}
            self._residuals[(sender, table)] = st
            self._maybe_promote_dense(st)
            return q, s
        # Commit without re-sorting: both key arrays are sorted, so hits
        # update in place (reusing the gather's searchsorted) and misses
        # splice in with one O(n) np.insert — the union1d rebuild this
        # replaces cost ~2.5 ms/step at the bench's 8k-key pushes.
        sq = st["sq"] + float((err * err).sum())
        if hit is not None and hit.any():
            old = st["vals"][pos[hit]]
            sq -= float((old * old).sum())
            st["vals"][pos[hit]] = err[hit]
            new = ~hit
        else:
            new = np.ones(len(k), dtype=bool)
        if new.any():
            nk = k[new]
            idx = np.searchsorted(st["keys"], nk)
            st["keys"] = np.insert(st["keys"], idx, nk)
            st["vals"] = np.insert(st["vals"], idx, err[new], axis=0)
        st["sq"] = max(sq, 0.0)
        self._maybe_promote_dense(st)
        return q, s

    def _maybe_promote_dense(self, st: dict) -> None:
        """Flip a hot sparse store to a slot-indexed dense array.

        Slot ids are bounded by the sender's localizer capacity, so once a
        store holds enough keys the O(n) sorted-merge per push costs more
        than a dense table it could scatter into directly.  Promotion is
        gated on the projected array size so fat-dim tables stay sparse.
        """
        if len(st["keys"]) < _DENSE_PROMOTE_KEYS:
            return
        tail = st["vals"].shape[1:]
        # slot ids come from power-of-two localizer buckets: round capacity
        # up so later pushes with higher slots rarely force a regrow
        cap = 1 << int(st["keys"][-1]).bit_length()
        if cap * int(np.prod(tail, dtype=np.int64)) * 4 > _DENSE_MAX_BYTES:
            return
        dense = np.zeros((cap,) + tail, np.float32)
        dense[st["keys"]] = st["vals"]
        st["vals"] = dense
        st["dense"] = True
        del st["keys"]

    def _ef_dense(self, cfg, st: dict, k, n_real: int, v: np.ndarray):
        """Error-feedback round trip against a dense slot-indexed store."""
        dense = st["vals"]
        top = int(k[-1])
        if top >= dense.shape[0]:
            cap = 1 << top.bit_length()  # pow2 growth: amortize regrows
            pad = np.zeros(
                (cap - dense.shape[0],) + dense.shape[1:], np.float32
            )
            dense = np.concatenate([dense, pad])
            st["vals"] = dense
        old = dense[k]
        g = v.astype(np.float32, copy=True)
        g[:n_real] += old
        q, s, dq = self._quantize_plane(cfg, g)
        err = np.ascontiguousarray((g - dq)[:n_real], dtype=np.float32)
        dense[k] = err
        st["sq"] = max(
            st["sq"] + float((err * err).sum()) - float((old * old).sum()), 0.0
        )
        return q, s

    # -- codec --------------------------------------------------------------
    def encode(self, msg: Message) -> Message:
        if not msg.is_request:
            return msg
        payload = msg.task.payload
        if (
            msg.task.customer == _BUNDLE_CUSTOMER
            and payload.get(_BUNDLE_KEY) is not None
        ):
            return self._encode_bundle(msg)
        if msg.task.kind is not TaskKind.PUSH:
            return msg
        if _group_bypass(payload):
            return msg
        table = payload.get("table")
        cfg = self._cfg(table)
        if cfg.codec == "none" or not msg.values:
            return msg
        entries: List[Optional[tuple]] = [None] * len(msg.values)
        new_vals = list(msg.values)
        raw = wire = 0
        with self._lock:
            for i, v in enumerate(msg.values):
                v = np.asarray(v)
                if v.dtype != np.float32 or not v.size:
                    continue
                q, s = self._encode_value(cfg, msg.sender, table, msg.keys, v)
                new_vals[i] = q
                entries[i] = (
                    cfg.codec, cfg.fp8_format, v.dtype.str, tuple(v.shape), s
                )
                raw += v.nbytes
                wire += q.nbytes + np.asarray(s).nbytes
        return self._finish_encode(msg, entries, new_vals, raw, wire)

    def _encode_bundle(self, msg: Message) -> Message:
        """One pass over a CoalescingVan bundle's concatenated value plane."""
        index = msg.task.payload[_BUNDLE_KEY]
        key_bytes = (
            np.ascontiguousarray(msg.keys).reshape(-1).view(np.uint8)
            if msg.keys is not None
            else np.empty(0, dtype=np.uint8)
        )
        entries: List[Optional[tuple]] = [None] * len(msg.values)
        new_vals = list(msg.values)
        raw = wire = 0
        k_off = v_off = 0
        with self._lock:
            for customer, kind, _t, _w, payload, is_request, key_meta, n_v in index:
                chunk = None
                if key_meta is not None:
                    dt, shape, nbytes = key_meta
                    chunk = key_bytes[k_off : k_off + nbytes]
                    k_off += nbytes
                if (
                    kind == TaskKind.PUSH.value
                    and is_request
                    and not _group_bypass(payload)
                ):
                    table = payload.get("table")
                    cfg = self._cfg(table)
                    if cfg.codec != "none":
                        keys = (
                            chunk.copy().view(np.dtype(dt)).reshape(shape)
                            if chunk is not None
                            else None
                        )
                        for j in range(v_off, v_off + n_v):
                            v = np.asarray(msg.values[j])
                            if v.dtype != np.float32 or not v.size:
                                continue
                            q, s = self._encode_value(
                                cfg, msg.sender, table, keys, v
                            )
                            new_vals[j] = q
                            entries[j] = (
                                cfg.codec, cfg.fp8_format, v.dtype.str,
                                tuple(v.shape), s,
                            )
                            raw += v.nbytes
                            wire += q.nbytes + np.asarray(s).nbytes
                v_off += n_v
        return self._finish_encode(msg, entries, new_vals, raw, wire)

    def _finish_encode(self, msg, entries, new_vals, raw, wire) -> Message:
        if raw == 0:  # nothing quantizable on this frame
            return msg
        out = _msg_copy(msg)
        out.values = new_vals
        out.task.payload[COMPRESSED_KEY] = {
            "v": entries,
            "saved": int(raw - wire),
        }
        with self._lock:
            self.raw_bytes += raw
            self.wire_bytes += wire
        flightrec.record(
            "compress.encode",
            node=msg.sender,
            recver=msg.recver,
            planes=sum(e is not None for e in entries),
            bytes_in=raw,
            bytes_out=wire,
        )
        return out

    def decode(self, msg: Message) -> Message:
        wc = msg.task.payload.get(COMPRESSED_KEY)
        if wc is None:
            return msg
        out = _msg_copy(msg)
        vals = list(msg.values)
        n = 0
        for i, ent in enumerate(wc["v"]):
            if ent is None:
                continue
            codec, fmt, dt, shape, scale = ent
            q = np.asarray(vals[i])
            if codec == "int8":
                x = dequantize_int8(q, scale)
            else:
                x = dequantize_fp8(q, scale, fmt=fmt)
            vals[i] = np.ascontiguousarray(
                x.astype(np.dtype(dt), copy=False)
            ).reshape(tuple(shape))
            n += 1
        out.values = vals
        out.task.payload = {
            k: v for k, v in msg.task.payload.items() if k != COMPRESSED_KEY
        }
        flightrec.record(
            "compress.decode", node=msg.recver, sender=msg.sender, planes=n
        )
        return out

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        # The frame never hit the wire: any residual committed during its
        # encode describes error the receiver never absorbed, and the
        # app-level retry will re-push the full gradient.  Conservatively
        # drop this sender's stores rather than replay carried error twice.
        marker = (encoded or msg).task.payload.get(COMPRESSED_KEY)
        if marker is not None:
            self.reset_residuals(sender=msg.sender, reason="send_failed")

    # -- lifecycle / metrics ------------------------------------------------
    def reset_residuals(
        self,
        *,
        sender: Optional[str] = None,
        table: Optional[str] = None,
        reason: str = "manual",
    ) -> int:
        """Drop residual stores matching ``sender``/``table`` (None = all)."""
        with self._lock:
            doomed = [
                key
                for key in self._residuals
                if (sender is None or key[0] == sender)
                and (table is None or key[1] == table)
            ]
            for key in doomed:
                del self._residuals[key]
            self.resets += 1
        flightrec.record(
            "compress.residual_reset",
            node=sender if sender is not None else "*",
            table=table if table is not None else "*",
            reason=reason,
            dropped=len(doomed),
        )
        return len(doomed)

    def residual_norm(self) -> float:
        """L2 norm of every outstanding residual (the EF debt gauge)."""
        with self._lock:
            sq = sum(st["sq"] for st in self._residuals.values())
        return float(np.sqrt(max(sq, 0.0)))

    def counters(self) -> dict:
        with self._lock:
            raw, wire = self.raw_bytes, self.wire_bytes
            resets = self.resets
            sq = sum(st["sq"] for st in self._residuals.values())
        out = {
            "compress_raw_bytes": raw,
            "compress_wire_bytes": wire,
            "compress_resets": resets,
            "compress_residual_norm": round(float(np.sqrt(max(sq, 0.0))), 6),
        }
        if raw:
            out["compress_ratio_pct"] = round(100.0 * wire / raw, 2)
        return out


def find_quantizers(van) -> List[QuantizingFilter]:
    """Every QuantizingFilter reachable from a van stack, outermost-first.

    Walks ``.inner`` links, collecting CoalescingVan ``codec`` slots and any
    QuantizingFilter sitting inside a ``filter_chain`` — deduplicated by
    identity (VanWrapper ``__getattr__`` delegation would otherwise report
    the same codec at every level).  Workers use this from ``adopt_routing``
    to reset residuals without knowing the stack shape.
    """
    out: List[QuantizingFilter] = []
    seen: set = set()
    seen_vans: set = set()
    v = van
    while v is not None and id(v) not in seen_vans:
        seen_vans.add(id(v))
        codec = getattr(v, "codec", None)
        if isinstance(codec, QuantizingFilter) and id(codec) not in seen:
            seen.add(id(codec))
            out.append(codec)
        chain = getattr(v, "filter_chain", None)
        for f in getattr(chain, "filters", ()) or ():
            if isinstance(f, QuantizingFilter) and id(f) not in seen:
                seen.add(id(f))
                out.append(f)
        v = getattr(v, "inner", None)
    return out


class AddNoiseFilter(Filter):
    """Debug filter: Gaussian noise on float32 values at encode time.

    The reference ships an ``add_noise`` codec (``src/filter/add_noise.h``
    [U]) for robustness experiments — perturb pushed gradients/pulled
    weights on the wire and watch whether training still converges (async
    SGD should; a brittle pipeline won't).  Decode is the identity: noise
    is injected, not round-tripped.
    """

    name = "add_noise"

    def __init__(self, sigma: float = 1e-3, seed: int = 0) -> None:
        self.sigma = sigma
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()  # the RNG is not thread-safe

    def encode(self, msg: Message) -> Message:
        out = _msg_copy(msg)
        vals = []
        for v in msg.values:
            v = np.asarray(v)
            if v.dtype == np.float32 and v.size:
                with self._lock:
                    noise = self._rng.normal(0.0, self.sigma, v.shape)
                v = (v + noise).astype(np.float32)
            vals.append(v)
        out.values = vals
        return out


class FilterChain:
    """Apply filters in order on send, reverse order on receive.

    Tracks wall-clock spent encoding/decoding (``overhead()``) so the
    default-on codecs are justified by measurement, not belief (VERDICT r3
    weak #8): per-message codec cost vs the wire bytes it saves.
    """

    def __init__(self, filters: List[Filter]) -> None:
        self.filters = filters
        self._t_lock = threading.Lock()
        self.encode_ns = 0
        self.decode_ns = 0
        self.encode_calls = 0
        self.decode_calls = 0

    def encode(self, msg: Message) -> Message:
        t0 = time.perf_counter_ns()
        for f in self.filters:
            msg = f.encode(msg)
        dt = time.perf_counter_ns() - t0
        with self._t_lock:
            self.encode_ns += dt
            self.encode_calls += 1
        return msg

    def decode(self, msg: Message) -> Message:
        t0 = time.perf_counter_ns()
        for f in reversed(self.filters):
            msg = f.decode(msg)
        dt = time.perf_counter_ns() - t0
        with self._t_lock:
            self.decode_ns += dt
            self.decode_calls += 1
        return msg

    def overhead(self) -> dict:
        """Per-message codec cost: mean encode/decode microseconds."""
        with self._t_lock:
            return {
                "encode_us_per_msg": round(
                    self.encode_ns / max(self.encode_calls, 1) / 1e3, 2
                ),
                "decode_us_per_msg": round(
                    self.decode_ns / max(self.decode_calls, 1) / 1e3, 2
                ),
                "encode_calls": self.encode_calls,
                "decode_calls": self.decode_calls,
            }

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        for f in self.filters:
            f.on_send_failed(msg, encoded)

    def stateless_subchain(self) -> "FilterChain":
        """The per-link-state-free filters, SAME instances (shared counters).

        Decode is marker-driven (each filter acts only on its own payload
        keys), so a receiver's full chain correctly decodes messages encoded
        with this subset — the Van uses it on reply paths that lack a
        route-table link identity.
        """
        return FilterChain([f for f in self.filters if f.stateless])

    def compressed_bytes(self) -> Tuple[int, int]:
        """(bytes_in, bytes_out) summed over compressing members."""
        bi = bo = 0
        for f in self.filters:
            if isinstance(f, CompressingFilter):
                bi += f.bytes_in
                bo += f.bytes_out
        return bi, bo


def quantizer_from_tables(
    tables, default: Optional[WireCompressionConfig] = None
) -> Optional[QuantizingFilter]:
    """Build the CoalescingVan codec from per-table configs, or None.

    ``tables``: iterable of :class:`~parameter_server_tpu.config.TableConfig`
    or a ``{name: TableConfig}`` dict (the shape servers/workers carry);
    their ``compression`` fields select per-table codecs; ``default``
    applies to tables without one.  Returns None when nothing asks for
    compression, so callers can pass the result straight to
    ``CoalescingVan(..., codec=...)``.
    """
    if isinstance(tables, dict):
        tables = tables.values()
    per_table = {
        t.name: t.compression
        for t in tables
        if getattr(t, "compression", None) is not None
    }
    if not per_table and (default is None or default.codec == "none"):
        return None
    return QuantizingFilter(default=default, per_table=per_table)


#: filter factories by spec token; order in the spec string = encode order.
_FILTER_FACTORIES = {
    "key_caching": KeyCachingFilter,
    "int8": FixingFloatFilter,
    "zlib": CompressingFilter,
    "noise": AddNoiseFilter,
    # the error-feedback int8 codec as a chain member (launcher opt-in);
    # the preferred composition is CoalescingVan(codec=...), where it runs
    # once per bundle, but in-chain it still handles bundle frames whole.
    "quantize": lambda: QuantizingFilter(
        WireCompressionConfig(codec="int8", error_feedback=True)
    ),
}

#: The launcher default for DCN vans (VERDICT r3 #7): codecs on by default —
#: the wire reduction should not depend on remembering a flag — but the
#: default stack is the LOSSLESS pair (ADVICE r4: an unconfigured launch
#: must not silently train on int8-quantized gradients).  ``"full"`` adds
#: the lossy int8 quantizer as an explicit opt-in; ``--filters none`` opts
#: out entirely.  zlib earns its slot even without int8: measured on the
#: 2w2s launch flow, key_caching -> key_caching+zlib cuts wire bytes 40%
#: (168 kB -> 100 kB; keys and headers compress well even though float
#: mantissas don't) for ~145 us extra encode per message.
DEFAULT_SPEC = "lossless"


def make_chain(spec: str) -> Optional[FilterChain]:
    """Build a chain from a launcher-friendly spec string.

    ``"none"``/empty -> None.  Otherwise a ``+``-separated pipeline over
    {key_caching, int8, zlib, noise}, applied in spec order on encode and
    reverse order on decode — e.g. ``"int8+zlib"`` quantizes then
    compresses (the useful DCN stack: zlib over raw float mantissas saves
    ~nothing).  ``"lossless"`` = ``key_caching+zlib`` (the default — bit-
    exact on the wire); ``"full"`` = ``key_caching+int8+zlib``, which adds
    the LOSSY int8 gradient/weight quantizer and is an explicit opt-in.
    ``noise`` is the debug add_noise codec.
    """
    if spec in ("", "none", None):
        return None
    if spec == "lossless":
        spec = "key_caching+zlib"
    elif spec == "full":
        spec = "key_caching+int8+zlib"
    filters = []
    for part in spec.split("+"):
        if part not in _FILTER_FACTORIES:
            raise ValueError(
                f"unknown filter {part!r} in spec; have "
                f"{sorted(_FILTER_FACTORIES)} (or 'none'/'lossless'/'full')"
            )
        filters.append(_FILTER_FACTORIES[part]())
    return FilterChain(filters)
