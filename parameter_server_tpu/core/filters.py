"""Wire filters: symmetric per-link message codecs (DCN plane).

Reference component #13 (``src/filter/*`` [U]): each RemoteNode link applies
a filter chain on send and the inverse chain on receive — key-list caching
(skip resending identical key arrays), compression (LZ4 there, zlib here —
stdlib, no vendored deps), and float->int fixed-point (int8 quantization,
``ops/quantize.py``).  ICI traffic never sees these; they exist for the DCN
Van and are exercised in-process through the LoopbackVan for tests and byte
accounting (the reference's network_usage.h role).

Filters mutate copies of the Message and must satisfy
``decode(encode(msg)) == msg`` (up to quantization error for FixingFloat).
"""

from __future__ import annotations

import hashlib
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from parameter_server_tpu.core.messages import Message
from parameter_server_tpu.ops.quantize import dequantize_int8, quantize_int8


def _msg_copy(msg: Message) -> Message:
    import dataclasses

    # copy the Task too: filters rewrite payload, and the sender's Message
    # object must stay untouched (Customer bookkeeping aliases it).
    task = dataclasses.replace(msg.task, payload=dict(msg.task.payload))
    return Message(
        task=task,
        sender=msg.sender,
        recver=msg.recver,
        keys=msg.keys,
        values=list(msg.values),
        is_request=msg.is_request,
    )


class Filter:
    """Filters with mutable per-link state guard it themselves (``_lock``);
    the Van applies chains concurrently from many sender threads."""

    name = "base"
    #: True when encode/decode need no per-link shared state, so the codec
    #: may run on paths without a route-table identity (e.g. TcpVan replies
    #: over the requester's connection).  KeyCaching is the stateful one.
    stateless = True

    def encode(self, msg: Message) -> Message:
        return msg

    def decode(self, msg: Message) -> Message:
        return msg

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        """Hook: the wire write for an encoded ``msg`` did not happen.

        Filters that committed per-link state during encode must roll it
        back here, or the link state desynchronizes from what the receiver
        actually saw.  ``encoded`` (when the Van has it) is the post-chain
        message, for filters whose rollback needs the encoded sizes.
        """


class KeyCachingFilter(Filter):
    """Drop the key array when the receiver has seen it (hash match).

    The reference caches key lists per link with a checksum
    (``src/filter/key_caching.h`` [U]); repeated pulls/pushes over the same
    key set (block iterations) then ship only the hash.
    """

    name = "key_caching"
    stateless = False

    def __init__(self) -> None:
        self._send_cache: Dict[tuple, Tuple[int, np.ndarray]] = {}
        self._recv_cache: Dict[tuple, Tuple[int, np.ndarray]] = {}
        self._lock = threading.Lock()
        self.hits = 0

    @staticmethod
    def _link(msg: Message) -> tuple:
        return (msg.sender, msg.recver, msg.task.customer, msg.task.kind)

    @staticmethod
    def _hash(keys: np.ndarray) -> int:
        # Order- and multiplicity-sensitive: hash the raw bytes (a permuted
        # key array must NOT hash-match, or values silently misalign).
        a = np.ascontiguousarray(keys)
        d = hashlib.blake2b(
            a.tobytes(), digest_size=8, person=a.dtype.str.encode()
        )
        return int.from_bytes(d.digest(), "little")

    def encode(self, msg: Message) -> Message:
        if msg.keys is None:
            return msg
        link = self._link(msg)
        h = self._hash(msg.keys)
        out = _msg_copy(msg)
        out.task.payload = dict(msg.task.payload, key_hash=h)
        with self._lock:
            cached = self._send_cache.get(link)
            if cached is not None and cached[0] == h:
                out.keys = None  # receiver restores from its cache
                self.hits += 1
            else:
                self._send_cache[link] = (h, msg.keys)
        return out

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        # The receiver never saw this frame: drop the link's send cache so
        # the next send re-ships the key list instead of a hash the peer
        # cannot resolve (which would poison every later hit on this set).
        with self._lock:
            self._send_cache.pop(self._link(msg), None)

    def decode(self, msg: Message) -> Message:
        h = msg.task.payload.get("key_hash")
        if h is None:
            return msg
        link = self._link(msg)
        out = _msg_copy(msg)
        with self._lock:
            if out.keys is None:
                cached = self._recv_cache.get(link)
                if cached is None or cached[0] != h:
                    raise RuntimeError(
                        f"key-cache miss on {link}: receiver lost the key list"
                    )
                out.keys = cached[1]
            else:
                self._recv_cache[link] = (h, out.keys)
        out.task.payload = {
            k: v for k, v in out.task.payload.items() if k != "key_hash"
        }
        return out


class CompressingFilter(Filter):
    """zlib-compress value AND key arrays (the reference's LZ4 role).

    Keys matter as much as values on this wire: pull requests are nothing
    but keys, and the sorted unique row ids the worker ships compress far
    better than random bytes.
    """

    name = "compressing"

    def __init__(self, level: int = 1) -> None:
        self.level = level
        self.bytes_in = 0
        self.bytes_out = 0
        self._lock = threading.Lock()  # counters only; codec is stateless

    def _compress(self, arr: np.ndarray) -> np.ndarray:
        raw = np.ascontiguousarray(arr).tobytes()
        comp = zlib.compress(raw, self.level)
        with self._lock:
            self.bytes_in += len(raw)
            self.bytes_out += len(comp)
        return np.frombuffer(comp, np.uint8)

    def encode(self, msg: Message) -> Message:
        out = _msg_copy(msg)
        blobs = []
        meta = []
        for v in msg.values:
            v = np.asarray(v)
            blobs.append(self._compress(v))
            meta.append((v.dtype.str, v.shape))
        out.values = blobs
        payload = dict(msg.task.payload, zlib_meta=meta)
        if msg.keys is not None:
            k = np.asarray(msg.keys)
            out.keys = self._compress(k)
            payload["zlib_keys"] = (k.dtype.str, k.shape)
        out.task.payload = payload
        return out

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        # Undo the byte accounting: encode committed bytes_in/bytes_out, but
        # the frame never hit the wire, so compressed_bytes()/wire totals
        # would overstate traffic on lossy links (ADVICE r3).  The encoded
        # message carries everything needed: blob sizes are the uint8 arrays
        # themselves, raw sizes reconstruct from the zlib_meta dtypes/shapes.
        if encoded is None:
            return
        meta = encoded.task.payload.get("zlib_meta")
        if meta is None:
            return
        raw = sum(
            int(np.dtype(dt).itemsize * np.prod(shape, dtype=np.int64))
            for dt, shape in meta
        )
        comp = sum(np.asarray(b).nbytes for b in encoded.values)
        kmeta = encoded.task.payload.get("zlib_keys")
        if kmeta is not None and encoded.keys is not None:
            dt, shape = kmeta
            raw += int(np.dtype(dt).itemsize * np.prod(shape, dtype=np.int64))
            comp += np.asarray(encoded.keys).nbytes
        with self._lock:
            self.bytes_in -= raw
            self.bytes_out -= comp

    def decode(self, msg: Message) -> Message:
        meta = msg.task.payload.get("zlib_meta")
        if meta is None:
            return msg
        out = _msg_copy(msg)
        out.values = [
            np.frombuffer(
                zlib.decompress(np.asarray(b).tobytes()), np.dtype(dt)
            ).reshape(shape)
            for b, (dt, shape) in zip(msg.values, meta)
        ]
        kmeta = msg.task.payload.get("zlib_keys")
        if kmeta is not None and msg.keys is not None:
            dt, shape = kmeta
            out.keys = np.frombuffer(
                zlib.decompress(np.asarray(msg.keys).tobytes()), np.dtype(dt)
            ).reshape(shape)
        out.task.payload = {
            k: v
            for k, v in out.task.payload.items()
            if k not in ("zlib_meta", "zlib_keys")
        }
        return out


class FixingFloatFilter(Filter):
    """float32 -> int8 + scale per value array (fixing_float analogue)."""

    name = "fixing_float"

    def __init__(self, stochastic: bool = False, seed: int = 0) -> None:
        self.stochastic = stochastic
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()  # the RNG is not thread-safe

    def encode(self, msg: Message) -> Message:
        out = _msg_copy(msg)
        vals = []
        scales = []
        quantized = []
        for v in msg.values:
            v = np.asarray(v)
            if v.dtype == np.float32 and v.size:
                # Per-row scales only pay off for wide rows: each costs 4 B
                # of (uncompressed, header-borne) f32, so on narrow arrays —
                # the dim=1 LR tables — they would rival the int8 payload
                # itself and INFLATE wire bytes.  Narrow arrays get one
                # per-tensor scale.
                per_row = v.ndim >= 2 and v.shape[-1] >= 16
                if self.stochastic:  # only the RNG path needs the lock
                    with self._lock:
                        q, s = quantize_int8(
                            v, per_row=per_row, stochastic=True,
                            rng=self._rng,
                        )
                else:
                    q, s = quantize_int8(v, per_row=per_row)
                vals.append(q)
                scales.append(s)
                quantized.append(True)
            else:
                vals.append(v)
                scales.append(None)
                quantized.append(False)
        out.values = vals
        out.task.payload = dict(
            msg.task.payload, q8_scales=scales, q8_mask=quantized
        )
        return out

    def decode(self, msg: Message) -> Message:
        mask = msg.task.payload.get("q8_mask")
        if mask is None:
            return msg
        scales = msg.task.payload["q8_scales"]
        out = _msg_copy(msg)
        out.values = [
            dequantize_int8(v, s) if is_q else v
            for v, s, is_q in zip(msg.values, scales, mask)
        ]
        out.task.payload = {
            k: v
            for k, v in msg.task.payload.items()
            if k not in ("q8_scales", "q8_mask")
        }
        return out


class AddNoiseFilter(Filter):
    """Debug filter: Gaussian noise on float32 values at encode time.

    The reference ships an ``add_noise`` codec (``src/filter/add_noise.h``
    [U]) for robustness experiments — perturb pushed gradients/pulled
    weights on the wire and watch whether training still converges (async
    SGD should; a brittle pipeline won't).  Decode is the identity: noise
    is injected, not round-tripped.
    """

    name = "add_noise"

    def __init__(self, sigma: float = 1e-3, seed: int = 0) -> None:
        self.sigma = sigma
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()  # the RNG is not thread-safe

    def encode(self, msg: Message) -> Message:
        out = _msg_copy(msg)
        vals = []
        for v in msg.values:
            v = np.asarray(v)
            if v.dtype == np.float32 and v.size:
                with self._lock:
                    noise = self._rng.normal(0.0, self.sigma, v.shape)
                v = (v + noise).astype(np.float32)
            vals.append(v)
        out.values = vals
        return out


class FilterChain:
    """Apply filters in order on send, reverse order on receive.

    Tracks wall-clock spent encoding/decoding (``overhead()``) so the
    default-on codecs are justified by measurement, not belief (VERDICT r3
    weak #8): per-message codec cost vs the wire bytes it saves.
    """

    def __init__(self, filters: List[Filter]) -> None:
        self.filters = filters
        self._t_lock = threading.Lock()
        self.encode_ns = 0
        self.decode_ns = 0
        self.encode_calls = 0
        self.decode_calls = 0

    def encode(self, msg: Message) -> Message:
        t0 = time.perf_counter_ns()
        for f in self.filters:
            msg = f.encode(msg)
        dt = time.perf_counter_ns() - t0
        with self._t_lock:
            self.encode_ns += dt
            self.encode_calls += 1
        return msg

    def decode(self, msg: Message) -> Message:
        t0 = time.perf_counter_ns()
        for f in reversed(self.filters):
            msg = f.decode(msg)
        dt = time.perf_counter_ns() - t0
        with self._t_lock:
            self.decode_ns += dt
            self.decode_calls += 1
        return msg

    def overhead(self) -> dict:
        """Per-message codec cost: mean encode/decode microseconds."""
        with self._t_lock:
            return {
                "encode_us_per_msg": round(
                    self.encode_ns / max(self.encode_calls, 1) / 1e3, 2
                ),
                "decode_us_per_msg": round(
                    self.decode_ns / max(self.decode_calls, 1) / 1e3, 2
                ),
                "encode_calls": self.encode_calls,
                "decode_calls": self.decode_calls,
            }

    def on_send_failed(
        self, msg: Message, encoded: Optional[Message] = None
    ) -> None:
        for f in self.filters:
            f.on_send_failed(msg, encoded)

    def stateless_subchain(self) -> "FilterChain":
        """The per-link-state-free filters, SAME instances (shared counters).

        Decode is marker-driven (each filter acts only on its own payload
        keys), so a receiver's full chain correctly decodes messages encoded
        with this subset — the Van uses it on reply paths that lack a
        route-table link identity.
        """
        return FilterChain([f for f in self.filters if f.stateless])

    def compressed_bytes(self) -> Tuple[int, int]:
        """(bytes_in, bytes_out) summed over compressing members."""
        bi = bo = 0
        for f in self.filters:
            if isinstance(f, CompressingFilter):
                bi += f.bytes_in
                bo += f.bytes_out
        return bi, bo


#: filter factories by spec token; order in the spec string = encode order.
_FILTER_FACTORIES = {
    "key_caching": KeyCachingFilter,
    "int8": FixingFloatFilter,
    "zlib": CompressingFilter,
    "noise": AddNoiseFilter,
}

#: The launcher default for DCN vans (VERDICT r3 #7): codecs on by default —
#: the wire reduction should not depend on remembering a flag — but the
#: default stack is the LOSSLESS pair (ADVICE r4: an unconfigured launch
#: must not silently train on int8-quantized gradients).  ``"full"`` adds
#: the lossy int8 quantizer as an explicit opt-in; ``--filters none`` opts
#: out entirely.  zlib earns its slot even without int8: measured on the
#: 2w2s launch flow, key_caching -> key_caching+zlib cuts wire bytes 40%
#: (168 kB -> 100 kB; keys and headers compress well even though float
#: mantissas don't) for ~145 us extra encode per message.
DEFAULT_SPEC = "lossless"


def make_chain(spec: str) -> Optional[FilterChain]:
    """Build a chain from a launcher-friendly spec string.

    ``"none"``/empty -> None.  Otherwise a ``+``-separated pipeline over
    {key_caching, int8, zlib, noise}, applied in spec order on encode and
    reverse order on decode — e.g. ``"int8+zlib"`` quantizes then
    compresses (the useful DCN stack: zlib over raw float mantissas saves
    ~nothing).  ``"lossless"`` = ``key_caching+zlib`` (the default — bit-
    exact on the wire); ``"full"`` = ``key_caching+int8+zlib``, which adds
    the LOSSY int8 gradient/weight quantizer and is an explicit opt-in.
    ``noise`` is the debug add_noise codec.
    """
    if spec in ("", "none", None):
        return None
    if spec == "lossless":
        spec = "key_caching+zlib"
    elif spec == "full":
        spec = "key_caching+int8+zlib"
    filters = []
    for part in spec.split("+"):
        if part not in _FILTER_FACTORIES:
            raise ValueError(
                f"unknown filter {part!r} in spec; have "
                f"{sorted(_FILTER_FACTORIES)} (or 'none'/'lossless'/'full')"
            )
        filters.append(_FILTER_FACTORIES[part]())
    return FilterChain(filters)
