"""FlightRecorder: per-node black-box event journal + postmortem bundles.

PR 3's observability plane (``core/netmon.py``, ``utils/trace.py``,
``core/fleet.py``) measures *rates and latencies*; it cannot answer "what
exactly happened on the wire in the two seconds before this chaos test
diverged".  This module is the black box: a bounded ring of structured,
monotonic-stamped events recorded from every interesting transport and KV
lifecycle transition (frame send/recv/reject, retransmit/dedup/gave-up,
incarnation and routing fences, migration ops, restarts, cancels, SLO
breaches), cheap enough to leave on in production.

Cost model: one :func:`record` call is a dict build plus a GIL-atomic
``deque.append`` — no lock, no I/O, no formatting (~1 us).  The ring is
bounded (default 4096 events), so a run that never crashes pays a fixed
memory ceiling and zero disk.

When something DOES go wrong — a recv-thread exception
(``core/van.py::_Endpoint._recv_loop``), a failing chaos test (conftest
hook), or an explicit :func:`dump` — the ring is split per node and written
as a **postmortem bundle**: one JSON file per node carrying its events,
wall/monotonic clock anchors, optional min-RTT clock offset
(``FleetMonitor.clock_offset``), transport counters, fleet snapshot, and
per-link histogram digests.  ``tools/postmortem.py`` merges bundles from
many processes into one causal, clock-rebased timeline.

Event kinds are closed over :data:`EVENTS`; ``tools/check_wrappers.py``
enforces by AST that every ``flightrec.record("<kind>", ...)`` call site
uses a literal kind from this registry, so the taxonomy cannot drift
stringly-typed.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

#: Closed event-kind registry.  ``tools/check_wrappers.py`` parses this
#: frozenset LITERAL by AST (no import), so keep it a plain frozenset of
#: plain string constants — no comprehensions, no concatenation.
EVENTS = frozenset({
    # transport: one logical message crossing the metered boundary
    "frame.send",
    "frame.recv",
    # transport: wire-level rejects (CRC / undecodable / unframeable)
    "frame.reject",
    # transport v2 backpressure (core/tcp_van.py): a colocated shm ring
    # refusing a frame (degraded to TCP or dropped for retransmit) and the
    # epoll backend's bounded per-conn write queue refusing a vectored send
    "net.ring_full",
    "net.writeq_full",
    # reliable delivery (core/resender.py)
    "resend.retransmit",
    "resend.dup",
    "resend.gave_up",
    # fences: stale-incarnation frames (resender) and wrong-owner /
    # stale-epoch requests (kv/server.py)
    "fence.incarnation",
    "fence.routing",
    "incarnation.advance",
    # coalescing (core/coalesce.py)
    "bundle.flush",
    # chaos injection (core/chaos.py) — fault name rides in fields
    "chaos.inject",
    # migration protocol (kv/server.py driver side + kv/migrate.py)
    "migrate.begin",
    "migrate.send",
    "migrate.stage",
    "migrate.commit",
    "migrate.install",
    "migrate.adopt",
    "migrate.release",
    "migrate.abort",
    # node lifecycle (kv/replica.py)
    "node.restart",
    "node.promote",
    # cancellation fences (core/postoffice.py)
    "cancel.drop",
    # recv-thread handler exception (core/van.py)
    "recv.exception",
    # SLO engine verdict transitions (utils/slo.py)
    "slo.breach",
    "slo.clear",
    # bundle written (self-describing marker, last event in a bundle)
    "postmortem.dump",
    # live telemetry plane (core/telemetry.py): frame published by a node /
    # duplicate-seq frame dropped by the scheduler's aggregator
    "telemetry.publish",
    "telemetry.drop",
    # device-plane apply ledger (kv/ledger.py): an in-flight device apply
    # registered at dispatch / retired by the reaper once the donated
    # buffers are ready / backlog bound crossed (edge-triggered both ways,
    # state field says which)
    "apply.submit",
    "apply.done",
    "apply.backlog",
    # read-heavy serving plane (kv/cache.py, serve/admission.py):
    # hot-row cache hit / miss (per serving request, n = row count),
    # cache entries dropped (watermark advance, routing-epoch adoption),
    # read traffic shed or deferred by admission control
    "cache.hit",
    "cache.miss",
    "cache.invalidate",
    "serve.shed",
    # quantized wire plane (core/filters.py QuantizingFilter): a frame's
    # value planes lossily encoded at flush / dequantized before dispatch /
    # error-feedback residual stores dropped (reason field says which
    # lifecycle edge: adopt_routing, incarnation_advance, send_failed)
    "compress.encode",
    "compress.decode",
    "compress.residual_reset",
    # hierarchical push (kv/worker.py group path + core/coalesce.py
    # GroupReducer): a group's value planes pre-reduced before the wire /
    # a leader elected for (table, step) (salt > 0 marks a fence
    # re-election) / the group degraded to direct per-worker push (reason
    # field says why: member_timeout, leader_timeout, dead_leader,
    # stale_set, wire_done_error)
    "group.reduce",
    "group.elect",
    "group.fallback",
    # durability plane (ISSUE 16, checkpoint.py + kv/server.py snapshot
    # ops): snapshot window armed / one segment file written (or carried
    # forward unchanged) / dirty-delta exported under the commit freeze /
    # a shard restored from a partitioned snapshot / a snapshot window
    # torn down without committing (server death, routing change mid-
    # snapshot, driver abort — the postmortem anomaly anchor)
    "ckpt.begin",
    "ckpt.segment",
    "ckpt.commit",
    "ckpt.restore",
    "ckpt.abort",
    # sampled request-tracing plane (ISSUE 18, core/tracectx.py): every
    # kind below fires ONLY for hash-sampled requests (the gate
    # tools/check_wrappers.py enforces).  submit = worker stamped a trace
    # ctx and handed the request to the van; wire_tx/wire_rx = the frame
    # crossed the per-conn choke point / was decoded off the wire (TCP or
    # shm ring alike); bundle = a coalesced frame fanned its members'
    # contexts back out; dispatch/reply = server handler entry / reply
    # built (verdict ok|fenced); apply = ApplyLedger retired the bundle
    # (host/h2d/device attribution); ack = the reply closed the span tree
    # back on the worker (tools/postmortem.py anchors on its absence);
    # retransmit = the resender re-sent a sampled frame
    "trace.submit",
    "trace.wire_tx",
    "trace.wire_rx",
    "trace.bundle",
    "trace.dispatch",
    "trace.reply",
    "trace.apply",
    "trace.ack",
    "trace.retransmit",
    # war-game plane (ISSUE 19): begin/end bracket a scenario run; phase =
    # a load phase became current; inject = a fault (gray failure,
    # partition, restart wave) landed — an ANOMALY kind, so postmortems
    # anchor on the injection that preceded the breach; heal = a fault was
    # lifted; action = the autoscaler/runner acted (scale_up, drain_down,
    # rebalance) on live telemetry
    "scenario.begin",
    "scenario.phase",
    "scenario.inject",
    "scenario.heal",
    "scenario.action",
    "scenario.end",
    # consistency plane (ISSUE 20, kv/server.py gate + kv/worker.py retry
    # loops): gate = a sender's FIRST ``__wait__`` defer on a gated table
    # (retries in between stay silent); release = that sender admitted
    # again — a gate with no later release is the wedged-fleet postmortem
    # anomaly anchor; shed = the gate deadline degraded a request (pull
    # shed to the stale cache or forced through ungated, push forced —
    # never dropped; how= says which); retune = the BoundTuner (or an
    # operator / scenario phase) changed a table's live mode/bound
    "consist.gate",
    "consist.release",
    "consist.shed",
    "consist.retune",
})

#: env var: when set, recv-thread exceptions auto-dump a bundle here.
DUMP_DIR_ENV = "PS_FLIGHTREC_DIR"


class FlightRecorder:
    """Bounded ring of ``(seq, t_mono, kind, fields)`` events.

    Lock-cheap by design: appends are GIL-atomic ``deque.append`` calls and
    the monotonically increasing ``seq`` (``itertools.count``) breaks ties
    between events sharing a clock tick.  Reads (:meth:`events`,
    :meth:`dump`) snapshot via ``list(deque)`` which is likewise safe — a
    concurrent append can only make the snapshot one event stale, never
    corrupt it.
    """

    def __init__(self, *, capacity: int = 4096, node: Optional[str] = None,
                 enabled: bool = True) -> None:
        self._ring: "collections.deque[tuple]" = collections.deque(
            maxlen=capacity
        )
        self._seq = itertools.count()
        self.node = node
        self.enabled = enabled
        #: paired wall/monotonic anchors captured together at construction:
        #: ``wall_anchor_s + (t_mono - mono_anchor_s)`` rebases any event
        #: stamp onto the wall clock (the merge_traces.py ``clock_t0_s``
        #: pattern, but for events instead of chrome spans).
        self.wall_anchor_s = time.time()
        self.mono_anchor_s = time.monotonic()
        #: this process's monotonic clock minus the reference (scheduler)
        #: clock, from the min-RTT sync (``FleetMonitor.clock_offset``);
        #: subtracted by the postmortem merger to line up cross-host events.
        self.clock_offset_s = 0.0

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event.  ``kind`` MUST be a literal from :data:`EVENTS`
        at every call site (AST-enforced); ``fields`` are free-form but must
        stay JSON-safe scalars (they are dumped verbatim into bundles)."""
        if not self.enabled:
            return
        self._ring.append(
            (next(self._seq), time.monotonic(), kind, fields)
        )

    def events(self) -> List[dict]:
        """JSON-safe copies of the current ring, oldest first."""
        return [
            {"seq": seq, "t_mono_s": t, "kind": kind, **fields}
            for seq, t, kind, fields in list(self._ring)
        ]

    def events_since(self, seq: int) -> List[dict]:
        """Events with ``seq`` strictly greater than the watermark, oldest
        first — the telemetry publisher's incremental scan.  Walks the ring
        from the newest end and stops at the watermark, so a steady-state
        caller pays O(new events), not O(capacity).  Iterates the live deque
        (no snapshot copy); a concurrent append invalidates the iterator, in
        which case the scan retries once against a snapshot."""
        out: List[dict] = []
        try:
            for s, t, kind, fields in reversed(self._ring):
                if s <= seq:
                    break
                out.append({"seq": s, "t_mono_s": t, "kind": kind, **fields})
        except RuntimeError:  # ring mutated mid-scan
            out = []
            for s, t, kind, fields in reversed(list(self._ring)):
                if s <= seq:
                    break
                out.append({"seq": s, "t_mono_s": t, "kind": kind, **fields})
        out.reverse()
        return out

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)

    # -- bundles -------------------------------------------------------------
    def dump(
        self,
        out_dir: str,
        *,
        counters: Optional[Dict[str, Any]] = None,
        fleet=None,
        van=None,
        reason: str = "explicit",
    ) -> List[str]:
        """Write postmortem bundle files under ``out_dir``; returns paths.

        The ring is split by each event's ``node`` field (events recorded
        without one land in the ``_process`` bundle) so a single-process
        cluster — the test topology — still yields the per-node bundle
        layout that ``tools/postmortem.py`` merges.  Alongside the events,
        each bundle carries whatever context the caller can supply:

        - ``counters``: any counter dict (e.g. ``transport_counters(van)``
          output, or a server's ``counters()``);
        - ``van``: a Van stack — its ``.inner`` chain is walked for layer
          ``counters()`` and the first MeteredVan's per-link digests;
        - ``fleet``: a FleetMonitor — snapshot + straggler flags ride along,
          its JSONL sink is flushed first (the no-truncated-last-line
          guarantee), and per-node min-RTT clock offsets are embedded so
          the merger can rebase cross-host rings.
        """
        os.makedirs(out_dir, exist_ok=True)
        self.record("postmortem.dump", reason=reason, dir=out_dir)
        events = self.events()

        stack_counters: Dict[str, Any] = {}
        link_digests: Optional[dict] = None
        if van is not None:
            stack_counters = _walk_counters(van)
            metered = _find_metered(van)
            if metered is not None:
                link_digests = metered.links()
        if counters:
            stack_counters.update(counters)

        fleet_snapshot = None
        fleet_offsets: Dict[str, float] = {}
        if fleet is not None:
            fleet.flush_jsonl()
            fleet_snapshot = {
                "nodes": fleet.snapshot(),
                "stragglers": fleet.stragglers(),
            }
            for node_id in fleet.nodes():
                off = fleet.clock_offset(node_id)
                if off is not None:
                    fleet_offsets[node_id] = off

        by_node: Dict[str, List[dict]] = {}
        for ev in events:
            by_node.setdefault(
                str(ev.get("node") or self.node or "_process"), []
            ).append(ev)

        paths = []
        for node_id, evs in sorted(by_node.items()):
            bundle = {
                "node": node_id,
                "pid": os.getpid(),
                "reason": reason,
                "wall_anchor_s": self.wall_anchor_s,
                "mono_anchor_s": self.mono_anchor_s,
                "clock_offset_s": fleet_offsets.get(
                    node_id, self.clock_offset_s
                ),
                "events": evs,
                "counters": stack_counters,
                "fleet": fleet_snapshot,
                "histograms": link_digests,
            }
            path = os.path.join(
                out_dir, f"flightrec_{_safe_name(node_id)}.json"
            )
            with open(path, "w") as f:
                json.dump(bundle, f)
                f.flush()
                os.fsync(f.fileno())
            paths.append(path)
        return paths


def _safe_name(node_id: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in node_id)


def _walk_counters(van) -> Dict[str, Any]:
    """Sum ``counters()`` over a Van wrapper stack (``.inner`` walk).

    Local re-implementation of ``utils.metrics.transport_counters`` to keep
    core/ free of a utils.metrics import (metrics imports core modules)."""
    totals: Dict[str, Any] = {}
    seen = set()
    v = van
    while v is not None and id(v) not in seen:
        seen.add(id(v))
        c = getattr(v, "counters", None)
        if callable(c):
            for k, val in c().items():
                if isinstance(val, (int, float)):
                    totals[k] = totals.get(k, 0) + val
        v = getattr(v, "inner", None)
    return totals


def _find_metered(van):
    """First wrapper exposing per-link digests (``links()``), or None."""
    seen = set()
    v = van
    while v is not None and id(v) not in seen:
        seen.add(id(v))
        if callable(getattr(v, "links", None)):
            return v
        v = getattr(v, "inner", None)
    return None


# -- module-level default recorder -------------------------------------------
#
# A process hosts many logical nodes in the test topology, so the canonical
# call-site convention is the MODULE function ``flightrec.record(kind,
# node=..., ...)`` against one shared per-process ring: every component
# stamps the node it acts for, and ``dump()`` splits per node.  The module
# indirection is also what makes the AST contract checkable — call sites are
# statically recognizable as ``flightrec.record(...)`` without executing
# anything.

_default = FlightRecorder()
_dump_lock = threading.Lock()


def get() -> FlightRecorder:
    """The process-wide default recorder."""
    return _default


def record(kind: str, **fields: Any) -> None:
    """Record one event on the default recorder (the canonical call form)."""
    _default.record(kind, **fields)


def configure(
    *,
    capacity: Optional[int] = None,
    enabled: Optional[bool] = None,
    clear: bool = False,
) -> FlightRecorder:
    """Adjust the default recorder in place (tests, bench overhead guard)."""
    global _default
    if capacity is not None and capacity != _default._ring.maxlen:
        fresh = FlightRecorder(
            capacity=capacity, node=_default.node, enabled=_default.enabled
        )
        fresh._ring.extend(_default._ring)
        fresh.clock_offset_s = _default.clock_offset_s
        _default = fresh
    if enabled is not None:
        _default.enabled = enabled
    if clear:
        _default.clear()
    return _default


def dump(out_dir: str, **kwargs: Any) -> List[str]:
    """Dump the default recorder's bundle (see :meth:`FlightRecorder.dump`).

    Serialized under a lock so concurrent failure triggers (two recv
    threads dying at once) produce whole files, not interleaved writes.
    """
    with _dump_lock:
        return _default.dump(out_dir, **kwargs)


def on_recv_exception(node_id: str, exc: BaseException) -> None:
    """Failure trigger wired into ``_Endpoint._recv_loop``: journal the
    handler exception and, when :data:`DUMP_DIR_ENV` names a directory,
    write a bundle there immediately — the thread survives, but the ring
    near the failure is captured before it wraps."""
    record(
        "recv.exception",
        node=node_id,
        exc_type=type(exc).__name__,
        exc=str(exc)[:200],
    )
    out_dir = os.environ.get(DUMP_DIR_ENV)
    if out_dir:
        try:
            dump(out_dir, reason=f"recv-exception:{node_id}")
        except OSError:
            pass


def anomaly_kinds() -> frozenset:
    """Event kinds the postmortem report treats as anomalies (shared with
    ``tools/postmortem.py`` so the CLI and the library agree)."""
    return frozenset({
        "frame.reject",
        "resend.gave_up",
        "fence.incarnation",
        "fence.routing",
        "node.restart",
        "migrate.abort",
        "recv.exception",
        "slo.breach",
        "apply.backlog",
        "serve.shed",
        "group.fallback",
        "ckpt.abort",
        "scenario.inject",
        "consist.shed",
    })
