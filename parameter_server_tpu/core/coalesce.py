"""Wire coalescing: bundle same-destination KV messages into one frame.

The reference parameter server wins throughput by batching communication
into few large ranged messages; PR 1's :class:`ReliableVan` made every frame
carry ACK/seq bookkeeping, so per-message overhead got *more* expensive.
:class:`CoalescingVan` amortizes it: PUSH/PULL messages headed for the same
link inside a flush window are merged into a single bundle frame — one
52-byte flat-frame header (``core/frame.py``), one seq/ACK leg, one filter
pass (key-cache / zlib / int8 quant see the concatenated arrays), one wire
message.  Bundling is re-encode-free by construction: member value arrays
become planes of the ONE bundle frame (the codec joins their buffers
directly), member key bytes concatenate into a single uint8 plane, and the
only new bytes are one header plus a compact tuple index in the meta
section.

Transport v2 extends "re-encode-free" down to the syscall: because member
value arrays stay separate planes here, ``frame.encode_vec`` hands the
transport the bundle as ``[header+meta] + plane`` views and the epoll
backend's vectored send (``ps_van_send_vec`` -> ``writev``) puts them on
the wire without EVER concatenating host-side — no join of the bundle
body exists anywhere between the members' original buffers and the kernel.
The same segment list slice-assigns piecewise into a colocated shm ring
(``core/shm_ring.py``), so both planes inherit the zero-concat property.

Stack position is OUTERMOST::

    CoalescingVan(ReliableVan(ChaosVan(LoopbackVan(filter_chain))))

so the reliability layer stamps exactly one sequence number per bundle and
the whole bundle is retransmitted / deduplicated as a unit — exactly-once
delivery of a bundle is exactly-once delivery of every sub-message, and the
in-order unpack on the receive side preserves per-link FIFO within it.

Wire format: a bundle is a CONTROL :class:`Task` for the reserved customer
``__bundle__`` whose payload carries a per-sub-message index of compact
tuples ``(customer, kind, time, wait_time, payload, is_request, key_meta,
n_values)``; ``Message.keys`` is the uint8 concatenation of every sub's key
bytes (content-hashable by the key-caching filter) and ``Message.values``
is the flat concatenation of every sub's value arrays (quantized per-array
by the int8 filter).

Both ends must be wrapped: an unwrapped receiver sees an unknown customer
``__bundle__`` and replies ``__error__`` (a loud config error, not silent
loss).  Sub-messages buffered at send time report delivery success
optimistically (True); if the bundle turns out undeliverable at flush time,
synthesized ``__error__`` replies are delivered to the local senders so
``Customer.wait`` fails fast instead of hanging — the async analogue of the
unwrapped vans' synchronous ``send() -> False`` contract.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

import numpy as np

from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.messages import Message, Task, TaskKind
from parameter_server_tpu.core.tracectx import TRACE_KEY, trace_ids
from parameter_server_tpu.core.van import Van, VanWrapper

logger = logging.getLogger(__name__)

#: reserved customer id for bundle frames (receivers not wrapped in a
#: CoalescingVan reply ``__error__`` for it — a visible config error).
BUNDLE_CUSTOMER = "__bundle__"
#: payload key holding the list of per-sub-message index dicts.
BUNDLE_KEY = "__subs__"


def _pack(subs: list[Message]) -> Message:
    """Merge ``subs`` (same sender/recver) into one bundle frame.

    The index is a flat tuple per sub (positional, no repeated dict keys) —
    it is the only per-sub overhead the bundle adds to the wire, so it is
    kept as small as the meta codec allows.
    """
    index = []
    key_chunks: list[np.ndarray] = []
    values: list = []
    for m in subs:
        if m.keys is not None:
            k = np.ascontiguousarray(m.keys)
            kb = k.reshape(-1).view(np.uint8)
            key_chunks.append(kb)
            key_meta = (k.dtype.str, tuple(k.shape), int(kb.nbytes))
        else:
            key_meta = None
        index.append(
            (
                m.task.customer,
                m.task.kind.value,
                m.task.time,
                m.task.wait_time,
                m.task.payload,
                m.is_request,
                key_meta,
                len(m.values),
            )
        )
        values.extend(m.values)
    keys = (
        np.concatenate(key_chunks)
        if key_chunks
        else np.empty(0, dtype=np.uint8)
    )
    return Message(
        task=Task(TaskKind.CONTROL, BUNDLE_CUSTOMER, payload={BUNDLE_KEY: index}),
        sender=subs[0].sender,
        recver=subs[0].recver,
        keys=keys,
        values=values,
        is_request=True,
    )


def _unpack(msg: Message) -> list[Message]:
    """Reconstruct the sub-messages of a bundle frame, in send order."""
    index = msg.task.payload[BUNDLE_KEY]
    key_bytes = (
        np.ascontiguousarray(msg.keys).reshape(-1).view(np.uint8)
        if msg.keys is not None
        else np.empty(0, dtype=np.uint8)
    )
    subs: list[Message] = []
    k_off = 0
    v_off = 0
    for customer, kind, time_, wait_time, payload, is_request, key_meta, n_v in index:
        if key_meta is not None:
            dtype, shape, nbytes = key_meta
            # .copy() gives an owned, aligned, writable buffer (frombuffer
            # views are read-only and the server mutates key arrays).
            keys = (
                key_bytes[k_off : k_off + nbytes]
                .copy()
                .view(np.dtype(dtype))
                .reshape(shape)
            )
            k_off += nbytes
        else:
            keys = None
        subs.append(
            Message(
                task=Task(
                    kind=TaskKind(kind),
                    customer=customer,
                    time=time_,
                    wait_time=wait_time,
                    payload=payload,
                ),
                sender=msg.sender,
                recver=msg.recver,
                keys=keys,
                values=list(msg.values[v_off : v_off + n_v]),
                is_request=is_request,
            )
        )
        v_off += n_v
    return subs


class _LinkBuffer:
    """Pending sub-messages for one (sender, recver) link."""

    __slots__ = ("msgs", "deadline", "flush_lock")

    def __init__(self) -> None:
        self.msgs: list[Message] = []
        self.deadline: float = float("inf")
        # serializes pop+wire-emit so two flushers can't reorder the link
        self.flush_lock = threading.Lock()


class CoalescingVan(VanWrapper):
    """Per-link submit-side bundler (see module docstring).

    Flush triggers, any of:

    - ``max_msgs`` sub-messages buffered on a link (count overflow — fires
      even inside a :meth:`window`),
    - ``max_delay`` seconds since the link's first buffered message (a
      background flusher thread; deferred while a :meth:`window` is open),
    - explicit :meth:`flush`, or a :meth:`window` exiting,
    - a non-bundlable frame (CONTROL, ACKs) sent on a link with a non-empty
      buffer — the buffer is flushed *first* so per-link FIFO holds across
      the passthrough.
    """

    def __init__(
        self,
        inner: Van,
        *,
        max_msgs: int = 64,
        max_delay: float = 0.002,
        codec=None,
    ) -> None:
        super().__init__(inner)
        self.max_msgs = int(max_msgs)
        self.max_delay = float(max_delay)
        #: optional lossy wire codec (``filters.QuantizingFilter``) applied
        #: ONCE per outgoing frame at flush time — a single pass over the
        #: bundled value plane — and inverted in ``unbundle`` before
        #: dispatch.  CONTROL passthrough traffic skips it.  Duck-typed
        #: (needs encode/decode/on_send_failed) to avoid a filters import.
        self.codec = codec
        if codec is not None:
            # Residual lifecycle: a peer incarnation advance (crash/restart,
            # same-id restart) means carried error must not replay into the
            # recovered server.  ReliableVan exposes the hook; find it by
            # walking inner (the stack order is fixed but spelled by config).
            reset = getattr(codec, "reset_residuals", None)
            v = inner
            while v is not None and reset is not None:
                hooks = v.__dict__.get("on_incarnation_advance")
                if isinstance(hooks, list):
                    hooks.append(
                        lambda node_id, inc, _r=reset: _r(
                            reason=f"incarnation_advance:{node_id}:{inc}"
                        )
                    )
                    break
                v = getattr(v, "inner", None)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._buffers: dict[tuple[str, str], _LinkBuffer] = {}
        self._handlers: dict[str, Callable[[Message], None]] = {}
        self._holds = 0
        self._stopped = False
        # counters
        self._frames = 0
        self._msgs = 0
        self._passthrough = 0
        self._flush_full = 0
        self._flush_timer = 0
        self._undeliverable = 0
        self._flusher = threading.Thread(
            target=self._flush_loop, name="coalesce-flusher", daemon=True
        )
        self._flusher.start()

    # -- send path ----------------------------------------------------------
    def send(self, msg: Message) -> bool:
        link = (msg.sender, msg.recver)
        if msg.task.kind is TaskKind.CONTROL:
            # ACKs / barriers / heartbeats bypass bundling, but must not
            # overtake buffered PUSH/PULL traffic on the same link.
            self._flush_link(link)
            with self._lock:
                self._passthrough += 1
            return self.inner.send(msg)
        with self._lock:
            buf = self._buffers.setdefault(link, _LinkBuffer())
            if not buf.msgs:
                buf.deadline = time.monotonic() + self.max_delay
                self._cv.notify()
            buf.msgs.append(msg)
            full = len(buf.msgs) >= self.max_msgs
            if full:
                self._flush_full += 1
        if full:
            # count overflow flushes even inside a window()
            self._flush_link(link)
        return True

    @contextlib.contextmanager
    def window(self):
        """Defer timer flushes for the duration; flush everything on exit.

        Senders wrap a multi-message burst (a multi-table push, a server's
        reply batch) so the whole burst lands in one frame per link even if
        assembling it takes longer than ``max_delay``.
        """
        with self._lock:
            self._holds += 1
        try:
            yield self
        finally:
            with self._lock:
                self._holds -= 1
                last = self._holds == 0
                self._cv.notify()
            if last:
                # only the LAST window out flushes: another thread's
                # still-open window must not have its half-built burst split
                self.flush_buffers()

    def flush_buffers(self) -> None:
        """Emit every non-empty link buffer (one frame per link)."""
        with self._lock:
            links = [l for l, b in self._buffers.items() if b.msgs]
        for link in links:
            self._flush_link(link)

    def flush(self, timeout: float = 5.0) -> bool:
        """Flush own buffers, then block on the inner stack's flush (e.g.
        ``ReliableVan.flush`` waiting for ACKs)."""
        self.flush_buffers()
        return self.inner.flush(timeout)

    def _flush_link(self, link: tuple[str, str]) -> None:
        with self._lock:
            buf = self._buffers.get(link)
        if buf is None:
            return
        with buf.flush_lock:  # pop + emit is atomic per link (FIFO)
            with self._lock:
                subs = buf.msgs
                if not subs:
                    return
                buf.msgs = []
                buf.deadline = float("inf")
                self._frames += 1
                self._msgs += len(subs)
            frame = subs[0] if len(subs) == 1 else _pack(subs)
            if len(subs) > 1:
                # sampled request tracing (ISSUE 18): a bundle carries its
                # sampled members' trace ids as an AGGREGATE context on
                # the (fresh, _pack-owned) bundle payload, so the wire
                # planes below see one trace key per frame; ``unbundle``
                # fans the receive stamp back out to the member contexts.
                # Bundles with no sampled member carry nothing.
                tids = [
                    t for s in subs for t in trace_ids(s.task.payload)
                ]
                if tids:
                    frame.task.payload[TRACE_KEY] = {"tids": tids}
            if self.codec is not None:
                encoded = self.codec.encode(frame)
            else:
                encoded = frame
            ok = self.inner.send(encoded)
            if not ok and self.codec is not None:
                self.codec.on_send_failed(frame, encoded)
        if len(subs) > 1:
            flightrec.record(
                "bundle.flush", node=link[0], recver=link[1],
                subs=len(subs), ok=ok,
            )
        if not ok:
            self._deliver_errors(subs)

    def _deliver_errors(self, subs: list[Message]) -> None:
        """Buffered sends returned True optimistically; if the flush finds
        the link dead, synthesize the ``__error__`` replies the Postoffice
        would have produced, so local ``Customer.wait`` fails fast."""
        with self._lock:
            self._undeliverable += len(subs)
        for sub in subs:
            if not sub.is_request:
                continue
            handler = self._handlers.get(sub.sender)
            if handler is None:
                continue
            err = Message(
                task=dataclasses.replace(
                    sub.task,
                    payload={"__error__": f"undeliverable to {sub.recver}"},
                ),
                sender=sub.recver,
                recver=sub.sender,
                is_request=False,
            )
            try:
                handler(err)
            except Exception:  # noqa: BLE001 — one bad error reply must not
                # strand the rest of the bundle's waiters
                logger.exception("coalesce: error-reply handler failed")

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                now = time.monotonic()
                nearest = min(
                    (b.deadline for b in self._buffers.values() if b.msgs),
                    default=float("inf"),
                )
                if self._holds > 0 or nearest > now:
                    # holds / empty buffers: sleep until notified (window
                    # exit, first buffered msg, close) — no busy spin
                    wait = (
                        None
                        if self._holds > 0 or nearest == float("inf")
                        else max(nearest - now, 1e-4)
                    )
                    self._cv.wait(timeout=wait)
                    continue
                expired = [
                    l
                    for l, b in self._buffers.items()
                    if b.msgs and b.deadline <= now
                ]
                self._flush_timer += len(expired)
            for link in expired:
                self._flush_link(link)

    # -- receive path -------------------------------------------------------
    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        with self._lock:
            self._handlers[node_id] = handler

        def unbundle(msg: Message) -> None:
            # Every delivery runs inside a window: replies the handler emits
            # coalesce into (at most) one response frame per link and are
            # flushed the moment handling ends — a sync round trip never
            # waits out ``max_delay``.
            with self.window():
                if self.codec is not None:
                    msg = self.codec.decode(msg)
                if msg.task.customer != BUNDLE_CUSTOMER:
                    handler(msg)
                    return
                subs = _unpack(msg)
                bctx = msg.task.payload.get(TRACE_KEY)
                if isinstance(bctx, dict):
                    # sampled request tracing (ISSUE 18): fan the bundle's
                    # receive stamp back out to its sampled members.  The
                    # ``rx`` stamp only exists on wire paths, where every
                    # member payload was freshly decoded — on a loopback
                    # plane (shared dicts, no rx) nothing is mutated.
                    rx = bctx.get("rx")
                    if rx is not None:
                        for sub in subs:
                            sctx = sub.task.payload.get(TRACE_KEY)
                            if isinstance(sctx, dict) and "rx" not in sctx:
                                sctx["rx"] = rx
                    flightrec.record(
                        "trace.bundle",
                        tids=trace_ids(msg.task.payload),
                        sender=msg.sender,
                        subs=len(subs),
                    )
                # grouped delivery: a Postoffice-bound handler takes the
                # whole bundle at once so batchable customers (the server
                # apply engine) see their members TOGETHER — one device
                # apply per same-table push run, one readback per bundle
                recv_batch = getattr(
                    getattr(handler, "__self__", None), "recv_batch", None
                )
                if recv_batch is not None:
                    recv_batch(subs)
                else:
                    for sub in subs:
                        handler(sub)

        self.inner.bind(node_id, unbundle)

    def unbind(self, node_id: str) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)
        self.inner.unbind(node_id)

    # -- lifecycle / metrics ------------------------------------------------
    def close(self) -> None:
        self.flush_buffers()
        with self._lock:
            self._stopped = True
            self._cv.notify()
        self._flusher.join(timeout=5)
        self.inner.close()

    def counters(self) -> dict:
        with self._lock:
            out = {
                "coalesce_frames": self._frames,
                "coalesce_msgs": self._msgs,
                "coalesce_passthrough": self._passthrough,
                "coalesce_flush_full": self._flush_full,
                "coalesce_flush_timer": self._flush_timer,
                "coalesce_undeliverable": self._undeliverable,
            }
        codec_counters = getattr(self.codec, "counters", None)
        if codec_counters is not None:
            out.update(codec_counters())
        return out


# -- hierarchical push: the reduce-then-push stage (ISSUE 15) ----------------
#
# GroupReducer is the leader-side half of the worker-group pre-reduction
# that runs UNDER the CoalescingVan: members ship their localized PUSH
# planes to the elected leader as CONTROL contributions (passthrough —
# never bundled, so they cannot deadlock behind the window), the leader
# rendezvouses them here per (table, step), and the ONE reduced tensor it
# pushes rides the normal coalesced/quantized frame plane.  It lives in
# this module because the stage is part of the wire-coalescing story: the
# reduction is what turns G per-member frames into one.


_PSUM_FN = None


def _psum_pmapped():
    """The pmapped group-axis psum, built once (stable function identity
    keeps XLA's compile cache warm across steps; only new shapes retrace)."""
    global _PSUM_FN
    if _PSUM_FN is None:
        import jax

        _PSUM_FN = jax.pmap(lambda x: jax.lax.psum(x, "g"), axis_name="g")
    return _PSUM_FN


class GroupReducer:
    """Per-(table, step) rendezvous + deterministic reduction.

    ``deposit`` collects one member's ``(keys, values)`` contribution;
    when ``expected`` members have deposited, the completed set is reduced
    and returned (exactly once — the set is consumed).  Reduction is
    deterministic: contributions are ordered by member id, and the merge
    path uses ``np.unique`` + ``np.add.at`` (stable, seeded-replay safe).

    Paths (``mode``, see ``config.GroupConfig.reduce``):

    - identical key sets + enough local devices: stack and ``jax.lax.psum``
      over a one-axis ``pmap`` mesh — the shared-mesh case where the
      pre-reduction IS the data-parallel psum (arXiv:1909.09756 /
      GSPMD-style arXiv:2105.04663);
    - identical key sets, too few devices: a single host/XLA sum (the
      loopback bench topology);
    - differing key sets: sorted-union merge — concat keys, ``np.unique``
      inverse, scatter-add.

    ``take_stale`` returns (and consumes) sets older than a timeout so the
    leader can flush a PARTIAL reduction when a member died mid-step —
    the contributions it did receive are never lost.
    """

    def __init__(self, expected: int, *, node: str, mode: str = "auto") -> None:
        self.expected = int(expected)
        self.node = node
        self.mode = mode
        self._lock = threading.Lock()
        #: (table, step) -> {"members": {id: (keys, vals, fanin)}, "t0": s}
        self._sets: dict[tuple, dict] = {}
        self.reduced_sets = 0
        self.partial_sets = 0

    def pending(self) -> int:
        with self._lock:
            return len(self._sets)

    def deposit(
        self,
        table: str,
        step: int,
        member: str,
        keys: np.ndarray,
        values: np.ndarray,
        fanin: int = 1,
    ):
        """Add one contribution; returns ``(keys, values, fanin)`` reduced
        over the full set when this deposit completes it, else None.
        Duplicate deposits (a retransmitted contribution) are ignored."""
        with self._lock:
            st = self._sets.setdefault(
                (table, step), {"members": {}, "t0": time.monotonic()}
            )
            if member in st["members"]:
                return None
            st["members"][member] = (keys, values, int(fanin))
            if len(st["members"]) < self.expected:
                return None
            del self._sets[(table, step)]
            self.reduced_sets += 1
        return self._reduce(table, step, st)

    def take(self, table: str, step: int):
        """Consume a specific pending set as a PARTIAL reduction, or None
        if it is absent (already completed or never started)."""
        with self._lock:
            st = self._sets.pop((table, step), None)
            if st is None:
                return None
            self.partial_sets += 1
        return self._reduce(table, step, st, partial=True)

    def take_stale(self, older_than_s: float) -> list:
        """Consume sets older than ``older_than_s``; returns
        ``[(table, step, (keys, values, fanin)), ...]`` partial reductions
        (the leader-death / member-death degradation path)."""
        cutoff = time.monotonic() - older_than_s
        with self._lock:
            doomed = [
                key for key, st in self._sets.items() if st["t0"] <= cutoff
            ]
            stale = [(key, self._sets.pop(key)) for key in doomed]
            self.partial_sets += len(stale)
        return [
            (t, step, self._reduce(t, step, st, partial=True))
            for (t, step), st in stale
        ]

    def _reduce(self, table: str, step: int, st: dict, *, partial=False):
        entries = [st["members"][m] for m in sorted(st["members"])]
        fanin = sum(e[2] for e in entries)
        k0 = np.asarray(entries[0][0])
        same_keys = self.mode != "merge" and all(
            np.array_equal(np.asarray(e[0]), k0) for e in entries[1:]
        )
        if same_keys:
            stacked = np.stack([np.asarray(e[1]) for e in entries])
            path = "sum"
            if len(entries) > 1:
                # psum over a shared mesh where one exists: one device per
                # member leg, reduced over the group axis on-device.  jax
                # is imported lazily so transport-only deployments never
                # pay for it.
                import jax

                if jax.local_device_count() >= len(entries):
                    out = np.asarray(_psum_pmapped()(stacked)[0])
                    path = "psum"
                else:
                    out = stacked.sum(axis=0, dtype=stacked.dtype)
            else:
                out = stacked[0]
            keys = k0
        else:
            path = "merge"
            cat_keys = np.concatenate([np.asarray(e[0]) for e in entries])
            cat_vals = np.concatenate(
                [
                    np.asarray(e[1]).reshape(np.asarray(e[0]).size, -1)
                    for e in entries
                ]
            )
            keys, inv = np.unique(cat_keys, return_inverse=True)
            out = np.zeros(
                (keys.size, cat_vals.shape[1]), dtype=cat_vals.dtype
            )
            np.add.at(out, inv, cat_vals)
            tail = np.asarray(entries[0][1]).shape[1:]
            out = out.reshape((keys.size,) + tuple(tail))
        flightrec.record(
            "group.reduce",
            node=self.node,
            table=table,
            step=step,
            members=len(entries),
            fanin=fanin,
            rows=int(np.asarray(keys).size),
            path=path,
            partial=partial,
        )
        return keys, out, fanin
