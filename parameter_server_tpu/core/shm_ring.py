"""SPSC shared-memory ring: the colocated-link fast path (ISSUE 17).

Colocated worker<->server links (same host, verified by boot id during the
``__shmneg__`` handshake in ``core/tcp_van.py``) bypass TCP entirely: the
sender writes PR 7 flat frames (``core/frame.py``) verbatim into an mmap'd
ring file, the receiver decodes with ``frombuffer`` views STRAIGHT OFF the
ring — zero copies end to end.  TCP stays attached as the control/fallback
plane, so chaos, migration, and restart paths are untouched: any doubt
about the ring (full, torn, peer dead) degrades that one frame to TCP.

Layout (one ring per direction; the handshake sets up both)::

    [64-byte header][data region of ``capacity`` bytes]

    header:  0  u32 magic "PSR1"
             4  u32 version
             8  u64 capacity (data-region bytes, multiple of 8)
            16  u64 head   (writer cursor: byte offset into data region)
            24  u64 tail   (reader cursor: published after handler release)
            32  u64 frames written (writer heartbeat for debugging)
            40  u32 closed flag (either side sets; other side tears down)
            44  ..  reserved

    record:  [u32 len][payload][pad to 8]      — always CONTIGUOUS
             [u32 0xFFFFFFFF]                  — wrap marker: jump to 0

Records never straddle the end of the data region: when a record does not
fit in the remaining contiguous space the writer stamps a wrap marker and
continues at offset 0, so every payload is a single contiguous slice and
``frame.decode`` can take zero-copy array views over it.  Offsets stay
8-aligned and ``capacity`` is a multiple of 8, so there is always room for
the 4-byte marker.

SPSC publication protocol (torn-write safety): the writer copies the whole
record (length word first, then payload) into the data region and only then
publishes the new ``head`` with a single aligned 8-byte store.  The reader
never looks past ``head``, so a writer that dies mid-record leaves nothing
visible — the record simply never existed, and the resender retransmits
over TCP once the conn death tears the link down.  x86-TSO store ordering
(plus CPython's opcode-level memcpy for the slice writes) makes the
payload-before-head order hold without fences.

Ordered reclamation: decoded Messages carry ``frombuffer`` views INTO the
ring, and they escape to ``_Endpoint`` inboxes, handler threads, and — on
CPU jax, which ALIASES host numpy buffers (``jnp.asarray`` is zero-copy
there) — even into asynchronously-dispatched device ops.  So :meth:`read`
does NOT advance the shared ``tail``: it hands out ``(idx, payload_view)``
and advances only a private cursor; the receiver in ``core/tcp_van.py``
wraps each record in a uint8 array and ties :meth:`release`\\ (idx) to its
garbage collection (``weakref.finalize``), which fires only when the LAST
view — numpy or jax alias — dies.  ``tail`` then advances over the longest
fully-released prefix; until then the writer sees that space as occupied
and falls back to TCP rather than overwrite a live view.
"""

from __future__ import annotations

import mmap
import os
import struct
import tempfile
import threading
import time
from collections import deque
from typing import Iterable, Optional, Tuple

MAGIC = b"PSR1"
VERSION = 1
HEADER_SIZE = 64
#: wrap marker in the length slot: "no record here, continue at offset 0".
_WRAP = 0xFFFFFFFF
#: default per-direction capacity; a full ring is a per-frame TCP fallback,
#: not an error, so this only needs to cover a burst of in-flight bundles.
DEFAULT_CAPACITY = 4 << 20

_pack_u32 = struct.Struct("<I").pack_into
_unpack_u32 = struct.Struct("<I").unpack_from
_pack_u64 = struct.Struct("<Q").pack_into
_unpack_u64 = struct.Struct("<Q").unpack_from

_OFF_CAPACITY = 8
_OFF_HEAD = 16
_OFF_TAIL = 24
_OFF_FRAMES = 32
_OFF_CLOSED = 40


def ring_dir() -> str:
    """Directory for ring files: /dev/shm when present (true shared memory,
    no writeback), else the tmpdir."""
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def boot_id() -> str:
    """Host identity for the colocation handshake: two processes share a
    kernel boot id iff they share a kernel — i.e. an mmap namespace."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:  # non-Linux dev box: never negotiate shm
        return f"no-boot-id-{os.getpid()}"


class ShmRingError(RuntimeError):
    """Ring file unusable (bad magic/version/size) — negotiate TCP-only."""


class ShmRing:
    """One direction of a colocated link.  Writer creates, reader attaches.

    Thread model: many sender threads may call :meth:`write` (internal
    lock); exactly one reader thread calls :meth:`poll`/:meth:`read`;
    :meth:`release` may be called from any handler thread.
    """

    def __init__(self, path: str, mm: mmap.mmap, *, writer: bool,
                 created: bool) -> None:
        self.path = path
        self._mm = mm
        self._mv = memoryview(mm)
        self._data = self._mv[HEADER_SIZE:]
        self.capacity = _unpack_u64(self._mm, _OFF_CAPACITY)[0]
        self._writer = writer
        self._created = created
        self._lock = threading.Lock()
        # reader-side private cursor + ordered-release bookkeeping
        self._read_pos = _unpack_u64(self._mm, _OFF_TAIL)[0]
        self._next_idx = 0
        self._pending: deque = deque()  # (idx, tail_after_record)
        self._released: set = set()
        # counters (surfaced through TcpVan.counters)
        self.frames_written = 0
        self.bytes_written = 0
        self.frames_read = 0
        self.ring_full = 0
        self._dead = False

    # -- construction --------------------------------------------------------
    @classmethod
    def create(cls, capacity: int = DEFAULT_CAPACITY,
               dir: Optional[str] = None) -> "ShmRing":
        """Writer side: create + size + mmap a fresh ring file."""
        capacity = max(4096, (capacity + 7) & ~7)
        fd, path = tempfile.mkstemp(prefix="psring-", suffix=".shm",
                                    dir=dir or ring_dir())
        try:
            os.ftruncate(fd, HEADER_SIZE + capacity)
            mm = mmap.mmap(fd, HEADER_SIZE + capacity)
        finally:
            os.close(fd)
        mm[0:4] = MAGIC
        _pack_u32(mm, 4, VERSION)
        _pack_u64(mm, _OFF_CAPACITY, capacity)
        _pack_u64(mm, _OFF_HEAD, 0)
        _pack_u64(mm, _OFF_TAIL, 0)
        _pack_u64(mm, _OFF_FRAMES, 0)
        _pack_u32(mm, _OFF_CLOSED, 0)
        return cls(path, mm, writer=True, created=True)

    @classmethod
    def attach(cls, path: str) -> "ShmRing":
        """Reader side: mmap an existing ring file (validates header)."""
        try:
            fd = os.open(path, os.O_RDWR)
        except OSError as e:
            raise ShmRingError(f"cannot open ring {path}: {e}") from e
        try:
            size = os.fstat(fd).st_size
            if size < HEADER_SIZE:
                raise ShmRingError(f"ring {path}: short file ({size} bytes)")
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        if mm[0:4] != MAGIC or _unpack_u32(mm, 4)[0] != VERSION:
            mm.close()
            raise ShmRingError(f"ring {path}: bad magic/version")
        cap = _unpack_u64(mm, _OFF_CAPACITY)[0]
        if cap % 8 or HEADER_SIZE + cap > size:
            mm.close()
            raise ShmRingError(f"ring {path}: bad capacity {cap}")
        return cls(path, mm, writer=False, created=False)

    # -- shared-header accessors ---------------------------------------------
    @property
    def head(self) -> int:
        return _unpack_u64(self._mm, _OFF_HEAD)[0]

    @property
    def tail(self) -> int:
        return _unpack_u64(self._mm, _OFF_TAIL)[0]

    @property
    def closed(self) -> bool:
        return self._dead or _unpack_u32(self._mm, _OFF_CLOSED)[0] != 0

    def mark_closed(self) -> None:
        """Either side: tell the peer the link is going away."""
        try:
            _pack_u32(self._mm, _OFF_CLOSED, 1)
        except ValueError:  # mmap already closed locally
            pass

    # -- writer side ---------------------------------------------------------
    def _free(self, head: int, tail: int) -> int:
        # one slot always stays unused so head == tail is unambiguous EMPTY
        return (tail - head - 8) % self.capacity

    def write(self, segments: Iterable, total: int,
              timeout: float = 0.0005) -> bool:
        """Copy ``segments`` (bytes-like, summing to ``total``) into the
        ring as one record.  False = no space within ``timeout`` (caller
        falls back to TCP for this frame and counts ``ring_full``).

        The only data movement here is the slice-assign INTO the shared
        mapping — the frame's own buffers are never duplicated host-side
        first (no ``tobytes``/``bytes()`` staging; ``check_wrappers``
        enforces that by AST).
        """
        slot = (4 + total + 7) & ~7
        if slot + 8 >= self.capacity:  # cannot ever fit: oversized frame
            return False
        with self._lock:
            if self.closed:
                return False
            head = self.head
            deadline = None
            while True:
                tail = self.tail
                avail_to_end = self.capacity - head
                need = slot if slot <= avail_to_end else avail_to_end + slot
                if self._free(head, tail) >= need:
                    break
                if deadline is None:
                    deadline = time.monotonic() + timeout
                elif time.monotonic() >= deadline:
                    self.ring_full += 1
                    return False
                time.sleep(0.00005)  # reader drains in parallel
                if self.closed:
                    return False
            if slot > avail_to_end:
                # stamp the wrap marker (alignment guarantees >= 8 bytes
                # remain) and restart the record at offset 0
                _pack_u32(self._data, head, _WRAP)
                head = 0
            pos = head + 4
            for seg in segments:
                n = seg.nbytes if isinstance(seg, memoryview) else len(seg)
                self._data[pos:pos + n] = seg
                pos += n
            _pack_u32(self._data, head, total)
            # publish: single aligned u64 store AFTER the record body
            _pack_u64(self._mm, _OFF_HEAD, (head + slot) % self.capacity)
            self.frames_written += 1
            self.bytes_written += total
            _pack_u64(self._mm, _OFF_FRAMES, self.frames_written)
            return True

    # -- reader side ---------------------------------------------------------
    def poll(self, timeout: float) -> bool:
        """True when a record is available (or the ring closed).  Spins
        briefly (hot path: sub-µs wakeup), then sleeps in short ticks."""
        for _ in range(200):
            if self.head != self._read_pos or self.closed:
                return True
        deadline = time.monotonic() + timeout
        tick = 0.0002
        while time.monotonic() < deadline:
            if self.head != self._read_pos or self.closed:
                return True
            time.sleep(tick)
            tick = min(tick * 2, 0.002)
        return self.head != self._read_pos

    def read(self) -> Optional[Tuple[int, memoryview]]:
        """Next record as ``(idx, payload_view)`` — a ZERO-COPY view into
        the mapping — or None when drained.  The shared ``tail`` does not
        move until :meth:`release`\\ (idx) confirms every earlier record's
        handler has finished with its views."""
        while True:
            head = self.head
            pos = self._read_pos
            if pos == head:
                return None
            n = _unpack_u32(self._data, pos)[0]
            if n == _WRAP:
                self._read_pos = 0
                continue
            if 4 + n > self.capacity - pos:  # corrupt length: poison ring
                self.mark_closed()
                return None
            slot = (4 + n + 7) & ~7
            view = self._data[pos + 4:pos + 4 + n]
            self._read_pos = (pos + slot) % self.capacity
            with self._lock:
                idx = self._next_idx
                self._next_idx += 1
                self._pending.append((idx, self._read_pos))
            self.frames_read += 1
            return idx, view

    def release(self, idx: int) -> None:
        """Handler done with record ``idx``: advance the shared ``tail``
        over the longest released prefix (out-of-order completions across
        endpoint threads are held until their predecessors finish)."""
        with self._lock:
            self._released.add(idx)
            advanced = None
            while self._pending and self._pending[0][0] in self._released:
                i, tail_after = self._pending.popleft()
                self._released.discard(i)
                advanced = tail_after
            if advanced is not None:
                try:
                    _pack_u64(self._mm, _OFF_TAIL, advanced)
                except ValueError:  # closed under us; writer is gone anyway
                    pass

    # -- lifecycle -----------------------------------------------------------
    def close(self, unlink: Optional[bool] = None) -> None:
        """Mark closed and drop the mapping.  The creator unlinks the file
        by default; an attached reader leaves it to the creator."""
        self._dead = True
        self.mark_closed()
        # the mmap cannot be closed while exported views (pending records
        # an endpoint handler still holds) are alive; release() bookkeeping
        # is abandoned — the OS reclaims the mapping when the views die.
        try:
            self._data.release()
            self._mv.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass
        if unlink is None:
            unlink = self._created
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def counters(self) -> dict:
        return {
            "shm_frames_written": self.frames_written,
            "shm_bytes_written": self.bytes_written,
            "shm_frames_read": self.frames_read,
            "shm_ring_full": self.ring_full,
        }
