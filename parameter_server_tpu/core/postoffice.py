"""Postoffice + Customer: per-node message hub and async RPC bookkeeping.

Reference roles (``src/system/postoffice.h``, ``src/system/customer.h`` [U]):
the Postoffice is the per-process hub that owns the Van and routes inbound
messages to Customers; a Customer issues tasks (``Submit -> timestamp``),
tracks outstanding responses, and exposes ``Wait(ts)``.  The Executor's
per-sender ordering bookkeeping is folded into Customer here: the LoopbackVan
delivers per-sender FIFO and same-sender ``wait_time`` dependencies are
therefore satisfied structurally; cross-worker staleness gating happens at
dispatch time via :class:`~parameter_server_tpu.core.clock.ConsistencyController`
(SURVEY.md §7 design stance: gate dispatch, don't park device work).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
from typing import Callable, Optional

from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.messages import (
    Message,
    Task,
    TaskKind,
    TimestampGenerator,
)
from parameter_server_tpu.core.van import Van
from parameter_server_tpu.utils.threads import CALLBACKS

#: pseudo-customer name of remote-cancellation control frames.  Intercepted
#: by the Postoffice before customer lookup, so a CANCEL needs no executor
#: and works even for customers that no longer exist on the receiver.
CANCEL_CUSTOMER = "__cancel__"

#: max remembered (origin, customer, ts) cancellation fences per node.
_CANCEL_CAP = 1024


class Postoffice:
    """Per-node hub: binds the node's Van endpoint, routes to customers."""

    def __init__(self, node_id: str, van: Van) -> None:
        self.node_id = node_id
        self.van = van
        self._customers: dict[str, "Customer"] = {}
        #: remote-cancellation fences: (origin, customer) -> cancelled ts
        #: set, FIFO-evicted at _CANCEL_CAP total entries.  A fence placed
        #: BEFORE the matching request arrives (the request leg was delayed
        #: or is a retransmit racing its canceller) drops that request
        #: instead of executing dead work — per-link FIFO means a cancel
        #: never overtakes a request on a healthy link, so fences only
        #: matter exactly when the request is late, which is the point.
        self._cancelled: dict[tuple[str, str], set[int]] = {}
        self._cancel_order: collections.deque = collections.deque()
        self._cancel_lock = threading.Lock()
        #: requests dropped because a cancellation fence matched.
        self.cancelled_drops = 0
        van.bind(node_id, self._on_recv)

    def register(self, customer: "Customer") -> None:
        if customer.name in self._customers:
            raise ValueError(f"customer {customer.name!r} already registered")
        self._customers[customer.name] = customer

    def counters(self) -> dict:
        """Dashboard-mergeable fence counters (utils.metrics attachments)."""
        return {"cancelled_drops": self.cancelled_drops}

    def send(self, msg: Message) -> bool:
        msg.sender = self.node_id
        return self.van.send(msg)

    # -- remote cancellation -------------------------------------------------
    def _on_cancel(self, msg: Message) -> None:
        key = (msg.sender, msg.task.payload["customer"])
        ts = int(msg.task.payload["time"])
        with self._cancel_lock:
            self._cancelled.setdefault(key, set()).add(ts)
            self._cancel_order.append((key, ts))
            while len(self._cancel_order) > _CANCEL_CAP:
                old_key, old_ts = self._cancel_order.popleft()
                fences = self._cancelled.get(old_key)
                if fences is not None:
                    fences.discard(old_ts)
                    if not fences:
                        del self._cancelled[old_key]

    def _consume_cancel(self, sender: str, customer: str, ts: int) -> bool:
        """True (once) if request ``ts`` from ``sender``/``customer`` was
        remotely cancelled; the fence is consumed — ReliableVan dedups
        duplicate deliveries below this layer, so one match is the most a
        fence can ever see."""
        with self._cancel_lock:
            fences = self._cancelled.get((sender, customer))
            if fences is None or ts not in fences:
                return False
            fences.discard(ts)
            if not fences:
                del self._cancelled[(sender, customer)]
            return True

    def _cancel_dropped(self, msg: Message) -> bool:
        """True iff a cancellation fence matched ``msg`` (now dropped)."""
        if not self._consume_cancel(
            msg.sender, msg.task.customer, msg.task.time
        ):
            return False
        self.cancelled_drops += 1
        flightrec.record(
            "cancel.drop", node=self.node_id, sender=msg.sender,
            customer=msg.task.customer, ts=msg.task.time,
        )
        logging.getLogger(__name__).info(
            "%s: dropped cancelled request ts=%s from %s/%s",
            self.node_id,
            msg.task.time,
            msg.sender,
            msg.task.customer,
        )
        return True

    def recv_batch(self, msgs: list[Message]) -> None:
        """Deliver the members of one unbundled frame together.

        Consecutive requests for a customer that implements
        ``handle_request_batch`` are handed over as ONE group (the
        bundle-batched server apply path); everything else — responses,
        cancels, unknown customers, non-batchable customers — routes
        through the ordinary per-message :meth:`_on_recv`, in frame order.
        Cancellation fences are still honoured per member.
        """
        i, n = 0, len(msgs)
        while i < n:
            msg = msgs[i]
            customer = (
                self._customers.get(msg.task.customer)
                if msg.is_request and msg.task.customer != CANCEL_CUSTOMER
                else None
            )
            if (
                customer is None
                or getattr(customer, "handle_request_batch", None) is None
            ):
                self._on_recv(msg)
                i += 1
                continue
            j = i
            live: list[Message] = []
            while (
                j < n
                and msgs[j].is_request
                and msgs[j].task.customer == msg.task.customer
            ):
                if not self._cancel_dropped(msgs[j]):
                    live.append(msgs[j])
                j += 1
            if live:
                try:
                    replies = customer.process_request_batch(live)
                except Exception as e:  # noqa: BLE001
                    # a batch-level failure must still answer EVERY member,
                    # or each requester's wait(ts) hangs forever
                    logging.getLogger(__name__).exception(
                        "%s: batch handler error (%d msgs) from %s",
                        self.node_id,
                        len(live),
                        msg.sender,
                    )
                    replies = []
                    for m in live:
                        reply = m.reply()
                        reply.task = dataclasses.replace(
                            m.task,
                            payload={
                                "__error__": f"{type(e).__name__}: {e}"
                            },
                        )
                        replies.append(reply)
                for reply in replies:
                    if reply is not None:
                        self.van.send(reply)
            i = j

    def _on_recv(self, msg: Message) -> None:
        if msg.is_request and msg.task.customer == CANCEL_CUSTOMER:
            self._on_cancel(msg)
            return  # fire-and-forget: the canceller already finalized
        if msg.is_request and self._cancel_dropped(msg):
            return
        customer = self._customers.get(msg.task.customer)
        if customer is None:
            # The reference glog-and-dropped here, which leaves the
            # requester's wait(ts) hanging forever.  Answer requests with an
            # __error__ payload instead so the task completes with a
            # reportable error; responses for unknown customers stay dropped
            # (replying to a response would ping-pong between two confused
            # nodes).
            if msg.is_request:
                logging.getLogger(__name__).warning(
                    "%s: request for unknown customer %r from %s",
                    self.node_id,
                    msg.task.customer,
                    msg.sender,
                )
                reply = msg.reply()
                reply.task = dataclasses.replace(
                    msg.task,
                    payload={
                        "__error__": (
                            f"unknown customer {msg.task.customer!r} "
                            f"on {self.node_id}"
                        )
                    },
                )
                self.van.send(reply)
            return
        if msg.is_request:
            try:
                reply = customer.process_request(msg)
            except Exception as e:  # noqa: BLE001
                # A failed handler must still answer: otherwise the
                # requester's wait(ts) hangs forever on the missing leg.  The
                # error rides back in the reply payload (Customer records it;
                # see Customer.errors) and the endpoint thread stays alive.
                logging.getLogger(__name__).exception(
                    "%s: handler error for %s from %s",
                    self.node_id,
                    msg.task.kind,
                    msg.sender,
                )
                reply = msg.reply()
                reply.task = dataclasses.replace(
                    msg.task, payload={"__error__": f"{type(e).__name__}: {e}"}
                )
            if reply is not None:
                self.van.send(reply)
        else:
            customer._on_response(msg)


class Customer:
    """Async task issuer/handler bound to one Postoffice node.

    ``submit`` assigns a timestamp, sends one message per receiver, and
    records how many responses complete the task; ``wait`` blocks on that.
    Server-side subclasses override :meth:`handle_request` to produce reply
    values (the reference's ``Parameter::GetValue/SetValue`` seam).
    """

    def __init__(self, name: str, post: Postoffice) -> None:
        self.name = name
        self.post = post
        self._ts = TimestampGenerator()
        self._pending: dict[int, int] = {}
        self._callbacks: dict[int, Callable[[list[Message]], None]] = {}
        self._responses: dict[int, list[Message]] = {}
        self._errors: dict[int, list[str]] = {}
        self._responded: dict[int, set[str]] = {}  # senders already counted
        self._receivers: dict[int, list[str]] = {}  # per-ts fan-out targets
        self._kept: set[int] = set()  # timestamps whose responses are retained
        self._executed: dict[str, int] = {}  # per-sender executed task time
        self._cond = threading.Condition()
        post.register(self)

    # -- requester side -----------------------------------------------------
    def submit(
        self,
        msgs: list[Message],
        callback: Optional[Callable[[list[Message]], None]] = None,
        *,
        keep_responses: bool = False,
    ) -> int:
        """Send one logical task as ``msgs`` (already sliced per receiver).

        All messages share the newly assigned timestamp; the task completes
        when every receiver has responded.  Returns the timestamp.

        Response bodies are retained only when ``keep_responses`` is set (the
        caller then MUST drain them via :meth:`take_responses`) or while a
        callback is pending — otherwise fire-and-forget tasks (pushes,
        heartbeats) would pin every reply payload for the process lifetime.
        """
        ts = self._ts.next()
        with self._cond:
            self._pending[ts] = len(msgs)
            self._receivers[ts] = [m.recver for m in msgs]
            if keep_responses or callback is not None:
                self._responses[ts] = []
            if callback is not None:
                self._callbacks[ts] = callback
            if keep_responses:
                self._kept.add(ts)
        undeliverable = []
        for m in msgs:
            m.task.customer = self.name
            m.task.time = ts
            if not self.post.send(m):
                undeliverable.append(m)
        if undeliverable:
            # Dead receiver(s): complete their legs immediately so wait()
            # cannot hang; the learner layer re-assigns work (WorkloadPool).
            # The drop is recorded as an error so callers that inspect
            # responses (pulls, checkpoints) can distinguish "acked" from
            # "silently dropped" instead of reading zeros.
            logging.getLogger(__name__).warning(
                "%s/%s: task %s undeliverable to %s (dropped)",
                self.post.node_id,
                self.name,
                ts,
                [m.recver for m in undeliverable],
            )
            with self._cond:
                for m in undeliverable:
                    self._errors.setdefault(ts, []).append(
                        f"{m.recver}: undeliverable"
                    )
                self._pending[ts] -= len(undeliverable)
                if self._pending[ts] <= 0:
                    self._finish_locked(ts)
        return ts

    def wait(self, ts: int, timeout: Optional[float] = None) -> bool:
        """Block until task ``ts`` has all responses.  False on timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: ts not in self._pending, timeout)

    def wait_deadline(self, ts: int, deadline: Optional[float]) -> bool:
        """Like :meth:`wait` against an absolute ``time.monotonic`` deadline
        (callers waiting on several tasks share one budget instead of
        resetting the clock per task)."""
        import time as _time

        timeout = None if deadline is None else deadline - _time.monotonic()
        if timeout is not None and timeout <= 0:
            return self.done(ts)
        return self.wait(ts, timeout)

    def cancel(
        self, ts: int, reason: str = "cancelled", *, remote: bool = False
    ) -> bool:
        """Finalize a still-pending task ``ts`` with an error.

        A timed-out :meth:`wait` used to leave the task pending forever —
        ``_pending``/``_responses``/``_errors`` state leaked, and a late
        response could complete a task the caller had already abandoned.
        ``cancel`` closes that hole: the task finishes NOW with ``reason``
        recorded as an error (``errors(ts)``/``check(ts)`` report it for
        kept tasks), late responses are ignored by the existing
        duplicate-response guard, and all bookkeeping is freed by the normal
        completion path.  Returns False if ``ts`` already completed.

        ``remote=True`` additionally sends a fire-and-forget CANCEL control
        frame to every receiver that has not yet responded, so a delayed or
        retransmitted request leg is DROPPED there instead of executing dead
        work (the reference ran abandoned tasks to completion).  Callers
        about to re-submit the same work (deadline-retry paths) should use
        it: without the fence, the original and the retry can both execute —
        for pushes that is a double-apply.  Off by default because some
        abandoned work must still run remotely (a sync-replica forward that
        the primary already applied must reach the replica eventually, or
        the chain diverges).
        """
        with self._cond:
            if ts not in self._pending:
                return False
            targets = []
            if remote:
                responded = self._responded.get(ts, set())
                targets = [
                    r
                    for r in self._receivers.get(ts, [])
                    if r not in responded
                ]
            self._errors.setdefault(ts, []).append(reason)
            self._finish_locked(ts)
        for recver in targets:
            self.post.send(
                Message(
                    task=Task(
                        TaskKind.CONTROL,
                        CANCEL_CUSTOMER,
                        time=ts,
                        payload={"customer": self.name, "time": ts},
                    ),
                    recver=recver,
                )
            )
        return True

    def done(self, ts: int) -> bool:
        with self._cond:
            return ts not in self._pending

    def pending_count(self) -> int:
        """Number of tasks still awaiting responses (in-flight depth)."""
        with self._cond:
            return len(self._pending)

    def responses(self, ts: int) -> list[Message]:
        """Collected response messages for a completed kept task."""
        with self._cond:
            return list(self._responses.get(ts, []))

    def take_responses(self, ts: int) -> list[Message]:
        """Drain (and forget) the responses of a ``keep_responses`` task."""
        with self._cond:
            self._kept.discard(ts)
            self._errors.pop(ts, None)
            return self._responses.pop(ts, [])

    def _on_response(self, msg: Message) -> None:
        ts = msg.task.time
        err = msg.task.payload.get("__error__")
        with self._cond:
            if ts not in self._pending:
                return  # late/duplicate response
            responded = self._responded.setdefault(ts, set())
            if msg.sender in responded:
                # duplicate leg (an app-layer retry racing its original):
                # counting it would complete the task with another
                # receiver's response missing
                return
            responded.add(msg.sender)
            if err is not None:
                self._errors.setdefault(ts, []).append(f"{msg.sender}: {err}")
            if ts in self._responses:
                self._responses[ts].append(msg)
            self._pending[ts] -= 1
            if self._pending[ts] <= 0:
                self._finish_locked(ts)

    def errors(self, ts: int) -> list[str]:
        """Remote handler errors reported in task ``ts``'s responses."""
        with self._cond:
            return list(self._errors.get(ts, []))

    def check(self, ts: int) -> None:
        """Raise if any receiver answered task ``ts`` with an error."""
        errs = self.errors(ts)
        if errs:
            raise RuntimeError(f"task {ts} failed on: " + "; ".join(errs))

    def _finish_locked(self, ts: int) -> None:
        del self._pending[ts]
        self._responded.pop(ts, None)
        self._receivers.pop(ts, None)
        cb = self._callbacks.pop(ts, None)
        if ts in self._kept:
            responses = self._responses.get(ts, [])
        else:
            responses = self._responses.pop(ts, [])
            # error strings are only retained for kept tasks (the callers
            # that inspect them); fire-and-forget errors were already logged
            self._errors.pop(ts, None)
        self._cond.notify_all()
        if cb is not None:
            # Fire off-thread (callbacks may re-submit) on the shared daemon
            # pool — thread-per-callback was unbounded thread creation under
            # high async push rates.
            CALLBACKS.submit(cb, responses)

    # -- responder side -----------------------------------------------------
    def process_request(self, msg: Message) -> Optional[Message]:
        """Route an inbound request through :meth:`handle_request`."""
        reply = self.handle_request(msg)
        with self._cond:
            prev = self._executed.get(msg.sender, -1)
            self._executed[msg.sender] = max(prev, msg.task.time)
        return reply

    #: subclasses that can process a frame's requests TOGETHER (one device
    #: apply per group, one readback per bundle) define this as a method
    #: ``(msgs) -> [reply|None, ...]``; Postoffice.recv_batch routes grouped
    #: delivery through it.  ``None`` here = not batchable.
    handle_request_batch = None

    def process_request_batch(
        self, msgs: list[Message]
    ) -> list[Optional[Message]]:
        """Route a grouped frame through :meth:`handle_request_batch`.

        The handler answers every member itself (per-member errors become
        ``__error__`` replies inside), so all members count as executed.
        """
        replies = self.handle_request_batch(msgs)
        with self._cond:
            for m in msgs:
                prev = self._executed.get(m.sender, -1)
                self._executed[m.sender] = max(prev, m.task.time)
        return replies

    def handle_request(self, msg: Message) -> Optional[Message]:
        """Override: process a request, return the reply Message (or None)."""
        raise NotImplementedError

    def executed_time(self, sender: str) -> int:
        with self._cond:
            return self._executed.get(sender, -1)
