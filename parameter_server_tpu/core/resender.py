"""ReliableVan: ACK / retransmit / dedup on top of any Van.

Reference analogue: ``src/system/resender.h`` [U] — the layer the reference
kept between the Van's ZeroMQ sockets and the Postoffice so that a message
lost *in flight* (not rejected at send time) is retransmitted until acked,
and a retransmission that races its own ack is deduplicated at the receiver
instead of double-applying a gradient push.

Protocol, per directed link ``(sender, recver)``:

- every outbound message is stamped with a monotonically increasing
  sequence number (``task.payload["__rseq__"]`` — payload-borne so it
  survives the TcpVan's pickle header unchanged);
- the receiving ReliableVan immediately answers with a tiny ACK control
  frame (customer ``__resender__``, never delivered to the Postoffice),
  then checks the seq against a per-link seen-window: fresh messages are
  delivered with the stamp stripped, repeats are counted in
  ``dup_suppressed`` and swallowed — retried pushes are idempotent;
- unacked sends are retransmitted by a single timer thread with
  exponential backoff plus seeded jitter, up to ``max_retries``; exhausting
  the budget drops the message (``gave_up``) and fires ``on_give_up`` so a
  higher layer can fail the task instead of hanging.

Send-time failures (``inner.send`` returning False: receiver unbound on a
LoopbackVan, no route on a TcpVan) stay fail-fast — the transport can
already *name* the receiver as absent, and ``Customer.submit`` turns that
into an immediate undeliverable error.  Retransmits, by contrast, keep
trying through send-time failures for the rest of their budget: a dead
server's identity can come back mid-retry via hot-standby promotion
(:func:`parameter_server_tpu.kv.replica.promote`), and the retransmit then
lands on the promoted replica.  Under a :class:`~parameter_server_tpu.core.
chaos.ChaosVan` (which accepts every frame and loses it in flight) every
loss is handled by retransmission — the stack to prove reliability is
``ReliableVan(ChaosVan(LoopbackVan()))``.

Dedup state is keyed by link, not by endpoint object, so a promoted standby
binding the dead primary's node id inherits the link's seq/window history
(same Van instance in-process); on a cross-process TcpVan promotion is a
route update and each process keeps its own windows.

Same-id restart (incarnation fencing): a process that crashes and restarts
UNDER THE SAME node id cannot reuse the link's seq space — its fresh seq 0
would read as a duplicate to every peer's seen-window, and its stale
pre-crash twin (a zombie that is slow to die) could keep emitting frames
that corrupt the successor's state.  Every frame therefore also carries the
sender's **incarnation** (:data:`~parameter_server_tpu.core.messages.
INCARNATION_KEY`, assigned by the scheduler on re-registration): receivers
key dedup windows by ``(link, incarnation)``, reset the window when a
peer's incarnation advances, and FENCE (drop + count ``rejected_stale``,
no ACK) frames from any lower incarnation.  ACKs echo ``(seq, inc)`` so a
zombie's ACK can never clear the successor's pending entries.
:meth:`ReliableVan.restart_node` is the local half of the lifecycle: it
resets the restarted node's outbound seq counters (the new process starts
at 0 under the new incarnation) and drops the dead process's unacked sends.

Integrity: each data frame is stamped with a CRC32 over its key/value bytes
(``__rcrc__``); a receiver that computes a different digest drops the frame
WITHOUT acking (``rejected_corrupt``), so the sender's normal retransmit
path repairs in-flight payload corruption (ChaosVan bit-flips, bad NICs)
exactly like loss.  Disable with ``integrity=False`` for stacks whose
base-van filter chain is intentionally lossy (int8 quantization).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
import zlib
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.frame import plane_view
from parameter_server_tpu.core.tracectx import TRACE_KEY, trace_ids
from parameter_server_tpu.core.messages import (
    INCARNATION_KEY,
    IncarnationRegistry,
    Message,
    Task,
    TaskKind,
)
from parameter_server_tpu.core.van import Van, VanWrapper

#: payload key carrying the per-link sequence stamp.
SEQ_KEY = "__rseq__"
#: payload key carrying the acked sequence number in ACK frames.
ACK_KEY = "__rack__"
#: payload key carrying the CRC32 of the frame's key/value bytes.
CRC_KEY = "__rcrc__"
#: customer name of ACK frames; intercepted below the Postoffice.
ACK_CUSTOMER = "__resender__"
#: payload keys stripped before a frame is delivered to the Postoffice.
_STAMP_KEYS = (SEQ_KEY, INCARNATION_KEY, CRC_KEY)

_log = logging.getLogger(__name__)


def payload_crc32(msg: Message) -> int:
    """CRC32 over the frame's key bytes and every value array's bytes.

    Covers exactly what in-flight corruption can touch and what the wire
    moves (tensor payloads); Task metadata is excluded on purpose — upper
    layers (netmon stamps, trace ctx) legitimately rewrite the payload dict
    between send and delivery.

    Device-resident values (``jax.Array``) are skipped on both ends: over
    an in-process Van they are delivered by reference (nothing on the wire
    to corrupt) and hashing them would force the device sync that
    ``push_device`` exists to avoid.  The skip decision is type-based, so
    sender and receiver agree on what was covered.

    Zero-copy: the CRC runs incrementally over each array's own buffer
    (``core/frame.py``'s byte view) — no ``tobytes()`` materialization on
    either the stamping or the verifying side.  ``ascontiguousarray`` is
    a no-op passthrough for the contiguous arrays the wire always carries
    and only copies genuinely strided inputs, where it is the cheapest way
    to a hashable buffer anyway.  Byte-for-byte the same digest as the
    old ``tobytes()`` form.
    """
    crc = 0
    if isinstance(msg.keys, np.ndarray):
        crc = zlib.crc32(plane_view(np.ascontiguousarray(msg.keys)), crc)
    for v in msg.values:
        if isinstance(v, np.ndarray):
            crc = zlib.crc32(plane_view(np.ascontiguousarray(v)), crc)
    return crc & 0xFFFFFFFF


class _SeenWindow:
    """Per-link receiver dedup: contiguous low-watermark + sparse set.

    ``fresh(seq)`` is True exactly once per seq.  Memory is bounded at
    ``size`` outstanding out-of-order seqs; past that the watermark jumps
    forward and anything below it reads as a duplicate (safe: the sender's
    retry budget is far smaller than any sane window).
    """

    __slots__ = ("size", "low", "seen")

    def __init__(self, size: int) -> None:
        self.size = size
        self.low = -1  # every seq <= low has been delivered
        self.seen: set[int] = set()

    def fresh(self, seq: int) -> bool:
        if seq <= self.low or seq in self.seen:
            return False
        self.seen.add(seq)
        while self.low + 1 in self.seen:
            self.low += 1
            self.seen.discard(self.low)
        if len(self.seen) > self.size:
            self.low = min(self.seen)
            self.seen = {s for s in self.seen if s > self.low}
        return True


@dataclasses.dataclass
class _Pending:
    msg: Message  # the stamped copy, resent verbatim
    link: Tuple[str, str]
    seq: int
    attempts: int = 0
    due: float = 0.0


class ReliableVan(VanWrapper):
    """Reliable-delivery Van decorator (see module docstring).

    ``timeout`` is the first retransmit deadline; attempt ``n`` waits
    ``timeout * backoff**n`` plus uniform seeded jitter of up to
    ``jitter`` of that value.  Defaults suit in-process tests (ms RTTs);
    DCN deployments should scale ``timeout`` to their RTT.
    """

    def __init__(
        self,
        inner: Van,
        *,
        timeout: float = 0.25,
        backoff: float = 2.0,
        jitter: float = 0.25,
        max_retries: int = 10,
        window: int = 4096,
        seed: int = 0,
        integrity: bool = True,
        on_give_up: Optional[Callable[[Message], None]] = None,
    ) -> None:
        super().__init__(inner)
        self.timeout = timeout
        self.backoff = backoff
        self.jitter = jitter
        self.max_retries = max_retries
        self.window = window
        self.integrity = integrity
        self.on_give_up = on_give_up
        self._rng = random.Random(seed)
        self._next_seq: Dict[Tuple[str, str], int] = {}
        self._pending: Dict[Tuple[Tuple[str, str], int, int], _Pending] = {}
        self._windows: Dict[Tuple[str, str], _SeenWindow] = {}
        #: node_id -> incarnation: stamps local sends, fences inbound frames.
        self.incarnations = IncarnationRegistry()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closed = False
        #: dashboard counters (metrics.transport_counters merges them).
        self.retransmits = 0
        self.dup_suppressed = 0
        self.gave_up = 0
        self.acks_sent = 0
        self.acks_received = 0
        #: frames dropped by the incarnation fence (zombie senders).
        self.rejected_stale = 0
        #: frames dropped by the CRC32 integrity check (bit-flips in flight).
        self.rejected_corrupt = 0
        #: callbacks ``(node_id, incarnation)`` fired (outside the lock)
        #: whenever a peer's incarnation ADVANCES — both the receive-side
        #: learn and the explicit :meth:`set_incarnation` path.  Consumers:
        #: the quantizing codec drops error-feedback residuals so carried
        #: quantization error never replays into a restarted peer.
        self.on_incarnation_advance: list = []
        self._thread = threading.Thread(
            target=self._retransmit_loop, name="resender-retx", daemon=True
        )
        self._thread.start()

    # -- receive side --------------------------------------------------------
    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        self.inner.bind(node_id, self._wrap_handler(handler))

    def _wrap_handler(
        self, handler: Callable[[Message], None]
    ) -> Callable[[Message], None]:
        def wrapped(msg: Message) -> None:
            if msg.task.customer == ACK_CUSTOMER:
                self._on_ack(msg)
                return
            seq = msg.task.payload.get(SEQ_KEY)
            if seq is None:
                handler(msg)  # unstamped (foreign/legacy) traffic
                return
            inc = msg.task.payload.get(INCARNATION_KEY, 0)
            known = self.incarnations.get(msg.sender)
            if inc < known:
                # Incarnation fence: a frame from a dead pre-restart process
                # (zombie).  Dropped WITHOUT an ACK — the zombie's resender
                # exhausts its budget into the void; acking would tell a
                # dead process its corruption landed.
                with self._lock:
                    self.rejected_stale += 1
                flightrec.record(
                    "fence.incarnation", node=msg.recver,
                    sender=msg.sender, inc=inc, known=known, seq=seq,
                )
                return
            crc = msg.task.payload.get(CRC_KEY)
            if crc is not None and self.integrity:
                if payload_crc32(msg) != crc:
                    # corrupted in flight: no ACK, so the sender's verbatim
                    # retransmit (its copy is intact) repairs it like a loss
                    with self._lock:
                        self.rejected_corrupt += 1
                    flightrec.record(
                        "frame.reject", node=msg.recver, reason="crc",
                        sender=msg.sender, seq=seq,
                    )
                    return
            link = (msg.sender, msg.recver)
            if inc > known and self.incarnations.learn(msg.sender, inc):
                # peer restarted: its new process counts seqs from 0 again —
                # reset every window keyed to the old incarnation's seq space
                self._reset_sender_windows(msg.sender)
                self._fire_incarnation_advance(msg.sender, inc)
            # ACK before processing: the sender's clock starts at *its* send
            self._send_ack(msg, seq, inc)
            with self._lock:
                win = self._windows.get(link)
                if win is None:
                    win = self._windows[link] = _SeenWindow(self.window)
                is_fresh = win.fresh(seq)
                if not is_fresh:
                    self.dup_suppressed += 1
            if not is_fresh:
                flightrec.record(
                    "resend.dup", node=msg.recver,
                    sender=msg.sender, seq=seq,
                )
                return
            # strip the stamps: replies share this Task's payload dict, and
            # a stale inherited seq would corrupt the reply link's dedup
            clean = dataclasses.replace(
                msg,
                task=dataclasses.replace(
                    msg.task,
                    payload={
                        k: v
                        for k, v in msg.task.payload.items()
                        if k not in _STAMP_KEYS
                    },
                ),
            )
            handler(clean)

        return wrapped

    def _reset_sender_windows(self, sender: str) -> None:
        """Drop dedup windows for every link originated by ``sender``."""
        with self._lock:
            for link in [l for l in self._windows if l[0] == sender]:
                del self._windows[link]

    def _send_ack(self, msg: Message, seq: int, inc: int) -> None:
        ack = Message(
            task=Task(
                TaskKind.CONTROL,
                ACK_CUSTOMER,
                payload={ACK_KEY: seq, INCARNATION_KEY: inc},
            ),
            sender=msg.recver,
            recver=msg.sender,
            is_request=False,
        )
        # ACKs are not themselves acked/stamped (that way lies recursion);
        # a lost ACK is repaired by the peer's retransmit -> dedup -> re-ACK
        self.inner.send(ack)
        with self._lock:
            self.acks_sent += 1

    def _on_ack(self, msg: Message) -> None:
        # ack for link (our node, peer): msg travelled peer -> us
        link = (msg.recver, msg.sender)
        seq = msg.task.payload.get(ACK_KEY)
        inc = msg.task.payload.get(INCARNATION_KEY, 0)
        with self._lock:
            self.acks_received += 1
            # keyed by (link, inc, seq): an ACK echoing a stale incarnation
            # (a zombie receiver acking pre-restart traffic) cannot clear a
            # successor incarnation's pending entry of the same seq
            self._pending.pop((link, inc, seq), None)

    # -- send side -----------------------------------------------------------
    def send(self, msg: Message) -> bool:
        if self._closed:
            return False
        link = (msg.sender, msg.recver)
        inc = self.incarnations.get(msg.sender)
        with self._lock:
            seq = self._next_seq.get(link, 0)
            self._next_seq[link] = seq + 1
        payload = {**msg.task.payload, SEQ_KEY: seq}
        if inc:
            payload[INCARNATION_KEY] = inc
        if self.integrity:
            payload[CRC_KEY] = payload_crc32(msg)
        stamped = dataclasses.replace(
            msg, task=dataclasses.replace(msg.task, payload=payload)
        )
        if not self.inner.send(stamped):
            return False  # fail-fast: see module docstring
        with self._wake:
            self._pending[(link, inc, seq)] = _Pending(
                stamped, link, seq, attempts=0,
                due=time.monotonic() + self._deadline(0),
            )
            self._wake.notify()
        return True

    def _deadline(self, attempt: int) -> float:
        base = self.timeout * (self.backoff ** attempt)
        return base * (1.0 + self.jitter * self._rng.random())

    def _retransmit_loop(self) -> None:
        while True:
            resend: list[_Pending] = []
            dead: list[_Pending] = []
            with self._wake:
                if self._closed:
                    return
                now = time.monotonic()
                nxt: Optional[float] = None
                for key, p in list(self._pending.items()):
                    if p.due > now:
                        nxt = p.due if nxt is None else min(nxt, p.due)
                        continue
                    p.attempts += 1
                    if p.attempts > self.max_retries:
                        del self._pending[key]
                        self.gave_up += 1
                        dead.append(p)
                    else:
                        p.due = now + self._deadline(p.attempts)
                        nxt = p.due if nxt is None else min(nxt, p.due)
                        resend.append(p)
                        self.retransmits += 1
                if not resend and not dead:
                    self._wake.wait(
                        timeout=(nxt - now) if nxt is not None else 0.2
                    )
                    continue
            for p in resend:
                flightrec.record(
                    "resend.retransmit", node=p.link[0],
                    recver=p.link[1], seq=p.seq, attempt=p.attempts,
                )
                payload = p.msg.task.payload
                if isinstance(payload, dict) and TRACE_KEY in payload:
                    # sampled request tracing (ISSUE 18): a sampled frame
                    # (or a bundle carrying sampled members) going around
                    # again — the context itself survives untouched
                    # (``_STAMP_KEYS`` never strips it), this event just
                    # makes the extra wire leg attributable
                    flightrec.record(
                        "trace.retransmit",
                        tids=trace_ids(payload),
                        recver=p.link[1],
                        seq=p.seq,
                        attempt=p.attempts,
                    )
                # send-time failure here is NOT fatal: the identity may be
                # rebound (promotion) before the budget runs out
                self.inner.send(p.msg)
            for p in dead:
                flightrec.record(
                    "resend.gave_up", node=p.link[0],
                    recver=p.link[1], seq=p.seq, attempts=p.attempts - 1,
                )
                _log.warning(
                    "resender: gave up on %s->%s seq=%s after %d attempts",
                    p.link[0], p.link[1], p.seq, p.attempts - 1,
                )
                if self.on_give_up is not None:
                    try:
                        self.on_give_up(p.msg)
                    except Exception:  # noqa: BLE001 — user hook
                        _log.exception("resender: on_give_up hook failed")

    # -- same-id restart lifecycle -------------------------------------------
    def set_incarnation(self, node_id: str, incarnation: int) -> bool:
        """Learn ``node_id``'s (possibly new) incarnation; True iff advanced.

        Called on every node when the scheduler broadcasts a bumped
        ``(id, incarnation)`` binding.  On an advance: frames still in
        flight from the node's PREVIOUS incarnation become stale (fenced at
        receivers), local sends from the node stamp the new incarnation and
        restart seq at 0 (the new process's counter), the dead process's
        unacked sends are dropped (their ACKs will never come), and dedup
        windows for links FROM the node reset so the fresh seq space is not
        eaten by pre-crash history.  Sends TO the node keep retransmitting
        untouched — they land on the restarted process, which dedups them
        against its recovered window state (see :meth:`drop_inbound_state`).
        """
        if not self.incarnations.learn(node_id, incarnation):
            return False
        flightrec.record(
            "incarnation.advance", node=node_id, inc=incarnation,
        )
        self._reset_sender_windows(node_id)
        with self._lock:
            for link in [l for l in self._next_seq if l[0] == node_id]:
                del self._next_seq[link]
            for key in [k for k in self._pending if k[0][0] == node_id]:
                del self._pending[key]
        self._fire_incarnation_advance(node_id, incarnation)
        return True

    def _fire_incarnation_advance(self, node_id: str, incarnation: int) -> None:
        for hook in list(self.on_incarnation_advance):
            try:
                hook(node_id, incarnation)
            except Exception:  # noqa: BLE001 — observer hooks must not
                _log.exception("resender: incarnation-advance hook failed")

    def restart_node(self, node_id: str) -> int:
        """Local-authority restart: bump ``node_id``'s incarnation in place.

        For tests and single-process clusters without a Manager; clusters
        with a scheduler should re-register instead (the Manager is the
        incarnation authority) and let the broadcast reach
        :meth:`set_incarnation`.  Returns the new incarnation.
        """
        inc = self.incarnations.get(node_id) + 1
        self.set_incarnation(node_id, inc)
        return inc

    def drop_inbound_state(self, node_id: str) -> None:
        """Forget dedup windows for links INTO ``node_id``.

        Models what a real crash loses at the RECEIVER: the restarted
        process has no memory of which peer seqs it already applied, so a
        pre-crash frame retransmitted into it re-delivers.  Call this on
        the checkpoint-fallback restore path (state rewound anyway —
        re-applies land inside the accepted rewind window).  The replica
        restore path must NOT call it: a sync chain forwards every applied
        push before acking, so "applied set == window content" — keeping
        the windows IS recovering the dedup state from the chain, and it
        is what makes same-id restart exactly-once end to end.
        """
        with self._lock:
            for link in [l for l in self._windows if l[1] == node_id]:
                del self._windows[link]

    # -- introspection / lifecycle -------------------------------------------
    def inflight(self) -> int:
        """Number of sends still awaiting an ACK."""
        with self._lock:
            return len(self._pending)

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every send is acked (or gave up), then flush inner.

        Delegates the REMAINING budget down the wrapper chain (the
        ``VanWrapper`` flush contract — ``tools/check_wrappers.py``): an
        inner van with its own buffers must get its chance to drain them.
        False on timeout at either layer.
        """
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.inflight() == 0:
                break
            time.sleep(0.005)
        if self.inflight() != 0:
            return False
        return self.inner.flush(max(deadline - time.monotonic(), 0.0))

    def counters(self) -> dict:
        with self._lock:
            return {
                "retransmits": self.retransmits,
                "dup_suppressed": self.dup_suppressed,
                "gave_up": self.gave_up,
                "acks_sent": self.acks_sent,
                "acks_received": self.acks_received,
                "rejected_stale": self.rejected_stale,
                "rejected_corrupt": self.rejected_corrupt,
            }

    def close(self) -> None:
        with self._wake:
            self._closed = True
            self._wake.notify()
        self._thread.join(timeout=5)
        self.inner.close()
