"""Van: the transport layer.

The reference Van owns ZeroMQ sockets, a node table, and a receive thread
(``src/system/van.h/.cc`` [U]).  Here Van is an abstract seam with two
implementations planned:

- :class:`LoopbackVan` (this module): in-process delivery between node
  endpoints via thread-safe queues.  This is both the unit-test seam
  (deterministic, no sockets — the role loopback-ZMQ plays in the reference's
  ``script/local.sh`` integration tests, SURVEY.md §4) and the single-host
  runtime, where scheduler/servers/workers are Python objects sharing one
  process and the actual tensor traffic rides XLA, not the Van.
- :class:`~parameter_server_tpu.core.tcp_van.TcpVan`: the DCN-plane Van —
  cross-host async Push/Pull over native TCP (``native/src/tcpvan.cc``);
  same interface.

Fault injection is first-class: :meth:`LoopbackVan.disconnect` makes a node
unreachable (dropped messages), emulating a dead socket for failure-path
tests — something the reference never had (SURVEY.md §4 "opportunity").
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Callable, Optional

from parameter_server_tpu.core.messages import Message


class Van:
    """Transport interface: connect endpoints, send messages."""

    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        raise NotImplementedError

    def send(self, msg: Message) -> bool:
        """Deliver ``msg`` to ``msg.recver``.  Returns False if unreachable."""
        raise NotImplementedError

    def unbind(self, node_id: str) -> None:
        """Tear down a bound node's endpoint so a replacement can bind the
        same id (elastic server recovery relies on this)."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until buffered / in-flight frames are settled.

        Base transports deliver synchronously, so this is a no-op; layers
        that buffer (``CoalescingVan``) or track in-flight frames
        (``ReliableVan``) override it.  Returns False on timeout.
        """
        return True

    def counters(self) -> dict:
        """Dashboard counters (merged across a wrapper stack by
        ``utils.metrics.transport_counters``)."""
        return {}


class VanWrapper(Van):
    """Base for decorator Vans (reliability, chaos).

    Delegates the Van interface to ``inner`` explicitly and everything else
    (``disconnect``/``reconnect``/``add_route``/``address``/...) through
    ``__getattr__``, so a stack like ``ReliableVan(ChaosVan(LoopbackVan()))``
    is a drop-in Van for the Postoffice, the Manager's route learning, and
    the fault-injection helpers alike.
    """

    def __init__(self, inner: Van) -> None:
        self.inner = inner

    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        self.inner.bind(node_id, handler)

    def send(self, msg: Message) -> bool:
        return self.inner.send(msg)

    def unbind(self, node_id: str) -> None:
        self.inner.unbind(node_id)

    def close(self) -> None:
        self.inner.close()

    def flush(self, timeout: float = 5.0) -> bool:
        # explicit (not via __getattr__: the base-class no-op would shadow
        # delegation) so flush() on any stack reaches every buffering layer
        return self.inner.flush(timeout)

    def __getattr__(self, name):
        # only reached for attributes not defined on the wrapper itself
        return getattr(self.inner, name)


class _Endpoint:
    """A bound node: its inbox queue and receive thread."""

    def __init__(self, node_id: str, handler: Callable[[Message], None]) -> None:
        self.node_id = node_id
        self.handler = handler
        self.inbox: "queue.Queue[Optional[Message]]" = queue.Queue()
        self.thread = threading.Thread(
            target=self._recv_loop, name=f"van-recv-{node_id}", daemon=True
        )
        self.thread.start()

    def _recv_loop(self) -> None:
        while True:
            msg = self.inbox.get()
            if msg is None:
                return
            try:
                self.handler(msg)
            except Exception as e:  # noqa: BLE001 — a bad message must not
                # kill the node's only receive thread (all later messages for
                # the node would silently queue forever)
                logging.getLogger(__name__).exception(
                    "van: handler error on node %r; message dropped",
                    self.node_id,
                )
                # black-box trigger: journal the exception and, when a dump
                # dir is configured, capture the ring before it wraps
                try:
                    from parameter_server_tpu.core import flightrec

                    flightrec.on_recv_exception(self.node_id, e)
                except Exception:  # noqa: BLE001 — observability must never
                    pass  # take down the recv thread it exists to debug

    def stop(self) -> None:
        self.inbox.put(None)
        self.thread.join(timeout=5)


class LoopbackVan(Van):
    """In-process Van: queues + one receive thread per bound node.

    Mirrors the reference Van's structure (recv thread pumping a socket) with
    a queue in place of the socket, so ordering guarantees match: messages
    from A to B arrive in send order; cross-sender order is unspecified.
    """

    def __init__(self, filter_chain=None) -> None:
        """``filter_chain``: optional ``core.filters.FilterChain`` applied
        encode-on-send / decode-on-receive (the reference's per-link filter
        stack; loopback exercises the same codec path DCN traffic uses)."""
        self._endpoints: dict[str, _Endpoint] = {}
        self._disconnected: set[str] = set()
        self._lock = threading.Lock()
        # Filter traffic serializes per LINK (sender, recver), not globally:
        # key-caching's encode/decode protocol needs wire-FIFO per link
        # (which real transports give for free), while traffic on different
        # links — the hot concurrent case — encodes in parallel.
        self.filter_chain = filter_chain
        self._link_locks: dict[tuple, threading.Lock] = {}
        #: counters for the dashboard (reference network_usage.h role).
        self.sent_messages = 0
        self.dropped_messages = 0

    def bind(self, node_id: str, handler: Callable[[Message], None]) -> None:
        with self._lock:
            if node_id in self._endpoints:
                raise ValueError(f"node {node_id!r} already bound")
            self._endpoints[node_id] = _Endpoint(node_id, handler)

    def send(self, msg: Message) -> bool:
        with self._lock:
            dead = (
                msg.recver in self._disconnected
                or msg.sender in self._disconnected
            )
            ep = self._endpoints.get(msg.recver)
        if dead or ep is None:
            with self._lock:
                self.dropped_messages += 1
            return False
        with self._lock:
            self.sent_messages += 1
        if self.filter_chain is not None:
            link = (msg.sender, msg.recver)
            with self._lock:
                link_lock = self._link_locks.setdefault(link, threading.Lock())
            with link_lock:
                msg = self.filter_chain.decode(self.filter_chain.encode(msg))
        ep.inbox.put(msg)
        return True

    # -- fault injection ----------------------------------------------------
    def disconnect(self, node_id: str) -> None:
        """Simulate a dead node: all traffic to/from it is dropped."""
        with self._lock:
            self._disconnected.add(node_id)

    def reconnect(self, node_id: str) -> None:
        with self._lock:
            self._disconnected.discard(node_id)

    def unbind(self, node_id: str) -> None:
        """Tear down a node's endpoint so a replacement can bind the same id
        (elastic recovery: a rebuilt server shard takes over its dead
        predecessor's identity and key range)."""
        with self._lock:
            ep = self._endpoints.pop(node_id, None)
        if ep is not None:
            ep.stop()

    def counters(self) -> dict:
        with self._lock:
            return {
                "sent": self.sent_messages,
                "dropped": self.dropped_messages,
            }

    def close(self) -> None:
        with self._lock:
            eps = list(self._endpoints.values())
            self._endpoints.clear()
        for ep in eps:
            ep.stop()
