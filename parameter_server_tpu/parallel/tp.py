"""Tensor-parallel sharding rules for the transformer family.

GSPMD replaces hand-written NCCL tensor-parallel collectives: annotate the
parameter tree with PartitionSpecs and XLA inserts the all-gathers /
reduce-scatters over the ICI ``model`` axis (PAPERS.md: GSPMD [V]).

Rules (matching ``models/transformer.py`` param naming):
- token embedding rows sharded over ``model`` — the PS table partition (the
  "PS-sharded embeddings" half of the Llama hybrid, BASELINE config #5);
- attention q/k/v sharded over heads; output projection over heads;
- MLP up/gate sharded over d_ff, down over d_ff (Megatron-style pairing:
  column- then row-parallel, one allreduce per block);
- norms, biases of row-parallel layers, and positional embeddings replicated.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parameter_server_tpu.parallel.mesh import MODEL_AXIS


def _spec_for(path: tuple[str, ...], value: Any) -> P:
    names = [p for p in path]
    leaf = names[-1]
    parent = names[-2] if len(names) >= 2 else ""
    ndim = getattr(value, "ndim", 0)

    if leaf == "embedding":
        return P(MODEL_AXIS, None)  # vocab-row sharded (PS table scheme)
    if leaf == "pos_embedding":
        return P()
    if parent in ("q", "k", "v"):
        if leaf == "kernel":  # [d_model, heads, head_dim]
            return P(None, MODEL_AXIS, None)
        return P(MODEL_AXIS, None)  # bias [heads, head_dim]
    if parent == "o":
        if leaf == "kernel":  # [heads, head_dim, d_model]
            return P(MODEL_AXIS, None, None)
        return P()  # row-parallel bias replicated
    if parent in ("gate", "up"):
        if leaf == "kernel":  # [d_model, d_ff]
            return P(None, MODEL_AXIS)
        return P(MODEL_AXIS)
    if parent == "down":
        if leaf == "kernel":  # [d_ff, d_model]
            return P(MODEL_AXIS, None)
        return P()
    if parent == "lm_head":
        return P(None, MODEL_AXIS) if ndim == 2 else P(MODEL_AXIS)
    return P()  # norms and everything else replicated


def transformer_param_shardings(params, mesh: Mesh):
    """Map a transformer param pytree to NamedShardings per the TP rules."""

    def assign(path, value):
        names = tuple(
            p.key if hasattr(p, "key") else str(p) for p in path
        )
        return NamedSharding(mesh, _spec_for(names, value))

    return jax.tree_util.tree_map_with_path(assign, params)


def place_params(params, mesh: Mesh):
    """Device-put a host param tree onto the mesh per the TP rules."""
    shardings = transformer_param_shardings(params, mesh)
    return jax.tree.map(jax.device_put, params, shardings)
