"""Tensor-parallel sharding rules for the transformer family.

GSPMD replaces hand-written NCCL tensor-parallel collectives: annotate the
parameter tree with PartitionSpecs and XLA inserts the all-gathers /
reduce-scatters over the ICI ``model`` axis (PAPERS.md: GSPMD [V]).

Rules (matching ``models/transformer.py`` param naming):
- token embedding rows sharded over ``model`` — the PS table partition (the
  "PS-sharded embeddings" half of the Llama hybrid, BASELINE config #5);
- attention q/k/v sharded over heads; output projection over heads;
- MLP up/gate sharded over d_ff, down over d_ff (Megatron-style pairing:
  column- then row-parallel, one allreduce per block);
- norms, biases of row-parallel layers, and positional embeddings replicated.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parameter_server_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def _spec_for(path: tuple[str, ...], value: Any) -> P:
    names = [p for p in path]
    leaf = names[-1]
    parent = names[-2] if len(names) >= 2 else ""
    ndim = getattr(value, "ndim", 0)

    if leaf == "embedding":
        return P(MODEL_AXIS, None)  # vocab-row sharded (PS table scheme)
    if leaf == "pos_embedding":
        return P()
    if parent in ("q", "k", "v"):
        if leaf == "kernel":  # [d_model, heads, head_dim]
            return P(None, MODEL_AXIS, None)
        return P(MODEL_AXIS, None)  # bias [heads, head_dim]
    if parent == "o":
        if leaf == "kernel":  # [heads, head_dim, d_model]
            return P(MODEL_AXIS, None, None)
        return P()  # row-parallel bias replicated
    if parent in ("gate", "up"):
        if leaf == "kernel":  # [d_model, d_ff]
            return P(None, MODEL_AXIS)
        return P(MODEL_AXIS)
    if parent == "down":
        if leaf == "kernel":  # [d_ff, d_model]
            return P(MODEL_AXIS, None)
        return P()
    if parent == "lm_head":
        return P(None, MODEL_AXIS) if ndim == 2 else P(MODEL_AXIS)
    return P()  # norms and everything else replicated


def _add_fsdp_axis(spec: P, shape, data_n: int, axis: str) -> P:
    """Extend a TP spec with ``data``-axis sharding on the first free dim.

    Fully-sharded data parallelism in GSPMD terms: params (and therefore
    optimizer moments, which inherit these shardings) are additionally
    split over the ``data`` axis instead of being replicated per data
    replica; XLA all-gathers them at use and reduce-scatters the gradient.
    The scaling-book recipe for fitting an 8B train state on a v5e-16 —
    TP-8 alone leaves params+moments+grads at ~15 GB/device (measured,
    BASELINE.md), over the 16 GB HBM.
    """
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (p, s) in enumerate(zip(parts, shape)):
        if p is None and s % data_n == 0 and s >= data_n:
            parts[i] = axis
            break
    return P(*parts)


def transformer_param_shardings(
    params, mesh: Mesh, *, fsdp: bool = False, fsdp_axis: str = DATA_AXIS
):
    """Map a transformer param pytree to NamedShardings per the TP rules.

    ``fsdp=True`` additionally shards every param's first still-replicated
    (and evenly divisible) dimension over ``fsdp_axis`` (default ``data``;
    the SP x TP trainer passes ``sp`` — any non-``model`` axis works).
    """
    data_n = int(mesh.shape.get(fsdp_axis, 1)) if fsdp else 1

    def assign(path, value):
        names = tuple(
            p.key if hasattr(p, "key") else str(p) for p in path
        )
        if names and names[0] == "blocks":
            # scan_blocks layout: every block param carries a leading
            # n_layers axis; the per-layer rules apply to the tail dims.
            # Under FSDP that leading axis is the ideal data-axis shard:
            # the scan gathers exactly ONE layer's params per iteration.
            inner = P(*_spec_for(names, _TailView(value)))
            spec = P(None, *inner)
        else:
            spec = _spec_for(names, value)
        if data_n > 1:
            spec = _add_fsdp_axis(spec, value.shape, data_n, fsdp_axis)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(assign, params)


class _TailView:
    """Shape/ndim proxy dropping the leading (layer-stack) axis."""

    def __init__(self, value):
        self.shape = tuple(value.shape[1:])
        self.ndim = len(self.shape)


def place_params(params, mesh: Mesh):
    """Device-put a host param tree onto the mesh per the TP rules."""
    shardings = transformer_param_shardings(params, mesh)
    return jax.tree.map(jax.device_put, params, shardings)
