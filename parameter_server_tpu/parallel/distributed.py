"""Multi-host runtime: jax.distributed init, global mesh, per-host data.

The reference runs one OS process per node role and wires them with its Van
(``script/local.sh`` + ``src/system/manager.h`` [U]); a TPU pod instead runs
one process per *host*, each owning its local chips, coordinated by the JAX
distributed service (gRPC).  This module is that runtime seam (SURVEY.md §7
build-order step 4 — the piece VERDICT r1 flagged missing):

- :func:`initialize` — process startup: ``jax.distributed.initialize``
  against the coordinator, with a CPU-sim path (``cpu_devices=k`` forces k
  virtual devices per process, so a v5e-16's 4-host topology is testable as
  4 processes x 4 fake devices on one machine; collectives ride Gloo instead
  of ICI, same program).
- :func:`global_mesh` — the pod-wide Mesh over ALL processes' devices.
  Axis layout puts the process (host/DCN) boundary on the leading axis so
  model-axis collectives stay intra-host (ICI) — the scaling-book rule of
  keeping the fast axis on the fast interconnect.
- :func:`host_local_batch` — per-host input sharding: each process supplies
  only its slice of the global batch (the reference's WorkloadPool file-shard
  assignment, reborn as ``jax.make_array_from_process_local_data``).

Single-process runs degrade gracefully: ``initialize`` is a no-op without a
coordinator, and ``host_local_batch`` falls back to ``jax.device_put``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np


def initialize(
    coordinator: Optional[str] = None,
    num_processes: int = 1,
    process_id: int = 0,
    *,
    cpu_devices: int = 0,
) -> None:
    """Join the distributed job (no-op when single-process).

    ``coordinator``: ``host:port`` of process 0's coordination service.
    ``cpu_devices > 0``: CPU-sim mode — pin this process to ``cpu_devices``
    virtual CPU devices (must run before any jax backend init).
    """
    if cpu_devices:
        from parameter_server_tpu.utils.platform import force_cpu

        force_cpu(cpu_devices)
    if coordinator is None or num_processes <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("data", "model"),
):
    """Mesh over every device of every process in the job.

    Default shape: ``(num_processes, local_device_count)`` for 2 axes — the
    data axis crosses the host (DCN) boundary, the model axis stays on one
    host's chips (ICI), so table-row collectives never leave the host.
    """
    import jax

    from parameter_server_tpu.parallel import mesh as mesh_lib

    devices = jax.devices()
    if shape is None and len(axis_names) == 2:
        shape = (jax.process_count(), len(devices) // jax.process_count())
    return mesh_lib.make_mesh(shape, axis_names, devices=devices)


def host_local_batch(sharding, local_data: np.ndarray,
                     global_shape: Sequence[int]):
    """Assemble a global array from this process's slice of the batch.

    ``local_data`` is the rows this host read from ITS data shard (the
    WorkloadPool assignment); the result is a global ``jax.Array`` sharded
    per ``sharding`` whose addressable pieces come from ``local_data``.
    Single-process jobs just device_put the (complete) data.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(local_data, sharding)
    return jax.make_array_from_process_local_data(
        sharding, local_data, tuple(global_shape)
    )


def local_batch_slice(process_id: int, num_processes: int,
                      global_batch: int) -> slice:
    """Contiguous rows of the global batch this process feeds.

    Matches the data-axis device order of :func:`global_mesh` (process-major),
    so a process's rows land on its own devices — no cross-host scatter.
    """
    if global_batch % num_processes:
        raise ValueError(
            f"global batch {global_batch} not divisible by {num_processes}"
        )
    per = global_batch // num_processes
    return slice(process_id * per, (process_id + 1) * per)
