"""AOT memory feasibility: does a config FIT the target pod, per XLA itself?

SURVEY §7 step 7 / VERDICT r3 #3: before claiming the Llama-3-8B hybrid
(BASELINE config #5) runs on a v5e-16, prove the per-device compiled memory.
The technique is the one ``tests/test_seq_parallel.py`` uses for ring
attention, pointed at the flagship: AOT-compile the REAL body train step
(fwd + bwd + adamw update, the exact ``HybridLMTrainer`` step_fn math) over
a simulated N-device mesh from ``ShapeDtypeStruct``s — no parameter is ever
materialized, so a 7B-param program analyzes fine on a dev box — and read
XLA's own ``memory_analysis()`` for the per-device argument/temp/output
budget.

Run as a module for the out-of-process entry the bench uses (a 16-device
virtual CPU topology must be fixed before jax initializes):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=16 \
      python -m parameter_server_tpu.parallel.feasibility --preset llama3-8b
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

#: v5e HBM per chip (bytes) — the budget the flagship config must fit.
V5E_HBM_BYTES = 16 * 1024**3


def peak_bytes_from_analysis(ma) -> int:
    """Live-at-peak per device from XLA's ``memory_analysis()``.

    arguments (params+opt+batch; donation aliases the outputs onto them)
    + temps + generated code; alias_bytes is the donated overlap counted
    inside argument_bytes, not extra.  ONE definition, shared by the
    feasibility table and ``tools/validate_peak_bytes.py`` — the validator
    must calibrate the formula the table actually ships.
    """
    return (
        int(ma.argument_size_in_bytes)
        + int(ma.temp_size_in_bytes)
        + int(ma.generated_code_size_in_bytes)
        + max(int(ma.output_size_in_bytes) - int(ma.alias_size_in_bytes), 0)
    )


def compile_body_step(
    cfg,
    mesh,
    batch: int,
    seq: int,
    *,
    learning_rate: float = 1e-3,
    loss_chunk: int = 0,
    fsdp: str = "none",
):
    """AOT-compile one hybrid-body train step; returns (compiled, inputs).

    ``inputs`` is the (params, opt_state, emb, tokens) tuple of
    ``ShapeDtypeStruct``s (sharding-annotated) the compiled step expects —
    the validator tool materializes real arrays against them to compare
    ``memory_analysis()`` with the allocator's actual high-water
    (VERDICT r4 weak #7).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel import mesh as mesh_lib
    from parameter_server_tpu.parallel.tp import transformer_param_shardings

    body = tfm.TransformerBody(cfg)
    tx = optax.adamw(learning_rate)

    if fsdp not in ("none", "full", "state"):
        raise ValueError(f"fsdp must be none|full|state, got {fsdp!r}")
    x0 = jax.ShapeDtypeStruct((1, 8, cfg.d_model), jnp.float32)
    param_shapes = jax.eval_shape(
        lambda x: body.init(jax.random.PRNGKey(0), x)["params"], x0
    )
    p_shard = transformer_param_shardings(
        param_shapes, mesh, fsdp=fsdp == "full"
    )
    s_shard = (
        p_shard
        if fsdp == "none"
        else transformer_param_shardings(param_shapes, mesh, fsdp=True)
    )
    params_in = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        param_shapes,
        p_shard,
    )
    opt_shapes = jax.eval_shape(tx.init, params_in)
    # adamw moments mirror the param tree: give each param-like leaf its
    # param's (or, under fsdp="state", the further data-sharded) sharding
    # (non-param leaves — the int count — stay unsharded)
    opt_in = optax.tree_map_params(
        tx,
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        opt_shapes,
        s_shard,
    )
    emb_in = jax.ShapeDtypeStruct(
        (batch, seq, cfg.d_model), jnp.float32,
        sharding=mesh_lib.batch_sharding(mesh, 3),
    )
    tokens = jax.ShapeDtypeStruct(
        (batch, seq), jnp.int32, sharding=mesh_lib.batch_sharding(mesh, 2)
    )

    if loss_chunk > 0:
        trunk = tfm.TransformerTrunk(cfg)

        def loss_fn(params, emb, targets):
            hidden = trunk.apply(
                {"params": {k: v for k, v in params.items() if k != "lm_head"}},
                emb,
            )
            return tfm.chunked_causal_lm_loss(
                hidden, params["lm_head"]["kernel"], targets, loss_chunk
            )

    else:

        def loss_fn(params, emb, targets):
            logits = body.apply({"params": params}, emb)
            return tfm.causal_lm_loss(logits, targets)

    def step_fn(params, opt_state, emb, targets):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            params, emb, targets
        )
        g_params, g_emb = grads
        updates, opt_state = tx.update(g_params, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, g_emb

    step = jax.jit(step_fn, donate_argnums=(0, 1))
    with mesh:
        compiled = step.lower(params_in, opt_in, emb_in, tokens).compile()
    return compiled, (params_in, opt_in, emb_in, tokens)


def body_train_step_memory(
    cfg,
    mesh,
    batch: int,
    seq: int,
    *,
    learning_rate: float = 1e-3,
    loss_chunk: int = 0,
    fsdp: str = "none",
) -> dict:
    """Per-device memory analysis of the hybrid body train step.

    Returns XLA's compiled memory breakdown (bytes, per device) for one
    ``HybridLMTrainer``-shaped step: loss+grads w.r.t. (params, emb_in),
    adamw update, batch sharded over ``data``, params TP-sharded over
    ``model`` (``parallel/tp.py`` rules).

    ``loss_chunk > 0`` fuses the lm_head into a rematerialized chunked loss
    (``chunked_causal_lm_loss``) instead of materializing full logits.
    ``fsdp``: ``"none"`` = TP shardings only; ``"full"`` = params AND
    moments data-sharded (measured: GSPMD hoists the param all-gather out
    of the layer scan, so the gathered stack reappears as a temp — little
    net win); ``"state"`` = moments-only data sharding (the elementwise
    adamw update needs no gather, so the saving is real).
    """
    import jax
    import numpy as np

    compiled, (params_in, _opt_in, _emb_in, _tokens) = compile_body_step(
        cfg, mesh, batch, seq,
        learning_rate=learning_rate, loss_chunk=loss_chunk, fsdp=fsdp,
    )
    ma = compiled.memory_analysis()
    n_params = sum(
        int(np.prod(s.shape)) for s in jax.tree.leaves(params_in)
    )
    out = {
        "n_body_params": n_params,
        "mesh": dict(mesh.shape),
        "batch": batch,
        "seq": seq,
        "remat": bool(cfg.remat),
        "scan_blocks": bool(cfg.scan_blocks),
        "loss_chunk": loss_chunk,
        "fsdp": fsdp,
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    out["peak_bytes"] = peak_bytes_from_analysis(ma)
    out["fits_v5e"] = out["peak_bytes"] <= V5E_HBM_BYTES
    return out


def llama3_8b_feasibility(
    *,
    mesh_shape: Sequence[int] = (2, 8),
    batch: int = 8,
    seq: int = 2048,
    remat: bool = True,
    loss_chunk: int = 512,
    fsdp: str = "state",
    scan_blocks: bool = True,
    dtype: Optional[str] = None,
) -> dict:
    """The flagship check: config #5's 8B body on a v5e-16-shaped mesh.

    Default knobs are the fitting recipe: (2, 8) mesh (TP capped at 8 by
    the 8 KV heads), scan-over-blocks with per-block remat (unrolled remat
    saves ~nothing — XLA's liveness only credits recompute inside scan),
    chunked fused-head loss, FSDP over the data axis.
    """
    import jax.numpy as jnp

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel import mesh as mesh_lib

    kw = dict(remat=remat, scan_blocks=scan_blocks)
    if dtype:
        kw["dtype"] = jnp.dtype(dtype)
    cfg = tfm.llama3_8b(**kw)
    mesh = mesh_lib.make_mesh(tuple(mesh_shape))
    return body_train_step_memory(
        cfg, mesh, batch, seq, loss_chunk=loss_chunk, fsdp=fsdp
    )


def dlrm_feasibility(
    *,
    rows_log2: int = 30,
    dim: int = 16,
    mesh_shape: Sequence[int] = (1, 16),
    batch: int = 8192,
    n_sparse: int = 26,
    n_dense: int = 13,
    slots_log2: int = 18,
    optimizer: str = "adagrad",
    learning_rate: float = 0.01,
) -> dict:
    """Billion-row DLRM (config #3) per-device memory, per XLA (VERDICT r4 #3).

    AOT-compiles the REAL ``SpmdDLRMTrainer`` step (``make_dlrm_step``)
    from ShapeDtypeStructs over a simulated pod mesh: a 2^30-row x dim-16
    table + optimizer rows row-sharded over the ``model`` axis — value and
    state are 64 GB EACH at the default shape, analyzed without ever being
    materialized.  ``slots_log2`` is the bucketed unique-slot count the
    step is compiled for (``localize_to_slots``' min_bucket mechanics).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from parameter_server_tpu.config import OptimizerConfig, TableConfig
    from parameter_server_tpu.kv.optim import make_optimizer
    from parameter_server_tpu.models.dlrm import DLRM, make_dlrm_step
    from parameter_server_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.make_mesh(tuple(mesh_shape))
    rows = 1 << rows_log2
    cfg = TableConfig(
        name="emb", rows=rows, dim=dim,
        # the caller's learning_rate drives BOTH planes: the embedding
        # optimizer here and the MLP adam below (it was silently pinned to
        # 0.05 for the table — ADVICE r5 #2)
        optimizer=OptimizerConfig(kind=optimizer, learning_rate=learning_rate),
    )
    opt = make_optimizer(cfg.optimizer)
    model = DLRM(bottom_mlp=(64, 32), top_mlp=(64, 32), emb_dim=dim)
    tx = optax.adam(learning_rate)
    n_model = mesh.shape[mesh_lib.MODEL_AXIS]
    total_rows = ((rows + 1 + n_model - 1) // n_model) * n_model
    step, _sh = make_dlrm_step(cfg, mesh, model, opt, tx, n_sparse)

    t_f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)  # noqa: E731
    t_i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)  # noqa: E731
    emb_value = t_f32(total_rows, dim)
    emb_state = {k: t_f32(total_rows, dim) for k in opt.state_shapes()}
    mlp_shapes = jax.eval_shape(
        lambda: model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((1, n_dense), jnp.float32),
            jnp.zeros((1, n_sparse, dim), jnp.float32),
        )["params"]
    )
    opt_shapes = jax.eval_shape(tx.init, mlp_shapes)
    n_slots = 1 << slots_log2
    with mesh:
        compiled = step.lower(
            emb_value, emb_state, mlp_shapes, opt_shapes,
            t_i32(n_slots), t_i32(batch * n_sparse),
            t_f32(batch, n_dense), t_f32(batch),
        ).compile()
    ma = compiled.memory_analysis()
    table_bytes_per_dev = (
        (1 + len(emb_state)) * total_rows * dim * 4 // n_model
    )
    out = {
        "rows_log2": rows_log2,
        "dim": dim,
        "mesh": dict(mesh.shape),
        "batch": batch,
        "n_sparse": n_sparse,
        "slots_log2": slots_log2,
        "optimizer": optimizer,
        "table_bytes_per_device": table_bytes_per_dev,
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    out["peak_bytes"] = peak_bytes_from_analysis(ma)
    out["fits_v5e"] = out["peak_bytes"] <= V5E_HBM_BYTES
    return out


def sp_8b_feasibility(
    *,
    mesh_shape: Sequence[int] = (2, 8),
    batch: int = 1,
    seq: int = 16384,
    remat: bool = True,
    loss_chunk: int = 512,
    fsdp: str = "state",
    scan_blocks: bool = True,
    dtype: Optional[str] = None,
) -> dict:
    """The composed long-context 8B check (VERDICT r4 #5).

    AOT-compiles ``SpTpLMTrainer``'s REAL step — ring attention over the
    ``sp`` axis (partial shard_map), TP over ``model``, moments-FSDP over
    ``sp``, scan+remat+per-shard chunked fused loss — from
    ShapeDtypeStructs on a simulated (sp, model) v5e-16 and reads the
    per-device compiled memory at long sequence lengths.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel.sp_fsdp import (
        MODEL_AXIS, SP_AXIS, make_sp_step,
    )
    from parameter_server_tpu.parallel.tp import transformer_param_shardings

    if fsdp not in ("none", "state"):
        raise ValueError(f"fsdp must be none|state, got {fsdp!r}")
    kw = dict(remat=remat, scan_blocks=scan_blocks)
    if dtype:
        # compute/activation dtype: bf16 halves the scan-saved residual
        # stack (params/moments stay fp32 — flax param_dtype default)
        kw["dtype"] = jnp.dtype(dtype)
    cfg = tfm.llama3_8b(**kw)
    devices = np.asarray(jax.devices()).reshape(mesh_shape)
    mesh = Mesh(devices, (SP_AXIS, MODEL_AXIS))
    cfg_run = dataclasses.replace(
        cfg, attn_impl="ring_spmd", sp_axis=SP_AXIS, spmd_mesh=mesh
    )
    cfg_dense = dataclasses.replace(cfg, attn_impl="dense")
    tx = optax.adamw(1e-3)
    step, _loss = make_sp_step(cfg_run, mesh, tx, loss_chunk)

    model_init = tfm.Transformer(cfg_dense)
    tokens0 = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    param_shapes = jax.eval_shape(
        lambda t: model_init.init(jax.random.PRNGKey(0), t)["params"], tokens0
    )
    p_shard = transformer_param_shardings(param_shapes, mesh)
    params_in = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        param_shapes,
        p_shard,
    )
    opt_shapes = jax.eval_shape(tx.init, params_in)
    s_shard = transformer_param_shardings(
        param_shapes, mesh,
        fsdp=fsdp == "state", fsdp_axis=SP_AXIS,
    )
    opt_in = optax.tree_map_params(
        tx,
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        opt_shapes,
        s_shard,
    )
    seq_sh = NamedSharding(mesh, P(None, SP_AXIS))
    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=seq_sh)
    msk = jax.ShapeDtypeStruct((batch, seq), jnp.float32, sharding=seq_sh)
    with mesh:
        compiled = step.lower(params_in, opt_in, tok, tok, msk).compile()
    ma = compiled.memory_analysis()
    n_params = sum(
        int(np.prod(s.shape)) for s in jax.tree.leaves(param_shapes)
    )
    out = {
        "n_body_params": n_params,
        "mesh": {SP_AXIS: int(mesh_shape[0]), MODEL_AXIS: int(mesh_shape[1])},
        "batch": batch,
        "seq": seq,
        "remat": remat,
        "scan_blocks": scan_blocks,
        "loss_chunk": loss_chunk,
        "fsdp": fsdp,
        "attn": "ring_spmd",
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    out["peak_bytes"] = peak_bytes_from_analysis(ma)
    out["fits_v5e"] = out["peak_bytes"] <= V5E_HBM_BYTES
    return out


def _compile_pp_step_aot(cfg, mesh, *, tp, n_micro, micro_batch, seq):
    """AOT-compile one ``make_pp_step`` train step from ShapeDtypeStructs.

    Shared PP harness for the pp-vs-dp and pp-x-tp feasibility checks:
    stage stack sharded by ``stage_sharding(tp=...)``, embed/head
    replicated (tp=False) or TP-sharded per the PS/Megatron rules
    (tp=True), and the adamw moment shardings PINNED to the params' —
    ``eval_shape`` drops shardings, and a multi-GB moment tree left to
    GSPMD's discretion could replicate, which would make the per-device
    verdicts depend on compiler whim.  Returns XLA's memory_analysis and
    the body-stack param count.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from parameter_server_tpu.parallel.pp import (
        PP_AXIS, make_pp_step, stage_sharding,
    )

    del Mesh  # mesh comes in ready-made
    n_stages = mesh.shape[PP_AXIS]
    step, _loss, stage_module, norm_module, tx = make_pp_step(
        cfg, mesh, learning_rate=1e-3, tp=tp
    )
    x0 = jnp.zeros((1, 8, cfg.d_model), jnp.float32)
    stage_shapes = jax.eval_shape(
        lambda k: jax.vmap(
            lambda kk: stage_module.init(kk, x0)["params"]
        )(k),
        jax.ShapeDtypeStruct((n_stages, 2), jnp.uint32),
    )
    st_shard = stage_sharding(mesh, stage_shapes, tp=tp)
    repl = NamedSharding(mesh, P())
    emb_sh = NamedSharding(mesh, P("model", None)) if tp else repl
    head_sh = NamedSharding(mesh, P(None, "model")) if tp else repl
    vocab, d_model = cfg.vocab_size, cfg.d_model
    pp_params = {
        "stages": jax.tree.map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
            stage_shapes, st_shard,
        ),
        "embed": jax.ShapeDtypeStruct(
            (vocab, d_model), jnp.float32, sharding=emb_sh
        ),
        "head": jax.ShapeDtypeStruct(
            (d_model, vocab), jnp.float32, sharding=head_sh
        ),
        "norm": jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl),
            jax.eval_shape(
                lambda: norm_module.init(jax.random.PRNGKey(0), x0)["params"]
            ),
        ),
    }
    param_shardings = {
        "stages": st_shard,
        "embed": emb_sh,
        "head": head_sh,
        "norm": jax.tree.map(lambda _: repl, pp_params["norm"]),
    }
    pp_opt = optax.tree_map_params(
        tx,
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        jax.eval_shape(tx.init, pp_params),
        param_shardings,
    )
    tok = jax.ShapeDtypeStruct(
        (n_micro, micro_batch, seq), jnp.int32,
        sharding=NamedSharding(mesh, P(PP_AXIS)),
    )
    with mesh:
        compiled = step.lower(pp_params, pp_opt, tok).compile()
    n_stack = sum(
        int(np.prod(s.shape)) for s in jax.tree.leaves(stage_shapes)
    )
    return compiled.memory_analysis(), n_stack


def pp_vs_dp_feasibility(
    *,
    n_stages: int = 4,
    n_micro: int = 8,
    micro_batch: int = 1,
    seq: int = 1024,
    vocab: int = 32_768,
    n_layers: int = 24,
    d_model: int = 2304,
    d_ff: int = 8064,
    n_heads: int = 18,
    n_kv_heads: int = 6,
) -> dict:
    """Where PP beats DP (VERDICT r4 #9): a body DP cannot hold at all.

    Pure DP replicates the FULL train state per device; for this ~1.8B
    fp32 model, params + adamw moments alone are ~29 GB — over a v5e
    chip's 16 GB at ANY batch size, so data parallelism is infeasible,
    best memory knobs (scan+remat+chunked loss) notwithstanding.  The
    same model pipelined over ``pp`` stages (``make_pp_step``, the real
    GPipe schedule) holds 1/S of the stack + replicated embed/head per
    device.  Both sides are AOT-compiled from ShapeDtypeStructs and
    judged by XLA's own memory analysis.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel import mesh as mesh_lib
    from parameter_server_tpu.parallel.pp import (
        PP_AXIS, make_pp_step, stage_sharding,
    )

    cfg = tfm.TransformerConfig(
        vocab_size=vocab, n_layers=n_layers, n_heads=n_heads,
        n_kv_heads=n_kv_heads, d_model=d_model, d_ff=d_ff,
        max_seq=seq, remat=True, scan_blocks=True,
    )

    # -- DP side: the full model on ONE device, best memory knobs ----------
    mesh1 = mesh_lib.make_mesh((1, 1), devices=jax.devices()[:1])
    body = tfm.Transformer(cfg)
    tx = optax.adamw(1e-3)
    tokens0 = jax.ShapeDtypeStruct((1, 8), jnp.int32)
    p_shapes = jax.eval_shape(
        lambda t: body.init(jax.random.PRNGKey(0), t)["params"], tokens0
    )
    params_in = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), p_shapes
    )
    opt_in = jax.eval_shape(tx.init, params_in)
    trunk = tfm.TransformerTrunk(cfg)

    def dp_loss(params, tokens):
        x = jnp.take(params["embedding"], tokens, axis=0)
        trunk_params = {
            k: v for k, v in params.items()
            if k not in ("embedding", "lm_head")
        }
        hidden = trunk.apply({"params": trunk_params}, x)
        return tfm.chunked_causal_lm_loss(
            hidden, params["lm_head"]["kernel"], tokens, 512
        )

    def dp_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(dp_loss)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    batch = n_micro * micro_batch  # same global tokens/step as the PP side
    tok_dp = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    with mesh1:
        dp_compiled = (
            jax.jit(dp_step, donate_argnums=(0, 1))
            .lower(params_in, opt_in, tok_dp)
            .compile()
        )
    dp_ma = dp_compiled.memory_analysis()
    dp_peak = peak_bytes_from_analysis(dp_ma)

    # -- PP side: the same model over pp stages (shared AOT harness;
    # rotary has no positional params; untied embed/head like the trainer)
    devices = np.asarray(jax.devices()[:n_stages])
    mesh_pp = Mesh(devices.reshape(n_stages), (PP_AXIS,))
    pp_ma, _n_stack = _compile_pp_step_aot(
        cfg, mesh_pp, tp=False, n_micro=n_micro,
        micro_batch=micro_batch, seq=seq,
    )
    pp_peak = peak_bytes_from_analysis(pp_ma)

    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(p_shapes))
    return {
        "n_params": n_params,
        "seq": seq,
        "global_batch": batch,
        "dp": {
            "devices": 1,
            "argument_bytes": int(dp_ma.argument_size_in_bytes),
            "temp_bytes": int(dp_ma.temp_size_in_bytes),
            "peak_bytes": dp_peak,
            "fits_v5e": dp_peak <= V5E_HBM_BYTES,
        },
        "pp": {
            "devices": n_stages,
            "n_micro": n_micro,
            "argument_bytes": int(pp_ma.argument_size_in_bytes),
            "temp_bytes": int(pp_ma.temp_size_in_bytes),
            "peak_bytes": pp_peak,
            "fits_v5e": pp_peak <= V5E_HBM_BYTES,
        },
        "pp_beats_dp": (pp_peak <= V5E_HBM_BYTES) and (dp_peak > V5E_HBM_BYTES),
    }


def pp_tp_feasibility(
    *,
    n_stages: int = 8,
    tp: int = 8,
    n_micro: int = 8,
    micro_batch: int = 1,
    seq: int = 2048,
    vocab: int = 32_000,
    n_layers: int = 48,
    d_model: int = 7168,
    d_ff: int = 19_456,
    n_heads: int = 56,
    n_kv_heads: int = 8,
) -> dict:
    """Depth x width: PP x TP for a body TP+FSDP alone cannot hold.

    The ~26B fp32-adamw LM here carries ~420 GB of train state — far over
    a v5e-16 even fully sharded; a (pp=8, model=8) v5e-64 mesh splits the
    stack 64 ways (``stage_sharding(tp=True)``: stage axis x the TP rules)
    while the microbatch pipeline keeps activations O(M/S) per device.
    AOT-compiled from ShapeDtypeStructs; XLA's own per-device verdict.
    Needs ``n_stages * tp`` virtual devices
    (``--xla_force_host_platform_device_count=64`` at the defaults).
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel.pp import PP_AXIS

    n_dev = n_stages * tp
    if len(jax.devices()) < n_dev:
        raise RuntimeError(
            f"pp_tp_feasibility needs {n_dev} devices (pp={n_stages} x "
            f"tp={tp}), have {len(jax.devices())} — set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_dev}"
        )
    cfg = tfm.TransformerConfig(
        vocab_size=vocab, n_layers=n_layers, n_heads=n_heads,
        n_kv_heads=n_kv_heads, d_model=d_model, d_ff=d_ff, max_seq=seq,
    )
    devices = np.asarray(jax.devices()[:n_dev])
    mesh = Mesh(devices.reshape(n_stages, tp), (PP_AXIS, "model"))
    ma, n_stack = _compile_pp_step_aot(
        cfg, mesh, tp=True, n_micro=n_micro,
        micro_batch=micro_batch, seq=seq,
    )
    n_params = n_stack + vocab * d_model * 2 + d_model  # + final norm scale
    out = {
        "n_params": n_params,
        "mesh": {"pp": n_stages, "model": tp},
        "devices": n_dev,
        "n_micro": n_micro,
        "micro_batch": micro_batch,
        "seq": seq,
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    out["peak_bytes"] = peak_bytes_from_analysis(ma)
    out["fits_v5e"] = out["peak_bytes"] <= V5E_HBM_BYTES
    return out


def main(argv=None) -> int:
    # the dev image's sitecustomize registers the axon TPU plugin before
    # JAX_PLATFORMS=cpu is consulted; a CPU-sim analysis must never dial the
    # chip relay (same trick as cli.py / __graft_entry__)
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from parameter_server_tpu.utils.platform import force_cpu

        force_cpu()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="llama3-8b",
                   choices=["llama3-8b", "llama3-8b-sp", "dlrm-1b",
                            "pp-vs-dp", "pp-tp-26b"])
    p.add_argument("--mesh", default=None,
                   help="data,model mesh shape (product = device count); "
                   "default 2,8 (llama3-8b) / 1,16 (dlrm-1b)")
    p.add_argument("--batch", type=int, default=None,
                   help="default 8 (llama3-8b) / 8192 (dlrm-1b)")
    # dlrm-1b knobs
    p.add_argument("--rows-log2", type=int, default=30)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--slots-log2", type=int, default=18,
                   help="bucketed unique-slot count the step compiles for")
    p.add_argument("--optimizer", default="adagrad")
    p.add_argument("--seq", type=int, default=None,
               help="default 2048 (llama presets) / 1024 (pp-vs-dp)")
    p.add_argument("--remat", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--loss-chunk", type=int, default=512,
                   help="0 = full logits; >0 = fused-head chunked loss")
    p.add_argument("--fsdp", default="state",
                   choices=["none", "full", "state"],
                   help="data-axis sharding of train state: none, full "
                   "(params+moments), state (moments only — the one whose "
                   "saving survives the scan, see body_train_step_memory)")
    p.add_argument("--scan-blocks", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--dtype", default=None, help="e.g. bfloat16")
    args = p.parse_args(argv)
    if args.preset in ("pp-tp-26b", "pp-vs-dp"):
        # these presets expose ONLY --seq; silently computing a fixed
        # config while echoing back a user's other knobs would label
        # numbers with a configuration that was never compiled
        ignored = {
            "--mesh": args.mesh, "--batch": args.batch, "--dtype": args.dtype
        }
        bad = [k for k, v in ignored.items() if v is not None]
        if bad:
            p.error(
                f"--preset {args.preset} supports only --seq; got {bad} "
                "(edit the feasibility function's keywords for other shapes)"
            )
    if args.preset == "pp-tp-26b":
        result = pp_tp_feasibility(
            seq=args.seq if args.seq is not None else 2048
        )
    elif args.preset == "pp-vs-dp":
        result = pp_vs_dp_feasibility(
            seq=args.seq if args.seq is not None else 1024
        )
    elif args.preset == "llama3-8b-sp":
        result = sp_8b_feasibility(
            mesh_shape=tuple(
                int(x) for x in (args.mesh or "2,8").split(",")
            ),
            batch=args.batch if args.batch is not None else 1,
            seq=args.seq if args.seq is not None else 2048,
            remat=args.remat,
            loss_chunk=args.loss_chunk,
            fsdp=args.fsdp,  # sp_8b_feasibility raises on "full" itself
            scan_blocks=args.scan_blocks,
            dtype=args.dtype,
        )
    elif args.preset == "dlrm-1b":
        result = dlrm_feasibility(
            rows_log2=args.rows_log2,
            dim=args.dim,
            mesh_shape=tuple(
                int(x) for x in (args.mesh or "1,16").split(",")
            ),
            batch=args.batch if args.batch is not None else 8192,
            slots_log2=args.slots_log2,
            optimizer=args.optimizer,
        )
    else:
        result = llama3_8b_feasibility(
            mesh_shape=tuple(
                int(x) for x in (args.mesh or "2,8").split(",")
            ),
            batch=args.batch if args.batch is not None else 8,
            seq=args.seq if args.seq is not None else 2048,
            remat=args.remat,
            loss_chunk=args.loss_chunk,
            fsdp=args.fsdp,
            scan_blocks=args.scan_blocks,
            dtype=args.dtype,
        )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
