"""AOT memory feasibility: does a config FIT the target pod, per XLA itself?

SURVEY §7 step 7 / VERDICT r3 #3: before claiming the Llama-3-8B hybrid
(BASELINE config #5) runs on a v5e-16, prove the per-device compiled memory.
The technique is the one ``tests/test_seq_parallel.py`` uses for ring
attention, pointed at the flagship: AOT-compile the REAL body train step
(fwd + bwd + adamw update, the exact ``HybridLMTrainer`` step_fn math) over
a simulated N-device mesh from ``ShapeDtypeStruct``s — no parameter is ever
materialized, so a 7B-param program analyzes fine on a dev box — and read
XLA's own ``memory_analysis()`` for the per-device argument/temp/output
budget.

Run as a module for the out-of-process entry the bench uses (a 16-device
virtual CPU topology must be fixed before jax initializes):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=16 \
      python -m parameter_server_tpu.parallel.feasibility --preset llama3-8b
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

#: v5e HBM per chip (bytes) — the budget the flagship config must fit.
V5E_HBM_BYTES = 16 * 1024**3


def peak_bytes_from_analysis(ma) -> int:
    """Live-at-peak per device from XLA's ``memory_analysis()``.

    arguments (params+opt+batch; donation aliases the outputs onto them)
    + temps + generated code; alias_bytes is the donated overlap counted
    inside argument_bytes, not extra.  ONE definition, shared by the
    feasibility table and ``tools/validate_peak_bytes.py`` — the validator
    must calibrate the formula the table actually ships.
    """
    return (
        int(ma.argument_size_in_bytes)
        + int(ma.temp_size_in_bytes)
        + int(ma.generated_code_size_in_bytes)
        + max(int(ma.output_size_in_bytes) - int(ma.alias_size_in_bytes), 0)
    )


def compile_body_step(
    cfg,
    mesh,
    batch: int,
    seq: int,
    *,
    learning_rate: float = 1e-3,
    loss_chunk: int = 0,
    fsdp: str = "none",
):
    """AOT-compile one hybrid-body train step; returns (compiled, inputs).

    ``inputs`` is the (params, opt_state, emb, tokens) tuple of
    ``ShapeDtypeStruct``s (sharding-annotated) the compiled step expects —
    the validator tool materializes real arrays against them to compare
    ``memory_analysis()`` with the allocator's actual high-water
    (VERDICT r4 weak #7).
    """
    import jax
    import jax.numpy as jnp
    import optax

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel import mesh as mesh_lib
    from parameter_server_tpu.parallel.tp import transformer_param_shardings

    body = tfm.TransformerBody(cfg)
    tx = optax.adamw(learning_rate)

    if fsdp not in ("none", "full", "state"):
        raise ValueError(f"fsdp must be none|full|state, got {fsdp!r}")
    x0 = jax.ShapeDtypeStruct((1, 8, cfg.d_model), jnp.float32)
    param_shapes = jax.eval_shape(
        lambda x: body.init(jax.random.PRNGKey(0), x)["params"], x0
    )
    p_shard = transformer_param_shardings(
        param_shapes, mesh, fsdp=fsdp == "full"
    )
    s_shard = (
        p_shard
        if fsdp == "none"
        else transformer_param_shardings(param_shapes, mesh, fsdp=True)
    )
    params_in = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        param_shapes,
        p_shard,
    )
    opt_shapes = jax.eval_shape(tx.init, params_in)
    # adamw moments mirror the param tree: give each param-like leaf its
    # param's (or, under fsdp="state", the further data-sharded) sharding
    # (non-param leaves — the int count — stay unsharded)
    opt_in = optax.tree_map_params(
        tx,
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        opt_shapes,
        s_shard,
    )
    emb_in = jax.ShapeDtypeStruct(
        (batch, seq, cfg.d_model), jnp.float32,
        sharding=mesh_lib.batch_sharding(mesh, 3),
    )
    tokens = jax.ShapeDtypeStruct(
        (batch, seq), jnp.int32, sharding=mesh_lib.batch_sharding(mesh, 2)
    )

    if loss_chunk > 0:
        trunk = tfm.TransformerTrunk(cfg)

        def loss_fn(params, emb, targets):
            hidden = trunk.apply(
                {"params": {k: v for k, v in params.items() if k != "lm_head"}},
                emb,
            )
            return tfm.chunked_causal_lm_loss(
                hidden, params["lm_head"]["kernel"], targets, loss_chunk
            )

    else:

        def loss_fn(params, emb, targets):
            logits = body.apply({"params": params}, emb)
            return tfm.causal_lm_loss(logits, targets)

    def step_fn(params, opt_state, emb, targets):
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            params, emb, targets
        )
        g_params, g_emb = grads
        updates, opt_state = tx.update(g_params, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, g_emb

    step = jax.jit(step_fn, donate_argnums=(0, 1))
    with mesh:
        compiled = step.lower(params_in, opt_in, emb_in, tokens).compile()
    return compiled, (params_in, opt_in, emb_in, tokens)


def body_train_step_memory(
    cfg,
    mesh,
    batch: int,
    seq: int,
    *,
    learning_rate: float = 1e-3,
    loss_chunk: int = 0,
    fsdp: str = "none",
) -> dict:
    """Per-device memory analysis of the hybrid body train step.

    Returns XLA's compiled memory breakdown (bytes, per device) for one
    ``HybridLMTrainer``-shaped step: loss+grads w.r.t. (params, emb_in),
    adamw update, batch sharded over ``data``, params TP-sharded over
    ``model`` (``parallel/tp.py`` rules).

    ``loss_chunk > 0`` fuses the lm_head into a rematerialized chunked loss
    (``chunked_causal_lm_loss``) instead of materializing full logits.
    ``fsdp``: ``"none"`` = TP shardings only; ``"full"`` = params AND
    moments data-sharded (measured: GSPMD hoists the param all-gather out
    of the layer scan, so the gathered stack reappears as a temp — little
    net win); ``"state"`` = moments-only data sharding (the elementwise
    adamw update needs no gather, so the saving is real).
    """
    import jax
    import numpy as np

    compiled, (params_in, _opt_in, _emb_in, _tokens) = compile_body_step(
        cfg, mesh, batch, seq,
        learning_rate=learning_rate, loss_chunk=loss_chunk, fsdp=fsdp,
    )
    ma = compiled.memory_analysis()
    n_params = sum(
        int(np.prod(s.shape)) for s in jax.tree.leaves(params_in)
    )
    out = {
        "n_body_params": n_params,
        "mesh": dict(mesh.shape),
        "batch": batch,
        "seq": seq,
        "remat": bool(cfg.remat),
        "scan_blocks": bool(cfg.scan_blocks),
        "loss_chunk": loss_chunk,
        "fsdp": fsdp,
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    out["peak_bytes"] = peak_bytes_from_analysis(ma)
    out["fits_v5e"] = out["peak_bytes"] <= V5E_HBM_BYTES
    return out


def llama3_8b_feasibility(
    *,
    mesh_shape: Sequence[int] = (2, 8),
    batch: int = 8,
    seq: int = 2048,
    remat: bool = True,
    loss_chunk: int = 512,
    fsdp: str = "state",
    scan_blocks: bool = True,
    dtype: Optional[str] = None,
) -> dict:
    """The flagship check: config #5's 8B body on a v5e-16-shaped mesh.

    Default knobs are the fitting recipe: (2, 8) mesh (TP capped at 8 by
    the 8 KV heads), scan-over-blocks with per-block remat (unrolled remat
    saves ~nothing — XLA's liveness only credits recompute inside scan),
    chunked fused-head loss, FSDP over the data axis.
    """
    import jax.numpy as jnp

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel import mesh as mesh_lib

    kw = dict(remat=remat, scan_blocks=scan_blocks)
    if dtype:
        kw["dtype"] = jnp.dtype(dtype)
    cfg = tfm.llama3_8b(**kw)
    mesh = mesh_lib.make_mesh(tuple(mesh_shape))
    return body_train_step_memory(
        cfg, mesh, batch, seq, loss_chunk=loss_chunk, fsdp=fsdp
    )


def main(argv=None) -> int:
    # the dev image's sitecustomize registers the axon TPU plugin before
    # JAX_PLATFORMS=cpu is consulted; a CPU-sim analysis must never dial the
    # chip relay (same trick as cli.py / __graft_entry__)
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from parameter_server_tpu.utils.platform import force_cpu

        force_cpu()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="llama3-8b", choices=["llama3-8b"])
    p.add_argument("--mesh", default="2,8",
                   help="data,model mesh shape (product = device count)")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--remat", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--loss-chunk", type=int, default=512,
                   help="0 = full logits; >0 = fused-head chunked loss")
    p.add_argument("--fsdp", default="state",
                   choices=["none", "full", "state"],
                   help="data-axis sharding of train state: none, full "
                   "(params+moments), state (moments only — the one whose "
                   "saving survives the scan, see body_train_step_memory)")
    p.add_argument("--scan-blocks", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--dtype", default=None, help="e.g. bfloat16")
    args = p.parse_args(argv)
    result = llama3_8b_feasibility(
        mesh_shape=tuple(int(x) for x in args.mesh.split(",")),
        batch=args.batch,
        seq=args.seq,
        remat=args.remat,
        loss_chunk=args.loss_chunk,
        fsdp=args.fsdp,
        scan_blocks=args.scan_blocks,
        dtype=args.dtype,
    )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
