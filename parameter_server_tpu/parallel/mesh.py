"""Device mesh construction and canonical sharding rules.

The TPU replacement for the reference's process topology (SURVEY.md §2 #6):
the scheduler's NodeAssigner key-range split becomes row-sharding of table
arrays over the ``"model"`` mesh axis; the worker pool becomes the ``"data"``
axis.  Gradient pre-reduction over ``"data"`` (the north star's
NCCL-intra-node-psum replacement) is inserted by GSPMD when data-sharded
per-position gradients reduce into model-sharded table rows.

Axis conventions (extended by later milestones):
  data    — data parallelism (batch dimension)
  model   — table row shards / tensor parallelism
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS, MODEL_AXIS),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over the available devices.

    Default shape: all devices on the data axis (pure DP), model axis 1.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != {len(devices)} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Row-sharded table over the model axis (NodeAssigner key ranges)."""
    return NamedSharding(mesh, P(MODEL_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Leading-axis (batch) sharding over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS, *(None,) * (ndim - 1)))
