"""parallel subpackage."""
