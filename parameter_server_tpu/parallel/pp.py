"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

The one parallelism strategy the reference lacks that SURVEY.md §2 deferred
("later-stage option via shard_map stages").  TPU-native formulation — no
per-stage processes, no RPC: the layer stack is split into ``n_stages``
contiguous stages whose weights shard over a ``pp`` mesh axis; microbatch
activations flow stage-to-stage with nearest-neighbor ``lax.ppermute`` over
ICI (the same ring the scaling-book pipeline recipe uses).  The whole
pipeline — all ticks, all stages — is ONE jit-compiled ``lax.scan``, so
XLA overlaps each tick's compute with the permute, and reverse-mode AD
through the scan + ppermute yields the backward pipeline automatically
(no hand-scheduled 1F1B needed for correctness).

Schedule: classic GPipe fill/drain.  With S stages and M microbatches the
scan runs ``M + S - 1`` ticks; stage 0 injects microbatch ``t`` at tick
``t``, stage ``S-1`` emits microbatch ``t-S+1``.  Devices idle during
fill/drain (the usual GPipe bubble, fraction ``(S-1)/(M+S-1)``) — raise M
to amortize.

Composable with DP: put ``pp`` beside a ``data`` axis in the mesh and
shard the microbatch dimension of the inputs over ``data``; XLA inserts
the gradient psum across ``data`` exactly as in the other trainers.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PP_AXIS = "pp"


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_micro: jax.Array,
    *,
    axis_name: str = PP_AXIS,
    remat: bool = True,
):
    """Run microbatches through the stage pipeline.  Call inside shard_map.

    ``stage_params``: THIS device's stage weights (any pytree).
    ``x_micro``: [n_micro/S, ...activation...] — THIS device's shard of the
    microbatch stack, sharded over ``pp`` by microbatch index (VERDICT r3
    #8: the r3 version replicated the full stack on every stage, O(M x
    activation) per device).  At tick ``t`` the owning stage delivers
    microbatch ``t`` to stage 0 over a ``psum`` (zeros elsewhere — same
    bandwidth class as the ring ppermute); final-stage outputs ride a
    second psum back to the owner, so per-device buffers stay O(M/S).
    Returns this device's [n_micro/S, ...] shard of the outputs (every
    stage holds its own microbatches' final logits/activations).

    ``remat=True`` wraps the per-tick stage application in
    ``jax.checkpoint``: the scanned backward then saves only each tick's
    stage INPUT instead of every attention/MLP intermediate — same
    scan+remat memory shape as ``TransformerConfig.scan_blocks``.

    The activation shape must be stage-invariant (true for transformer
    blocks), because one buffer flows around the ring.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m_local = x_micro.shape[0]
    n_micro = m_local * n
    perm = [(i, (i + 1) % n) for i in range(n)]
    # x_micro is the device's own shard (device-varying), so zeros derived
    # from it are varying too and may ride the ring loop carry directly
    recv0 = jnp.zeros_like(x_micro[0])
    out0 = jnp.zeros_like(x_micro)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def tick(carry, t):
        recv, out_buf = carry
        mb = jnp.clip(t, 0, n_micro - 1)
        # owner of microbatch mb delivers it to every stage via psum (only
        # stage 0 uses it); owner = mb // m_local, local slot = mb % m_local
        local_slot = jnp.clip(mb % m_local, 0, m_local - 1)
        mine = jax.lax.dynamic_index_in_dim(x_micro, local_slot, keepdims=False)
        inject = jax.lax.psum(
            jnp.where(idx == mb // m_local, mine, jnp.zeros_like(mine)),
            axis_name,
        )
        x_in = jnp.where(idx == 0, inject, recv)
        y = fn(stage_params, x_in)
        # the LAST stage finishes microbatch t-(n-1) at tick t; ship it to
        # its owner (psum: zeros from every other stage)
        out_mb = jnp.clip(t - (n - 1), 0, n_micro - 1)
        emit = jnp.logical_and(idx == n - 1, t >= n - 1)
        done = jax.lax.psum(
            jnp.where(emit, y, jnp.zeros_like(y)), axis_name
        )
        out_slot = jnp.clip(out_mb % m_local, 0, m_local - 1)
        i_own_it = jnp.logical_and(idx == out_mb // m_local, t >= n - 1)
        current = jax.lax.dynamic_index_in_dim(
            out_buf, out_slot, keepdims=False
        )
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(i_own_it, done, current), out_slot, 0
        )
        recv = jax.lax.ppermute(y, axis_name, perm)
        return (recv, out_buf), None

    (_, out_buf), _ = jax.lax.scan(
        tick, (recv0, out0), jnp.arange(n_micro + n - 1)
    )
    return out_buf


def pipeline_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    tail_params,
    x_micro: jax.Array,
    tgt_micro: jax.Array,
    *,
    axis_name: str = PP_AXIS,
):
    """1F1B-interleaved pipeline with MANUAL backward.  Call inside shard_map.

    GPipe-through-AD (``pipeline_apply``) saves one residual per tick for
    the whole scan — O(M) stage inputs live on every device while the
    backward drains.  1F1B interleaves: each microbatch's backward runs as
    soon as the last stage finishes its forward, so a stage only ever
    holds the activations of the microbatches in flight — O(S), M-
    independent.  That is the schedule's entire point; FLOPs are identical
    (one fwd + one recompute + one bwd per microbatch per stage).

    SPMD-uniform retiming: per scan iteration ``i`` every stage ``s`` runs
    exactly ONE forward (microbatch ``i - s``) and ONE backward
    (microbatch ``i - (2(S-1) - s)``), both masked outside their range —
    the last stage's backward consumes its SAME-iteration forward, seeded
    by ``loss_fn``'s vjp, and gradients ride the reverse ring one hop per
    iteration.  ``loss_fn(tail_params, y, tgt) -> scalar`` is computed on
    every stage and masked (SPMD uniformity): the head matmul costs S x
    its share of FLOPs — cheap next to the body whenever head << stages,
    the regime pipeline parallelism exists for.  Activation stash:
    ``[2S, ...]`` ring-indexed by microbatch (in-flight <= 2S-1).

    Returns ``(loss_mean, dstage_params, dtail_params, dx_micro)``; the
    caller owns the embedding backward (vjp of its own lookup with
    ``dx_micro``) and the optimizer step.  ``dtail_params`` is already
    psum'd over ``axis_name``; ``dstage_params`` is each device's own
    stage gradient; ``dx_micro`` is sharded by microbatch owner like
    ``x_micro``.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m_local = x_micro.shape[0]
    n_micro = m_local * n
    perm_f = [(i, (i + 1) % n) for i in range(n)]
    perm_b = [(i, (i - 1) % n) for i in range(n)]
    stash_slots = 2 * n
    #: the full varying-manual-axes set of this shard_map context (e.g.
    #: {'data', 'pp'} under DP x PP) — fresh invariant values that will
    #: accumulate varying data must be cast to ALL of it, not just pp
    ctx_vma = tuple(sorted(x_micro.aval.vma))

    def zeros_of(tree):
        return jax.tree.map(jnp.zeros_like, tree)

    recv_f0 = jnp.zeros_like(x_micro[0])
    recv_b0 = jnp.zeros_like(x_micro[0])
    stash0 = jnp.zeros((stash_slots,) + x_micro.shape[1:], x_micro.dtype)
    dx0 = jnp.zeros_like(x_micro)

    def tick(carry, i):
        recv_f, recv_b, stash, loss_sum, dstage, dtail, dx_buf = carry

        # ---- forward of microbatch m_f = i - idx -------------------------
        # NB: every psum DELIVERY below must key on a device-UNIFORM
        # microbatch index (the consuming stage's), or the sum mixes each
        # device's own notion of "its" microbatch into garbage.
        m_f = i - idx
        active_f = jnp.logical_and(m_f >= 0, m_f < n_micro)
        mb_f = jnp.clip(m_f, 0, n_micro - 1)
        # stage 0 injects microbatch i (its own fwd): uniform index
        mb_inj = jnp.clip(i, 0, n_micro - 1)
        slot_inj = jnp.clip(mb_inj % m_local, 0, m_local - 1)
        mine = jax.lax.dynamic_index_in_dim(
            x_micro, slot_inj, keepdims=False
        )
        inject = jax.lax.psum(
            jnp.where(idx == mb_inj // m_local, mine, jnp.zeros_like(mine)),
            axis_name,
        )
        x_in = jnp.where(idx == 0, inject, recv_f)
        x_in = jnp.where(active_f, x_in, jnp.zeros_like(x_in))
        y = stage_fn(stage_params, x_in)
        # stash the stage INPUT for the recompute-backward, ring-indexed
        st_slot = mb_f % stash_slots
        cur = jax.lax.dynamic_index_in_dim(stash, st_slot, keepdims=False)
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(active_f, x_in, cur), st_slot, 0
        )

        # ---- backward of microbatch m_b = i - (2(S-1) - idx) -------------
        m_b = i - (2 * (n - 1) - idx)
        active_b = jnp.logical_and(m_b >= 0, m_b < n_micro)
        mb_b = jnp.clip(m_b, 0, n_micro - 1)
        is_last = idx == n - 1
        # the last stage's backward is the SAME iteration as its forward:
        # m_b == m_f there, so y is this iteration's; its target index
        # i - (S-1) is the uniform delivery key
        mb_tgt = jnp.clip(i - (n - 1), 0, n_micro - 1)
        tslot_tgt = jnp.clip(mb_tgt % m_local, 0, m_local - 1)
        tmine = jax.lax.dynamic_index_in_dim(
            tgt_micro, tslot_tgt, keepdims=False
        )
        tgt = jax.lax.psum(
            jnp.where(idx == mb_tgt // m_local, tmine, jnp.zeros_like(tmine)),
            axis_name,
        )
        # vjp wrt a device-INVARIANT input auto-psums the partial across
        # the mesh axis (the shard_map transpose rule) — which would mix
        # every stage's masked-tick garbage into dtail_i BEFORE our gate.
        # Cast the tail params varying so the partial stays per-device;
        # the single explicit psum after the scan does the reduction.
        tail_v = jax.tree.map(
            lambda a: jax.lax.pcast(a, ctx_vma, to="varying"), tail_params
        )
        loss_m, vjp_tail = jax.vjp(
            lambda tp, yy: loss_fn(tp, yy, tgt), tail_v, y
        )
        one = jax.lax.pcast(jnp.float32(1.0), ctx_vma, to="varying")
        dtail_i, dy_last = vjp_tail(one)
        dy = jnp.where(is_last, dy_last, recv_b)
        xb_slot = mb_b % stash_slots
        x_b = jax.lax.dynamic_index_in_dim(stash, xb_slot, keepdims=False)
        _, vjp_stage = jax.vjp(stage_fn, stage_params, x_b)
        dp_i, dx_i = vjp_stage(dy)

        # select, don't multiply: 0 * inf/NaN from a masked tick's garbage
        # inputs would still poison the accumulators
        last_b = jnp.logical_and(active_b, is_last)
        dstage = jax.tree.map(
            lambda a, g: a + jnp.where(active_b, g, jnp.zeros_like(g)),
            dstage, dp_i,
        )
        dtail = jax.tree.map(
            lambda a, g: a + jnp.where(last_b, g, jnp.zeros_like(g)),
            dtail, dtail_i,
        )
        loss_sum = loss_sum + jnp.where(last_b, loss_m, 0.0)

        # stage 0 finished microbatch i - 2(S-1): ship d(embedding input)
        # home (uniform delivery key again)
        m_dx = i - 2 * (n - 1)
        dx_valid = jnp.logical_and(m_dx >= 0, m_dx < n_micro)
        mb_dx = jnp.clip(m_dx, 0, n_micro - 1)
        done_dx = jax.lax.psum(
            jnp.where(
                jnp.logical_and(idx == 0, active_b),
                dx_i,
                jnp.zeros_like(dx_i),
            ),
            axis_name,
        )
        own_dx = jnp.logical_and(idx == mb_dx // m_local, dx_valid)
        dslot = jnp.clip(mb_dx % m_local, 0, m_local - 1)
        cur_dx = jax.lax.dynamic_index_in_dim(dx_buf, dslot, keepdims=False)
        dx_buf = jax.lax.dynamic_update_index_in_dim(
            dx_buf, jnp.where(own_dx, done_dx, cur_dx), dslot, 0
        )

        # rings: activations forward, gradients backward (zeros if masked)
        recv_f = jax.lax.ppermute(
            jnp.where(active_f, y, jnp.zeros_like(y)), axis_name, perm_f
        )
        recv_b = jax.lax.ppermute(
            jnp.where(active_b, dx_i, jnp.zeros_like(dx_i)),
            axis_name,
            perm_b,
        )
        return (recv_f, recv_b, stash, loss_sum, dstage, dtail, dx_buf), None

    n_iters = n_micro + 2 * (n - 1)
    # carries that start device-invariant but accumulate device-varying
    # values must be marked varying up front (shard_map VMA typing)
    vary = lambda t: jax.tree.map(  # noqa: E731
        lambda a: jax.lax.pcast(a, ctx_vma, to="varying"), t
    )
    carry0 = (
        recv_f0, recv_b0, vary(stash0), vary(jnp.float32(0.0)),
        zeros_of(stage_params), vary(zeros_of(tail_params)), dx0,
    )
    (_, _, _, loss_sum, dstage, dtail, dx_buf), _ = jax.lax.scan(
        tick, carry0, jnp.arange(n_iters)
    )
    # loss lives on the last stage only; every stage accumulated its own
    # dstage; dtail is last-stage-only -> share both
    loss_mean = jax.lax.psum(loss_sum, axis_name) / n_micro
    dtail = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), dtail)
    # match the pmean-loss convention of the GPipe path: grads of the MEAN
    dstage = jax.tree.map(lambda g: g / n_micro, dstage)
    dtail = jax.tree.map(lambda g: g / n_micro, dtail)
    dx_buf = dx_buf / n_micro
    return loss_mean, dstage, dtail, dx_buf


def stack_stage_params(per_stage_params) -> object:
    """Stack a list of per-stage pytrees along a new leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_sharding(mesh: Mesh, tree, *, tp: bool = False) -> object:
    """Shard stage-stacked params: leading axis over ``pp``.

    ``tp=True`` additionally applies the tensor-parallel rules
    (``parallel/tp.py``) to the tail dims over the ``model`` axis — the
    PP x TP composition: each device holds 1/(S x TP) of the stack.
    """
    if not tp:
        return jax.tree.map(
            lambda leaf: NamedSharding(
                mesh, P(PP_AXIS, *(None,) * (leaf.ndim - 1))
            ),
            tree,
        )
    from parameter_server_tpu.parallel.tp import _spec_for, _TailView

    def spec(path, leaf):
        names = tuple(p.key if hasattr(p, "key") else str(p) for p in path)
        tail = _spec_for(names, _TailView(leaf))
        return NamedSharding(mesh, P(PP_AXIS, *tail))

    return jax.tree_util.tree_map_with_path(spec, tree)


def make_pp_step(
    cfg, mesh: Mesh, *, learning_rate: float = 1e-3,
    schedule: str = "gpipe", tp: bool = False,
):
    """Build the jitted PP train step WITHOUT materializing any params.

    Factored from ``PipelinedLMTrainer`` so the PP-vs-DP feasibility
    comparison (VERDICT r4 #9) can AOT-compile the real pipelined step
    from ShapeDtypeStructs: params = {stages (stacked, pp-sharded), embed,
    head, norm}; inputs = tokens_micro [n_micro, mb, seq] int32.

    ``schedule``: "gpipe" (AD through the scanned pipeline; O(M) saved
    residuals per device) or "1f1b" (``pipeline_1f1b``'s manual interleaved
    backward; O(S) stash — same math, same FLOPs, M-independent memory).

    ``tp=True`` composes the pipeline with tensor parallelism: the mesh
    carries a ``model`` axis that stays AUTO (GSPMD) while only pp/data go
    manual in the shard_map — stage weights shard over BOTH the stage and
    the model axes (``stage_sharding(tp=True)``), the same partial-manual
    trick as ``ops.ring_attention_spmd``.  The depth x width sharding a
    30B+ body needs (see ``feasibility.pp_tp_feasibility``).

    Returns ``(step_fn_jitted, loss_fn_jitted, stage_module, norm_module,
    tx)``; shardings ride on the inputs.
    """
    import optax

    from parameter_server_tpu.models import transformer as tfm
    from parameter_server_tpu.parallel.mesh import DATA_AXIS

    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule must be gpipe|1f1b, got {schedule!r}")
    n_stages = mesh.shape[PP_AXIS]
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers {cfg.n_layers} % pp {n_stages} != 0")
    if cfg.positional != "rotary":
        # learned positional embeddings are a stage-0-only parameter and
        # would break the uniform per-stage weight stacking — and Stage
        # below never adds them, so a learned-pos config would silently
        # train with NO positional signal; rotary is positionless state.
        # Guard HERE (the shared entry): the feasibility path calls this
        # directly, not through the trainer.
        raise ValueError(
            "make_pp_step requires cfg.positional == 'rotary'; "
            f"got {cfg.positional!r}"
        )
    per_stage = cfg.n_layers // n_stages

    class Stage(tfm.nn.Module):  # type: ignore[name-defined]
        @tfm.nn.compact
        def __call__(self, x):
            positions = jnp.arange(x.shape[1])[None, :]
            for _ in range(per_stage):
                x = tfm.Block(cfg)(x, positions)
            return x

    stage_module = Stage()
    norm_module = tfm.Norm(cfg.norm)
    tx = optax.adamw(learning_rate)
    from parameter_server_tpu.parallel.mesh import MODEL_AXIS as _MODEL

    if tp and _MODEL not in mesh.axis_names:
        raise ValueError(
            f"tp=True needs a {_MODEL!r} mesh axis, got {mesh.axis_names}"
        )
    data_axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
    axis = PP_AXIS
    #: only pp (and data) go manual; a model axis, if present, stays AUTO
    #: so GSPMD keeps distributing the TP'd weight math inside the stages
    manual = frozenset(n for n in mesh.axis_names if n != _MODEL)
    # ONE definition of the input specs for both schedules (the GPipe and
    # 1F1B paths must stay spec-identical or trajectory parity breaks)
    x_spec = P(axis, data_axis, None, None) if data_axis else P(axis)
    tok_spec = P(axis, data_axis, None) if data_axis else P(axis)

    def stage_fn(stage_params_local, x):
        local = jax.tree.map(lambda a: a[0], stage_params_local)
        return stage_module.apply({"params": local}, x)

    def check_micro(tokens_micro):
        # trace-time twin of the PipelinedLMTrainer ctor check: AOT and
        # feasibility callers reach here without the trainer, and an uneven
        # microbatch split otherwise dies as an opaque GSPMD sharding error
        # inside the shard_map (ADVICE r5 #3)
        n_micro = tokens_micro.shape[0]
        if n_micro % n_stages:
            raise ValueError(
                f"n_micro {n_micro} % pp stages {n_stages} != 0"
            )

    def loss_from(params, tokens_micro):
        check_micro(tokens_micro)
        x = jnp.take(params["embed"], tokens_micro, axis=0)

        def body(stages, x_micro, tokens_ref):
            out = pipeline_apply(stage_fn, stages, x_micro, axis_name=axis)
            out = norm_module.apply({"params": params["norm"]}, out)
            logits = jnp.einsum("mbsd,dv->mbsv", out, params["head"])
            losses = jax.vmap(tfm.causal_lm_loss)(logits, tokens_ref)
            loss = jax.lax.pmean(jnp.mean(losses), axis)
            if data_axis is not None:
                loss = jax.lax.pmean(loss, data_axis)
            return loss

        shard = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(
                jax.tree.map(lambda _: P(axis), params["stages"]),
                x_spec,
                tok_spec,
            ),
            out_specs=P(),
            axis_names=manual,
        )
        return shard(params["stages"], x, tokens_micro)

    def tail_loss(tail, y, tgt):
        # one microbatch's head+loss: y [mb, seq, d], tgt [mb, seq]
        out = norm_module.apply({"params": tail["norm"]}, y)
        logits = jnp.einsum("bsd,dv->bsv", out, tail["head"])
        return tfm.causal_lm_loss(logits, tgt)

    def loss_and_grads_1f1b(params, tokens_micro):
        check_micro(tokens_micro)
        x, vjp_emb = jax.vjp(
            lambda e: jnp.take(e, tokens_micro, axis=0), params["embed"]
        )
        tail = {"norm": params["norm"], "head": params["head"]}

        def body(stages, tail_in, x_micro, tok_micro):
            loss, dstage, dtail, dx = pipeline_1f1b(
                stage_fn, tail_loss, stages, tail_in, x_micro, tok_micro,
                axis_name=axis,
            )
            if data_axis is not None:  # DP: mean loss and grads over data
                loss = jax.lax.pmean(loss, data_axis)
                dstage = jax.tree.map(
                    lambda g: jax.lax.pmean(g, data_axis), dstage
                )
                dtail = jax.tree.map(
                    lambda g: jax.lax.pmean(g, data_axis), dtail
                )
                # dx shards stay per-data-replica (vjp_emb sum-scatters
                # them into the SHARED embedding) — scale to match the
                # pmean'd loss the other gradients differentiate
                dx = dx / jax.lax.axis_size(data_axis)
            return loss, dstage, dtail, dx

        stage_spec = jax.tree.map(lambda _: P(axis), params["stages"])
        tail_spec = jax.tree.map(lambda _: P(), tail)
        shard = jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(stage_spec, tail_spec, x_spec, tok_spec),
            out_specs=(P(), stage_spec, tail_spec, x_spec),
            axis_names=manual,
        )
        loss, dstage, dtail, dx = shard(
            params["stages"], tail, x, tokens_micro
        )
        (d_embed,) = vjp_emb(dx)
        grads = {
            "stages": dstage,
            "embed": d_embed,
            "head": dtail["head"],
            "norm": dtail["norm"],
        }
        return loss, grads

    def step_fn(params, opt_state, tokens_micro):
        if schedule == "1f1b":
            loss, grads = loss_and_grads_1f1b(params, tokens_micro)
        else:
            loss, grads = jax.value_and_grad(loss_from)(params, tokens_micro)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return (
        jax.jit(step_fn, donate_argnums=(0, 1)),
        jax.jit(loss_from),
        stage_module,
        norm_module,
        tx,
    )


class PipelinedLMTrainer:
    """Causal-LM trainer with the transformer body pipelined over ``pp``.

    Embedding and LM head are replicated (they are the small matmuls next
    to the body at depth); the block stack splits into ``pp`` stages of
    ``n_layers / pp`` blocks each.  One jit step = embed -> microbatch
    pipeline (shard_map over ``pp``) -> head/loss on the last stage ->
    adamw update; the loss and all gradients flow back through the scanned
    pipeline by reverse-mode AD.
    """

    def __init__(
        self,
        cfg,
        mesh: Mesh,
        *,
        n_micro: int = 4,
        learning_rate: float = 1e-3,
        seed: int = 0,
        schedule: str = "gpipe",
        dashboard=None,
    ) -> None:
        import optax

        from parameter_server_tpu.models import transformer as tfm
        from parameter_server_tpu.utils import metrics as metrics_lib

        if PP_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must carry a {PP_AXIS!r} axis, got {mesh.axis_names}")
        n_stages = mesh.shape[PP_AXIS]
        if cfg.n_layers % n_stages:
            raise ValueError(
                f"n_layers {cfg.n_layers} % pp stages {n_stages} != 0"
            )
        if n_micro % n_stages:
            # the microbatch stack is sharded over pp by microbatch index
            # (each stage owns n_micro/S end to end) — an uneven split
            # would die as an opaque sharding error inside shard_map
            raise ValueError(
                f"n_micro {n_micro} % pp stages {n_stages} != 0"
            )
        # (positional == 'rotary' is enforced by make_pp_step — the shared
        # entry the feasibility path also uses)
        self.cfg = cfg
        self.mesh = mesh
        self.n_micro = n_micro
        self.n_stages = n_stages

        (
            self._step,
            self._loss,
            self.stage_module,
            self.norm_module,
            self.tx,
        ) = make_pp_step(
            cfg, mesh, learning_rate=learning_rate, schedule=schedule
        )
        key = jax.random.PRNGKey(seed)
        keys = jax.random.split(key, n_stages + 3)
        x0 = jnp.zeros((1, 8, cfg.d_model), jnp.float32)
        # init the stacked stage weights INSIDE jit with pp-sharded outputs:
        # each stage materializes directly on its own device — an eager
        # init + stack would hold the FULL layer stack on device 0, the
        # exact allocation pipeline parallelism exists to avoid
        shapes = jax.eval_shape(
            lambda k: jax.vmap(
                lambda kk: self.stage_module.init(kk, x0)["params"]
            )(k),
            keys[:n_stages],
        )
        with mesh:
            self.stage_params = jax.jit(
                lambda k: jax.vmap(
                    lambda kk: self.stage_module.init(kk, x0)["params"]
                )(k),
                out_shardings=stage_sharding(mesh, shapes),
            )(keys[:n_stages])

        emb_key, head_key, norm_key = keys[-3], keys[-2], keys[-1]
        repl = NamedSharding(mesh, P())
        self.embed = jax.device_put(
            (jax.random.normal(emb_key, (cfg.vocab_size, cfg.d_model)) * 0.02
             ).astype(jnp.float32),
            repl,
        )
        self.head = jax.device_put(
            (jax.random.normal(head_key, (cfg.d_model, cfg.vocab_size)) * 0.02
             ).astype(jnp.float32),
            repl,
        )
        # final norm lives with the head OUTSIDE the pipeline (replicated):
        # the canonical body (models/transformer._apply_body) normalizes the
        # residual stream after the block stack; omitting it here would make
        # PP train a subtly different model than the other trainers
        self.norm = jax.device_put(
            self.norm_module.init(norm_key, x0)["params"], repl
        )
        params0 = {
            "stages": self.stage_params,
            "embed": self.embed,
            "head": self.head,
            "norm": self.norm,
        }
        # init INSIDE jit with the Adam moments CONSTRAINED to the params'
        # shardings (mu/nu for the stage stack stay pp-sharded; replicating
        # them would materialize 2x the full stack per device — the exact
        # OOM pipeline parallelism exists to avoid)
        param_shardings = jax.tree.map(lambda a: a.sharding, params0)

        def _init_opt(p):
            return optax.tree_map_params(
                self.tx,
                lambda leaf, sh: jax.lax.with_sharding_constraint(leaf, sh),
                self.tx.init(p),
                param_shardings,
            )

        with mesh:
            self.opt_state = jax.jit(_init_opt)(params0)

        # MFU wiring (VERDICT r3 weak #4): 6ND over the matmul-participating
        # params — the full stage stack (the stacked leading axis sums all
        # layers) + head; the embedding gather is not matmul work.  GPipe's
        # fill/drain bubble is NOT credited: MFU counts model FLOPs, so the
        # bubble shows up as lower MFU, which is the honest accounting.
        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        self.n_matmul_params = sum(
            int(np.prod(leaf.shape))
            for leaf in jax.tree.leaves(self.stage_params)
        ) + int(np.prod(self.head.shape)) + sum(
            int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(self.norm)
        )
        self.step_count = 0

    def _params(self):
        return {
            "stages": self.stage_params,
            "embed": self.embed,
            "head": self.head,
            "norm": self.norm,
        }

    def _micro(self, tokens: np.ndarray) -> np.ndarray:
        tokens = np.asarray(tokens)
        if tokens.shape[0] % self.n_micro:
            raise ValueError(
                f"batch {tokens.shape[0]} % n_micro {self.n_micro} != 0"
            )
        return tokens.reshape(
            self.n_micro, tokens.shape[0] // self.n_micro, tokens.shape[1]
        ).astype(np.int32)

    def step(self, tokens: np.ndarray) -> float:
        """tokens [B, S] -> loss; B must split into n_micro microbatches."""
        micro = self._micro(tokens)
        params, self.opt_state, loss = self._step(
            self._params(), self.opt_state, jnp.asarray(micro)
        )
        self.stage_params = params["stages"]
        self.embed = params["embed"]
        self.head = params["head"]
        self.norm = params["norm"]
        loss_f = float(loss)
        self.step_count += 1
        tokens = np.asarray(tokens)
        self.dashboard.flops_per_example = (
            6.0 * self.n_matmul_params * tokens.shape[1]
        )
        self.dashboard.record(
            self.step_count, loss_f, examples=int(tokens.shape[0])
        )
        return loss_f

    def loss(self, tokens: np.ndarray) -> float:
        return float(self._loss(self._params(), jnp.asarray(self._micro(tokens))))
