"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

The one parallelism strategy the reference lacks that SURVEY.md §2 deferred
("later-stage option via shard_map stages").  TPU-native formulation — no
per-stage processes, no RPC: the layer stack is split into ``n_stages``
contiguous stages whose weights shard over a ``pp`` mesh axis; microbatch
activations flow stage-to-stage with nearest-neighbor ``lax.ppermute`` over
ICI (the same ring the scaling-book pipeline recipe uses).  The whole
pipeline — all ticks, all stages — is ONE jit-compiled ``lax.scan``, so
XLA overlaps each tick's compute with the permute, and reverse-mode AD
through the scan + ppermute yields the backward pipeline automatically
(no hand-scheduled 1F1B needed for correctness).

Schedule: classic GPipe fill/drain.  With S stages and M microbatches the
scan runs ``M + S - 1`` ticks; stage 0 injects microbatch ``t`` at tick
``t``, stage ``S-1`` emits microbatch ``t-S+1``.  Devices idle during
fill/drain (the usual GPipe bubble, fraction ``(S-1)/(M+S-1)``) — raise M
to amortize.

Composable with DP: put ``pp`` beside a ``data`` axis in the mesh and
shard the microbatch dimension of the inputs over ``data``; XLA inserts
the gradient psum across ``data`` exactly as in the other trainers.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

PP_AXIS = "pp"


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x_micro: jax.Array,
    *,
    axis_name: str = PP_AXIS,
    remat: bool = True,
):
    """Run microbatches through the stage pipeline.  Call inside shard_map.

    ``stage_params``: THIS device's stage weights (any pytree).
    ``x_micro``: [n_micro/S, ...activation...] — THIS device's shard of the
    microbatch stack, sharded over ``pp`` by microbatch index (VERDICT r3
    #8: the r3 version replicated the full stack on every stage, O(M x
    activation) per device).  At tick ``t`` the owning stage delivers
    microbatch ``t`` to stage 0 over a ``psum`` (zeros elsewhere — same
    bandwidth class as the ring ppermute); final-stage outputs ride a
    second psum back to the owner, so per-device buffers stay O(M/S).
    Returns this device's [n_micro/S, ...] shard of the outputs (every
    stage holds its own microbatches' final logits/activations).

    ``remat=True`` wraps the per-tick stage application in
    ``jax.checkpoint``: the scanned backward then saves only each tick's
    stage INPUT instead of every attention/MLP intermediate — same
    scan+remat memory shape as ``TransformerConfig.scan_blocks``.

    The activation shape must be stage-invariant (true for transformer
    blocks), because one buffer flows around the ring.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    m_local = x_micro.shape[0]
    n_micro = m_local * n
    perm = [(i, (i + 1) % n) for i in range(n)]
    # x_micro is the device's own shard (device-varying), so zeros derived
    # from it are varying too and may ride the ring loop carry directly
    recv0 = jnp.zeros_like(x_micro[0])
    out0 = jnp.zeros_like(x_micro)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def tick(carry, t):
        recv, out_buf = carry
        mb = jnp.clip(t, 0, n_micro - 1)
        # owner of microbatch mb delivers it to every stage via psum (only
        # stage 0 uses it); owner = mb // m_local, local slot = mb % m_local
        local_slot = jnp.clip(mb % m_local, 0, m_local - 1)
        mine = jax.lax.dynamic_index_in_dim(x_micro, local_slot, keepdims=False)
        inject = jax.lax.psum(
            jnp.where(idx == mb // m_local, mine, jnp.zeros_like(mine)),
            axis_name,
        )
        x_in = jnp.where(idx == 0, inject, recv)
        y = fn(stage_params, x_in)
        # the LAST stage finishes microbatch t-(n-1) at tick t; ship it to
        # its owner (psum: zeros from every other stage)
        out_mb = jnp.clip(t - (n - 1), 0, n_micro - 1)
        emit = jnp.logical_and(idx == n - 1, t >= n - 1)
        done = jax.lax.psum(
            jnp.where(emit, y, jnp.zeros_like(y)), axis_name
        )
        out_slot = jnp.clip(out_mb % m_local, 0, m_local - 1)
        i_own_it = jnp.logical_and(idx == out_mb // m_local, t >= n - 1)
        current = jax.lax.dynamic_index_in_dim(
            out_buf, out_slot, keepdims=False
        )
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(i_own_it, done, current), out_slot, 0
        )
        recv = jax.lax.ppermute(y, axis_name, perm)
        return (recv, out_buf), None

    (_, out_buf), _ = jax.lax.scan(
        tick, (recv0, out0), jnp.arange(n_micro + n - 1)
    )
    return out_buf


def stack_stage_params(per_stage_params) -> object:
    """Stack a list of per-stage pytrees along a new leading stage axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_sharding(mesh: Mesh, tree) -> object:
    """Shard stage-stacked params: leading axis over ``pp``, rest unsharded."""
    def spec(leaf):
        return NamedSharding(mesh, P(PP_AXIS, *(None,) * (leaf.ndim - 1)))

    return jax.tree.map(spec, tree)


class PipelinedLMTrainer:
    """Causal-LM trainer with the transformer body pipelined over ``pp``.

    Embedding and LM head are replicated (they are the small matmuls next
    to the body at depth); the block stack splits into ``pp`` stages of
    ``n_layers / pp`` blocks each.  One jit step = embed -> microbatch
    pipeline (shard_map over ``pp``) -> head/loss on the last stage ->
    adamw update; the loss and all gradients flow back through the scanned
    pipeline by reverse-mode AD.
    """

    def __init__(
        self,
        cfg,
        mesh: Mesh,
        *,
        n_micro: int = 4,
        learning_rate: float = 1e-3,
        seed: int = 0,
        dashboard=None,
    ) -> None:
        import optax

        from parameter_server_tpu.models import transformer as tfm
        from parameter_server_tpu.utils import metrics as metrics_lib

        if PP_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh must carry a {PP_AXIS!r} axis, got {mesh.axis_names}")
        n_stages = mesh.shape[PP_AXIS]
        if cfg.n_layers % n_stages:
            raise ValueError(
                f"n_layers {cfg.n_layers} % pp stages {n_stages} != 0"
            )
        if n_micro % n_stages:
            # the microbatch stack is sharded over pp by microbatch index
            # (each stage owns n_micro/S end to end) — an uneven split
            # would die as an opaque sharding error inside shard_map
            raise ValueError(
                f"n_micro {n_micro} % pp stages {n_stages} != 0"
            )
        if cfg.positional != "rotary":
            # learned positional embeddings are a stage-0-only parameter and
            # would break the uniform per-stage weight stacking; rotary is
            # positionless state (computed per block from indices)
            raise ValueError(
                "PipelinedLMTrainer requires cfg.positional == 'rotary'; "
                f"got {cfg.positional!r}"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.n_micro = n_micro
        self.n_stages = n_stages
        per_stage = cfg.n_layers // n_stages

        # one flax module = one stage (per_stage sequential blocks)
        stage_cfg_layers = per_stage

        class Stage(tfm.nn.Module):  # type: ignore[name-defined]
            @tfm.nn.compact
            def __call__(self, x):
                positions = jnp.arange(x.shape[1])[None, :]
                for _ in range(stage_cfg_layers):
                    x = tfm.Block(cfg)(x, positions)
                return x

        self.stage_module = Stage()
        key = jax.random.PRNGKey(seed)
        keys = jax.random.split(key, n_stages + 3)
        x0 = jnp.zeros((1, 8, cfg.d_model), jnp.float32)
        # init the stacked stage weights INSIDE jit with pp-sharded outputs:
        # each stage materializes directly on its own device — an eager
        # init + stack would hold the FULL layer stack on device 0, the
        # exact allocation pipeline parallelism exists to avoid
        shapes = jax.eval_shape(
            lambda k: jax.vmap(
                lambda kk: self.stage_module.init(kk, x0)["params"]
            )(k),
            keys[:n_stages],
        )
        with mesh:
            self.stage_params = jax.jit(
                lambda k: jax.vmap(
                    lambda kk: self.stage_module.init(kk, x0)["params"]
                )(k),
                out_shardings=stage_sharding(mesh, shapes),
            )(keys[:n_stages])

        emb_key, head_key, norm_key = keys[-3], keys[-2], keys[-1]
        repl = NamedSharding(mesh, P())
        self.embed = jax.device_put(
            (jax.random.normal(emb_key, (cfg.vocab_size, cfg.d_model)) * 0.02
             ).astype(jnp.float32),
            repl,
        )
        self.head = jax.device_put(
            (jax.random.normal(head_key, (cfg.d_model, cfg.vocab_size)) * 0.02
             ).astype(jnp.float32),
            repl,
        )
        # final norm lives with the head OUTSIDE the pipeline (replicated):
        # the canonical body (models/transformer._apply_body) normalizes the
        # residual stream after the block stack; omitting it here would make
        # PP train a subtly different model than the other trainers
        self.norm_module = tfm.Norm(cfg.norm)
        self.norm = jax.device_put(
            self.norm_module.init(norm_key, x0)["params"], repl
        )
        self.tx = optax.adamw(learning_rate)
        params0 = {
            "stages": self.stage_params,
            "embed": self.embed,
            "head": self.head,
            "norm": self.norm,
        }
        # init INSIDE jit with the Adam moments CONSTRAINED to the params'
        # shardings (mu/nu for the stage stack stay pp-sharded; replicating
        # them would materialize 2x the full stack per device — the exact
        # OOM pipeline parallelism exists to avoid)
        param_shardings = jax.tree.map(lambda a: a.sharding, params0)

        def _init_opt(p):
            return optax.tree_map_params(
                self.tx,
                lambda leaf, sh: jax.lax.with_sharding_constraint(leaf, sh),
                self.tx.init(p),
                param_shardings,
            )

        with mesh:
            self.opt_state = jax.jit(_init_opt)(params0)

        stage_module, tx, axis = self.stage_module, self.tx, PP_AXIS
        norm_module = self.norm_module
        #: DP composition: a "data" axis beside "pp" shards the microbatch
        #: rows; every device still runs the same pipeline schedule and the
        #: loss pmean over "data" (whose grads transpose to the psum) is the
        #: usual DP gradient allreduce.
        from parameter_server_tpu.parallel.mesh import DATA_AXIS

        data_axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else None

        def stage_fn(stage_params_local, x):
            # shard_map hands the local slice with a leading length-1 stage
            # axis; peel it for the module
            local = jax.tree.map(lambda a: a[0], stage_params_local)
            return stage_module.apply({"params": local}, x)

        def loss_from(params, tokens_micro):
            # tokens_micro: [n_micro, mb, seq] int32; the microbatch axis is
            # SHARDED over pp (each stage owns n_micro/S microbatches end to
            # end — VERDICT r3 #8's O(M/S) injection buffer), the mb axis
            # over data when present
            x = jnp.take(params["embed"], tokens_micro, axis=0)

            def body(stages, x_micro, tokens_ref):
                out = pipeline_apply(stage_fn, stages, x_micro, axis_name=axis)
                out = norm_module.apply({"params": params["norm"]}, out)
                logits = jnp.einsum("mbsd,dv->mbsv", out, params["head"])
                # per-microbatch causal loss over THIS device's owned
                # microbatches; every stage holds an equal share, so the
                # global mean is the pp-pmean of local means
                losses = jax.vmap(tfm.causal_lm_loss)(logits, tokens_ref)
                loss = jax.lax.pmean(jnp.mean(losses), axis)
                if data_axis is not None:  # DP: mean over batch shards
                    loss = jax.lax.pmean(loss, data_axis)
                return loss

            x_spec = (
                P(axis, data_axis, None, None) if data_axis else P(axis)
            )
            tok_spec = P(axis, data_axis, None) if data_axis else P(axis)
            shard = jax.shard_map(
                body,
                mesh=self.mesh,
                in_specs=(
                    jax.tree.map(lambda _: P(axis), params["stages"]),
                    x_spec,
                    tok_spec,
                ),
                out_specs=P(),
            )
            return shard(params["stages"], x, tokens_micro)

        def step_fn(params, opt_state, tokens_micro):
            loss, grads = jax.value_and_grad(loss_from)(params, tokens_micro)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))
        self._loss = jax.jit(loss_from)

        # MFU wiring (VERDICT r3 weak #4): 6ND over the matmul-participating
        # params — the full stage stack (the stacked leading axis sums all
        # layers) + head; the embedding gather is not matmul work.  GPipe's
        # fill/drain bubble is NOT credited: MFU counts model FLOPs, so the
        # bubble shows up as lower MFU, which is the honest accounting.
        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        self.n_matmul_params = sum(
            int(np.prod(leaf.shape))
            for leaf in jax.tree.leaves(self.stage_params)
        ) + int(np.prod(self.head.shape)) + sum(
            int(np.prod(leaf.shape)) for leaf in jax.tree.leaves(self.norm)
        )
        self.step_count = 0

    def _params(self):
        return {
            "stages": self.stage_params,
            "embed": self.embed,
            "head": self.head,
            "norm": self.norm,
        }

    def _micro(self, tokens: np.ndarray) -> np.ndarray:
        tokens = np.asarray(tokens)
        if tokens.shape[0] % self.n_micro:
            raise ValueError(
                f"batch {tokens.shape[0]} % n_micro {self.n_micro} != 0"
            )
        return tokens.reshape(
            self.n_micro, tokens.shape[0] // self.n_micro, tokens.shape[1]
        ).astype(np.int32)

    def step(self, tokens: np.ndarray) -> float:
        """tokens [B, S] -> loss; B must split into n_micro microbatches."""
        micro = self._micro(tokens)
        params, self.opt_state, loss = self._step(
            self._params(), self.opt_state, jnp.asarray(micro)
        )
        self.stage_params = params["stages"]
        self.embed = params["embed"]
        self.head = params["head"]
        self.norm = params["norm"]
        loss_f = float(loss)
        self.step_count += 1
        tokens = np.asarray(tokens)
        self.dashboard.flops_per_example = (
            6.0 * self.n_matmul_params * tokens.shape[1]
        )
        self.dashboard.record(
            self.step_count, loss_f, examples=int(tokens.shape[0])
        )
        return loss_f

    def loss(self, tokens: np.ndarray) -> float:
        return float(self._loss(self._params(), jnp.asarray(self._micro(tokens))))
