"""Sequence parallelism COMPOSED with TP + FSDP-state in one GSPMD program.

``SpLMTrainer`` (parallel/sp_lm.py) proves ring-attention AD with params
replicated per device — fine for the mechanism, impossible for an 8B model
on 16 GB chips (fp32 params alone are 32 GB).  This module is the at-scale
composition VERDICT r4 #5 asked for: one jit-compiled train step where

- the SEQUENCE is sharded over the ``sp`` mesh axis (ring attention —
  exact, O(S/n) activations per device),
- the WEIGHTS are tensor-parallel over the ``model`` axis (Megatron-style
  column/row pairing via ``parallel/tp.py``'s GSPMD rules),
- the OPTIMIZER MOMENTS are additionally sharded over ``sp``
  (``fsdp="state"`` — the knob whose saving survives the layer scan, same
  as the dense 8B recipe), and
- ``cfg.scan_blocks`` + ``cfg.remat`` + a per-shard chunked fused-head
  loss bound activation memory.

The architectural trick is ``ops.ring_attention_spmd``: ring attention in
a PARTIAL ``jax.shard_map`` (``axis_names={'sp'}``) — only the ring's axis
goes manual, so the flax trunk stays an ordinary GSPMD program and the TP
shardings on every matmul keep flowing through XLA untouched.  Contrast
``SpLMTrainer``, which wraps the WHOLE trunk in a shard_map and therefore
cannot express per-weight partitioning without manual collectives.

The loss runs in a second partial shard_map: each device computes its
local sequence chunk's fused-head NLL (rematerialized chunks, vocab dim
still free for the ``model`` axis) and a single ``psum`` over ``sp``
produces the global mean — same shift semantics as ``causal_lm_loss``.

Reference analogue: the long-context/sequence-parallel training the
reference's NCCL/MPI backend composes with its tensor parallelism
(SURVEY.md §5 long-context row [U]); here the composition is one XLA
program over a (sp, model) mesh with ICI collectives.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parameter_server_tpu.models import transformer as tfm
from parameter_server_tpu.utils import metrics as metrics_lib

SP_AXIS = "sp"
MODEL_AXIS = "model"


def sp_chunked_causal_loss(
    hidden: jax.Array,
    head_kernel: jax.Array,
    targets: jax.Array,
    mask: jax.Array,
    *,
    mesh: Mesh,
    chunk: int,
) -> jax.Array:
    """Fused-head causal NLL on a sequence sharded over ``sp``.

    ``chunked_causal_lm_loss`` slices the GLOBAL sequence axis, which under
    an ``sp`` sharding would make every chunk a cross-device reshard; here
    each device chunks its LOCAL shard instead (partial shard_map, manual
    only over ``sp``), keeps one rematerialized ``[B, chunk, V]`` slab live
    at a time — the vocab dim stays free for the ``model`` TP sharding —
    and a ``psum`` over ``sp`` delivers the global masked mean.

    ``targets``/``mask`` carry the caller's shift convention (targets[t] =
    tokens[t+1], mask kills the last global position), so the result equals
    ``causal_lm_loss(hidden @ head_kernel, tokens)`` up to summation order.
    """

    def local(h_l, t_l, m_l, w):
        B, s_local, _d = h_l.shape
        c = min(chunk, s_local)
        pad = (-s_local) % c
        if pad:
            h_l = jnp.pad(h_l, ((0, 0), (0, pad), (0, 0)))
            t_l = jnp.pad(t_l, ((0, 0), (0, pad)))
            m_l = jnp.pad(m_l, ((0, 0), (0, pad)))
        n_chunks = (s_local + pad) // c
        xs = h_l.reshape(B, n_chunks, c, -1).transpose(1, 0, 2, 3)
        tg = t_l.reshape(B, n_chunks, c).transpose(1, 0, 2)
        mk = m_l.reshape(B, n_chunks, c).transpose(1, 0, 2)

        @jax.checkpoint
        def chunk_nll(xc, tc, mc):
            logits = jnp.einsum(
                "bcd,dv->bcv", xc, w, preferred_element_type=jnp.float32
            )
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
            return jnp.sum(nll * mc)

        def body(acc, args):
            return acc + chunk_nll(*args), None

        # carry starts device-varying over sp (each shard accumulates its
        # own NLL): mark it so, or the scan rejects the carry type (VMA)
        acc0 = jax.lax.pcast(jnp.float32(0.0), (SP_AXIS,), to="varying")
        total, _ = jax.lax.scan(body, acc0, (xs, tg, mk))
        loss_sum = jax.lax.psum(total, SP_AXIS)
        count = jax.lax.psum(jnp.sum(m_l), SP_AXIS)
        return loss_sum / jnp.maximum(count, 1.0)

    seq3 = P(None, SP_AXIS, None)
    seq2 = P(None, SP_AXIS)
    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(seq3, seq2, seq2, P()),
        out_specs=P(),
        axis_names=frozenset({SP_AXIS}),
    )(hidden, targets, mask, head_kernel)


def make_sp_step(cfg_run: tfm.TransformerConfig, mesh: Mesh, tx, chunk: int):
    """Build the jitted composed train step (no params materialized).

    ``cfg_run`` must already carry ``attn_impl="ring_spmd"`` + the mesh;
    shardings ride on the input arrays (or ShapeDtypeStructs — the 8B
    feasibility path compiles this exact step from shapes alone, the same
    AOT technique as ``feasibility.compile_body_step``).
    """
    import optax

    trunk = tfm.TransformerTrunk(cfg_run)

    def loss_fn(params, tokens, targets, mask):
        x = jnp.take(params["embedding"], tokens, axis=0)
        trunk_params = {
            k: v
            for k, v in params.items()
            if k not in ("embedding", "lm_head")
        }
        hidden = trunk.apply({"params": trunk_params}, x)
        return sp_chunked_causal_loss(
            hidden, params["lm_head"]["kernel"], targets, mask,
            mesh=mesh, chunk=chunk,
        )

    def step_fn(params, opt_state, tokens, targets, mask):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, targets, mask
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step_fn, donate_argnums=(0, 1)), jax.jit(loss_fn)


class SpTpLMTrainer:
    """Causal LM: sequence over ``sp`` x weights over ``model`` x
    moments-FSDP over ``sp`` — the composed long-context trainer."""

    def __init__(
        self,
        cfg: tfm.TransformerConfig,
        mesh: Mesh,
        *,
        learning_rate: float = 1e-3,
        seed: int = 0,
        fsdp: str = "state",
        loss_chunk: int = 512,
        dashboard: Optional[metrics_lib.Dashboard] = None,
    ) -> None:
        import optax

        from parameter_server_tpu.parallel.tp import (
            transformer_param_shardings,
        )

        for axis in (SP_AXIS, MODEL_AXIS):
            if axis not in mesh.axis_names:
                raise ValueError(
                    f"mesh must carry a {axis!r} axis, got {mesh.axis_names}"
                )
        if not cfg.causal:
            raise ValueError("SpTpLMTrainer is a causal-LM trainer")
        if cfg.tie_embeddings:
            raise ValueError(
                "SpTpLMTrainer needs untied embeddings (fused head loss)"
            )
        if fsdp not in ("none", "state"):
            raise ValueError(f"fsdp must be none|state, got {fsdp!r}")
        self.mesh = mesh
        self.n_shards = mesh.shape[SP_AXIS]
        #: runtime twin: ring attention via the partial shard_map
        self.cfg = dataclasses.replace(
            cfg, attn_impl="ring_spmd", sp_axis=SP_AXIS, spmd_mesh=mesh
        )
        cfg_dense = dataclasses.replace(cfg, attn_impl="dense")
        self.tx = optax.adamw(learning_rate)
        self.loss_chunk = int(loss_chunk)

        # init with the dense twin (identical param tree), then place per
        # the TP rules; moments optionally further sharded over sp
        model_init = tfm.Transformer(cfg_dense)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = model_init.init(jax.random.PRNGKey(seed), tokens0)["params"]
        p_shard = transformer_param_shardings(params, mesh)
        self.params = jax.tree.map(jax.device_put, params, p_shard)
        opt_state = self.tx.init(self.params)  # inherits param shardings
        if fsdp == "state":
            import optax as _optax

            s_shard = transformer_param_shardings(
                params, mesh, fsdp=True, fsdp_axis=SP_AXIS
            )
            opt_state = _optax.tree_map_params(
                self.tx,
                lambda leaf, sh: jax.device_put(leaf, sh),
                opt_state,
                s_shard,
            )
        self.opt_state = opt_state

        self._step, self._loss = make_sp_step(
            self.cfg, mesh, self.tx, self.loss_chunk
        )
        self._seq_sharding = NamedSharding(mesh, P(None, SP_AXIS))

        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        self.n_matmul_params = metrics_lib.lm_matmul_params(
            self.params, frozenset({"pos_embedding", "embedding"})
        )
        self.step_count = 0

    def _place(self, tokens: np.ndarray):
        """Next-token shift + mask, seq-sharded over ``sp`` (GLOBAL views:
        GSPMD owns the distribution, unlike SpLMTrainer's local shards)."""
        tokens = np.asarray(tokens, np.int32)
        B, S = tokens.shape
        if S % self.n_shards:
            raise ValueError(f"seq {S} % sp shards {self.n_shards} != 0")
        if self.cfg.positional == "learned" and S > self.cfg.max_seq:
            raise ValueError(
                f"sequence {S} exceeds learned-positional max_seq "
                f"{self.cfg.max_seq}"
            )
        targets = np.concatenate(
            [tokens[:, 1:], np.zeros((B, 1), np.int32)], axis=1
        )
        mask = np.broadcast_to(
            (np.arange(S) < S - 1).astype(np.float32), (B, S)
        )
        put = lambda a: jax.device_put(a, self._seq_sharding)  # noqa: E731
        return put(tokens), put(targets), put(np.ascontiguousarray(mask))

    def step(self, tokens: np.ndarray) -> float:
        tok, tgt, msk = self._place(tokens)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, tok, tgt, msk
        )
        loss_f = float(loss)
        self.step_count += 1
        self.dashboard.flops_per_example = (
            6.0 * self.n_matmul_params * tokens.shape[1]
        )
        self.dashboard.record(
            self.step_count, loss_f, examples=int(tokens.shape[0])
        )
        return loss_f

    def loss(self, tokens: np.ndarray) -> float:
        tok, tgt, msk = self._place(tokens)
        return float(self._loss(self.params, tok, tgt, msk))
