"""Step a largest-that-fits sharded DLRM table for real (VERDICT r4 #3).

The 2^30-row claim has two halves: the AOT memory proof
(``feasibility.dlrm_feasibility`` — never materialized) and THIS module,
which actually allocates a multi-gigabyte row-sharded table on a mesh and
drives real train steps through ``SpmdDLRMTrainer`` — gather unique rows,
MLP fwd/bwd, row-wise optimizer, scatter back — recording init/step wall
times and the touched-rows traffic model.

Run out of process (the virtual topology must be fixed before jax
initializes)::

    python -m parameter_server_tpu.parallel.dlrm_scale \
        --rows-log2 28 --dim 16 --mesh 1,8 --batch 8192 --steps 4

At the default shape the table is 16 GiB value+state (2 GiB/device on the
8-dev mesh) — the CPU-mesh stand-in for a v5e-16's 2^30 x dim-16 table at
the same bytes-per-device ratio class.  Per-step memory stays O(batch):
the step touches only the bucketed unique rows, never the table.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows-log2", type=int, default=28)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--mesh", default="1,8",
                   help="data,model shape; product = virtual device count")
    p.add_argument("--batch", type=int, default=8192)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--min-bucket", type=int, default=1 << 14)
    p.add_argument("--table-init", default="zeros",
                   choices=["zeros", "normal"],
                   help="zeros = memset-speed bring-up (default here: at "
                   "tens of GB the gaussian draw dominates wall time; the "
                   "layout and step are identical)")
    args = p.parse_args(argv)
    mesh_shape = tuple(int(x) for x in args.mesh.split(","))
    n_dev = 1
    for s in mesh_shape:
        n_dev *= s

    from parameter_server_tpu.utils.platform import force_cpu

    force_cpu(n_devices=n_dev)

    import jax
    import numpy as np

    from parameter_server_tpu.config import OptimizerConfig, TableConfig
    from parameter_server_tpu.data.synthetic import SyntheticDLRM
    from parameter_server_tpu.models.dlrm import SpmdDLRMTrainer
    from parameter_server_tpu.parallel import mesh as mesh_lib

    rows = 1 << args.rows_log2
    cfg = TableConfig(
        name="emb", rows=rows, dim=args.dim,
        optimizer=OptimizerConfig(kind="adagrad", learning_rate=0.05),
    )
    mesh = mesh_lib.make_mesh(mesh_shape)
    t0 = time.perf_counter()
    trainer = SpmdDLRMTrainer(
        cfg, mesh, learning_rate=0.01, min_bucket=args.min_bucket,
        table_init=args.table_init,
    )
    jax.block_until_ready(trainer.emb_value)
    init_s = time.perf_counter() - t0

    # shard accounting straight from the arrays, not arithmetic
    shard_bytes = max(
        s.data.nbytes for s in trainer.emb_value.addressable_shards
    )
    n_state = len(trainer.emb_state)

    from parameter_server_tpu.utils.keys import localize_to_slots

    stream = SyntheticDLRM(key_space=rows, batch_size=args.batch, seed=3)
    losses, step_ms, uniq, slot_counts = [], [], [], []
    for i in range(args.steps + 1):  # +1 warmup/compile step
        keys, dense, labels = stream.next_batch()
        t0 = time.perf_counter()
        loss = trainer.step(keys, dense, labels)
        dt = (time.perf_counter() - t0) * 1e3
        if i:  # step 0 pays compile
            step_ms.append(dt)
            losses.append(loss)
            uniq.append(len(np.unique(keys)))
            # the step gathers/scatters the BUCKETED slot array (padded to
            # a power of two), not just the unique keys — count what the
            # device actually touches
            slots, _inv, _n = localize_to_slots(
                keys, trainer.localizer, min_bucket=trainer.min_bucket
            )
            slot_counts.append(slots.shape[0])
        else:
            compile_ms = dt
    mean_uniq = float(np.mean(uniq))
    mean_slots = float(np.mean(slot_counts))
    # touched-rows traffic: (value + n_state state arrays) x (read + write)
    bytes_per_step = mean_slots * args.dim * 4 * (1 + n_state) * 2
    out = {
        "rows_log2": args.rows_log2,
        "dim": args.dim,
        "mesh": dict(mesh.shape),
        "batch": args.batch,
        "table_gib": round(
            (1 + n_state) * trainer.total_rows * args.dim * 4 / 2**30, 2
        ),
        "shard_gib_per_device": round(
            (1 + n_state) * shard_bytes / 2**30, 3
        ),
        "init_s": round(init_s, 1),
        "compile_ms": round(compile_ms, 0),
        "step_ms_median": round(float(np.median(step_ms)), 1),
        "step_ms": [round(x, 1) for x in step_ms],
        "unique_rows_per_step": round(mean_uniq, 0),
        "gathered_slots_per_step": round(mean_slots, 0),
        "touched_mb_per_step": round(bytes_per_step / 1e6, 2),
        "losses": [round(x, 4) for x in losses],
        "backend": jax.default_backend(),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
