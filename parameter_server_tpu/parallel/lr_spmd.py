"""SPMD sparse-LR training over a (data, model) mesh — GSPMD formulation.

The multi-chip version of :func:`models.linear.dense_fused_impl`: identical
math, with sharding annotations instead of message passing.

- table value/state: row-sharded over ``model`` (the reference's server
  key-range partition, ``src/system/assigner.h`` [U]);
- batch (slots, labels): sharded over ``data`` (the reference's worker data
  shards, ``src/learner/workload_pool.h`` [U]);
- XLA inserts the cross-axis collectives: gathering data-sharded positions
  from model-sharded rows, and reducing data-sharded gradient contributions
  into the model-sharded gradient buffer — the latter IS the north star's
  "psum over ICI before Push" (NCCL pre-reduction replacement); no NCCL, no
  explicit Van traffic on the data plane.

Semantics match the single-device dense path exactly (same floating-point
reduction order is NOT guaranteed across mesh shapes, but convergence
trajectories agree to float tolerance — tested on the 8-device CPU mesh).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.kv.optim import (
    ServerOptimizer,
    make_optimizer,
    require_dense_apply,
)
from parameter_server_tpu.models import linear
from parameter_server_tpu.parallel import mesh as mesh_lib
from parameter_server_tpu.utils.keys import HashLocalizer


class ShardedLRState(NamedTuple):
    value: jax.Array  # [total_rows, 1] sharded P(model, None)
    state: Dict[str, jax.Array]
    bias: jax.Array  # [1, 1] replicated
    bias_state: Dict[str, jax.Array]


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


class SpmdLRTrainer:
    """Sparse LR over a mesh: dense-apply step with GSPMD shardings."""

    def __init__(self, table_cfg: TableConfig, mesh: Mesh, *, seed: int = 0):
        require_dense_apply(table_cfg.optimizer)
        self.cfg = table_cfg
        self.mesh = mesh
        self.optimizer: ServerOptimizer = make_optimizer(table_cfg.optimizer)
        self.localizer = HashLocalizer(table_cfg.rows, seed=seed)
        n_model = mesh.shape[mesh_lib.MODEL_AXIS]
        #: trash row is id == cfg.rows; extra rows pad to an even shard split.
        self.total_rows = _round_up(table_cfg.rows + 1, n_model)

        t_shard = mesh_lib.table_sharding(mesh)
        r_shard = mesh_lib.replicated(mesh)
        state_shardings = ShardedLRState(
            value=t_shard,
            state={k: t_shard for k in self.optimizer.state_shapes()},
            bias=r_shard,
            bias_state={k: r_shard for k in self.optimizer.state_shapes()},
        )

        # Initialize INSIDE jit with out_shardings (not host device_put):
        # each shard materializes directly on its device — no host round-trip
        # for the table, and it works when the mesh spans multiple processes
        # (a pod), where no single process could device_put the global array.
        def init_fn() -> ShardedLRState:
            return ShardedLRState(
                value=jnp.zeros((self.total_rows, 1), jnp.float32),
                state={
                    k: jnp.full((self.total_rows, 1), fill, jnp.float32)
                    for k, fill in self.optimizer.state_shapes().items()
                },
                bias=jnp.zeros((1, 1), jnp.float32),
                bias_state={
                    k: jnp.zeros((1, 1), jnp.float32)
                    for k in self.optimizer.state_shapes()
                },
            )

        with mesh:
            self.state = jax.jit(init_fn, out_shardings=state_shardings)()
        batch2 = mesh_lib.batch_sharding(mesh, 2)
        batch1 = mesh_lib.batch_sharding(mesh, 1)

        trash_row = table_cfg.rows  # NOT -1: rows pad beyond rows+1 (shard split)

        def step_fn(state: ShardedLRState, slots_pos, labels):
            v, s, b, bs, loss = linear.dense_fused_impl(
                state.value,
                state.state,
                state.bias,
                state.bias_state,
                slots_pos,
                labels,
                self.optimizer,
                trash_row,
            )
            return ShardedLRState(v, s, b, bs), loss

        self._step = jax.jit(
            step_fn,
            in_shardings=(state_shardings, batch2, batch1),
            out_shardings=(state_shardings, r_shard),
            donate_argnums=(0,),
        )
        self._batch2, self._batch1 = batch2, batch1

    def place_batch(
        self,
        keys: np.ndarray,
        labels: np.ndarray,
        *,
        global_batch: Optional[int] = None,
    ):
        """Hash keys to slots on host and shard the batch over the mesh.

        ``keys``/``labels`` are THIS process's slice of the global batch
        (the whole batch when single-process): each pod host hashes and
        stages only the rows its own devices consume — the WorkloadPool
        data-shard assignment, with no cross-host batch scatter.

        ``global_batch``: total rows across all processes.  Defaults to
        ``local * process_count`` (an even data-axis split over processes);
        pass it explicitly when the data axis does not cross the process
        boundary (each process then feeds the full batch).
        """
        from parameter_server_tpu.parallel import distributed

        slots_pos = np.asarray(self.localizer.assign(keys))
        labels = np.asarray(labels)
        gb = global_batch or labels.shape[0] * jax.process_count()
        return (
            distributed.host_local_batch(
                self._batch2, slots_pos, (gb, slots_pos.shape[1])
            ),
            distributed.host_local_batch(self._batch1, labels, (gb,)),
        )

    def step(
        self,
        keys: np.ndarray,
        labels: np.ndarray,
        *,
        global_batch: Optional[int] = None,
    ) -> float:
        slots, labels_d = self.place_batch(
            keys, labels, global_batch=global_batch
        )
        self.state, loss = self._step(self.state, slots, labels_d)
        return float(loss)

    def step_placed(self, slots, labels_d) -> jax.Array:
        """Async step on pre-placed batches (no host sync)."""
        self.state, loss = self._step(self.state, slots, labels_d)
        return loss
