"""Sequence-parallel causal-LM trainer: ring attention INSIDE the model.

Long-context training as a first-class trainer, not just a library op
(SURVEY §5 long-context row): the sequence axis is sharded over an ``sp``
mesh axis, every position-local sublayer (norms, MLP, rotary, embedding
gather, head matmul, loss) runs on the local shard untouched, and attention
is the exact ring algorithm (``ops/ring_attention.py``) — K/V blocks rotate
over ICI ppermute while each device accumulates the online softmax for its
Q shard.  Per-device activation memory is O(seq/n) blockwise (asserted at
8k tokens in tests/test_seq_parallel.py); this module makes a transformer
TRAIN in that regime end to end.

The whole step is one jit program: shard_map over ``sp`` (inputs sharded on
the sequence axis, params replicated — their gradients psum over ``sp`` by
the shard_map transpose rule), reverse-AD through the ring, adamw update.
The param tree is identical to the dense-attention model, so checkpoints
move freely between the two.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from parameter_server_tpu.models import transformer as tfm
from parameter_server_tpu.utils import metrics as metrics_lib

SP_AXIS = "sp"


class SpLMTrainer:
    """Causal LM trained with the sequence sharded over ``sp``."""

    def __init__(
        self,
        cfg: tfm.TransformerConfig,
        mesh: Mesh,
        *,
        learning_rate: float = 1e-3,
        seed: int = 0,
        dashboard: Optional[metrics_lib.Dashboard] = None,
        attn: str = "ring",
    ) -> None:
        """``attn``: "ring" (K/V rotate; O(S/n) memory everywhere, the
        long-context default) or "ulysses" (all-to-all head redistribution;
        two collectives per attention, full-sequence scores per head subset
        — preferable when heads >> devices and S^2/n_heads fits)."""
        import optax

        if attn not in ("ring", "ulysses"):
            raise ValueError(f"attn must be ring|ulysses, got {attn!r}")
        if SP_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh must carry a {SP_AXIS!r} axis, got {mesh.axis_names}"
            )
        if not cfg.causal:
            raise ValueError("SpLMTrainer is a causal-LM trainer")
        if cfg.tie_embeddings:
            raise ValueError(
                "SpLMTrainer needs untied embeddings (the head matmul runs "
                "on sequence shards via params['lm_head'])"
            )
        self.mesh = mesh
        self.n_shards = mesh.shape[SP_AXIS]
        #: DP x SP composition: a "data" axis beside "sp" shards the batch
        #: rows; the loss mean over both axes transposes to the usual DP
        #: gradient psum on top of the SP one.
        from parameter_server_tpu.parallel.mesh import DATA_AXIS

        self._data_axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
        #: the SP twin of the caller's config (same param tree)
        self.cfg = dataclasses.replace(cfg, attn_impl=attn, sp_axis=SP_AXIS)
        cfg_dense = dataclasses.replace(cfg, attn_impl="dense")
        self.tx = optax.adamw(learning_rate)

        # init OUTSIDE shard_map with the dense twin (identical params)
        model_init = tfm.Transformer(cfg_dense)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = model_init.init(jax.random.PRNGKey(seed), tokens0)["params"]
        repl = NamedSharding(mesh, P())
        self.params = jax.device_put(params, repl)
        self.opt_state = jax.device_put(self.tx.init(self.params), repl)

        trunk = tfm.TransformerTrunk(self.cfg)
        tx = self.tx

        def local_loss(params, tok_l, tgt_l, msk_l):
            # inside shard_map: tok_l [B, S/n] — this device's seq shard
            idx = jax.lax.axis_index(SP_AXIS)
            B, s_local = tok_l.shape
            positions = jnp.broadcast_to(
                idx * s_local + jnp.arange(s_local)[None], (B, s_local)
            )
            x = jnp.take(params["embedding"], tok_l, axis=0)
            trunk_params = {
                k: v
                for k, v in params.items()
                if k not in ("embedding", "lm_head")
            }
            hidden = trunk.apply(
                {"params": trunk_params}, x, positions=positions
            )
            logits = jnp.einsum(
                "bsd,dv->bsv", hidden, params["lm_head"]["kernel"],
                preferred_element_type=jnp.float32,
            )
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, tgt_l[..., None], axis=-1)[..., 0]
            axes = (
                (SP_AXIS,)
                if self._data_axis is None
                else (self._data_axis, SP_AXIS)
            )
            loss_sum = jax.lax.psum(jnp.sum(nll * msk_l), axes)
            count = jax.lax.psum(jnp.sum(msk_l), axes)
            return loss_sum / jnp.maximum(count, 1.0)

        seq_spec = P(self._data_axis, SP_AXIS)

        def loss_from(params, tokens, targets, mask):
            shard = jax.shard_map(
                local_loss,
                mesh=mesh,
                in_specs=(P(), seq_spec, seq_spec, seq_spec),
                out_specs=P(),
            )
            return shard(params, tokens, targets, mask)

        def step_fn(params, opt_state, tokens, targets, mask):
            loss, grads = jax.value_and_grad(loss_from)(
                params, tokens, targets, mask
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))
        self._loss = jax.jit(loss_from)
        self._seq_sharding = NamedSharding(mesh, seq_spec)

        # MFU wiring: 6ND over matmul-participating params (gathers out)
        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        self.n_matmul_params = metrics_lib.lm_matmul_params(
            self.params, frozenset({"pos_embedding", "embedding"})
        )
        self.step_count = 0

    def _place(self, tokens: np.ndarray):
        """Host-side next-token shift + mask, sharded on the seq axis."""
        tokens = np.asarray(tokens, np.int32)
        B, S = tokens.shape
        if S % self.n_shards:
            raise ValueError(f"seq {S} % sp shards {self.n_shards} != 0")
        # the dense path raises on S > max_seq inside _apply_body, but the
        # positions-given (SP) path cannot — jnp.take silently clips, which
        # would train learned positionals on corrupted rows (ADVICE r4).
        # The trainer knows the GLOBAL sequence here; validate it.
        if self.cfg.positional == "learned" and S > self.cfg.max_seq:
            raise ValueError(
                f"global sequence {S} exceeds learned-positional "
                f"max_seq {self.cfg.max_seq}"
            )
        targets = np.concatenate(
            [tokens[:, 1:], np.zeros((B, 1), np.int32)], axis=1
        )
        mask = np.broadcast_to(
            (np.arange(S) < S - 1).astype(np.float32), (B, S)
        )
        put = lambda a: jax.device_put(a, self._seq_sharding)  # noqa: E731
        return put(tokens), put(targets), put(np.ascontiguousarray(mask))

    def step(self, tokens: np.ndarray) -> float:
        tok, tgt, msk = self._place(tokens)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, tok, tgt, msk
        )
        loss_f = float(loss)
        self.step_count += 1
        self.dashboard.flops_per_example = (
            6.0 * self.n_matmul_params * tokens.shape[1]
        )
        self.dashboard.record(
            self.step_count, loss_f, examples=int(tokens.shape[0])
        )
        return loss_f

    def loss(self, tokens: np.ndarray) -> float:
        tok, tgt, msk = self._place(tokens)
        return float(self._loss(self.params, tok, tgt, msk))
