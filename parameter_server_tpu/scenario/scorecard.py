"""SLO-breach-minutes scorecard + automated incident report (ISSUE 19).

:func:`build_scorecard` folds a finished
:class:`~parameter_server_tpu.scenario.runner.ScenarioRunner` into one
machine-readable dict: the per-node x per-SLO breach timeline integrated
into **SLO-breach-minutes** (off the engine's edge-triggered interval
accounting, so out-of-order frames and clock offsets are already
handled), plus the ground-truth totals the availability number alone
hides — bytes migrated, requests shed, fence rejects, frames the
partitions ate.  Serialize with :func:`scorecard_json` — key-sorted,
rounded — so two same-seed runs emit byte-identical JSON (the
``bench.py --wargame`` reproducibility gate diffs exactly that string).

:func:`render_report` is the human half: a worked incident report that
finds the WORST breach window and auto-attaches (a) the flight-recorder
postmortem chain around it (``tools/postmortem.py`` — the
``scenario.inject`` anomaly that preceded the breach anchors the chain)
and (b) the critical-path attribution of the sampled requests inside it
(``tools/critpath.py`` — which plane ate the latency budget).
"""

from __future__ import annotations

import json
import pathlib
import sys
import tempfile
from typing import Dict, List, Optional

from parameter_server_tpu.core import flightrec

_TOOLS = pathlib.Path(__file__).resolve().parents[2] / "tools"


def _tool(name: str):
    """Import a repo tool module (tools/ is not a package); None if gone."""
    if str(_TOOLS) not in sys.path:
        sys.path.insert(0, str(_TOOLS))
    try:
        return __import__(name)
    except Exception:
        return None


def build_scorecard(runner) -> dict:
    """Machine-readable scorecard for one finished run."""
    eng = runner.engine
    end = runner.scenario.duration_s
    timeline = eng.breach_timeline(now=end)
    by_slo: Dict[str, float] = {}
    by_node: Dict[str, float] = {}
    for iv in timeline:
        dur_min = (iv["t1"] - iv["t0"]) / 60.0
        by_slo[iv["slo"]] = by_slo.get(iv["slo"], 0.0) + dur_min
        by_node[iv["node"]] = by_node.get(iv["node"], 0.0) + dur_min
    totals = {"served": 0, "shed": 0, "fence_rejects": 0, "restarts": 0}
    for sim in runner.nodes.values():
        for k in totals:
            totals[k] += int(getattr(sim, k))
    for k, v in runner.retired_totals.items():
        totals[k] = totals.get(k, 0) + int(v)
    chaos_counters = (
        runner.chaos.counters() if runner.chaos is not None else {}
    )
    return {
        "scenario": {
            "name": runner.scenario.name,
            "seed": runner.scenario.seed,
            "nodes": runner.scenario.nodes,
            "duration_s": round(end, 3),
            "tick_s": runner.scenario.tick_s,
            "schedule_events": len(runner.schedule),
        },
        "fleet": {
            "start": runner.scenario.nodes,
            "end": len(runner.nodes),
        },
        "slo": {
            "breach_minutes": round(eng.breach_seconds(now=end) / 60.0, 4),
            "by_slo": {
                k: round(v, 4) for k, v in sorted(by_slo.items())
            },
            "by_node": {
                k: round(v, 4) for k, v in sorted(by_node.items())
            },
            "timeline": [
                {
                    "slo": iv["slo"],
                    "node": iv["node"],
                    "t0": round(iv["t0"], 3),
                    "t1": round(iv["t1"], 3),
                    **({"open": True} if iv.get("open") else {}),
                }
                for iv in timeline
            ],
        },
        "totals": {
            **{k: int(v) for k, v in sorted(totals.items())},
            "bytes_migrated": int(runner.bytes_migrated),
            "partition_dropped_frames": int(
                chaos_counters.get("chaos_partition_drops", 0)
                or chaos_counters.get("partition_drops", 0)
            ),
        },
        "autoscaler": {
            "enabled": runner.autoscaler is not None,
            "actions": [
                {
                    "t": round(a["t"], 3),
                    "kind": a["kind"],
                    **({"node": a["node"]} if a.get("node") else {}),
                }
                for a in runner.actions
            ],
        },
        "telemetry": {
            "frames": runner.agg.frames,
            "dedup_drops": sum(runner.agg._drops.values()),
            "ring_cap_per_node": (
                next(iter(runner.agg._rings.values())).maxlen
                if runner.agg._rings else runner.agg.window
            ),
        },
    }


def scorecard_json(card: dict) -> str:
    """Canonical serialization — the bit-reproducibility surface."""
    return json.dumps(card, sort_keys=True, separators=(",", ":"))


def worst_breach_window(card: dict) -> Optional[dict]:
    """The single longest breach interval (the incident to explain)."""
    timeline = card["slo"]["timeline"]
    if not timeline:
        return None
    return max(timeline, key=lambda iv: (iv["t1"] - iv["t0"], -iv["t0"]))


def _wall_window(runner, t0: float, t1: float):
    """Map a virtual-time window onto wall-monotonic bounds (with slack)."""
    ticks = sorted(runner.wall_of_tick)
    if not ticks:
        return None
    lo = max((t for t in ticks if t <= t0), default=ticks[0])
    hi = min((t for t in ticks if t >= t1), default=ticks[-1])
    slack = 0.05
    return (
        runner.wall_of_tick[lo] - slack,
        runner.wall_of_tick[hi] + slack,
    )


def render_report(runner, card: Optional[dict] = None) -> List[str]:
    """The human incident report for one finished run."""
    if card is None:
        card = build_scorecard(runner)
    sc = card["scenario"]
    lines = [
        f"== war game: {sc['name']} (seed {sc['seed']}) ==",
        f"fleet {card['fleet']['start']} -> {card['fleet']['end']} nodes, "
        f"{sc['duration_s']:.0f}s simulated, "
        f"{sc['schedule_events']} scheduled events",
        f"SLO-breach-minutes: {card['slo']['breach_minutes']:.2f}"
        + "".join(
            f"  [{k}: {v:.2f}]"
            for k, v in card["slo"]["by_slo"].items()
        ),
        f"totals: served={card['totals']['served']} "
        f"shed={card['totals']['shed']} "
        f"fence_rejects={card['totals']['fence_rejects']} "
        f"bytes_migrated={card['totals']['bytes_migrated']} "
        f"partition_dropped_frames="
        f"{card['totals']['partition_dropped_frames']}",
        f"autoscaler: "
        f"{'on' if card['autoscaler']['enabled'] else 'off'}, "
        f"{len(card['autoscaler']['actions'])} actions"
        + "".join(
            f"\n  t={a['t']:8.1f}s  {a['kind']:<10s} {a.get('node', '')}"
            for a in card["autoscaler"]["actions"][:12]
        ),
    ]
    worst = worst_breach_window(card)
    if worst is None:
        lines.append("no SLO breaches — nothing to explain")
        return lines
    lines.append(
        f"-- worst breach window: {worst['slo']} on {worst['node']} "
        f"t={worst['t0']:.1f}s..{worst['t1']:.1f}s "
        f"({(worst['t1'] - worst['t0']) / 60.0:.2f} breach-minutes) --"
    )
    # (a) flight-recorder postmortem chain around the window
    pm = _tool("postmortem")
    if pm is not None:
        try:
            with tempfile.TemporaryDirectory(prefix="wargame_pm_") as d:
                paths = flightrec.dump(d, reason="wargame-report")
                merged = pm.merge_bundles(paths)
                # drop the per-frame publish markers — at 200 publishers
                # they bury the injects/breaches the chain exists to show
                events = [
                    ev for ev in merged["events"]
                    if ev.get("kind") != "telemetry.publish"
                ]
                window = _wall_window(runner, worst["t0"], worst["t1"])
                if window is not None:
                    inside = [
                        ev for ev in events
                        if window[0] <= float(ev.get("t_mono_s") or 0.0)
                        <= window[1]
                    ]
                    if inside:
                        events = inside
                merged = dict(merged, events=events)
                lines.append("postmortem chain (worst breach window):")
                lines.extend("  " + ln for ln in pm.report(merged, last=20))
        except Exception as e:  # report must never fail the run
            lines.append(f"postmortem chain unavailable: {e}")
    else:
        lines.append("postmortem chain unavailable: tools/postmortem.py "
                     "not importable")
    # (b) critpath attribution of sampled requests inside the window
    cp = _tool("critpath")
    if cp is not None:
        sampled = [
            ev for ev in runner.trace_events
            if worst["t0"] <= ev["t_s"] <= worst["t1"] + 1.0
        ]
        if sampled:
            try:
                reqs = cp.requests(sampled)
                lines.append(
                    "critpath attribution (sampled requests in window):"
                )
                lines.extend("  " + ln for ln in cp.render(reqs, show=1))
            except Exception as e:
                lines.append(f"critpath attribution unavailable: {e}")
        else:
            lines.append("critpath attribution: no sampled requests in "
                         "the window")
    else:
        lines.append("critpath attribution unavailable: tools/critpath.py "
                     "not importable")
    return lines
