"""Declarative war-game scenarios compiled to absolute-time schedules.

A :class:`Scenario` is a pure, seeded spec: an initial fleet size, a list
of :class:`Phase` objects (each with a :class:`LoadCurve` shaping offered
load over the phase), and a list of :class:`Fault` injections (gray
failures, partitions, restart waves, scale events) at phase-relative
times.  :func:`compile_schedule` expands it into a flat, absolute-time
event list — every random choice (which node a cascade hits next, where a
flash crowd moves the hot set) is drawn from ``random.Random(seed)`` in a
fixed order, so the same spec + seed always compiles to the bit-identical
schedule.  The runner replays that schedule; it never draws randomness of
its own.

Load curves are *multipliers* on the scenario's base offered rate:

- ``flat``: constant ``base``;
- ``diurnal``: ``base * (1 + amplitude * sin(2*pi*t/period_s))`` clamped
  at >= 0 — the classic day/night swing;
- ``flash_crowd``: ``base``, stepping to ``base * peak`` over ``ramp_s``
  at ``at_s`` and holding for ``hold_s`` before ramping back.  With
  ``shift_hot_set`` the crowd also lands on a NEW Zipf hot set (the
  compile step draws the new hot nodes), which is what makes flash crowds
  dangerous: caches and shard placement tuned for the old hot set are
  suddenly wrong.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Tuple

from parameter_server_tpu.utils.slo import SloSpec

_CURVES = ("flat", "diurnal", "flash_crowd")
_FAULTS = (
    "slow_node", "partition", "restart_wave", "scale_up", "drain_down",
)


@dataclasses.dataclass(frozen=True)
class LoadCurve:
    """Offered-load multiplier over one phase's local time."""

    kind: str = "flat"
    base: float = 1.0
    #: diurnal swing as a fraction of ``base`` (0.5 => 0.5x..1.5x).
    amplitude: float = 0.5
    period_s: float = 600.0
    #: flash-crowd peak multiplier relative to ``base``.
    peak: float = 4.0
    #: flash-crowd start, seconds into the phase.
    at_s: float = 0.0
    ramp_s: float = 5.0
    hold_s: float = 30.0
    #: flash crowd lands on a new Zipf hot set (compile draws it).
    shift_hot_set: bool = False

    def __post_init__(self) -> None:
        if self.kind not in _CURVES:
            raise ValueError(
                f"LoadCurve kind must be one of {_CURVES}, got {self.kind!r}"
            )
        if self.base < 0:
            raise ValueError(f"base must be >= 0, got {self.base!r}")
        if self.kind == "diurnal" and self.period_s <= 0:
            raise ValueError("diurnal period_s must be > 0")
        if self.kind == "flash_crowd" and self.peak < 1.0:
            raise ValueError(f"flash peak must be >= 1, got {self.peak!r}")

    def multiplier(self, t: float) -> float:
        """Load multiplier at ``t`` seconds into the phase."""
        if self.kind == "flat":
            return self.base
        if self.kind == "diurnal":
            return max(
                0.0,
                self.base
                * (1.0 + self.amplitude * math.sin(2.0 * math.pi * t / self.period_s)),
            )
        # flash_crowd: trapezoid base -> base*peak -> base
        rel = t - self.at_s
        if rel < 0:
            return self.base
        ramp = max(self.ramp_s, 1e-9)
        if rel < self.ramp_s:
            return self.base * (1.0 + (self.peak - 1.0) * rel / ramp)
        if rel < self.ramp_s + self.hold_s:
            return self.base * self.peak
        rel -= self.ramp_s + self.hold_s
        if rel < self.ramp_s:
            return self.base * (self.peak - (self.peak - 1.0) * rel / ramp)
        return self.base


#: phase-level consistency-plane settings (ISSUE 20): mode names match
#: ``config.ConsistencyMode`` values; the runner applies them through the
#: ``consist_set`` control broadcast at the phase boundary.
_CONSIST_MODES = ("bsp", "ssp", "asp")


@dataclasses.dataclass(frozen=True)
class Phase:
    name: str
    duration_s: float
    load: LoadCurve = LoadCurve()
    #: flip the fleet's gated tables to this consistency mode at phase
    #: start (None = leave as-is).  Lets a war game answer "does BSP
    #: survive this straggler cascade, and what does SSP(4) buy us?"
    #: inside one scenario.
    consistency_mode: Optional[str] = None
    #: SSP staleness bound for the flip (ignored unless mode == "ssp").
    consistency_bound: int = 4

    def __post_init__(self) -> None:
        if self.duration_s <= 0:
            raise ValueError(
                f"phase {self.name!r}: duration_s must be > 0"
            )
        if (
            self.consistency_mode is not None
            and self.consistency_mode not in _CONSIST_MODES
        ):
            raise ValueError(
                f"phase {self.name!r}: consistency_mode must be one of "
                f"{_CONSIST_MODES}, got {self.consistency_mode!r}"
            )
        if self.consistency_bound < 0:
            raise ValueError(
                f"phase {self.name!r}: consistency_bound must be >= 0"
            )


@dataclasses.dataclass(frozen=True)
class Fault:
    """One injection, timed relative to the START of phase ``phase``.

    Kinds and their parameters:

    - ``slow_node``: gray failure — ``slow_ms`` extra service latency on
      ``node`` (or a seeded-random serving node) for ``duration_s``;
      ``cascade`` > 0 trips that many FURTHER nodes at ``cascade_gap_s``
      intervals (each for the same duration) — the correlated-failure
      shape that breaks naive per-node alerting;
    - ``partition``: ``node`` (or seeded-random) loses the control plane
      (symmetric node <-> scheduler partition) for ``duration_s``, then
      heals;
    - ``restart_wave``: ``count`` rolling same-id restarts, ``gap_s``
      apart, each node offline ``duration_s``;
    - ``scale_up`` / ``drain_down``: forced fleet-shape events (the
      autoscaler's own actions ride separately, off live telemetry).
    """

    kind: str
    phase: str
    at_s: float
    node: Optional[str] = None
    duration_s: float = 30.0
    slow_ms: float = 200.0
    cascade: int = 0
    cascade_gap_s: float = 10.0
    count: int = 1
    gap_s: float = 10.0

    def __post_init__(self) -> None:
        if self.kind not in _FAULTS:
            raise ValueError(
                f"Fault kind must be one of {_FAULTS}, got {self.kind!r}"
            )
        if self.at_s < 0:
            raise ValueError(f"at_s must be >= 0, got {self.at_s!r}")
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s must be > 0, got {self.duration_s!r}"
            )
        if self.cascade < 0 or self.count < 1:
            raise ValueError("cascade must be >= 0 and count >= 1")


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A complete seeded war game.  Compile with :func:`compile_schedule`."""

    name: str
    seed: int
    nodes: int
    phases: Tuple[Phase, ...]
    faults: Tuple[Fault, ...] = ()
    #: runner tick (virtual seconds per control sweep).
    tick_s: float = 1.0
    #: fleet-aggregate offered load at multiplier 1.0 (requests/s).
    base_qps: float = 1000.0
    #: per-node service capacity (requests/s).
    node_capacity_qps: float = 120.0

    def __post_init__(self) -> None:
        if self.nodes < 2:
            raise ValueError(f"nodes must be >= 2, got {self.nodes!r}")
        if not self.phases:
            raise ValueError("a scenario needs at least one phase")
        names = [p.name for p in self.phases]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate phase names: {names}")
        known = set(names)
        for f in self.faults:
            if f.phase not in known:
                raise ValueError(
                    f"fault {f.kind!r} names unknown phase {f.phase!r}"
                )
        if self.tick_s <= 0 or self.base_qps <= 0 or self.node_capacity_qps <= 0:
            raise ValueError("tick_s/base_qps/node_capacity_qps must be > 0")

    @property
    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    def phase_starts(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        t = 0.0
        for p in self.phases:
            out[p.name] = t
            t += p.duration_s
        return out

    def multiplier(self, t: float) -> float:
        """Offered-load multiplier at absolute scenario time ``t``."""
        t0 = 0.0
        for p in self.phases:
            if t < t0 + p.duration_s or p is self.phases[-1]:
                return p.load.multiplier(t - t0)
            t0 += p.duration_s
        return self.phases[-1].load.multiplier(t - t0)


def _server_ids(n: int) -> List[str]:
    return [f"S{i}" for i in range(n)]


def compile_schedule(scenario: Scenario) -> List[dict]:
    """Expand a :class:`Scenario` into the absolute-time event list.

    Every event is a plain dict ``{"t": float, "event": str, ...}``,
    sorted by ``(t, order drawn)``; random node choices come from ONE
    ``random.Random(scenario.seed)`` consumed in spec order, so the
    schedule is a pure function of the spec.  Event kinds: ``phase``,
    ``inject`` (fault=slow_node|partition|restart), ``heal``
    (fault=slow_node|partition), ``scale`` (action=scale_up|drain_down),
    ``hot_shift`` (the flash crowd's new hot node), ``end``.
    """
    rng = random.Random(scenario.seed)
    starts = scenario.phase_starts()
    servers = _server_ids(scenario.nodes)
    events: List[dict] = []
    # the initial hot node is itself a seeded draw: draw order is fixed
    # (hot set first, then phases in order, then faults in order)
    hot = rng.choice(servers)
    events.append({"t": 0.0, "event": "hot_shift", "node": hot})
    for p in scenario.phases:
        ev = {"t": starts[p.name], "event": "phase", "phase": p.name}
        if p.consistency_mode is not None:
            ev["consistency_mode"] = p.consistency_mode
            if p.consistency_mode == "ssp":
                ev["consistency_bound"] = p.consistency_bound
        events.append(ev)
        if p.load.kind == "flash_crowd" and p.load.shift_hot_set:
            hot = rng.choice([s for s in servers if s != hot])
            events.append({
                "t": starts[p.name] + p.load.at_s,
                "event": "hot_shift",
                "node": hot,
            })
    for f in scenario.faults:
        t0 = starts[f.phase] + f.at_s
        if f.kind == "slow_node":
            victims = [f.node or rng.choice(servers)]
            for _ in range(f.cascade):
                pool = [s for s in servers if s not in victims]
                if not pool:
                    break
                victims.append(rng.choice(pool))
            for i, node in enumerate(victims):
                t = t0 + i * f.cascade_gap_s
                events.append({
                    "t": t, "event": "inject", "fault": "slow_node",
                    "node": node, "slow_ms": f.slow_ms,
                })
                events.append({
                    "t": t + f.duration_s, "event": "heal",
                    "fault": "slow_node", "node": node,
                })
        elif f.kind == "partition":
            node = f.node or rng.choice(servers)
            events.append({
                "t": t0, "event": "inject", "fault": "partition",
                "node": node,
            })
            events.append({
                "t": t0 + f.duration_s, "event": "heal",
                "fault": "partition", "node": node,
            })
        elif f.kind == "restart_wave":
            pool = list(servers)
            for i in range(f.count):
                node = f.node if (f.node and i == 0) else rng.choice(pool)
                if node in pool and len(pool) > 1:
                    pool.remove(node)
                events.append({
                    "t": t0 + i * f.gap_s, "event": "inject",
                    "fault": "restart", "node": node,
                    "offline_s": f.duration_s,
                })
        else:  # scale_up / drain_down
            events.append({"t": t0, "event": "scale", "action": f.kind})
    events.append({"t": scenario.duration_s, "event": "end"})
    # stable sort preserves draw order among same-time events
    events.sort(key=lambda e: e["t"])
    for ev in events:
        ev["t"] = round(ev["t"], 6)
    return events


def wargame_plane_specs(
    *,
    serve_p99_ms: float = 150.0,
    shed_per_s: float = 1.0,
    window_s: float = 8.0,
) -> List[SloSpec]:
    """The war game's scoring SLOs over the sim fleet's telemetry.

    - ``serve-p99``: windowed p99 of each node's ``serve.lat`` digest
      (service + queueing, milliseconds) — the availability headline;
    - ``shed-rate``: per-second rate of the cumulative ``shed`` counter —
      requests turned away count against the SLO even when the survivors
      are fast.
    """
    return [
        SloSpec(
            "serve-p99",
            "serve.lat",
            serve_p99_ms,
            source="p99",
            window_s=window_s,
            min_samples=2,
        ),
        SloSpec(
            "shed-rate",
            "shed",
            shed_per_s,
            source="rate",
            window_s=window_s,
            min_samples=2,
        ),
    ]


# -- canonical scenarios ------------------------------------------------------

def smoke_scenario(seed: int = 0) -> Scenario:
    """Tier-1 seeded 8-node smoke: one flash crowd + one gray failure +
    one partition-then-heal, short enough for the default test budget."""
    return Scenario(
        name="smoke-8",
        seed=seed,
        nodes=8,
        base_qps=640.0,
        node_capacity_qps=120.0,
        tick_s=1.0,
        phases=(
            Phase("warmup", 20.0, LoadCurve("flat", base=0.8)),
            Phase("crowd", 60.0, LoadCurve(
                "flash_crowd", base=0.9, peak=2.5, at_s=10.0,
                ramp_s=5.0, hold_s=20.0, shift_hot_set=True,
            )),
            Phase("cooldown", 20.0, LoadCurve("flat", base=0.7)),
        ),
        faults=(
            Fault("slow_node", "crowd", at_s=15.0, duration_s=20.0,
                  slow_ms=400.0),
            Fault("partition", "cooldown", at_s=2.0, duration_s=8.0),
        ),
    )


def reference_scenario(seed: int = 0) -> Scenario:
    """The BASELINE.md reference drill: 50 nodes, flash crowd + one gray
    failure + one partition-then-heal (the ISSUE 19 acceptance shape)."""
    return Scenario(
        name="reference-50",
        seed=seed,
        nodes=50,
        # 50 x 120 = 6000 qps of fleet capacity; the flash peak offers
        # 4000 x 0.9 x 1.8 = 6480 qps (~108%) — an overload added capacity
        # can actually catch, so the closed loop has a real fight to win
        # (at 2-3x overload EVERY node drowns regardless and scaling up
        # only adds breach surface)
        base_qps=4000.0,
        node_capacity_qps=120.0,
        tick_s=1.0,
        phases=(
            Phase("steady", 30.0, LoadCurve("flat", base=0.8)),
            Phase("crowd", 90.0, LoadCurve(
                "flash_crowd", base=0.9, peak=1.8, at_s=10.0,
                ramp_s=8.0, hold_s=40.0, shift_hot_set=True,
            )),
            Phase("recovery", 40.0, LoadCurve("flat", base=0.75)),
        ),
        faults=(
            Fault("slow_node", "crowd", at_s=20.0, duration_s=30.0,
                  slow_ms=500.0),
            Fault("partition", "recovery", at_s=5.0, duration_s=12.0),
        ),
    )


def drill_scenario(seed: int = 0) -> Scenario:
    """The full 200-node production drill (``slow``-marked): diurnal base
    load, a hot-set-shifting flash crowd, a cascading gray failure, a
    rolling restart wave, a partition-then-heal, and forced scale events."""
    return Scenario(
        name="drill-200",
        seed=seed,
        nodes=200,
        base_qps=16000.0,
        node_capacity_qps=120.0,
        tick_s=1.0,
        phases=(
            Phase("day", 120.0, LoadCurve(
                "diurnal", base=0.8, amplitude=0.4, period_s=120.0,
            )),
            Phase("crowd", 120.0, LoadCurve(
                "flash_crowd", base=0.9, peak=3.0, at_s=15.0,
                ramp_s=10.0, hold_s=60.0, shift_hot_set=True,
            )),
            Phase("night", 80.0, LoadCurve("flat", base=0.6)),
        ),
        faults=(
            Fault("slow_node", "day", at_s=40.0, duration_s=40.0,
                  slow_ms=400.0, cascade=2, cascade_gap_s=15.0),
            Fault("restart_wave", "crowd", at_s=30.0, count=3,
                  gap_s=15.0, duration_s=6.0),
            Fault("partition", "night", at_s=10.0, duration_s=15.0),
            Fault("scale_up", "crowd", at_s=5.0),
            Fault("drain_down", "night", at_s=40.0),
        ),
    )
