"""War-game plane (ISSUE 19): declarative fleet scenarios, a deterministic
simulated-fleet runner, and the SLO-breach-minutes scorecard.

- :mod:`parameter_server_tpu.scenario.dsl` — seeded scenario specs
  (phases with load curves, fault injections) compiled to an absolute-time
  event schedule;
- :mod:`parameter_server_tpu.scenario.runner` — drives a 50-200-node
  simulated fleet over a real ``ChaosVan(LoopbackVan())`` wire through the
  schedule, autoscaler closed-loop on live telemetry;
- :mod:`parameter_server_tpu.scenario.scorecard` — integrates the breach
  timeline into SLO-breach-minutes and renders the JSON scorecard + the
  human incident report (postmortem chain + critpath attribution).
"""

from parameter_server_tpu.scenario.dsl import (  # noqa: F401
    Fault,
    LoadCurve,
    Phase,
    Scenario,
    compile_schedule,
    drill_scenario,
    reference_scenario,
    smoke_scenario,
    wargame_plane_specs,
)
from parameter_server_tpu.scenario.runner import ScenarioRunner  # noqa: F401
from parameter_server_tpu.scenario.scorecard import (  # noqa: F401
    build_scorecard,
    render_report,
)
