"""Deterministic war-game runner: a simulated fleet over a real wire.

:class:`ScenarioRunner` replays a compiled schedule
(:func:`~parameter_server_tpu.scenario.dsl.compile_schedule`) against a
50-200-node simulated fleet in VIRTUAL time.  The parts that matter for
control-plane scaling are real:

- telemetry frames are built by real
  :class:`~parameter_server_tpu.core.telemetry.TelemetryPublisher`
  instances (delta encoding, digest series, event summaries) and travel
  as real CONTROL messages over a real
  :class:`~parameter_server_tpu.core.chaos.ChaosVan` wire (pass any Van —
  loopback by default, a TCP/shm stack for wire realism) to a scheduler
  handler that ingests into a real
  :class:`~parameter_server_tpu.core.telemetry.TelemetryAggregator` +
  :class:`~parameter_server_tpu.utils.slo.SloEngine`;
- partitions drop those frames on the wire (``ChaosVan.partition``), gray
  failures are registered with ``ChaosVan.slow_node`` AND degrade the
  victim's service model;
- the autoscaler (:class:`~parameter_server_tpu.learner.elastic.
  AutoscalePolicy`) closes the loop on the aggregator's LIVE verdicts,
  never on sim ground truth.

What is simulated is each node's serving behaviour: a fluid queue
(offered load in, capacity out, bounded queue that sheds) whose latency
feeds the node's ``serve.lat`` digest.  Everything is driven by one
thread on a virtual clock — the only wall-clock waits are for the van's
recv thread to drain each tick's expected deliveries — so two runs with
the same scenario produce identical telemetry, identical breach edges,
and an identical scorecard.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Dict, List, Optional

from parameter_server_tpu.config import TelemetryConfig
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.chaos import ChaosVan
from parameter_server_tpu.core.manager import TELEMETRY
from parameter_server_tpu.core.messages import (
    SCHEDULER,
    Message,
    Task,
    TaskKind,
)
from parameter_server_tpu.core.telemetry import (
    TelemetryAggregator,
    TelemetryPublisher,
)
from parameter_server_tpu.core.van import LoopbackVan
from parameter_server_tpu.learner.elastic import (
    AutoscaleConfig,
    AutoscalePolicy,
)
from parameter_server_tpu.scenario import dsl
from parameter_server_tpu.utils.slo import SloEngine
from parameter_server_tpu.utils.trace import LatencyHistogram

class _SimFleet:
    """Clock-offset oracle for the aggregator (stragglers: none)."""

    def __init__(self, offsets: Dict[str, float]) -> None:
        self._offsets = offsets

    def clock_offset(self, node: str) -> float:
        return self._offsets.get(node, 0.0)

    def stragglers(self, now: Optional[float] = None) -> Dict[str, list]:
        return {}


def _node_offset(node: str, max_offset_s: float) -> float:
    """Deterministic per-node clock offset in [-max, +max] — a pure hash,
    no RNG draw, so adding nodes never shifts anyone else's offset."""
    if max_offset_s <= 0:
        return 0.0
    frac = (zlib.crc32(node.encode()) % 10_000) / 10_000.0
    return (2.0 * frac - 1.0) * max_offset_s


class _SimNode:
    """One simulated serving node: fluid queue + telemetry source.

    The model is intentionally simple and fully deterministic: per tick,
    ``offered`` requests arrive, up to ``capacity`` (degraded by a gray
    failure's ``slow_ms``) are served, the rest queue; the queue is
    bounded at ``max_queue_s`` worth of capacity and overflow is SHED.
    Service latency = base + gray-failure delay + queueing delay, recorded
    into the cumulative ``serve.lat`` digest the SLO engine reads.
    """

    def __init__(
        self,
        node_id: str,
        *,
        capacity_qps: float,
        base_ms: float = 20.0,
        max_queue_s: float = 2.0,
    ) -> None:
        self.node_id = node_id
        self.capacity = capacity_qps
        self.base_s = base_ms / 1e3
        self.max_queue_s = max_queue_s
        self.queue = 0.0
        self.slow_ms = 0.0
        self.partitioned = False
        #: virtual time a same-id restart brings the node back, or None.
        self.offline_until: Optional[float] = None
        self.served = 0.0
        self.shed = 0.0
        self.fence_rejects = 0.0
        self.restarts = 0
        self.last_latency_s = self.base_s
        self._lat = LatencyHistogram()

    # -- telemetry source interface ------------------------------------------
    def counters(self) -> dict:
        return {
            "served": int(self.served),
            "shed": int(self.shed),
            "fence_rejects": int(self.fence_rejects),
            "restarts": self.restarts,
        }

    def latency_digests(self) -> dict:
        return {"serve.lat": self._lat.to_dict()}

    # -- model ----------------------------------------------------------------
    def step(self, offered_qps: float, tick_s: float, now: float) -> None:
        if self.offline_until is not None:
            if now < self.offline_until:
                # dead process: clients get fenced, nothing is served
                self.fence_rejects += offered_qps * tick_s
                return
            # revived (same-id restart): queue was lost with the process
            self.offline_until = None
            self.queue = 0.0
            self.restarts += 1
        slow_s = self.slow_ms / 1e3
        # a gray failure stretches every service slot: capacity shrinks by
        # the ratio of healthy to degraded service time
        eff_cap = self.capacity * self.base_s / (self.base_s + slow_s)
        arriving = offered_qps * tick_s
        budget = eff_cap * tick_s
        done = min(self.queue + arriving, budget)
        self.queue = self.queue + arriving - done
        qcap = eff_cap * self.max_queue_s
        if self.queue > qcap:
            self.shed += self.queue - qcap
            self.queue = qcap
        self.served += done
        latency = self.base_s + slow_s + (
            self.queue / eff_cap if eff_cap > 0 else 0.0
        )
        self.last_latency_s = latency
        # one digest sample per tick: the p99 spec windows over ticks
        self._lat.record(latency)


class ScenarioRunner:
    """Drive one compiled scenario; collect everything the scorecard needs.

    ``run()`` returns the machine-readable scorecard dict
    (:func:`~parameter_server_tpu.scenario.scorecard.build_scorecard`);
    the runner object keeps the engine/aggregator/chaos state for the
    human report.
    """

    def __init__(
        self,
        scenario: dsl.Scenario,
        *,
        autoscale: bool = True,
        autoscale_config: Optional[AutoscaleConfig] = None,
        slo_specs=None,
        van=None,
        telemetry_config: Optional[TelemetryConfig] = None,
        jsonl_path: Optional[str] = None,
        base_ms: float = 20.0,
        hot_boost: float = 3.0,
        table_rows: int = 1 << 20,
        table_dim: int = 32,
        max_clock_offset_s: float = 0.25,
        autoscale_every_ticks: int = 5,
        trace_sample: bool = True,
        ingest_timeout_s: float = 30.0,
    ) -> None:
        self.scenario = scenario
        self.schedule = dsl.compile_schedule(scenario)
        self.hot_boost = hot_boost
        self.base_ms = base_ms
        self.table_rows = table_rows
        self.table_dim = table_dim
        self.autoscale_every = max(1, autoscale_every_ticks)
        self.trace_sample = trace_sample
        self.ingest_timeout_s = ingest_timeout_s
        self._max_offset = max_clock_offset_s

        self.van = van if van is not None else ChaosVan(
            LoopbackVan(), seed=scenario.seed
        )
        self.chaos: Optional[ChaosVan] = (
            self.van if isinstance(self.van, ChaosVan) else None
        )
        self.engine = SloEngine(
            list(slo_specs) if slo_specs is not None
            else dsl.wargame_plane_specs()
        )
        self._offsets: Dict[str, float] = {}
        self.agg = TelemetryAggregator(
            slo=self.engine,
            fleet=_SimFleet(self._offsets),
            config=telemetry_config or TelemetryConfig(),
            jsonl_path=jsonl_path,
            evaluate_scope="node",
        )
        if autoscale and autoscale_config is None:
            # headroom scales with the scenario: a 50-node drill must be
            # able to actually scale up, not just rebalance at the default
            # 16-server ceiling
            autoscale_config = AutoscaleConfig(
                max_servers=max(16, 2 * scenario.nodes)
            )
        self.autoscaler: Optional[AutoscalePolicy] = (
            AutoscalePolicy(autoscale_config) if autoscale else None
        )

        self.nodes: Dict[str, _SimNode] = {}
        self.pubs: Dict[str, TelemetryPublisher] = {}
        #: per-node extra load weight on top of the uniform 1.0 (hot set).
        self.extra_weight: Dict[str, float] = {}
        self.hot_node: Optional[str] = None
        self._next_index = 0
        self.bytes_migrated = 0
        #: counters of drained nodes (ground truth survives retirement).
        self.retired_totals: Dict[str, int] = {
            "served": 0, "shed": 0, "fence_rejects": 0, "restarts": 0,
        }
        self.actions: List[dict] = []
        self.now = 0.0
        self.phase: Optional[str] = None
        #: consistency plane (ISSUE 20): the phase knob's current setting.
        #: The runner simulates load, not training, so the flip is state +
        #: callbacks: a driver running a REAL fleet appends a callable
        #: ``(mode, bound) -> None`` (typically a ``consist_set`` broadcast
        #: through any live worker) to ``on_consistency_mode``.
        self.consistency_mode: Optional[str] = None
        self.consistency_bound: Optional[int] = None
        self.on_consistency_mode: List = []
        #: synthetic sampled-request trace events (critpath.py shapes,
        #: pre-rebased: ``t_s`` is virtual time) for the incident report.
        self.trace_events: List[dict] = []
        self._trace_seq = 0
        #: virtual-time -> wall-monotonic anchors (postmortem windowing).
        self.wall_of_tick: Dict[float, float] = {}

        self._cond = threading.Condition()
        self._ingested = 0
        self._ingest_now = 0.0
        self.van.bind(SCHEDULER, self._on_msg)
        for _ in range(scenario.nodes):
            self._add_node(record=False)

    # -- fleet shape ----------------------------------------------------------
    def _add_node(self, *, record: bool = True) -> str:
        node = f"S{self._next_index}"
        self._next_index += 1
        self.nodes[node] = _SimNode(
            node,
            capacity_qps=self.scenario.node_capacity_qps,
            base_ms=self.base_ms,
        )
        # per-node recorder: frames summarize only this node's events
        # without scanning the shared process ring 200x per beat
        self.pubs[node] = TelemetryPublisher(
            node,
            recorder=flightrec.FlightRecorder(capacity=512, node=node),
            sources=(self.nodes[node],),
        )
        self._offsets[node] = _node_offset(node, self._max_offset)
        if record:
            # joining node takes its uniform share: 1/(n+1) of the table
            moved = self.table_rows // max(1, len(self.nodes))
            self.bytes_migrated += moved * self.table_dim * 4
        return node

    def _remove_node(self, node: str) -> None:
        # its shard moves to the survivors before the process exits
        self.bytes_migrated += (
            self.table_rows // max(1, len(self.nodes))
        ) * self.table_dim * 4
        sim = self.nodes.get(node)
        if sim is not None:
            for k in self.retired_totals:
                self.retired_totals[k] += int(getattr(sim, k))
        self.nodes.pop(node, None)
        self.pubs.pop(node, None)
        self.extra_weight.pop(node, None)
        if self.hot_node == node:
            self.hot_node = None

    # -- wire -----------------------------------------------------------------
    def _on_msg(self, msg: Message) -> None:
        if msg.task.payload.get("cmd") != TELEMETRY:
            return
        self.agg.ingest(
            msg.sender,
            msg.task.payload.get("frame") or {},
            now=self._ingest_now,
        )
        with self._cond:
            self._ingested += 1
            self._cond.notify_all()

    def _publish_tick(self) -> None:
        """Build + send every online node's frame; wait for ingestion.

        Frames into a partitioned link are still SENT (and dropped by the
        chaos layer, exactly like production); the runner only waits for
        the deliveries the partition map says can arrive, so virtual time
        never advances past an un-ingested frame.
        """
        self._ingest_now = self.now
        with self._cond:
            start = self._ingested
        expected = 0
        for node, sim in sorted(self.nodes.items()):
            if sim.offline_until is not None:
                continue  # dead process publishes nothing
            frame = self.pubs[node].frame(self.now + self._offsets[node])
            self.van.send(Message(
                task=Task(
                    TaskKind.CONTROL,
                    "scenario",
                    payload={"cmd": TELEMETRY, "frame": frame},
                ),
                sender=node,
                recver=SCHEDULER,
            ))
            if not sim.partitioned:
                expected += 1
        deadline = time.monotonic() + self.ingest_timeout_s
        with self._cond:
            while self._ingested < start + expected:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"tick t={self.now}: ingested "
                        f"{self._ingested - start}/{expected} frames"
                    )
                self._cond.wait(timeout=left)

    # -- schedule execution ---------------------------------------------------
    def _apply_event(self, ev: dict) -> None:
        kind = ev["event"]
        if kind == "phase":
            self.phase = ev["phase"]
            self.agg.set_phase(ev["phase"])
            flightrec.record(
                "scenario.phase", node=SCHEDULER, phase=ev["phase"],
                t_virtual=ev["t"],
            )
            mode = ev.get("consistency_mode")
            if mode is not None:
                bound = ev.get("consistency_bound")
                self.consistency_mode = mode
                self.consistency_bound = bound
                for cb in self.on_consistency_mode:
                    cb(mode, bound)
                flightrec.record(
                    "consist.retune", node=SCHEDULER, table="*",
                    mode=mode, bound=-1 if bound is None else int(bound),
                    why=f"scenario phase {ev['phase']}",
                )
        elif kind == "hot_shift":
            if self.hot_node is not None:
                self.extra_weight.pop(self.hot_node, None)
            node = ev["node"]
            if node in self.nodes:
                self.hot_node = node
                self.extra_weight[node] = self.hot_boost - 1.0
        elif kind == "inject":
            fault = ev["fault"]
            node = ev.get("node")
            sim = self.nodes.get(node)
            if sim is None:
                return
            if fault == "slow_node":
                sim.slow_ms = float(ev["slow_ms"])
                if self.chaos is not None:
                    self.chaos.slow_node(node, sim.slow_ms)
            elif fault == "partition":
                sim.partitioned = True
                if self.chaos is not None:
                    self.chaos.partition(node, SCHEDULER, symmetric=True)
            elif fault == "restart":
                sim.offline_until = self.now + float(ev["offline_s"])
            flightrec.record(
                "scenario.inject", node=node, fault=fault,
                t_virtual=ev["t"],
            )
            self._record_node_event(node, "scenario.inject", fault=fault)
        elif kind == "heal":
            fault = ev["fault"]
            node = ev.get("node")
            sim = self.nodes.get(node)
            if sim is None:
                return
            if fault == "slow_node":
                sim.slow_ms = 0.0
                if self.chaos is not None:
                    self.chaos.slow_node(node, 0.0)
            elif fault == "partition":
                sim.partitioned = False
                if self.chaos is not None:
                    self.chaos.heal(node, SCHEDULER)
                    self.chaos.heal(SCHEDULER, node)
            flightrec.record(
                "scenario.heal", node=node, fault=fault, t_virtual=ev["t"],
            )
            self._record_node_event(node, "scenario.heal", fault=fault)
        elif kind == "scale":
            self._execute({"kind": ev["action"], "reason": "scheduled"})
        elif kind == "end":
            pass

    def _record_node_event(self, node: str, kind: str, **fields) -> None:
        """Mirror a scenario event into the victim's publisher recorder so
        it rides that node's next telemetry frame (event-rate channel)."""
        pub = self.pubs.get(node)
        if pub is not None and pub._recorder is not None:
            pub._recorder.record(kind, node=node, **fields)

    def _execute(self, intent: dict) -> None:
        """Carry out one autoscaler/scheduled intent on the sim fleet."""
        kind = intent["kind"]
        done = dict(intent)
        done["t"] = self.now
        if kind == "scale_up":
            count = max(1, int(intent.get("count", 1)))
            done["node"] = ",".join(
                self._add_node() for _ in range(count)
            )
        elif kind == "drain_down":
            node = intent.get("node")
            if node is None or node not in self.nodes:
                # retire the coldest non-hot node (deterministic order)
                pool = [
                    n for n in sorted(self.nodes)
                    if n != self.hot_node and self.nodes[n].offline_until is None
                ]
                if not pool:
                    return
                node = pool[-1]
            if len(self.nodes) <= 2:
                return
            done["node"] = node
            self._remove_node(node)
        elif kind == "rebalance":
            node = intent.get("node") or self.hot_node
            if node is None or node not in self.nodes:
                return
            extra = self.extra_weight.get(node, 0.0)
            if extra <= 0.0:
                return
            # move half the hot share to the least-loaded peer
            pool = [n for n in sorted(self.nodes) if n != node]
            if not pool:
                return
            coldest = min(
                pool, key=lambda n: (self.extra_weight.get(n, 0.0), n)
            )
            moved_w = extra / 2.0
            self.extra_weight[node] = extra - moved_w
            self.extra_weight[coldest] = (
                self.extra_weight.get(coldest, 0.0) + moved_w
            )
            total_w = len(self.nodes) + sum(self.extra_weight.values())
            moved_rows = int(self.table_rows * moved_w / max(total_w, 1e-9))
            self.bytes_migrated += moved_rows * self.table_dim * 4
            done["node"] = node
            done["moved_rows"] = moved_rows
        self.actions.append(done)
        target = done.get("node")
        flightrec.record(
            "scenario.action",
            # a multi-node scale_up is the scheduler's act, not any one
            # node's — keep the postmortem's per-node index clean
            node=(
                target if target and "," not in target else SCHEDULER
            ),
            action=kind, target=target or "",
            reason=intent.get("reason", ""),
            t_virtual=self.now,
        )

    # -- load model -----------------------------------------------------------
    def _weights(self) -> Dict[str, float]:
        return {
            n: 1.0 + self.extra_weight.get(n, 0.0)
            for n in sorted(self.nodes)
        }

    def _offered(self) -> Dict[str, float]:
        total = self.scenario.base_qps * self.scenario.multiplier(self.now)
        w = self._weights()
        wsum = sum(w.values()) or 1.0
        return {n: total * wi / wsum for n, wi in w.items()}

    # -- synthetic sampled request (critpath shapes) --------------------------
    def _sample_trace(self, offered: Dict[str, float]) -> None:
        """Emit one sampled request's span set per tick, targeted at the
        currently worst-latency node — the requests the incident report's
        critpath attribution will decompose for the worst breach window.

        The stamps are derived from the victim's queue model (``t_s`` is
        VIRTUAL time, already rebased), shaped exactly like
        ``tools/critpath.merge_events`` output so ``critpath.requests``
        consumes them directly.
        """
        live = [
            n for n, s in self.nodes.items() if s.offline_until is None
        ]
        if not live:
            return
        victim = max(
            sorted(live), key=lambda n: self.nodes[n].last_latency_s
        )
        sim = self.nodes[victim]
        self._trace_seq += 1
        tid = f"W0/{self._trace_seq}"
        t0 = self.now
        serialize = 0.0002
        send_q = 0.0003
        wire = 0.0005
        queue_s = max(sim.last_latency_s - sim.base_s - sim.slow_ms / 1e3, 0.0)
        service = sim.base_s + sim.slow_ms / 1e3
        t_send = t0 + serialize
        t_tx = t_send + send_q
        t_rx = t_tx + wire
        t_disp = t_rx + queue_s
        t_reply = t_disp + service
        t_ack = t_reply + wire
        self.trace_events.extend([
            {"kind": "trace.submit", "tid": tid, "node": "W0",
             "t_s": t_send, "_t0_s": t0, "op": "pull", "legs": 1},
            {"kind": "trace.wire_tx", "tids": [tid], "node": "W0",
             "recver": victim, "t_s": t_tx},
            {"kind": "trace.wire_rx", "tids": [tid], "node": victim,
             "sender": "W0", "t_s": t_rx},
            {"kind": "trace.dispatch", "tid": tid, "node": victim,
             "t_s": t_disp},
            {"kind": "trace.reply", "tid": tid, "node": victim,
             "t_s": t_reply, "verdict": "ok"},
            {"kind": "trace.ack", "tid": tid, "node": "W0", "t_s": t_ack,
             "e2e_ms": round((t_ack - t0) * 1e3, 3)},
        ])

    # -- main loop ------------------------------------------------------------
    def run(self) -> dict:
        from parameter_server_tpu.scenario import scorecard as sc

        # size the global ring to the run: every tick publishes one
        # telemetry.publish marker per node into it, and a 200-node drill
        # would otherwise evict the injects/breaches the postmortem needs
        need = int(
            len(self.nodes)
            * (self.scenario.duration_s / self.scenario.tick_s)
        ) + 4096
        if (flightrec.get()._ring.maxlen or 0) < need:
            flightrec.configure(capacity=need)
        flightrec.record(
            "scenario.begin", node=SCHEDULER,
            scenario=self.scenario.name, seed=self.scenario.seed,
            nodes=len(self.nodes),
        )
        pending = list(self.schedule)
        tick = 0
        end_t = self.scenario.duration_s
        while self.now < end_t or pending:
            while pending and pending[0]["t"] <= self.now:
                self._apply_event(pending.pop(0))
            if self.now >= end_t:
                break
            self.wall_of_tick[self.now] = time.monotonic()
            offered = self._offered()
            for node in sorted(self.nodes):
                self.nodes[node].step(
                    offered[node], self.scenario.tick_s, self.now
                )
            if self.trace_sample:
                self._sample_trace(offered)
            self._publish_tick()
            # one full-fleet sweep per tick: nodes whose frames were lost
            # to a partition still age out of their windows on time
            self.engine.evaluate(self.now)
            if (
                self.autoscaler is not None
                and tick % self.autoscale_every == 0
            ):
                view = {}
                for node, row in self.agg.latest().items():
                    if node not in self.nodes:
                        continue  # drained node's last rows linger
                    view[node] = {
                        "healthy": bool(row.get("healthy", True)),
                        "load": offered.get(node, 0.0),
                    }
                for intent in self.autoscaler.tick(self.now, view):
                    self._execute(intent)
            self.now = round(self.now + self.scenario.tick_s, 6)
            tick += 1
        self.agg.set_phase(None)
        flightrec.record(
            "scenario.end", node=SCHEDULER, scenario=self.scenario.name,
            breach_min=round(self.engine.breach_seconds(now=end_t) / 60.0, 4),
        )
        return sc.build_scorecard(self)

    def close(self) -> None:
        try:
            self.agg.close()  # flush the JSONL spill, if any
        except Exception:
            pass
        try:
            self.van.close()
        except Exception:
            pass
