"""SLO-driven admission control for the serving plane (ISSUE 13).

A serving worker that keeps pulling through an overloaded fleet makes the
overload worse AND serves its training tenants worse — the classic shared-
plane failure.  The admission controller sits in front of
:meth:`~parameter_server_tpu.kv.worker.KVWorker.pull_serve` and sheds or
defers read traffic when either overload signal fires:

- the **SLO plane** says so: ``SloEngine.healthy()`` is level-triggered
  over live telemetry (PR 8), so a breach of any armed spec — serving
  p99, apply backlog — flips the gate within one telemetry beat;
- the **device plane** says so: the server's ApplyLedger stamped
  ``__busy__`` onto a recent ack (PR 12), which this worker remembers
  per-server (:meth:`KVWorker.server_busy`) — the fast local signal that
  needs no aggregator round-trip.

What "shed" means is the configured policy (:class:`~parameter_server_tpu.
config.ServeConfig`):

- ``"reject"``: fail fast with :class:`ShedError` carrying an advisory
  ``retry_after_s`` — the client's backoff hint;
- ``"stale"``: answer from the cache IGNORING freshness (bounded only by
  what the cache holds); keys not fully cached still shed — degraded but
  bounded, never silently partial;
- ``"queue"``: park the read up to ``queue_deadline_s`` waiting for
  health, then serve (adding the wait to latency) or shed.

Every shed is a ``serve.shed`` flight-recorder event and a counter the
telemetry plane turns into pstop's SHED/S column.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from parameter_server_tpu.config import ServeConfig
from parameter_server_tpu.core import flightrec
from parameter_server_tpu.core.messages import server_id
from parameter_server_tpu.kv.worker import KVWorker


class ShedError(RuntimeError):
    """A read was shed by admission control; retry after ``retry_after_s``."""

    def __init__(self, why: str, retry_after_s: float) -> None:
        super().__init__(why)
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Policy gate in front of a serving worker's read path.

    ``healthy``: zero-arg callable, False = overloaded (typically
    ``lambda: eng.healthy(node)`` over the live ``SloEngine``); None = no
    SLO feed, gate on ``__busy__`` hints alone.
    """

    def __init__(
        self,
        worker: KVWorker,
        *,
        healthy: Optional[Callable[[], bool]] = None,
        cfg: Optional[ServeConfig] = None,
        node: Optional[str] = None,
    ) -> None:
        self.worker = worker
        self.healthy = healthy
        self.cfg = cfg or ServeConfig()
        self.node = node or worker.post.node_id
        #: dashboard counters (telemetry-mergeable; SHED/S in pstop)
        self.serve_shed = 0
        self.serve_stale = 0
        self.serve_queue_waits = 0

    # -- overload signal ------------------------------------------------------
    def overloaded(self, table: Optional[str] = None) -> bool:
        """True when either overload signal is live.

        ``table`` scopes the ``__busy__`` scan to that table's owners;
        None scans every server the routing table names.
        """
        if self.healthy is not None and not self.healthy():
            return True
        routing = self.worker.routing
        servers = (
            routing.tables[table].distinct_owners()
            if table is not None
            else routing.servers()
        )
        return any(
            self.worker.server_busy(server_id(s), self.cfg.busy_within_s)
            for s in servers
        )

    # -- the gated read -------------------------------------------------------
    def pull(
        self, table: str, keys: np.ndarray, timeout: Optional[float] = None
    ) -> np.ndarray:
        """Admission-controlled read: :meth:`KVWorker.pull_serve` when the
        plane is healthy, the configured shed policy when it is not."""
        if not self.overloaded(table):
            return self.worker.pull_serve(table, keys, timeout)
        policy = self.cfg.policy
        if policy == "stale":
            rows = self.worker.pull_stale(table, keys)
            if rows is not None:
                self.serve_stale += 1
                return rows
            return self._shed(table, keys, "overloaded; keys not cached")
        if policy == "queue":
            deadline = time.monotonic() + self.cfg.queue_deadline_s
            self.serve_queue_waits += 1
            while time.monotonic() < deadline:
                if not self.overloaded(table):
                    return self.worker.pull_serve(table, keys, timeout)
                time.sleep(self.cfg.queue_poll_s)
            return self._shed(table, keys, "overloaded past queue deadline")
        return self._shed(table, keys, "overloaded")

    def _shed(self, table: str, keys, why: str) -> np.ndarray:
        self.serve_shed += 1
        flightrec.record(
            "serve.shed", node=self.node, table=table,
            n=int(np.asarray(keys).size), policy=self.cfg.policy,
            why=why[:120],
        )
        raise ShedError(
            f"read of {int(np.asarray(keys).size)} keys of {table!r} shed "
            f"({self.cfg.policy}): {why}",
            self.cfg.retry_after_s,
        )

    def counters(self) -> dict:
        """Telemetry-mergeable counters (ride the worker's frame)."""
        return {
            "serve_shed": self.serve_shed,
            "serve_stale": self.serve_stale,
            "serve_queue_waits": self.serve_queue_waits,
        }
