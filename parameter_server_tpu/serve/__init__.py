"""Read-heavy serving plane (ISSUE 13).

Layers a model-serving surface over the training substrate: hot-row
caching with version-clock invalidation lives in ``kv/cache.py`` (it is a
KV concern), while this package holds what is serving-specific —
SLO-driven admission control (:mod:`.admission`) and the open-loop
synthetic load generator (:mod:`.loadgen`) behind ``bench.py --serve``.
"""

from parameter_server_tpu.serve.admission import AdmissionController, ShedError
from parameter_server_tpu.serve.loadgen import LoadGenerator, LoadReport

__all__ = [
    "AdmissionController",
    "ShedError",
    "LoadGenerator",
    "LoadReport",
]
