"""Open-loop synthetic serving load with Zipfian key popularity (ISSUE 13).

Simulates the serving plane's canonical tenant: on the order of 10^6
concurrent clients, each issuing reads at a tiny individual rate.  The
superposition of that many independent thin Poisson streams is itself a
Poisson stream at the summed rate, so the generator draws ONE aggregate
arrival process (exponential gaps at ``clients * per_client_qps``) instead
of simulating a million timers — statistically identical arrivals, none of
the bookkeeping.

Two properties make the numbers honest:

- **Open loop**: arrivals are scheduled in advance and never wait for the
  previous request — a slow server faces a growing backlog exactly as a
  real fleet of independent clients would, instead of the closed-loop
  auto-throttle that hides overload.
- **Coordinated-omission-free latency**: each request's latency is
  measured from its SCHEDULED arrival, not from when the loop got around
  to sending it, so queueing delay behind a stall lands in the histogram
  instead of vanishing.

Key popularity is Zipfian (``P(rank k) ∝ 1/k^s``) over a rank permutation
of the key space, so hot ranks scatter across servers rather than packing
into one shard's range.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from parameter_server_tpu.serve.admission import ShedError
from parameter_server_tpu.utils.trace import LatencyHistogram


@dataclasses.dataclass
class LoadReport:
    """One run's serving scorecard (the ``bench.py --serve`` record body)."""

    pulls: int
    served: int
    shed: int
    duration_s: float
    offered_qps: float
    p50_ms: float
    p99_ms: float
    hit_rate: float
    shed_rate: float
    cache_hits: int
    cache_misses: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class LoadGenerator:
    """Drive ``pull_fn(table, keys)`` with open-loop Zipfian read traffic.

    ``pull_fn``: the read entry point — ``AdmissionController.pull`` (sheds
    count) or ``KVWorker.pull_serve`` (no admission).  ``cache``: the
    worker's :class:`~parameter_server_tpu.kv.cache.HotRowCache`, read
    before/after for the run's hit/miss delta; None reports zeros.

    ``clients``/``per_client_qps`` set the aggregate offered rate
    (``clients * per_client_qps``); the default models 10^6 clients at one
    read every ~100 s.  All randomness is seeded — two runs with the same
    arguments offer the identical request sequence.
    """

    def __init__(
        self,
        pull_fn: Callable,
        *,
        table: str = "w",
        num_keys: int,
        keys_per_pull: int = 8,
        clients: int = 1_000_000,
        per_client_qps: float = 1e-5,
        zipf_s: float = 1.1,
        seed: int = 0,
        cache=None,
        rate_fn: Optional[Callable[[float], float]] = None,
    ) -> None:
        if num_keys <= 0:
            raise ValueError("num_keys must be positive")
        self.pull_fn = pull_fn
        self.table = table
        self.keys_per_pull = int(keys_per_pull)
        self.qps = float(clients) * float(per_client_qps)
        if self.qps <= 0:
            raise ValueError("aggregate rate must be positive")
        self.seed = int(seed)
        self.cache = cache
        #: optional offered-load curve (ISSUE 19): a multiplier on the base
        #: rate as a function of run time, so one generator can follow a
        #: diurnal sine or a flash-crowd step instead of a flat rate.  The
        #: inhomogeneous Poisson process is realized by thinning, so the
        #: arrival stream stays seeded-deterministic for a fixed curve.
        self.rate_fn = rate_fn
        rng = np.random.default_rng(self.seed)
        # Zipf pmf over ranks 1..num_keys, inverse-CDF sampled; ranks map
        # to key ids through a seeded permutation (hot keys spread across
        # the row space, therefore across shards)
        pmf = 1.0 / np.power(np.arange(1, num_keys + 1, dtype=np.float64), zipf_s)
        pmf /= pmf.sum()
        self._cdf = np.cumsum(pmf)
        self._rank_to_key = rng.permutation(num_keys).astype(np.int64)

    def shift_hot_set(self, seed: int) -> None:
        """Re-draw the rank -> key permutation (ISSUE 19 flash crowds).

        The Zipf pmf over RANKS is unchanged; which concrete keys are hot
        changes, which is exactly what a flash crowd does to a serving
        cache — the hit-rate machinery has to re-learn the hot set.
        Seeded, so scenario replays shift to the identical new hot set.
        """
        rng = np.random.default_rng(int(seed))
        self._rank_to_key = rng.permutation(
            self._rank_to_key.size
        ).astype(np.int64)

    def _arrivals(self, rng, duration_s: float):
        """Scheduled arrival offsets + per-request key batches.

        With a ``rate_fn`` the arrivals follow the inhomogeneous Poisson
        process ``qps * rate_fn(t)`` via thinning: draw a homogeneous
        stream at the curve's peak rate, keep each arrival with
        probability ``rate_fn(t)/peak``.  Same rng, fixed draw order —
        deterministic for a fixed seed + curve.
        """
        if self.rate_fn is None:
            n = max(1, rng.poisson(self.qps * duration_s))
            sched = np.sort(rng.random(n) * duration_s)
        else:
            grid = np.linspace(0.0, duration_s, 1025)
            mult = np.array([float(self.rate_fn(t)) for t in grid])
            if np.any(mult < 0):
                raise ValueError("rate_fn must be >= 0")
            peak = float(mult.max())
            if peak <= 0:
                sched = np.zeros(1)
            else:
                n = max(1, rng.poisson(self.qps * peak * duration_s))
                cand = np.sort(rng.random(n) * duration_s)
                accept = rng.random(n) * peak <= np.array(
                    [float(self.rate_fn(t)) for t in cand]
                )
                sched = cand[accept]
                if sched.size == 0:
                    sched = cand[:1]
        n = sched.shape[0]
        u = rng.random((n, self.keys_per_pull))
        ranks = np.searchsorted(self._cdf, u, side="left")
        keys = self._rank_to_key[np.minimum(ranks, self._rank_to_key.size - 1)]
        return sched, keys

    def run(self, duration_s: float) -> LoadReport:
        """Offer ``duration_s`` worth of scheduled traffic, then report.

        Runs past ``duration_s`` if the server is slower than the offered
        rate (open loop: every scheduled request is still issued, and its
        queueing delay is measured).
        """
        rng = np.random.default_rng(self.seed + 1)
        sched, keys = self._arrivals(rng, duration_s)
        hist = LatencyHistogram()
        hits0 = misses0 = 0
        if self.cache is not None:
            hits0, misses0 = self.cache.hits, self.cache.misses
        served = 0
        shed = 0
        t0 = time.perf_counter()
        for i in range(sched.shape[0]):
            now = time.perf_counter() - t0
            if now < sched[i]:
                time.sleep(sched[i] - now)
            try:
                self.pull_fn(self.table, keys[i])
                served += 1
                # latency from the SCHEDULED arrival (includes queueing)
                hist.record((time.perf_counter() - t0) - float(sched[i]))
            except ShedError:
                shed += 1
        dur = time.perf_counter() - t0
        hits = misses = 0
        if self.cache is not None:
            hits = self.cache.hits - hits0
            misses = self.cache.misses - misses0
        n = sched.shape[0]
        looked = hits + misses
        return LoadReport(
            pulls=int(n),
            served=served,
            shed=shed,
            duration_s=round(dur, 3),
            offered_qps=round(self.qps, 3),
            p50_ms=round(1e3 * hist.percentile(0.5), 3),
            p99_ms=round(1e3 * hist.percentile(0.99), 3),
            hit_rate=round(hits / looked, 4) if looked else 0.0,
            shed_rate=round(shed / n, 4) if n else 0.0,
            cache_hits=int(hits),
            cache_misses=int(misses),
        )
