"""Offline model evaluation from saved checkpoints.

Reference analogue: ``src/app/linear_method/model_evaluation.h`` [U] — after
SaveModel, read the servers' weight files back and score a validation set
(AUC).  Here the saved artifact is the sharded checkpoint
(``checkpoint.py``); evaluation reassembles the global table on the host and
scores batches without standing up a cluster.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Tuple

import numpy as np

from parameter_server_tpu import checkpoint
from parameter_server_tpu.utils import metrics as metrics_lib
from parameter_server_tpu.utils.keys import HashLocalizer

Batch = Tuple[np.ndarray, np.ndarray]  # (keys [B, nnz], labels [B])


def _scores_lr(weights: np.ndarray, slots_pos: np.ndarray, bias: float) -> np.ndarray:
    return weights[slots_pos, 0].sum(axis=-1) + bias


def evaluate_checkpoint(
    root: str,
    table: str,
    batches: Iterable[Batch],
    *,
    step: Optional[int] = None,
    model: str = "lr",
    localizer: Optional[HashLocalizer] = None,
    hash_bits: Optional[int] = None,
    bias: float = 0.0,
) -> dict:
    """Score ``batches`` against the saved model; returns metrics.

    ``model``: ``"lr"`` (sum of weights) or ``"fm"`` (factorization machine,
    table dim = 1 + k).  The key->row mapping must match training: an
    explicit ``localizer`` wins; otherwise the manifest's recorded localizer
    metadata (``KVWorker.save_model`` writes it) is reconstructed; only as a
    last resort is a default ``HashLocalizer`` assumed, with ``hash_bits``
    overriding its width (a 32-bit device-hash table scored with the 64-bit
    default mis-assigns every row — VERDICT r2 weak #5).

    Note: weights are read as raw value rows; for lazy-weight optimizers
    (FTRL) pass the training-time table through ``KVTable.weights()`` and a
    direct scorer instead — the checkpoint stores z/n, not w.
    """
    from parameter_server_tpu.utils.keys import localizer_from_meta

    if step is None:
        step = checkpoint.latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no committed checkpoint under {root}")
    weights = checkpoint.load_global_weights(root, step, table)
    rows = weights.shape[0]
    loc = localizer
    if loc is None:
        meta = checkpoint.read_info(root, step).extras.get("localizers", {})
        if table in meta:
            m = dict(meta[table])
            if hash_bits is not None and m.get("kind") == "HashLocalizer":
                # override the width only — the recorded seed must survive,
                # or the override reintroduces the mis-scoring it exists to fix
                m["hash_bits"] = hash_bits
            loc = localizer_from_meta(m)
    if loc is None:
        loc = HashLocalizer(rows, hash_bits=hash_bits or 64)

    if model == "lr":
        score: Callable = lambda sp: _scores_lr(weights, sp, bias)
    elif model == "fm":
        from parameter_server_tpu.models.fm import eval_logits_np

        score = lambda sp: eval_logits_np(weights, bias, sp)
    else:
        raise ValueError(f"unknown model {model!r}")

    scores, labels_all = [], []
    for keys, labels in batches:
        slots_pos = np.minimum(loc.assign(keys), rows - 1)
        scores.append(score(slots_pos))
        labels_all.append(labels)
    s = np.concatenate(scores)
    y = np.concatenate(labels_all)
    return {
        "step": step,
        "examples": int(y.shape[0]),
        "auc": metrics_lib.auc(y, s),
        "logloss": float(
            np.mean(np.maximum(s, 0) - s * y + np.log1p(np.exp(-np.abs(s))))
        ),
    }
