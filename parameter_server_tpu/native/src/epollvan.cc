// Epoll wire plane for the DCN Van (ISSUE 17, Transport v2).
//
// Same plain-C ABI as tcpvan.cc — the Python layer (core/tcp_van.py) loads
// either backend interchangeably — but the thread model is inverted: ONE
// event-loop thread multiplexes every connection (listen fd + all conns +
// an eventfd for cross-thread wakeups) instead of tcpvan's accept thread +
// one recv thread per connection.  At 10k+ connections (the serving-plane
// fan-in) per-connection threads stop being a viable model: this is the
// epoll rebuild the MLPerf-pods scale reference demands.
//
// Additions over the tcpvan ABI:
//   ps_van_send_vec(handle, conn, bufs[], lens[], n) — vectored send: the
//     12-byte wire header + a frame's segments (flat-frame header+meta,
//     then each value plane) go to writev() as an iovec, so a coalesced
//     bundle's member planes never concatenate host-side.  Returns 0 ok,
//     -1 dead conn, -2 write queue full (typed backpressure: the caller
//     counts writeq_full and lets the resender retransmit).
//
// Send path: callers run on arbitrary Python threads.  Under the conn's
// out-mutex, if nothing is queued we writev() straight from the caller's
// buffers (common case: zero staging copies); only the unsent TAIL of a
// partial write is copied into the bounded per-conn write queue and
// EPOLLOUT is armed for the loop thread to drain.  Once anything is queued
// the whole frame is queued (frames must not interleave on the wire).
//
// Recv path: a per-conn state machine reads the [u32 magic][u64 len]
// header, then malloc()s the payload ONCE and reads directly into it —
// ps_van_recv hands that same buffer to Python (no tcpvan-style memcpy on
// dequeue); Python decodes zero-copy views over it and frees it when the
// last view dies.  Inbound backpressure: when the shared frame queue hits
// max_queue the loop unregisters EPOLLIN on further-readable conns;
// ps_van_recv re-arms them (via eventfd) once the queue drains below half.
//
// Wire format is byte-identical to tcpvan: [u32 magic][u64 len][payload].

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>
#include <errno.h>
#include <fcntl.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50535641;  // "PSVA" — same wire as tcpvan
constexpr uint64_t kMaxFrame = 1ULL << 33;  // 8 GB sanity cap
constexpr size_t kMaxWriteQueue = 64ULL << 20;  // per-conn queued-byte bound
constexpr int kMaxIov = 64;  // syscall iovec cap; longer frames chunk

struct Frame {
  uint8_t* data = nullptr;  // malloc'd; ownership moves to ps_van_recv
  uint64_t len = 0;
  int conn_id = 0;
};

struct Conn {
  int fd = -1;
  int id = -1;
  std::atomic<bool> open{false};

  // ---- send side (out_mu): bounded queue of unsent bytes ----
  std::mutex out_mu;
  std::deque<std::vector<uint8_t>> outq;
  size_t outq_head_off = 0;  // consumed prefix of outq.front()
  size_t outq_bytes = 0;
  bool want_out = false;  // EPOLLOUT armed

  // ---- recv side (loop thread only): header/payload state machine ----
  uint8_t head_buf[12];
  size_t head_got = 0;
  uint8_t* body = nullptr;
  uint64_t body_len = 0, body_got = 0;
  // EPOLLIN dropped for inbound backpressure; atomic because arm() reads
  // it from sender threads while the loop thread flips it
  std::atomic<bool> paused{false};
};

struct VanImpl {
  int listen_fd = -1, epfd = -1, evfd = -1;
  int port = 0;
  std::thread loop_thread;
  std::atomic<bool> running{true};
  std::atomic<int> next_conn{0};

  std::mutex conns_mu;
  std::vector<std::unique_ptr<Conn>> conns;
  std::vector<Conn*> pending_reg;  // connects awaiting loop registration
  std::vector<int> pending_close;  // disconnects awaiting loop-side reap

  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<Frame> queue;
  size_t max_queue = 4096;
  bool resume_needed = false;  // conns paused; re-arm when queue drains

  std::atomic<int64_t> bytes_sent{0}, bytes_recv{0};
  std::atomic<int64_t> writeq_full{0};
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  if (fl >= 0) fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void wake_loop(VanImpl* van) {
  uint64_t one = 1;
  ssize_t r = ::write(van->evfd, &one, 8);
  (void)r;
}

void arm(VanImpl* van, Conn* c, bool out) {
  epoll_event ev{};
  ev.events = (c->paused.load() ? 0u : static_cast<uint32_t>(EPOLLIN)) |
              (out ? static_cast<uint32_t>(EPOLLOUT) : 0u) | EPOLLRDHUP;
  ev.data.ptr = c;
  epoll_ctl(van->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

// Queue the tail [done, total) of an iovec array (out_mu held).
void queue_tail(Conn* c, const iovec* iov, int n, size_t done) {
  for (int i = 0; i < n; ++i) {
    size_t len = iov[i].iov_len;
    if (done >= len) { done -= len; continue; }
    auto* base = static_cast<const uint8_t*>(iov[i].iov_base) + done;
    c->outq.emplace_back(base, base + (len - done));
    c->outq_bytes += len - done;
    done = 0;
  }
}

// Attempt a direct vectored write (out_mu held, outq empty).  Returns bytes
// written, or -1 on a fatal socket error.
ssize_t try_writev(Conn* c, const iovec* iov, int n, size_t total) {
  size_t done = 0;
  int idx = 0;
  iovec local[kMaxIov];
  while (done < total) {
    // skip fully-written segments, adjust the partially-written one
    size_t skip = done;
    int li = 0;
    for (int i = idx; i < n && li < kMaxIov; ++i) {
      size_t len = iov[i].iov_len;
      if (skip >= len) { skip -= len; idx = i + 1; continue; }
      local[li].iov_base = static_cast<uint8_t*>(iov[i].iov_base) + skip;
      local[li].iov_len = len - skip;
      skip = 0;
      ++li;
    }
    ssize_t w = ::writev(c->fd, local, li);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) return static_cast<ssize_t>(done);
      if (errno == EINTR) continue;
      return -1;
    }
    done += static_cast<size_t>(w);
    if (static_cast<size_t>(w) == 0) return static_cast<ssize_t>(done);
    // a short write means the socket buffer is full: stop, queue the rest
    if (done < total) {
      // recompute from 'done' on the next loop iteration only if the
      // kernel took the whole local batch; otherwise bail to the queue
      size_t batch = 0;
      for (int i = 0; i < li; ++i) batch += local[i].iov_len;
      if (static_cast<size_t>(w) < batch) return static_cast<ssize_t>(done);
    }
  }
  return static_cast<ssize_t>(done);
}

// Common send body: frame the payload segments and write/queue them.
int send_segments(VanImpl* van, int conn_id, const uint8_t* const* bufs,
                  const int64_t* lens, int nseg) {
  Conn* conn = nullptr;
  {
    std::lock_guard<std::mutex> lk(van->conns_mu);
    for (auto& c : van->conns)
      if (c->id == conn_id) { conn = c.get(); break; }
  }
  if (!conn || !conn->open.load()) return -1;

  uint64_t total = 0;
  for (int i = 0; i < nseg; ++i) total += static_cast<uint64_t>(lens[i]);
  uint8_t header[12];
  memcpy(header, &kMagic, 4);
  memcpy(header + 4, &total, 8);

  iovec iov[kMaxIov];
  int n = 0;
  iov[n].iov_base = header;
  iov[n].iov_len = 12;
  ++n;
  for (int i = 0; i < nseg; ++i) {
    if (lens[i] == 0) continue;
    if (n == kMaxIov) return -3;  // caller retries via single-buffer path
    iov[n].iov_base = const_cast<uint8_t*>(bufs[i]);
    iov[n].iov_len = static_cast<size_t>(lens[i]);
    ++n;
  }
  size_t wire = 12 + total;

  bool dead = false;
  int rc = 0;
  {
    // lock order: out_mu is a LEAF — never acquire conns_mu/q_mu under it
    // (the loop thread's reap path takes conns_mu -> out_mu)
    std::lock_guard<std::mutex> lk(conn->out_mu);
    if (!conn->open.load()) return -1;
    size_t done = 0;
    if (conn->outq.empty()) {
      ssize_t w = try_writev(conn, iov, n, wire);
      if (w < 0) {
        conn->open.store(false);
        dead = true;
      } else {
        done = static_cast<size_t>(w);
      }
    }
    if (!dead && done < wire) {
      // bounded queue: admit the whole frame or none (frames never split
      // ACROSS the admission decision — partial direct writes above are
      // already on the wire and their tail MUST queue regardless)
      if (done == 0 && conn->outq_bytes + wire > kMaxWriteQueue) {
        van->writeq_full.fetch_add(1);
        return -2;
      }
      queue_tail(conn, iov, n, done);
      if (!conn->want_out) {
        conn->want_out = true;
        arm(van, conn, true);
        wake_loop(van);
      }
    }
  }
  if (dead) {
    {
      std::lock_guard<std::mutex> clk(van->conns_mu);
      van->pending_close.push_back(conn->id);
    }
    wake_loop(van);
    return -1;
  }
  van->bytes_sent += static_cast<int64_t>(wire);
  return rc;
}

void push_frame(VanImpl* van, Frame&& f, bool* paused_any) {
  std::lock_guard<std::mutex> lk(van->q_mu);
  van->queue.push_back(std::move(f));
  if (van->queue.size() >= van->max_queue) {
    *paused_any = true;  // loop pauses EPOLLIN on the conns it services
    van->resume_needed = true;
  }
}

// Drain readable bytes on a conn (loop thread).  Returns false when the
// conn died (EOF / error / oversized frame).
bool service_read(VanImpl* van, Conn* c) {
  for (;;) {
    {
      std::lock_guard<std::mutex> lk(van->q_mu);
      if (van->queue.size() >= van->max_queue) {
        // inbound backpressure: stop reading this conn until Python drains
        van->resume_needed = true;
        c->paused = true;
        std::lock_guard<std::mutex> olk(c->out_mu);
        arm(van, c, c->want_out);
        return true;
      }
    }
    if (c->head_got < 12) {
      ssize_t r = ::recv(c->fd, c->head_buf + c->head_got, 12 - c->head_got, 0);
      if (r == 0) return false;
      if (r < 0)
        return errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR;
      c->head_got += static_cast<size_t>(r);
      if (c->head_got < 12) return true;
      uint32_t magic;
      memcpy(&magic, c->head_buf, 4);
      memcpy(&c->body_len, c->head_buf + 4, 8);
      if (magic != kMagic || c->body_len > kMaxFrame) return false;
      c->body = static_cast<uint8_t*>(
          malloc(c->body_len ? c->body_len : 1));
      c->body_got = 0;
      if (!c->body) return false;
    }
    while (c->body_got < c->body_len) {
      ssize_t r = ::recv(c->fd, c->body + c->body_got,
                         c->body_len - c->body_got, 0);
      if (r == 0) return false;
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          return true;
        return false;
      }
      c->body_got += static_cast<size_t>(r);
    }
    // complete frame: hand the malloc'd buffer to the shared queue
    van->bytes_recv += static_cast<int64_t>(c->body_len) + 12;
    Frame f;
    f.data = c->body;
    f.len = c->body_len;
    f.conn_id = c->id;
    c->body = nullptr;
    c->head_got = 0;
    bool paused = false;
    push_frame(van, std::move(f), &paused);
    van->q_cv.notify_all();
  }
}

// Flush the queued tail on EPOLLOUT (loop thread).
bool service_write(VanImpl* van, Conn* c) {
  std::lock_guard<std::mutex> lk(c->out_mu);
  while (!c->outq.empty()) {
    iovec iov[kMaxIov];
    int n = 0;
    size_t off = c->outq_head_off;
    for (auto& chunk : c->outq) {
      if (n == kMaxIov) break;
      iov[n].iov_base = chunk.data() + off;
      iov[n].iov_len = chunk.size() - off;
      off = 0;
      ++n;
    }
    ssize_t w = ::writev(c->fd, iov, n);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
        return true;
      return false;
    }
    size_t left = static_cast<size_t>(w);
    c->outq_bytes -= left;
    while (left > 0 && !c->outq.empty()) {
      size_t avail = c->outq.front().size() - c->outq_head_off;
      if (left >= avail) {
        left -= avail;
        c->outq.pop_front();
        c->outq_head_off = 0;
      } else {
        c->outq_head_off += left;
        left = 0;
      }
    }
  }
  c->want_out = false;
  arm(van, c, false);
  return true;
}

void reap_conn(VanImpl* van, Conn* c) {
  if (c->fd < 0) return;  // idempotent: already reaped
  epoll_ctl(van->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  ::close(c->fd);
  c->fd = -1;
  c->open.store(false);
  free(c->body);
  c->body = nullptr;
  {
    std::lock_guard<std::mutex> lk(c->out_mu);
    c->outq.clear();
    c->outq_bytes = 0;
  }
  Frame f;
  f.conn_id = -(c->id + 2);  // same closed-conn sentinel as tcpvan
  {
    std::lock_guard<std::mutex> lk(van->q_mu);
    van->queue.push_back(std::move(f));
  }
  van->q_cv.notify_all();
}

Conn* add_conn(VanImpl* van, int fd, bool from_loop) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  set_nonblock(fd);
  auto conn = std::make_unique<Conn>();
  conn->fd = fd;
  conn->id = van->next_conn++;
  conn->open.store(true);
  Conn* raw = conn.get();
  {
    std::lock_guard<std::mutex> lk(van->conns_mu);
    if (!van->running.load()) {
      ::close(fd);
      return nullptr;
    }
    van->conns.push_back(std::move(conn));
    if (from_loop) {
      epoll_event ev{};
      ev.events = EPOLLIN | EPOLLRDHUP;
      ev.data.ptr = raw;
      epoll_ctl(van->epfd, EPOLL_CTL_ADD, fd, &ev);
    } else {
      van->pending_reg.push_back(raw);
    }
  }
  if (!from_loop) wake_loop(van);
  return raw;
}

void event_loop(VanImpl* van) {
  constexpr int kMaxEvents = 256;
  epoll_event events[kMaxEvents];
  while (van->running.load()) {
    int n = epoll_wait(van->epfd, events, kMaxEvents, 200);
    if (!van->running.load()) break;
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    // cross-thread work: register fresh connects, reap dead conns, resume
    // paused conns once Python drained the queue
    {
      std::lock_guard<std::mutex> lk(van->conns_mu);
      for (Conn* c : van->pending_reg) {
        if (c->fd < 0) continue;
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLRDHUP;
        {
          std::lock_guard<std::mutex> olk(c->out_mu);
          if (c->want_out) ev.events |= EPOLLOUT;
        }
        ev.data.ptr = c;
        epoll_ctl(van->epfd, EPOLL_CTL_ADD, c->fd, &ev);
      }
      van->pending_reg.clear();
      for (int id : van->pending_close) {
        for (auto& c : van->conns)
          if (c->id == id && c->fd >= 0) reap_conn(van, c.get());
      }
      van->pending_close.clear();
    }
    bool resume = false;
    {
      std::lock_guard<std::mutex> lk(van->q_mu);
      if (van->resume_needed && van->queue.size() < van->max_queue / 2) {
        van->resume_needed = false;
        resume = true;
      }
    }
    if (resume) {
      std::lock_guard<std::mutex> lk(van->conns_mu);
      for (auto& c : van->conns) {
        if (c->paused && c->fd >= 0) {
          c->paused = false;
          std::lock_guard<std::mutex> olk(c->out_mu);
          arm(van, c.get(), c->want_out);
        }
      }
    }
    for (int i = 0; i < n; ++i) {
      if (events[i].data.ptr == nullptr) {  // eventfd tick: drain it
        uint64_t v;
        ssize_t r = ::read(van->evfd, &v, 8);
        (void)r;
        continue;
      }
      if (events[i].data.ptr == van) {  // listen fd
        for (;;) {
          int fd = ::accept(van->listen_fd, nullptr, nullptr);
          if (fd < 0) break;
          add_conn(van, fd, /*from_loop=*/true);
        }
        continue;
      }
      auto* c = static_cast<Conn*>(events[i].data.ptr);
      if (c->fd < 0) continue;  // reaped earlier this batch
      bool alive = true;
      if (events[i].events & EPOLLOUT) alive = service_write(van, c);
      if (alive && (events[i].events & (EPOLLIN | EPOLLRDHUP | EPOLLHUP |
                                        EPOLLERR)))
        alive = service_read(van, c);
      if (!alive) {
        std::lock_guard<std::mutex> lk(van->conns_mu);
        reap_conn(van, c);
      }
    }
  }
}

}  // namespace

extern "C" {

void* ps_van_new(const char* host, int port, int* actual_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = host && *host ? inet_addr(host) : INADDR_ANY;
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 1024) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  set_nonblock(fd);
  auto* van = new VanImpl();
  van->listen_fd = fd;
  van->port = ntohs(addr.sin_port);
  van->epfd = epoll_create1(0);
  van->evfd = eventfd(0, EFD_NONBLOCK);
  if (van->epfd < 0 || van->evfd < 0) {
    ::close(fd);
    if (van->epfd >= 0) ::close(van->epfd);
    if (van->evfd >= 0) ::close(van->evfd);
    delete van;
    return nullptr;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.ptr = van;  // listen marker
  epoll_ctl(van->epfd, EPOLL_CTL_ADD, fd, &ev);
  ev.events = EPOLLIN;
  ev.data.ptr = nullptr;  // eventfd marker
  epoll_ctl(van->epfd, EPOLL_CTL_ADD, van->evfd, &ev);
  if (actual_port) *actual_port = van->port;
  van->loop_thread = std::thread(event_loop, van);
  return van;
}

int ps_van_connect(void* vvan, const char* host, int port) {
  auto* van = static_cast<VanImpl*>(vvan);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = inet_addr(host);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  Conn* c = add_conn(van, fd, /*from_loop=*/false);
  return c ? c->id : -1;
}

int ps_van_send(void* vvan, int conn_id, const uint8_t* data, int64_t len) {
  const uint8_t* bufs[1] = {data};
  int64_t lens[1] = {len};
  int rc = send_segments(static_cast<VanImpl*>(vvan), conn_id, bufs, lens, 1);
  return rc == -2 ? -1 : rc;  // legacy contract: only 0 / -1
}

// Vectored send: 0 ok, -1 dead conn, -2 write queue full (typed
// backpressure), -3 too many segments (caller joins and retries).
int ps_van_send_vec(void* vvan, int conn_id, const uint8_t* const* bufs,
                    const int64_t* lens, int nseg) {
  return send_segments(static_cast<VanImpl*>(vvan), conn_id, bufs, lens, nseg);
}

int64_t ps_van_recv(void* vvan, double timeout_s, uint8_t** out_data,
                    int* out_conn) {
  auto* van = static_cast<VanImpl*>(vvan);
  Frame f;
  bool resume;
  {
    std::unique_lock<std::mutex> lk(van->q_mu);
    bool ok = van->q_cv.wait_for(
        lk, std::chrono::duration<double>(timeout_s),
        [van] { return !van->queue.empty() || !van->running.load(); });
    if (!van->running.load() && van->queue.empty()) return -3;
    if (!ok) return -1;
    f = std::move(van->queue.front());
    van->queue.pop_front();
    resume = van->resume_needed && van->queue.size() < van->max_queue / 2;
  }
  if (resume) wake_loop(van);  // loop re-arms paused conns
  if (f.conn_id < 0) {
    if (out_conn) *out_conn = -f.conn_id - 2;
    return -2;
  }
  if (out_conn) *out_conn = f.conn_id;
  // ZERO-COPY handoff: the recv state machine read straight into this
  // malloc'd buffer; Python decodes views over it and ps_van_free()s it.
  *out_data = f.data ? f.data : static_cast<uint8_t*>(malloc(1));
  return static_cast<int64_t>(f.len);
}

void ps_van_free(uint8_t* buf) { free(buf); }

void ps_van_disconnect(void* vvan, int conn_id) {
  auto* van = static_cast<VanImpl*>(vvan);
  {
    std::lock_guard<std::mutex> lk(van->conns_mu);
    bool found = false;
    for (auto& c : van->conns)
      if (c->id == conn_id && c->fd >= 0) { found = true; break; }
    if (!found) return;
    van->pending_close.push_back(conn_id);
  }
  wake_loop(van);
}

int64_t ps_van_bytes_sent(void* vvan) {
  return static_cast<VanImpl*>(vvan)->bytes_sent.load();
}
int64_t ps_van_bytes_recv(void* vvan) {
  return static_cast<VanImpl*>(vvan)->bytes_recv.load();
}
int64_t ps_van_writeq_full(void* vvan) {
  return static_cast<VanImpl*>(vvan)->writeq_full.load();
}
int ps_van_port(void* vvan) { return static_cast<VanImpl*>(vvan)->port; }

void ps_van_close(void* vvan) {
  auto* van = static_cast<VanImpl*>(vvan);
  van->running.store(false);
  wake_loop(van);
  if (van->loop_thread.joinable()) van->loop_thread.join();
  ::close(van->listen_fd);
  {
    std::lock_guard<std::mutex> lk(van->conns_mu);
    for (auto& c : van->conns) {
      if (c->fd >= 0) ::close(c->fd);
      free(c->body);
    }
  }
  {
    std::lock_guard<std::mutex> lk(van->q_mu);
    for (auto& f : van->queue) free(f.data);
    van->queue.clear();
  }
  van->q_cv.notify_all();
  ::close(van->epfd);
  ::close(van->evfd);
  delete van;
}

}  // extern "C"
