// Native TCP transport core for the DCN Van.
//
// The reference's Van owns ZeroMQ sockets, a node table, and a receive
// thread (``src/system/van.h/.cc`` [U] — SURVEY.md #2).  On TPU the ICI data
// plane is XLA collectives; what remains for a wire transport is the DCN /
// control plane: async Push/Pull between hosts.  This file is that wire:
// length-prefixed frames over TCP, one recv thread per connection, a shared
// inbound frame queue drained by the Python dispatch thread.
//
// Scope split: C++ owns sockets, framing, threads, and the queue (the
// perf-critical, syscall-heavy part); Python owns routing, serialization,
// and handlers.  ABI is plain C for ctypes.
//
// Frame format on the wire: [u32 magic][u64 payload_len][payload bytes].

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50535641;  // "PSVA"

struct Frame {
  std::vector<uint8_t> data;
  int conn_id;
};

struct Conn {
  int fd = -1;
  int id = -1;
  std::thread recv_thread;
  std::mutex send_mu;
  std::atomic<bool> open{false};
};

struct VanImpl {
  int listen_fd = -1;
  int port = 0;
  std::thread accept_thread;
  std::atomic<bool> running{true};
  std::atomic<int> next_conn{0};

  std::mutex conns_mu;
  std::vector<std::unique_ptr<Conn>> conns;

  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<Frame> queue;
  // Backpressure bound: recv threads park when the Python side falls this
  // many frames behind, instead of buffering unboundedly.
  size_t max_queue = 4096;

  std::atomic<int64_t> bytes_sent{0}, bytes_recv{0};
};

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void recv_loop(VanImpl* van, Conn* conn) {
  while (van->running.load() && conn->open.load()) {
    uint32_t magic;
    uint64_t len;
    if (!read_full(conn->fd, &magic, 4) || magic != kMagic) break;
    if (!read_full(conn->fd, &len, 8)) break;
    if (len > (1ULL << 33)) break;  // 8 GB sanity cap: corrupt stream
    Frame f;
    f.conn_id = conn->id;
    f.data.resize(len);
    if (len && !read_full(conn->fd, f.data.data(), len)) break;
    van->bytes_recv += static_cast<int64_t>(len) + 12;
    {
      std::unique_lock<std::mutex> lk(van->q_mu);
      van->q_cv.wait(lk, [van, conn] {
        return van->queue.size() < van->max_queue || !van->running.load() ||
               !conn->open.load();
      });
      if (!van->running.load() || !conn->open.load()) break;
      van->queue.push_back(std::move(f));
    }
    van->q_cv.notify_all();
  }
  conn->open.store(false);
  // signal disconnect to the drainer with an empty sentinel frame
  {
    std::lock_guard<std::mutex> lk(van->q_mu);
    Frame f;
    f.conn_id = -(conn->id + 2);  // negative = conn closed marker
    van->queue.push_back(std::move(f));
  }
  van->q_cv.notify_all();
}

Conn* add_conn(VanImpl* van, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto conn = std::make_unique<Conn>();
  conn->fd = fd;
  conn->id = van->next_conn++;
  conn->open.store(true);
  Conn* raw = conn.get();
  // Everything (including the thread start) happens under conns_mu so
  // ps_van_close can never observe a half-constructed entry, and a conn
  // accepted concurrently with close() gets shut down here instead of
  // being missed by close()'s shutdown sweep (which may already have run).
  std::lock_guard<std::mutex> lk(van->conns_mu);
  if (!van->running.load()) {
    raw->open.store(false);
    ::shutdown(fd, SHUT_RDWR);
  }
  raw->recv_thread = std::thread(recv_loop, van, raw);
  van->conns.push_back(std::move(conn));
  return raw;
}

void accept_loop(VanImpl* van) {
  while (van->running.load()) {
    sockaddr_in addr{};
    socklen_t alen = sizeof(addr);
    int fd = ::accept(van->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    if (fd < 0) {
      if (!van->running.load()) return;
      continue;
    }
    add_conn(van, fd);
  }
}

Conn* get_conn(VanImpl* van, int conn_id) {
  std::lock_guard<std::mutex> lk(van->conns_mu);
  for (auto& c : van->conns)
    if (c->id == conn_id) return c.get();
  return nullptr;
}

}  // namespace

extern "C" {

// Create a Van bound to host:port (port 0 = ephemeral). Returns handle or
// nullptr; *actual_port receives the bound port.
void* ps_van_new(const char* host, int port, int* actual_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = host && *host ? inet_addr(host) : INADDR_ANY;
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  auto* van = new VanImpl();
  van->listen_fd = fd;
  van->port = ntohs(addr.sin_port);
  if (actual_port) *actual_port = van->port;
  van->accept_thread = std::thread(accept_loop, van);
  return van;
}

// Connect to a peer. Returns conn id >= 0, or -1 on failure.
int ps_van_connect(void* vvan, const char* host, int port) {
  auto* van = static_cast<VanImpl*>(vvan);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = inet_addr(host);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  return add_conn(van, fd)->id;
}

// Send one frame on a connection. Returns 0 ok, -1 failure.
int ps_van_send(void* vvan, int conn_id, const uint8_t* data, int64_t len) {
  auto* van = static_cast<VanImpl*>(vvan);
  Conn* conn = get_conn(van, conn_id);
  if (!conn || !conn->open.load()) return -1;
  std::lock_guard<std::mutex> lk(conn->send_mu);
  uint64_t ulen = static_cast<uint64_t>(len);
  if (!write_full(conn->fd, &kMagic, 4) || !write_full(conn->fd, &ulen, 8) ||
      (len && !write_full(conn->fd, data, ulen))) {
    conn->open.store(false);
    return -1;
  }
  van->bytes_sent += len + 12;
  return 0;
}

// Wait for an inbound frame. Returns payload length (>= 0) and fills
// *out_data (malloc'd, free with ps_van_free) and *out_conn;
// -1 on timeout; -2 when a connection closed (out_conn = its id);
// -3 when the van is shut down.
int64_t ps_van_recv(void* vvan, double timeout_s, uint8_t** out_data,
                    int* out_conn) {
  auto* van = static_cast<VanImpl*>(vvan);
  std::unique_lock<std::mutex> lk(van->q_mu);
  bool ok = van->q_cv.wait_for(
      lk, std::chrono::duration<double>(timeout_s),
      [van] { return !van->queue.empty() || !van->running.load(); });
  if (!van->running.load() && van->queue.empty()) return -3;
  if (!ok) return -1;
  Frame f = std::move(van->queue.front());
  van->queue.pop_front();
  lk.unlock();
  van->q_cv.notify_all();  // wake parked recv threads (backpressure)
  if (f.conn_id < 0) {
    if (out_conn) *out_conn = -f.conn_id - 2;
    return -2;
  }
  if (out_conn) *out_conn = f.conn_id;
  auto* buf = static_cast<uint8_t*>(malloc(f.data.size() ? f.data.size() : 1));
  if (!f.data.empty()) memcpy(buf, f.data.data(), f.data.size());
  *out_data = buf;
  return static_cast<int64_t>(f.data.size());
}

void ps_van_free(uint8_t* buf) { free(buf); }

// Close one connection (fault injection / peer removal / failed-send reap).
// Fully reclaims the fd and recv thread; the Conn object itself stays in
// `conns` as a tombstone so raw pointers held by concurrent ps_van_send
// calls remain valid (send fails via open == false).
void ps_van_disconnect(void* vvan, int conn_id) {
  auto* van = static_cast<VanImpl*>(vvan);
  std::lock_guard<std::mutex> reap_lk(van->conns_mu);
  Conn* conn = nullptr;
  for (auto& c : van->conns)
    if (c->id == conn_id) { conn = c.get(); break; }
  if (!conn) return;
  if (conn->open.exchange(false)) ::shutdown(conn->fd, SHUT_RDWR);
  // Order the open=false store with the recv thread's backpressure predicate:
  // without holding q_mu between the store and the notify, the thread can
  // evaluate its predicate (open still true), lose the notify, then park
  // forever — and the join() below would wedge every caller on conns_mu.
  { std::lock_guard<std::mutex> qlk(van->q_mu); }
  van->q_cv.notify_all();  // wake its recv thread if parked on backpressure
  if (conn->recv_thread.joinable()) conn->recv_thread.join();
  std::lock_guard<std::mutex> send_lk(conn->send_mu);  // no in-flight writer
  if (conn->fd >= 0) {
    ::close(conn->fd);
    conn->fd = -1;
  }
}

int64_t ps_van_bytes_sent(void* vvan) {
  return static_cast<VanImpl*>(vvan)->bytes_sent.load();
}
int64_t ps_van_bytes_recv(void* vvan) {
  return static_cast<VanImpl*>(vvan)->bytes_recv.load();
}
int ps_van_port(void* vvan) { return static_cast<VanImpl*>(vvan)->port; }

void ps_van_close(void* vvan) {
  auto* van = static_cast<VanImpl*>(vvan);
  van->running.store(false);
  ::shutdown(van->listen_fd, SHUT_RDWR);
  ::close(van->listen_fd);
  {
    std::lock_guard<std::mutex> lk(van->conns_mu);
    for (auto& c : van->conns)
      if (c->open.exchange(false)) ::shutdown(c->fd, SHUT_RDWR);
  }
  // Same lost-wakeup ordering as ps_van_disconnect: a recv thread parked on
  // the backpressure predicate must observe running/open flipped before the
  // notify, or the joins below hang.
  { std::lock_guard<std::mutex> qlk(van->q_mu); }
  van->q_cv.notify_all();
  if (van->accept_thread.joinable()) van->accept_thread.join();
  {
    std::lock_guard<std::mutex> lk(van->conns_mu);
    for (auto& c : van->conns) {
      if (c->recv_thread.joinable()) c->recv_thread.join();
      if (c->fd >= 0) ::close(c->fd);  // -1 = already reaped by disconnect
    }
  }
  delete van;
}

}  // extern "C"
