// Native text parsers for the host data path.
//
// The reference keeps its example parsers in C++ because text parsing is the
// CPU-bound half of sparse training (``src/data/text_parser.h/.cc``,
// ``src/data/slot_reader.h`` [U] — see SURVEY.md #18); we do the same.  Two
// formats:
//
//   libsvm:  "<label> <idx>:<val> <idx>:<val> ...\n"   -> CSR batch
//   criteo:  "<label>\t<13 ints>\t<26 hex cats>\n"     -> dense + hashed keys
//
// Exposed as a plain C ABI loaded via ctypes (no pybind11 in this image).
// Contract with the Python side (data/text.py): two-call protocol — count()
// sizes the output arrays, fill() parses into caller-allocated numpy buffers.
// Both calls are single pass over the buffer per thread; fill() splits the
// buffer at line boundaries across nthreads worker threads.
//
// Key hashing MUST stay bit-identical to utils/keys.py::mix64 (splitmix64
// finalizer, same constants) — tests assert C++ vs numpy parity.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kMixMul1 = 0xFF51AFD7ED558CCDULL;
constexpr uint64_t kMixMul2 = 0xC4CEB9FE1A85EC53ULL;

inline uint64_t mix64(uint64_t x, uint64_t seed) {
  x = (x ^ seed) * kMixMul1;
  x ^= x >> 33;
  x *= kMixMul2;
  x ^= x >> 33;
  return x;
}

// Sentinel mixed per-slot for missing criteo categorical fields.
constexpr uint64_t kMissingCat = 0xFFFFFFFFFFFFFFFEULL;

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline double parse_float(const char* p, const char* end, const char** out) {
  // Hand-rolled strtod subset: [-+]?digits[.digits][eE[-+]digits].
  // Avoids strtod's locale + NUL-termination requirements on a mmap'd
  // buffer.  No digits in the mantissa => *out == input p (no consumption),
  // which callers use to detect malformed fields.  The Python fallback
  // (_float_prefix in data/text.py) mirrors this function bit-for-bit.
  const char* start = p;
  bool neg = false;
  if (p < end && (*p == '+' || *p == '-')) neg = (*p++ == '-');
  double v = 0.0;
  int digits = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10.0 + (*p++ - '0');
    ++digits;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      v += (*p++ - '0') * scale;
      scale *= 0.1;
      ++digits;
    }
  }
  if (digits == 0) {
    *out = start;
    return 0.0;
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '+' || *p == '-')) eneg = (*p++ == '-');
    int ex = 0;
    // saturate: any exponent > 9999 already over/underflows double, and an
    // unchecked accumulator is signed-int-overflow UB on 10+ digit exponents
    while (p < end && *p >= '0' && *p <= '9') {
      if (ex < 10000) ex = ex * 10 + (*p - '0');
      ++p;
    }
    if (v != 0.0) v *= std::pow(10.0, eneg ? -ex : ex);  // avoid 0*inf = nan
  }
  *out = p;
  return neg ? -v : v;
}

inline uint64_t parse_u64(const char* p, const char* end, const char** out) {
  uint64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
  *out = p;
  return v;
}

inline int hex_digit(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

// Split [buf, buf+len) into nchunks at line boundaries. Returns nchunks+1
// offsets; chunk i is [off[i], off[i+1]) and starts at a line start.
std::vector<int64_t> line_chunks(const char* buf, int64_t len, int nchunks) {
  std::vector<int64_t> off(1, 0);
  for (int i = 1; i < nchunks; ++i) {
    int64_t target = len * i / nchunks;
    if (target <= off.back()) target = off.back();
    const void* nl = memchr(buf + target, '\n', len - target);
    int64_t cut = nl ? (static_cast<const char*>(nl) - buf) + 1 : len;
    off.push_back(cut);
  }
  off.push_back(len);
  return off;
}

void run_chunks(const char* buf, int64_t len, int nthreads,
                const std::vector<int64_t>& off,
                void (*fn)(const char*, const char*, int, void*), void* ctx) {
  int n = static_cast<int>(off.size()) - 1;
  if (nthreads <= 1 || n <= 1) {
    for (int i = 0; i < n; ++i) fn(buf + off[i], buf + off[i + 1], i, ctx);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n);
  for (int i = 0; i < n; ++i)
    threads.emplace_back(fn, buf + off[i], buf + off[i + 1], i, ctx);
  for (auto& t : threads) t.join();
}

// ---------------------------------------------------------------- libsvm ---

struct LibsvmCounts {
  std::vector<int64_t> rows, nnz;
};

inline bool at_token_end(const char* p, const char* end) {
  return p >= end || *p == ' ' || *p == '\t' || *p == '\r' || *p == '\n';
}

inline const char* skip_token(const char* p, const char* end) {
  while (!at_token_end(p, end)) ++p;
  return p;
}

// Parse one "key:value" feature token. Returns true iff well-formed: key is
// all digits, optional ":value" where value is a non-empty numeric, and the
// token terminates at whitespace/EOL.  Malformed tokens are skipped whole
// (never partially consumed — guarantees forward progress; the Python
// fallback applies the same accept/skip rule, keeping parity).
inline bool parse_feature(const char* p, const char* end, const char** out,
                          uint64_t* key, float* val) {
  const char* start = p;
  const char* q;
  *key = parse_u64(p, end, &q);
  if (q == p) {  // no digits: malformed (qid:, comments handled by caller)
    *out = skip_token(p, end);
    return false;
  }
  p = q;
  *val = 1.0f;
  if (p < end && *p == ':') {
    ++p;
    *val = static_cast<float>(parse_float(p, end, &q));
    if (q == p) {  // empty/non-numeric value
      *out = skip_token(start, end);
      return false;
    }
    p = q;
  }
  if (!at_token_end(p, end)) {  // trailing junk glued to the token
    *out = skip_token(start, end);
    return false;
  }
  *out = p;
  return true;
}

void libsvm_count_chunk(const char* p, const char* end, int idx, void* vctx) {
  auto* ctx = static_cast<LibsvmCounts*>(vctx);
  int64_t rows = 0, nnz = 0;
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') { ++p; continue; }  // blank line
    if (*p == '#') {  // full-line comment (fallback parity)
      while (p < end && *p != '\n') ++p;
      continue;
    }
    ++rows;
    // label: numeric prefix; junk label parses as 0 and is token-skipped
    const char* q;
    parse_float(p, end, &q);
    p = (q == p) ? skip_token(p, end) : q;
    // features
    while (p < end && *p != '\n') {
      p = skip_ws(p, end);
      if (p >= end || *p == '\n') break;
      if (*p == '#') {  // trailing comment: skip to EOL
        while (p < end && *p != '\n') ++p;
        break;
      }
      uint64_t key;
      float val;
      if (parse_feature(p, end, &q, &key, &val)) ++nnz;
      p = q;
    }
    if (p < end) ++p;  // consume '\n'
  }
  ctx->rows[idx] = rows;
  ctx->nnz[idx] = nnz;
}

struct LibsvmFill {
  float* labels;
  int64_t* indptr;       // [rows + 1], indptr[0] pre-set to 0 by Python
  uint64_t* indices;
  float* values;
  std::vector<int64_t> row_base, nnz_base;  // per-chunk output offsets
};

void libsvm_fill_chunk(const char* p, const char* end, int idx, void* vctx) {
  auto* ctx = static_cast<LibsvmFill*>(vctx);
  int64_t r = ctx->row_base[idx];
  int64_t k = ctx->nnz_base[idx];
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') { ++p; continue; }
    if (*p == '#') {
      while (p < end && *p != '\n') ++p;
      continue;
    }
    const char* q;
    ctx->labels[r] = static_cast<float>(parse_float(p, end, &q));
    p = (q == p) ? skip_token(p, end) : q;
    while (p < end && *p != '\n') {
      p = skip_ws(p, end);
      if (p >= end || *p == '\n') break;
      if (*p == '#') {
        while (p < end && *p != '\n') ++p;
        break;
      }
      uint64_t key;
      float val;
      if (parse_feature(p, end, &q, &key, &val)) {
        ctx->indices[k] = key;
        ctx->values[k] = val;
        ++k;
      }
      p = q;
    }
    ctx->indptr[r + 1] = k;
    ++r;
    if (p < end) ++p;
  }
}

// ---------------------------------------------------------------- criteo ---

struct CriteoCtx {
  std::vector<int64_t> rows;     // count phase
  float* labels = nullptr;       // fill phase
  float* dense = nullptr;        // [rows, n_dense]
  uint64_t* keys = nullptr;      // [rows, n_cat]
  std::vector<int64_t> row_base;
  int n_dense = 13, n_cat = 26;
};

inline bool line_blank(const char* p, const char* e) {
  // whitespace-only lines are skipped (fallback parity: line.strip())
  for (; p < e; ++p)
    if (*p != ' ' && *p != '\t' && *p != '\r') return false;
  return true;
}

void criteo_count_chunk(const char* p, const char* end, int idx, void* vctx) {
  auto* ctx = static_cast<CriteoCtx*>(vctx);
  int64_t rows = 0;
  while (p < end) {
    const void* nl = memchr(p, '\n', end - p);
    const char* e = nl ? static_cast<const char*>(nl) : end;
    if (!line_blank(p, e)) ++rows;
    p = e + 1;
  }
  ctx->rows[idx] = rows;
}

void criteo_fill_chunk(const char* p, const char* end, int idx, void* vctx) {
  auto* ctx = static_cast<CriteoCtx*>(vctx);
  int64_t r = ctx->row_base[idx];
  const int nd = ctx->n_dense, nc = ctx->n_cat;
  while (p < end) {
    const void* nlv = memchr(p, '\n', end - p);
    const char* eol = nlv ? static_cast<const char*>(nlv) : end;
    if (line_blank(p, eol)) { p = eol + 1; continue; }
    // label: numeric prefix, then field-isolate (junk never desyncs columns)
    const char* q;
    ctx->labels[r] = static_cast<float>(parse_float(p, eol, &q));
    p = q;
    while (p < eol && *p != '\t') ++p;
    if (p < eol) ++p;
    // dense ints (may be empty between tabs -> 0, matching criteo missing);
    // junk after the numeric prefix is skipped so columns never desync
    float* drow = ctx->dense + r * nd;
    for (int i = 0; i < nd; ++i) {
      drow[i] = 0.0f;
      if (p < eol && *p != '\t') {
        drow[i] = static_cast<float>(parse_float(p, eol, &q));
        p = q;
      }
      while (p < eol && *p != '\t') ++p;  // field-isolate
      if (p < eol) ++p;
    }
    // categorical hex fields -> per-slot salted mix64 keys
    uint64_t* krow = ctx->keys + r * nc;
    for (int i = 0; i < nc; ++i) {
      uint64_t raw = 0;
      bool present = false;
      while (p < eol && *p != '\t') {
        int d = hex_digit(*p);
        if (d < 0) break;
        raw = (raw << 4) | static_cast<uint64_t>(d);
        present = true;
        ++p;
      }
      while (p < eol && *p != '\t') ++p;  // tolerate junk
      krow[i] = mix64(present ? raw : kMissingCat,
                      static_cast<uint64_t>(i) + 1);
      if (p < eol && *p == '\t') ++p;
    }
    ++r;
    p = eol + 1;
  }
}

}  // namespace

extern "C" {

// Count rows/nnz of a libsvm buffer.  Writes per-chunk counts into the
// caller-allocated chunk_rows/chunk_nnz (each of size nthreads) so the
// subsequent ps_libsvm_fill can place chunk output without re-counting —
// one count pass + one fill pass total.
void ps_libsvm_count(const char* buf, int64_t len, int nthreads,
                     int64_t* out_rows, int64_t* out_nnz,
                     int64_t* chunk_rows, int64_t* chunk_nnz) {
  int nt = nthreads > 0 ? nthreads : 1;
  auto off = line_chunks(buf, len, nt);
  int n = static_cast<int>(off.size()) - 1;
  LibsvmCounts ctx{std::vector<int64_t>(n, 0), std::vector<int64_t>(n, 0)};
  run_chunks(buf, len, nthreads, off, libsvm_count_chunk, &ctx);
  int64_t rows = 0, nnz = 0;
  for (int i = 0; i < n; ++i) {
    rows += ctx.rows[i];
    nnz += ctx.nnz[i];
    if (chunk_rows) chunk_rows[i] = ctx.rows[i];
    if (chunk_nnz) chunk_nnz[i] = ctx.nnz[i];
  }
  *out_rows = rows;
  *out_nnz = nnz;
}

// Fill caller-allocated CSR buffers (sized from ps_libsvm_count), with the
// per-chunk counts that call produced (same buf/len/nthreads required).
// indptr has rows+1 entries; this writes indptr[1..rows].
void ps_libsvm_fill(const char* buf, int64_t len, int nthreads,
                    const int64_t* chunk_rows, const int64_t* chunk_nnz,
                    float* labels, int64_t* indptr, uint64_t* indices,
                    float* values) {
  auto off = line_chunks(buf, len, nthreads > 0 ? nthreads : 1);
  int n = static_cast<int>(off.size()) - 1;
  LibsvmFill ctx;
  ctx.labels = labels;
  ctx.indptr = indptr;
  ctx.indices = indices;
  ctx.values = values;
  ctx.row_base.assign(n, 0);
  ctx.nnz_base.assign(n, 0);
  for (int i = 1; i < n; ++i) {
    ctx.row_base[i] = ctx.row_base[i - 1] + chunk_rows[i - 1];
    ctx.nnz_base[i] = ctx.nnz_base[i - 1] + chunk_nnz[i - 1];
  }
  indptr[0] = 0;
  run_chunks(buf, len, nthreads, off, libsvm_fill_chunk, &ctx);
}

void ps_criteo_count(const char* buf, int64_t len, int nthreads,
                     int64_t* out_rows, int64_t* chunk_rows) {
  auto off = line_chunks(buf, len, nthreads > 0 ? nthreads : 1);
  int n = static_cast<int>(off.size()) - 1;
  CriteoCtx ctx;
  ctx.rows.assign(n, 0);
  run_chunks(buf, len, nthreads, off, criteo_count_chunk, &ctx);
  int64_t rows = 0;
  for (int i = 0; i < n; ++i) {
    rows += ctx.rows[i];
    if (chunk_rows) chunk_rows[i] = ctx.rows[i];
  }
  *out_rows = rows;
}

void ps_criteo_fill(const char* buf, int64_t len, int nthreads,
                    const int64_t* chunk_rows, int n_dense, int n_cat,
                    float* labels, float* dense, uint64_t* keys) {
  auto off = line_chunks(buf, len, nthreads > 0 ? nthreads : 1);
  int n = static_cast<int>(off.size()) - 1;
  CriteoCtx ctx;
  ctx.labels = labels;
  ctx.dense = dense;
  ctx.keys = keys;
  ctx.n_dense = n_dense;
  ctx.n_cat = n_cat;
  ctx.row_base.assign(n, 0);
  for (int i = 1; i < n; ++i)
    ctx.row_base[i] = ctx.row_base[i - 1] + chunk_rows[i - 1];
  run_chunks(buf, len, nthreads, off, criteo_fill_chunk, &ctx);
}

// Exposed for hash-parity tests against utils/keys.py::mix64.
uint64_t ps_mix64(uint64_t x, uint64_t seed) { return mix64(x, seed); }

}  // extern "C"
