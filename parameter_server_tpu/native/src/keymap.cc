// Native persistent key->slot map: the Localizer hot path.
//
// The reference keeps the streaming-key vocabulary in the server's C++ hash
// map (``src/parameter/kv_map.h`` / ``src/util/localizer.h`` [U] —
// SURVEY.md #11/#20).  Here the map is host-side (the device table is a
// dense HBM array indexed by the slots this map hands out), and at Criteo
// rates (16k batch x 39 slots) a Python-level loop — or even vectorized
// numpy probing, which pays a full batch-sized temporary per probe round —
// is the bottleneck (VERDICT r1 weak #3).  This is a flat open-addressing
// table (linear probing, power-of-two size, load factor <= 1/2) with the
// exact assign() semantics of utils.keys.Localizer:
//
//   PAD_KEY (2^64-1)        -> capacity  (the trash row)
//   known key               -> its stable slot
//   new key, vocab not full -> next sequential id (arrival order)
//   new key, vocab full     -> key % capacity  (feature-hash overflow,
//                              NOT cached; sets the overflow flag)
//
// ABI is plain C for ctypes.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

constexpr uint64_t kEmpty = 0xFFFFFFFFFFFFFFFFull;  // == PAD_KEY

inline uint64_t mix64(uint64_t x) {
  // splitmix64 avalanche — same constants as utils.keys.mix64(seed=0), so
  // probe distributions match the Python fallback (not semantically
  // required, but keeps perf characteristics identical).
  x *= 0xFF51AFD7ED558CCDull;
  x ^= x >> 33;
  x *= 0xC4CEB9FE1A85EC53ull;
  x ^= x >> 33;
  return x;
}

struct KeyMap {
  int64_t capacity = 0;   // max vocab (slot ids are 0..capacity-1)
  int64_t n = 0;          // assigned vocab size
  uint64_t size = 0;      // table size, power of two
  uint64_t mask = 0;
  uint64_t* keys = nullptr;
  int32_t* vals = nullptr;
  bool overflowed = false;
  bool grow_failed = false;  // OOM latch: stop re-attempting huge mallocs

  bool alloc(uint64_t new_size) {
    uint64_t* new_keys =
        static_cast<uint64_t*>(malloc(new_size * sizeof(uint64_t)));
    int32_t* new_vals =
        static_cast<int32_t*>(malloc(new_size * sizeof(int32_t)));
    if (!new_keys || !new_vals) {  // OOM must not leave dangling pointers
      free(new_keys);
      free(new_vals);
      return false;
    }
    size = new_size;
    mask = new_size - 1;
    keys = new_keys;
    vals = new_vals;
    memset(keys, 0xFF, new_size * sizeof(uint64_t));  // all kEmpty
    return true;
  }

  bool grow() {
    uint64_t old_size = size;
    uint64_t* old_keys = keys;
    int32_t* old_vals = vals;
    if (!alloc(size * 2)) {
      // OOM: keep the old table intact.  The map still works — inserts
      // continue until the table is literally full; assign_one falls back
      // to feature hashing at capacity, so correctness is preserved.  The
      // latch stops every later insert from re-attempting the same
      // multi-hundred-MB malloc pair under memory pressure.
      keys = old_keys;
      vals = old_vals;
      size = old_size;
      mask = old_size - 1;
      grow_failed = true;
      return false;
    }
    for (uint64_t i = 0; i < old_size; ++i) {
      if (old_keys[i] == kEmpty) continue;
      uint64_t p = mix64(old_keys[i]) & mask;
      while (keys[p] != kEmpty) p = (p + 1) & mask;
      keys[p] = old_keys[i];
      vals[p] = old_vals[i];
    }
    free(old_keys);
    free(old_vals);
    return true;
  }

  // find-or-insert one key; returns its slot
  inline int32_t assign_one(uint64_t k) {
    uint64_t p = mix64(k) & mask;
    // Bounded probe: after grow()-OOM the load factor may exceed 1/2, and a
    // literally full table would otherwise spin forever on an absent key.
    for (uint64_t probes = 0; probes < size; ++probes) {
      uint64_t cur = keys[p];
      if (cur == k) return vals[p];
      if (cur == kEmpty) {
        if (n < capacity) {
          int32_t slot = static_cast<int32_t>(n++);
          keys[p] = k;
          vals[p] = slot;
          if (static_cast<uint64_t>(n) * 2 > size && !grow_failed) grow();
          return slot;
        }
        break;
      }
      p = (p + 1) & mask;
    }
    overflowed = true;
    return static_cast<int32_t>(k % static_cast<uint64_t>(capacity));
  }
};

}  // namespace

extern "C" {

void* ps_keymap_new(int64_t capacity) {
  if (capacity <= 0) return nullptr;
  auto* m = new KeyMap();
  m->capacity = capacity;
  if (!m->alloc(1 << 16)) {  // OOM -> nullptr; Python raises MemoryError
    delete m;
    return nullptr;
  }
  return m;
}

void ps_keymap_free(void* h) {
  auto* m = static_cast<KeyMap*>(h);
  if (!m) return;
  free(m->keys);
  free(m->vals);
  delete m;
}

int64_t ps_keymap_len(void* h) { return static_cast<KeyMap*>(h)->n; }

int ps_keymap_overflowed(void* h) {
  return static_cast<KeyMap*>(h)->overflowed ? 1 : 0;
}

// Assign slots for n keys (PAD -> capacity). Sequential; insertion order is
// the arrival order, matching the Python Localizer exactly.
void ps_keymap_assign(void* h, const uint64_t* in, int64_t n, int32_t* out) {
  auto* m = static_cast<KeyMap*>(h);
  const int32_t trash = static_cast<int32_t>(m->capacity);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t k = in[i];
    out[i] = (k == kEmpty) ? trash : m->assign_one(k);
  }
}

}  // extern "C"
