"""Native (C++) components, built lazily with g++ and loaded via ctypes.

The reference builds its host-perf-critical paths (text parsers, transport)
in C++; we do the same (SURVEY.md §2 native checklist).  pybind11 is not in
this image, so the ABI is plain ``extern "C"`` + ctypes.

:func:`load` compiles ``src/<name>.cc`` into ``lib/<name>.so`` on first use
(cached; rebuilt when the source is newer) and returns the loaded CDLL, or
``None`` when no toolchain is available — callers must degrade to their
Python fallbacks so the package works on toolchain-less hosts.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "lib")
_CXX = os.environ.get("PS_CXX", "g++")
_FLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread", "-Wall"]

_lock = threading.Lock()
_cache: dict[str, Optional[ctypes.CDLL]] = {}


class NativeBuildError(RuntimeError):
    pass


def _build(name: str) -> str:
    src = os.path.join(_SRC_DIR, f"{name}.cc")
    out = os.path.join(_LIB_DIR, f"{name}.so")
    if not os.path.exists(src):
        raise NativeBuildError(f"no native source {src}")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    os.makedirs(_LIB_DIR, exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    cmd = [_CXX, *_FLAGS, src, "-o", tmp]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native build failed: {' '.join(cmd)}\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp, out)  # atomic vs concurrent builders in other processes
    return out


def load(name: str, *, required: bool = False) -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library ``name``.

    Returns None if the toolchain is missing/broken unless ``required``.
    Disable entirely with ``PS_NO_NATIVE=1`` (forces Python fallbacks).
    """
    with _lock:
        if name in _cache and not required:
            return _cache[name]
        if name in _cache and _cache[name] is not None:
            return _cache[name]
        if os.environ.get("PS_NO_NATIVE") and not required:
            _cache[name] = None
            return None
        try:
            path = _build(name)
            lib = ctypes.CDLL(path)
        except (NativeBuildError, OSError) as e:
            if required:
                raise
            _cache[name] = None
            return None
        _cache[name] = lib
        return lib
