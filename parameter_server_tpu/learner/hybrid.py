"""Hybrid LM trainer: PS-served embeddings + GSPMD-synchronous transformer.

BASELINE config #5 as specified ("Llama-3 8B hybrid PS-embeddings + XLA
allreduce transformer", SURVEY.md §7 step 7; the composition VERDICT r1
flagged missing): ONE training step combines both planes —

- **embedding rows ride the Van**: pulled from / pushed to a
  :class:`~parameter_server_tpu.kv.server.KVServer` through
  :class:`~parameter_server_tpu.kv.worker.KVWorker` (async timestamps,
  filter-capable, DCN-routable, elastic) with an
  :class:`~parameter_server_tpu.utils.keys.IdentityLocalizer` so token id ==
  table row (the reference's key-range partition over the vocabulary);
- **the dense body is synchronous GSPMD**: batch sharded over the mesh's
  ``data`` axis, params TP-sharded per ``parallel/tp.py``; XLA inserts the
  gradient allreduce (the "NCCL allreduce" half of the config).

Why this split scales: the embedding table is the memory giant (Llama-3 8B:
128k x 4096 x 4 B = 2.1 GB plus optimizer rows — and DLRM-class tables are
100x that) with *sparse* per-step access (only the batch's unique tokens),
exactly the PS access pattern; the body is dense compute, exactly the GSPMD
pattern.  Serving rows from PS also admits staleness: pushes are not waited
on individually but bounded by a delay window τ (SSP; τ=0 = BSP), so
embedding traffic overlaps body compute — the reference's bounded-delay
pipelining (``Task.wait_time``) applied to the embedding plane.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from parameter_server_tpu.config import OptimizerConfig, TableConfig
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.models import transformer as tfm
from parameter_server_tpu.parallel import mesh as mesh_lib
from parameter_server_tpu.parallel.tp import place_params
from parameter_server_tpu.utils import metrics as metrics_lib
from parameter_server_tpu.utils.keys import IdentityLocalizer
from parameter_server_tpu.utils.trace import NULL_TRACER


def embedding_table_cfg(
    cfg: tfm.TransformerConfig,
    *,
    learning_rate: float = 0.05,
    optimizer: str = "adagrad",
) -> TableConfig:
    """KV table config for the PS-served embedding: row per token id."""
    return TableConfig(
        name="emb",
        rows=cfg.vocab_size,
        dim=cfg.d_model,
        optimizer=OptimizerConfig(kind=optimizer, learning_rate=learning_rate),
        init_scale=0.02,  # normal(0.02) rows, matching the dense init
    )


def embedding_localizers(cfg: tfm.TransformerConfig) -> Dict[str, object]:
    """Localizer map for :class:`KVWorker`: identity (token id == row)."""
    return {"emb": IdentityLocalizer(cfg.vocab_size)}


class HybridLMTrainer:
    """One step = Van pull (rows) -> GSPMD body fwd/bwd -> Van push (grads).

    ``max_delay``: how many embedding pushes may be in flight before the
    next step blocks on the oldest ack (τ of SSP; 0 = BSP, every push
    waited before the next pull).
    """

    def __init__(
        self,
        cfg: tfm.TransformerConfig,
        mesh,
        worker: KVWorker,
        *,
        table: str = "emb",
        learning_rate: float = 1e-3,
        max_delay: int = 0,
        seed: int = 0,
        dashboard: Optional[metrics_lib.Dashboard] = None,
        push_timeout: float = 60.0,
        tracer=None,
        loss_chunk: int = 0,
    ) -> None:
        """``loss_chunk > 0`` fuses the lm_head into the rematerialized
        chunked loss (``chunked_causal_lm_loss``): the f32 [B, S, vocab]
        logits never materialize — one of the three knobs (with
        ``cfg.scan_blocks`` and ``cfg.remat``) that fit the 8B body on a
        v5e-16 (see ``parallel/feasibility.py``)."""
        if cfg.tie_embeddings:
            raise ValueError(
                "hybrid requires untied embeddings: the lm_head is dense "
                "(GSPMD), the input table is PS-served"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.worker = worker
        self.table = table
        self.max_delay = max_delay
        self.push_timeout = push_timeout
        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        self.body = tfm.TransformerBody(cfg)
        self.tx = optax.adamw(learning_rate)
        x0 = jnp.zeros((1, 8, cfg.d_model), jnp.float32)
        params = self.body.init(jax.random.PRNGKey(seed), x0)["params"]
        self.params = place_params(params, mesh)
        self.opt_state = self.tx.init(self.params)
        self._batch3 = mesh_lib.batch_sharding(mesh, 3)
        self._batch2 = mesh_lib.batch_sharding(mesh, 2)
        self._inflight: collections.deque[int] = collections.deque()
        #: (pull_ts, tokens) announced via ``step(next_tokens=...)``
        self._prefetch: Optional[tuple] = None
        self.tracer = tracer or NULL_TRACER
        self.step_count = 0
        body, tx = self.body, self.tx

        if loss_chunk > 0:
            trunk = tfm.TransformerTrunk(cfg)

            def loss_fn(params, emb_in, targets):
                hidden = trunk.apply(
                    {
                        "params": {
                            k: v for k, v in params.items() if k != "lm_head"
                        }
                    },
                    emb_in,
                )
                return tfm.chunked_causal_lm_loss(
                    hidden, params["lm_head"]["kernel"], targets, loss_chunk
                )

        else:

            def loss_fn(params, emb_in, targets):
                logits = body.apply({"params": params}, emb_in)
                return tfm.causal_lm_loss(logits, targets)

        batch3 = self._batch3

        def step_fn(params, opt_state, emb_in, targets):
            # grads w.r.t. (params, emb_in): the emb_in gradient is what
            # flows back to the PS table as per-position row updates
            (loss, grads) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                params, emb_in, targets
            )
            g_params, g_emb = grads
            # pin the embedding gradient to the batch sharding: each pod
            # host then extracts exactly ITS batch rows from addressable
            # shards for the local Van push (no cross-host gather)
            g_emb = jax.lax.with_sharding_constraint(g_emb, batch3)
            updates, opt_state = tx.update(g_params, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, g_emb

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))
        #: body parameter count for the MFU column (6ND rule: fwd+bwd train
        #: FLOPs ~ 6 x params x tokens; set per step since the sequence
        #: length rides the batch).  Public: bench --hybrid reuses it so the
        #: two MFU computations cannot drift.
        self.n_body_params = sum(
            int(np.prod(p.shape)) for p in jax.tree.leaves(self.params)
        )

    def _local_batch_rows(self, arr: jax.Array, sl: slice) -> np.ndarray:
        """This process's rows ``[sl]`` of a batch-sharded global array.

        Reads only addressable shards (no cross-host transfer): the array is
        constrained to the batch sharding, whose data-axis layout is
        process-major — a host's devices hold exactly its batch slice
        (model-axis replicas repeat rows; idempotent overwrite).
        """
        shape = (sl.stop - sl.start,) + tuple(arr.shape[1:])
        out = np.zeros(shape, np.float32)
        for shard in arr.addressable_shards:
            r = shard.index[0]
            start = 0 if r.start is None else int(r.start)
            stop = arr.shape[0] if r.stop is None else int(r.stop)
            # a non-process-major data-axis layout would put addressable
            # rows OUTSIDE this process's slice; the Python slice below
            # would then silently write wrong rows — fail loudly instead
            # (ADVICE r4)
            if not (sl.start <= start and stop <= sl.stop):
                raise AssertionError(
                    f"addressable shard rows [{start}, {stop}) fall outside "
                    f"this process's batch slice [{sl.start}, {sl.stop}) — "
                    "mesh data-axis layout is not process-major"
                )
            out[start - sl.start : stop - sl.start] = np.asarray(shard.data)
        return out

    # -- the hybrid hot path -------------------------------------------------
    def step(
        self,
        tokens: np.ndarray,
        *,
        next_tokens: Optional[np.ndarray] = None,
        pull_timeout: float = 60.0,
    ) -> float:
        """tokens [B, S] -> loss.  Van pull + GSPMD step + Van push.

        Device-resident embedding plane (VERDICT r2 #2): rows arrive as
        device arrays (``pull_result_device``) and gradients leave as device
        arrays (``push_device``) — the only host traffic is the int32 token
        ids.  Pass ``next_tokens`` to PREFETCH the following step's rows:
        the pull is issued right after this step's body dispatch, so its Van
        latency hides behind device compute exactly like the push τ window
        hides ack latency (pulls get the same overlap pushes have).
        """
        tokens = np.asarray(tokens)
        # Dual-plane pod shape (VERDICT r3 #2): when the GSPMD mesh spans OS
        # processes, each process owns its local_batch_slice of the global
        # batch end to end — pulls only its rows' embeddings over ITS Van
        # connection, feeds them to its own devices
        # (make_array_from_process_local_data), and later pushes only its
        # rows' gradients.  Single-process runs keep the device-resident
        # reply path.
        multiproc = jax.process_count() > 1
        if multiproc:
            from parameter_server_tpu.parallel import distributed

            sl = distributed.local_batch_slice(
                jax.process_index(), jax.process_count(), tokens.shape[0]
            )
            tokens_feed = tokens[sl]
        else:
            sl = slice(0, tokens.shape[0])
            tokens_feed = tokens
        # 1) PS plane: this batch's embedding rows — from the prefetch if
        # step(t-1) announced them, else pulled synchronously now
        ts = None
        if self._prefetch is not None:
            pts, ptok = self._prefetch
            self._prefetch = None
            if ptok.shape == tokens.shape and np.array_equal(ptok, tokens):
                ts = pts
            else:  # caller deviated from the announced batch: drain + repull
                self.worker.pull_result(pts, timeout=pull_timeout)
        if ts is None:
            ts = self.worker.pull(self.table, tokens_feed)
        if multiproc:
            from parameter_server_tpu.parallel import distributed

            with self.tracer.span("hybrid.pull_wait"):
                emb_local = self.worker.pull_result(ts, timeout=pull_timeout)
            emb_d = distributed.host_local_batch(
                self._batch3,
                np.asarray(emb_local, np.float32),
                (tokens.shape[0], tokens.shape[1], self.cfg.d_model),
            )
            tok_d = distributed.host_local_batch(
                self._batch2,
                np.ascontiguousarray(tokens_feed.astype(np.int32)),
                tokens.shape,
            )
        else:
            with self.tracer.span("hybrid.pull_wait"):
                emb_in = self.worker.pull_result_device(
                    ts, timeout=pull_timeout
                )
            emb_d = jax.device_put(
                jnp.asarray(emb_in, jnp.float32), self._batch3
            )
            tok_d = jax.device_put(jnp.asarray(tokens, jnp.int32), self._batch2)
        # 2) dense plane: synchronous GSPMD body step (XLA allreduce).
        # Single-process: dispatch is async — the arrays below are futures,
        # so the prefetch and push issue while the body still runs on
        # device.  Multi-process: _local_batch_rows below must block on the
        # device step to read g_emb shards, so push/prefetch issue AFTER
        # device compute there (the overlap window is the Van RTT against
        # the NEXT step's host work, not against this body step).
        with self.tracer.span("hybrid.body_dispatch"):
            self.params, self.opt_state, loss, g_emb = self._step(
                self.params, self.opt_state, emb_d, tok_d
            )
        # 3) PS plane: push per-position embedding gradients device-to-device
        # (server-side optimizer applies them); bounded-delay, not per-push
        # blocking.  Push MUST precede the prefetch pull: both are async
        # submits, and per-link FIFO then guarantees the prefetched rows
        # include this step's update (pull-before-push would silently hand
        # back one-update-stale rows even at max_delay=0).
        if multiproc:
            g_local = self._local_batch_rows(g_emb, sl)
            ts = self.worker.push(
                self.table,
                tokens_feed.reshape(-1),
                g_local.reshape(-1, self.cfg.d_model),
            )
        else:
            ts = self.worker.push_device(
                self.table,
                tokens.reshape(-1),
                g_emb.reshape(-1, self.cfg.d_model),
            )
        # 4) prefetch the NEXT batch's rows while the body computes
        if next_tokens is not None:
            next_tokens = np.asarray(next_tokens)
            if multiproc:
                from parameter_server_tpu.parallel import distributed

                # slice by the NEXT batch's size (it may differ from this
                # step's), not this step's sl
                nsl = distributed.local_batch_slice(
                    jax.process_index(),
                    jax.process_count(),
                    next_tokens.shape[0],
                )
            else:
                nsl = slice(0, next_tokens.shape[0])
            self._prefetch = (
                self.worker.pull(self.table, next_tokens[nsl]),
                next_tokens,
            )
        self._inflight.append(ts)
        while len(self._inflight) > self.max_delay:
            old = self._inflight.popleft()
            if not self.worker.wait(old, timeout=self.push_timeout):
                raise TimeoutError(f"embedding push ts={old} not acked")
        self.step_count += 1
        with self.tracer.span("hybrid.loss_sync"):
            loss_f = float(loss)
        emb_mb = tokens.size * self.cfg.d_model * 4 * 2 / 1e6  # pull + push
        # one example = one sequence: 6 x body params x seq tokens
        self.dashboard.flops_per_example = (
            6.0 * self.n_body_params * tokens.shape[1]
        )
        self.dashboard.record(
            self.step_count,
            loss_f,
            examples=tokens.shape[0],
            extra={"emb_plane_mb": round(emb_mb, 3)},
        )
        return loss_f

    def drain(self) -> None:
        """Block until every in-flight embedding push is acked (epoch end).

        Also consumes a dangling announced prefetch — otherwise its kept
        responses (full embedding-row arrays under ``device_replies``) stay
        pinned in the Customer for the process lifetime.
        """
        while self._inflight:
            old = self._inflight.popleft()
            if not self.worker.wait(old, timeout=self.push_timeout):
                raise TimeoutError(f"embedding push ts={old} not acked")
        if self._prefetch is not None:
            pts, _ptok = self._prefetch
            self._prefetch = None
            self.worker.pull_result(pts, timeout=self.push_timeout)

    # -- checkpoint/resume for the WHOLE config-#5 state --------------------
    # The embedding plane already checkpoints through the PS machinery
    # (KVWorker.save_model -> per-server shards + manifest); the body's
    # params/adamw moments are the missing half.  Both planes commit under
    # one step so a resumed run is consistent across them.
    def save(self, root: str, step: int, *, timeout: float = 600.0) -> None:
        """Checkpoint emb table (PS shards) + body params/opt (npz)."""
        import os

        self.drain()  # every push applied before the server shards snapshot
        self.worker.save_model(root, step, timeout=timeout)
        flat = {}
        for i, leaf in enumerate(jax.tree.leaves(self.params)):
            flat[f"p{i}"] = self._full_host(leaf)
        for i, leaf in enumerate(jax.tree.leaves(self.opt_state)):
            flat[f"o{i}"] = self._full_host(leaf)
        if jax.process_index() == 0:
            path = os.path.join(root, f"hybrid_body_{step:06d}.npz")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **flat)
            os.replace(tmp, path)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"hybrid-ckpt-{step}")

    def restore(self, root: str, step: int, *, timeout: float = 600.0) -> None:
        """Restore both planes; the trainer continues mid-trajectory."""
        import os

        self.worker.load_model(root, step, timeout=timeout)
        path = os.path.join(root, f"hybrid_body_{step:06d}.npz")
        with np.load(path) as z:
            p_leaves = jax.tree.leaves(self.params)
            o_leaves = jax.tree.leaves(self.opt_state)
            new_p = [
                jax.device_put(z[f"p{i}"], leaf.sharding)
                for i, leaf in enumerate(p_leaves)
            ]
            new_o = [
                jax.device_put(
                    np.asarray(z[f"o{i}"], jax.tree.leaves(self.opt_state)[i].dtype),
                    leaf.sharding,
                )
                for i, leaf in enumerate(o_leaves)
            ]
        self.params = jax.tree.unflatten(
            jax.tree.structure(self.params), new_p
        )
        self.opt_state = jax.tree.unflatten(
            jax.tree.structure(self.opt_state), new_o
        )

    @staticmethod
    def _full_host(leaf) -> np.ndarray:
        """Host copy of a (possibly multi-process sharded) array."""
        if jax.process_count() > 1 and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(leaf, tiled=True)
            )
        return np.asarray(leaf)

    def logits(self, tokens: np.ndarray, *, pull_timeout: float = 60.0):
        tokens = np.asarray(tokens)
        emb_in = self.worker.pull_sync(self.table, tokens, timeout=pull_timeout)
        return np.asarray(
            self.body.apply(
                {"params": self.params}, jnp.asarray(emb_in, jnp.float32)
            )
        )
