"""Language-model trainers: BERT MLM and causal LM (Llama) over the mesh.

BASELINE configs #4/#5.  DP x TP: batch sharded over ``data``, params sharded
per ``parallel/tp.py`` (embedding rows over ``model`` = the PS-shard; XLA
emits the tensor-parallel collectives).  Optimizer state inherits the param
shardings (eager ``zeros_like`` preserves sharding), so the whole train state
is mesh-partitioned without further annotation.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from parameter_server_tpu.models import transformer as tfm
from parameter_server_tpu.parallel import mesh as mesh_lib
from parameter_server_tpu.utils import metrics as metrics_lib


def make_mlm_batch(
    tokens: np.ndarray, vocab_size: int, rng: np.random.Generator,
    mask_token: int = 0, mask_rate: float = 0.15,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """BERT masking: 15% positions; 80% [MASK], 10% random, 10% kept."""
    mask = rng.random(tokens.shape) < mask_rate
    r = rng.random(tokens.shape)
    inputs = tokens.copy()
    inputs[mask & (r < 0.8)] = mask_token
    rand_sites = mask & (r >= 0.8) & (r < 0.9)
    inputs[rand_sites] = rng.integers(
        0, vocab_size, size=int(rand_sites.sum()), dtype=tokens.dtype
    )
    return inputs, tokens, mask.astype(np.float32)


class SpmdLMTrainer:
    """DP x TP trainer for the transformer family."""

    def __init__(
        self,
        cfg: tfm.TransformerConfig,
        mesh,
        *,
        learning_rate: float = 1e-3,
        seed: int = 0,
        dashboard: Optional[metrics_lib.Dashboard] = None,
        fsdp: bool = False,
        loss_chunk: int = 0,
    ) -> None:
        """``fsdp=True`` shards params AND optimizer moments over the data
        axis besides the TP rules (see ``parallel/tp.py``); ``loss_chunk``
        > 0 computes the causal loss with the fused-head rematerialized
        chunks — the at-scale memory knobs, composable with
        ``cfg.scan_blocks``/``cfg.remat``."""
        self.cfg = cfg
        self.mesh = mesh
        self.model = tfm.Transformer(cfg)
        self.tx = optax.adamw(learning_rate)
        if loss_chunk > 0 and (not cfg.causal or cfg.tie_embeddings):
            raise ValueError(
                "loss_chunk requires a causal model with untied embeddings "
                "(the fused head reads params['lm_head'])"
            )
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = self.model.init(jax.random.PRNGKey(seed), tokens0)["params"]
        from parameter_server_tpu.parallel.tp import (
            transformer_param_shardings,
        )

        shardings = transformer_param_shardings(params, mesh, fsdp=fsdp)
        params = jax.tree.map(jax.device_put, params, shardings)
        self.params = params
        # optimizer state inherits param shardings through eager zeros_like
        self.opt_state = self.tx.init(self.params)
        self._batch2 = mesh_lib.batch_sharding(mesh, 2)
        model, tx = self.model, self.tx

        if cfg.causal and loss_chunk > 0:
            trunk = tfm.TransformerTrunk(cfg)

            def loss_fn(params, inputs, targets, mask):
                x = jnp.take(params["embedding"], inputs, axis=0)
                trunk_params = {
                    k: v
                    for k, v in params.items()
                    if k not in ("embedding", "lm_head")
                }
                hidden = trunk.apply({"params": trunk_params}, x)
                return tfm.chunked_causal_lm_loss(
                    hidden, params["lm_head"]["kernel"], targets, loss_chunk
                )

        elif cfg.causal:

            def loss_fn(params, inputs, targets, mask):
                logits = model.apply({"params": params}, inputs)
                return tfm.causal_lm_loss(logits, targets)

        else:

            def loss_fn(params, inputs, targets, mask):
                logits = model.apply({"params": params}, inputs)
                return tfm.mlm_loss(logits, targets, mask)

        def step_fn(params, opt_state, inputs, targets, mask):
            loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets, mask)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))

        # -- MFU wiring (VERDICT r3 weak #4): 6ND over the matmul-
        # participating params.  The input-embedding gather is not matmul
        # work UNLESS the table is tied (then it IS the lm_head projection);
        # positional embeddings are always a gather.
        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        drop = frozenset({"pos_embedding"}) | (
            frozenset() if cfg.tie_embeddings else frozenset({"embedding"})
        )
        self.n_matmul_params = metrics_lib.lm_matmul_params(
            self.params, drop
        )
        self.step_count = 0

    def _record(self, loss: float, batch: int, seq: int) -> None:
        self.step_count += 1
        # one example = one sequence: 6 x matmul params x seq tokens
        self.dashboard.flops_per_example = (
            6.0 * self.n_matmul_params * seq
        )
        self.dashboard.record(self.step_count, loss, examples=batch)

    # -- steps --------------------------------------------------------------
    def step_causal(self, tokens: np.ndarray) -> float:
        if not self.cfg.causal:
            raise ValueError("step_causal on a non-causal (MLM) trainer")
        tokens_d = jax.device_put(jnp.asarray(tokens, jnp.int32), self._batch2)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, tokens_d, tokens_d, tokens_d
        )
        loss_f = float(loss)
        self._record(loss_f, tokens.shape[0], tokens.shape[1])
        return loss_f

    def step_mlm(
        self, inputs: np.ndarray, targets: np.ndarray, mask: np.ndarray
    ) -> float:
        if self.cfg.causal:
            raise ValueError("step_mlm on a causal-LM trainer")
        put = lambda x, dt: jax.device_put(  # noqa: E731
            jnp.asarray(x, dt), self._batch2
        )
        self.params, self.opt_state, loss = self._step(
            self.params,
            self.opt_state,
            put(inputs, jnp.int32),
            put(targets, jnp.int32),
            put(mask, jnp.float32),
        )
        loss_f = float(loss)
        self._record(loss_f, np.asarray(inputs).shape[0], np.asarray(inputs).shape[1])
        return loss_f

    def logits(self, tokens: np.ndarray) -> np.ndarray:
        return np.asarray(
            self.model.apply({"params": self.params}, jnp.asarray(tokens, jnp.int32))
        )
