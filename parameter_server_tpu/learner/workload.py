"""WorkloadPool: data-shard assignment with dead-worker reassignment.

Reference analogue (``src/learner/workload_pool.h/.cc`` [U — reference mount
empty, public layout]): the scheduler owns a pool of workloads (file shards /
example ranges); workers ask for the next one, report completion, and a dead
worker's outstanding workloads return to the pool so surviving workers pick
them up.  Straggler handling: a workload outstanding far beyond the typical
completion time may be speculatively duplicated to an idle worker; the first
completion wins (the second is ignored).

Pure host-side logic — ports ~1:1 per SURVEY.md §2 #15.  Thread-safe: called
from worker loops and the Manager's failure callbacks concurrently.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Workload:
    """One unit of assignable work (a file shard, an example range, ...)."""

    workload_id: int
    payload: Any = None
    #: workers currently assigned (>1 only under speculative duplication).
    assigned_to: List[str] = dataclasses.field(default_factory=list)
    #: per-assignment start time, keyed by worker — durations are measured
    #: from the *winner's own* assignment so speculative duplicates never
    #: corrupt the completion-time history.
    started_at: Dict[str, float] = dataclasses.field(default_factory=dict)
    done: bool = False
    completed_by: Optional[str] = None


class WorkloadPool:
    def __init__(
        self,
        payloads: List[Any],
        *,
        straggler_factor: float = 4.0,
        min_history: int = 3,
    ) -> None:
        """``straggler_factor``: a workload outstanding longer than
        ``factor * median(done durations)`` becomes eligible for speculative
        re-assignment (needs ``min_history`` completions first)."""
        self._workloads: Dict[int, Workload] = {
            i: Workload(i, p) for i, p in enumerate(payloads)
        }
        self._pending: List[int] = list(self._workloads)
        self._durations: List[float] = []
        self.straggler_factor = straggler_factor
        self.min_history = min_history
        self._lock = threading.Lock()
        self._dead: set[str] = set()

    # -- assignment ----------------------------------------------------------
    def get(self, worker: str) -> Optional[Workload]:
        """Next workload for ``worker``; None when nothing is assignable.

        Preference order: fresh pending work, then speculative duplicates of
        straggling workloads (never duplicating onto the same worker).
        """
        with self._lock:
            if worker in self._dead:
                return None
            if self._pending:
                wid = self._pending.pop(0)
                w = self._workloads[wid]
                w.assigned_to.append(worker)
                w.started_at[worker] = time.monotonic()
                return w
            straggler = self._find_straggler_locked(worker)
            if straggler is not None:
                straggler.assigned_to.append(worker)
                straggler.started_at[worker] = time.monotonic()
                return straggler
        return None

    def _find_straggler_locked(self, worker: str) -> Optional[Workload]:
        if len(self._durations) < self.min_history:
            return None
        med = sorted(self._durations)[len(self._durations) // 2]
        cutoff = self.straggler_factor * max(med, 1e-9)
        now = time.monotonic()
        for w in self._workloads.values():
            live = [a for a in w.assigned_to if a not in self._dead]
            if (
                not w.done
                and len(live) == 1  # exactly the one straggling assignee
                and worker not in w.assigned_to
                and now - w.started_at.get(live[0], now) > cutoff
            ):
                return w
        return None

    def finish(self, worker: str, workload_id: int) -> bool:
        """Report completion.  Returns True iff this completion counted
        (False for the loser of a speculative duplicate or an unknown id)."""
        with self._lock:
            w = self._workloads.get(workload_id)
            if w is None or w.done:
                return False
            w.done = True
            w.completed_by = worker
            # A dead worker's in-flight finish may land after mark_dead
            # requeued the id — drop it from pending so get() never hands
            # out completed work.
            if workload_id in self._pending:
                self._pending.remove(workload_id)
            # duration from THIS worker's assignment; a finish from a worker
            # with no recorded start (requeue race) adds no history
            start = w.started_at.get(worker)
            if start is not None:
                self._durations.append(time.monotonic() - start)
            return True

    # -- elasticity ----------------------------------------------------------
    def mark_dead(self, worker: str) -> List[int]:
        """Return the dead worker's unfinished workloads to the pool.

        Wire this to ``Manager.on_node_dead`` — the reference's
        ``Executor::ReplaceNode`` + pool re-assignment path [U].
        """
        requeued: List[int] = []
        with self._lock:
            self._dead.add(worker)
            for w in self._workloads.values():
                if w.done or worker not in w.assigned_to:
                    continue
                w.assigned_to = [a for a in w.assigned_to if a != worker]
                if not w.assigned_to and w.workload_id not in self._pending:
                    self._pending.append(w.workload_id)
                    requeued.append(w.workload_id)
        return requeued

    def mark_alive(self, worker: str) -> None:
        with self._lock:
            self._dead.discard(worker)

    # -- progress ------------------------------------------------------------
    def all_done(self) -> bool:
        with self._lock:
            return all(w.done for w in self._workloads.values())

    def num_done(self) -> int:
        with self._lock:
            return sum(w.done for w in self._workloads.values())

    def __len__(self) -> int:
        return len(self._workloads)
