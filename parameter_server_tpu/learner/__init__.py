"""learner subpackage."""
