"""FM learner: single-device fused trainer for the factorization machine.

Reference analogue: the factorization-machine app over the SGD scaffold
(``src/app/factorization_machine/`` + ``src/learner/sgd.h`` [U]).  The Van
path needs no dedicated class — ``KVWorker.pull/push`` with
``models.fm.fm_grad_rows`` is the loop (see ``tests/test_fm.py``); this
module provides the fused local path mirroring
:class:`~parameter_server_tpu.learner.sgd.LocalLRTrainer`.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import TableConfig
from parameter_server_tpu.kv.table import KVTable
from parameter_server_tpu.models import fm
from parameter_server_tpu.utils import metrics as metrics_lib
from parameter_server_tpu.utils.keys import HashLocalizer, localize_to_slots


class LocalFMTrainer:
    """Single-device FM: fused pull+grad+apply+scatter per step.

    ``table_cfg.dim`` must be ``1 + k`` (linear weight + k factors); use
    ``init_scale > 0`` so factor vectors break symmetry (column 0's linear
    weight tolerates random init like the reference's FM).
    """

    def __init__(
        self,
        table_cfg: TableConfig,
        *,
        min_bucket: int = 1024,
        dashboard: Optional[metrics_lib.Dashboard] = None,
        seed: int = 0,
    ) -> None:
        if table_cfg.dim < 2:
            raise ValueError("FM table dim must be 1 + k (k >= 1 factors)")
        self.cfg = table_cfg
        self.table = KVTable(table_cfg, seed=seed)
        self.optimizer = self.table.optimizer
        self.localizer = HashLocalizer(table_cfg.rows)
        self.min_bucket = min_bucket
        self.bias = jnp.zeros((1, 1), dtype=jnp.float32)
        self.bias_state = {
            k: jnp.zeros((1, 1), dtype=jnp.float32)
            for k in self.optimizer.state_shapes()
        }
        self.dashboard = dashboard or metrics_lib.Dashboard(print_every=0)
        self.step_count = 0

    def step(self, keys: np.ndarray, labels: np.ndarray) -> float:
        t = self.table
        slots, inverse, _n = localize_to_slots(
            keys, self.localizer, min_bucket=self.min_bucket
        )
        t.value, t.state, self.bias, self.bias_state, loss = fm.fused_train_step(
            t.value,
            t.state,
            self.bias,
            self.bias_state,
            jnp.asarray(slots),
            jnp.asarray(inverse),
            jnp.asarray(labels),
            self.optimizer,
            slots.shape[0],
        )
        self.step_count += 1
        return float(loss)

    def train(self, batch_fn, num_steps: int) -> None:
        for _ in range(num_steps):
            keys, labels = batch_fn()
            loss = self.step(keys, labels)
            self.dashboard.record(self.step_count, loss, examples=labels.shape[0])

    def eval_auc(self, batch_fn, num_batches: int) -> float:
        weights = np.asarray(self.table.weights())
        bias = float(
            np.asarray(self.optimizer.pull_weights(self.bias, self.bias_state))[0, 0]
        )
        scores, labels_all = [], []
        for _ in range(num_batches):
            keys, labels = batch_fn()
            slots_pos = self.localizer.assign(keys)
            # PAD slots (== capacity) cannot appear with fixed-nnz batches;
            # guard anyway by clipping into the real row range
            slots_pos = np.minimum(slots_pos, self.cfg.rows - 1)
            scores.append(fm.eval_logits_np(weights, bias, slots_pos))
            labels_all.append(labels)
        return metrics_lib.auc(np.concatenate(labels_all), np.concatenate(scores))
