"""SGD learners: the minibatch pull -> grad -> push scaffolds.

Reference analogue: ``src/learner/sgd.h`` minibatch scaffolds plus the async
SGD / FTRL worker loops of ``src/app/linear_method/async_sgd.h`` [U].

Two drivers over the same model math (``models/linear.py``):

- :class:`LocalLRTrainer` — single-process fast path: the table lives on the
  local device and each step is one fused XLA program.  This is the
  examples/sec/chip bench path (BASELINE config #1).
- :class:`AsyncLRLearner` — the classic PS topology over the Van: N worker
  threads pull/push through :class:`~parameter_server_tpu.kv.worker.KVWorker`
  under a :class:`~parameter_server_tpu.core.clock.ConsistencyController`
  (BSP/SSP/ASP), servers apply updates.  This is the semantics/API path and
  the seam where DCN multi-host traffic will attach.
"""

from __future__ import annotations

import functools
import threading
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import (
    ConsistencyConfig,
    OptimizerConfig,
    TableConfig,
)
from parameter_server_tpu.core.clock import ConsistencyController
from parameter_server_tpu.kv.optim import make_optimizer, require_dense_apply
from parameter_server_tpu.kv.table import KVTable
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.models import linear
from parameter_server_tpu.utils import metrics as metrics_lib
from parameter_server_tpu.utils.keys import (
    HashLocalizer,
    ensure_uint32_keys,
    localize_to_slots,
)
from parameter_server_tpu.utils.threads import run_threads

Batch = Tuple[np.ndarray, np.ndarray]  # (keys [B, nnz], labels [B])
BatchFn = Callable[[], Batch]


class LocalLRTrainer:
    """Single-device sparse LR: fused pull+grad+apply+scatter per step."""

    def __init__(
        self,
        table_cfg: TableConfig,
        *,
        min_bucket: int = 1024,
        dashboard: Optional[metrics_lib.Dashboard] = None,
        mode: str = "rows",
        device_hash: bool = False,
    ) -> None:
        """``mode="rows"``: bucketed-unique gather/apply/scatter (general).
        ``mode="dense"``: per-position hashed slots + full-table apply — no
        host dedup; requires l1 == l2 == 0 and a g=0-stable optimizer.
        ``device_hash``: hash keys ON DEVICE (32-bit; dense mode) — raw
        uint32 keys ship to the chip and :meth:`step_block` runs K steps per
        dispatch (for hosts/tunnels where the transfer is the bottleneck)."""
        if table_cfg.dim != 1:
            raise ValueError("LR weight table must have dim=1")
        if mode not in ("rows", "dense"):
            raise ValueError(f"unknown mode {mode!r}")
        if mode == "dense":
            require_dense_apply(table_cfg.optimizer)
        if device_hash and mode != "dense":
            raise ValueError("device_hash requires mode='dense'")
        self.mode = mode
        self.device_hash = device_hash
        self.cfg = table_cfg
        self.table = KVTable(table_cfg)
        self.optimizer = self.table.optimizer
        self.localizer = HashLocalizer(
            table_cfg.rows, hash_bits=32 if device_hash else 64
        )
        self.min_bucket = min_bucket
        self.bias = jnp.zeros((1, 1), dtype=jnp.float32)
        self.bias_state = {
            k: jnp.zeros((1, 1), dtype=jnp.float32)
            for k in self.optimizer.state_shapes()
        }
        self.dashboard = dashboard or metrics_lib.Dashboard(print_every=0)
        self.step_count = 0

    def step(self, keys: np.ndarray, labels: np.ndarray) -> float:
        t = self.table
        if self.mode == "dense":
            slots_pos = self.localizer.assign(keys)  # [B, nnz], no dedup
            (
                t.value,
                t.state,
                self.bias,
                self.bias_state,
                loss,
            ) = linear.dense_fused_train_step(
                t.value,
                t.state,
                self.bias,
                self.bias_state,
                jnp.asarray(slots_pos),
                jnp.asarray(labels),
                self.optimizer,
                self.cfg.rows,
            )
        else:
            slots, inverse, _n = localize_to_slots(
                keys, self.localizer, min_bucket=self.min_bucket
            )
            t.value, t.state, self.bias, self.bias_state, loss = (
                linear.fused_train_step(
                    t.value,
                    t.state,
                    self.bias,
                    self.bias_state,
                    jnp.asarray(slots),
                    jnp.asarray(inverse),
                    jnp.asarray(labels),
                    self.optimizer,
                    slots.shape[0],
                )
            )
        self.step_count += 1
        return float(loss)

    def step_async(self, keys: np.ndarray, labels: np.ndarray) -> jax.Array:
        """Dense-mode step without host sync; returns the device loss.

        Lets the host race ahead preparing batches while the device queue
        drains (the PS pipelining analogue for the single-chip path).
        """
        if self.mode != "dense":
            raise ValueError("step_async requires mode='dense'")
        t = self.table
        slots_pos = self.localizer.assign(keys)
        (
            t.value,
            t.state,
            self.bias,
            self.bias_state,
            loss,
        ) = linear.dense_fused_train_step(
            t.value,
            t.state,
            self.bias,
            self.bias_state,
            jnp.asarray(slots_pos),
            jnp.asarray(labels),
            self.optimizer,
            self.cfg.rows,
        )
        self.step_count += 1
        return loss

    def step_block(
        self, keys_block: np.ndarray, labels_block: np.ndarray
    ) -> jax.Array:
        """K dense steps in one dispatch (requires ``device_hash``).

        ``keys_block``: ``[K, B, nnz]`` keys (must fit uint32);
        ``labels_block``: ``[K, B]``.  Returns the device losses ``[K]``
        without host sync — the block analogue of :meth:`step_async`.

        Pass keys at their RAW width: the out-of-range guard below only runs
        on non-uint32 input, so a caller-side ``astype(np.uint32)`` silently
        wraps bad keys before the check can see them (ADVICE r2).
        """
        if not self.device_hash:
            raise ValueError("step_block requires device_hash=True")
        keys_block = ensure_uint32_keys(keys_block)
        return self.step_block_device(
            jnp.asarray(keys_block), jnp.asarray(labels_block)
        )

    def step_block_device(
        self, keys_block: jax.Array, labels_block: jax.Array
    ) -> jax.Array:
        """:meth:`step_block` for ALREADY device-resident uint32 inputs.

        The overlapped ingest path (``data.prefetch.PrefetchPipeline``)
        validates and casts keys on its producer thread
        (``utils.keys.ensure_uint32_keys``) and stages the H2D copy there
        too, so this method is pure dispatch — no host work on the critical
        path between scan blocks.  Callers own the validation contract:
        feed it anything but checked uint32 keys and bad keys wrap
        silently, which is why the host-side :meth:`step_block` remains the
        default entry point.
        """
        if not self.device_hash:
            raise ValueError("step_block_device requires device_hash=True")
        t = self.table
        (
            t.value,
            t.state,
            self.bias,
            self.bias_state,
            losses,
        ) = linear.dense_scan_train_step(
            t.value,
            t.state,
            self.bias,
            self.bias_state,
            keys_block,
            labels_block,
            self.optimizer,
            self.cfg.rows,
            self.localizer.seed,
        )
        self.step_count += int(keys_block.shape[0])
        return losses

    def train_stream(self, pipeline, num_blocks: Optional[int] = None) -> list:
        """Drain a :class:`~parameter_server_tpu.data.prefetch.PrefetchPipeline`
        of ``(keys_block, labels_block)`` device pairs through
        :meth:`step_block_device`; returns the per-block device loss arrays.

        The prefetch producer assembles and stages block ``i+1`` while the
        device executes block ``i`` — the ingest-overlap loop the scan-block
        design was built for.
        """
        losses = []
        for kd, yd in pipeline:
            losses.append(self.step_block_device(kd, yd))
            if num_blocks is not None and len(losses) >= num_blocks:
                break
        return losses

    def train(self, batch_fn: BatchFn, num_steps: int) -> None:
        for _ in range(num_steps):
            keys, labels = batch_fn()
            loss = self.step(keys, labels)
            self.dashboard.record(
                self.step_count, loss, examples=labels.shape[0]
            )

    def eval_auc(self, batch_fn: BatchFn, num_batches: int) -> float:
        scores, labels_all = [], []
        for _ in range(num_batches):
            keys, labels = batch_fn()
            slots, inverse, _n = localize_to_slots(
                keys, self.localizer, min_bucket=self.min_bucket
            )
            logits = linear.eval_logits(
                self.table.value,
                self.table.state,
                self.bias,
                self.bias_state,
                jnp.asarray(slots),
                jnp.asarray(inverse),
                labels.shape[0],
                self.optimizer,
            )
            scores.append(np.asarray(logits))
            labels_all.append(labels)
        return metrics_lib.auc(np.concatenate(labels_all), np.concatenate(scores))


class AsyncLRLearner:
    """Multi-worker PS loop over the Van with BSP/SSP/ASP gating.

    Each worker thread: ``wait_turn -> pull(w) -> grad -> push(g) -> advance``.
    Under ASP pushes from stale pulls interleave freely; under BSP the vector
    clock enforces lockstep — same mechanism, same code path, mirroring the
    reference's single DAG mechanism for all three modes.
    """

    def __init__(
        self,
        workers: list[KVWorker],
        consistency: ConsistencyConfig,
        *,
        table: str = "w",
        dashboard: Optional[metrics_lib.Dashboard] = None,
    ) -> None:
        self.workers = workers
        self.controller = ConsistencyController(consistency, len(workers))
        self.table = table
        self.dashboard = dashboard or metrics_lib.Dashboard(print_every=0)
        self._lock = threading.Lock()
        self._losses: list[float] = []

    def run(
        self,
        batch_fns: list[BatchFn],
        steps_per_worker: int,
        *,
        timeout: float = 60.0,
    ) -> list[float]:
        """Run all workers to completion; returns per-iteration mean losses."""
        run_threads(
            [
                functools.partial(
                    self._worker_loop, w, batch_fns[i], i, steps_per_worker,
                    timeout,
                )
                for i, w in enumerate(self.workers)
            ],
            name="sgd-worker",
        )
        return list(self._losses)

    def _worker_loop(
        self,
        kv: KVWorker,
        batch_fn: BatchFn,
        index: int,
        steps: int,
        timeout: float,
    ) -> None:
        for t in range(steps):
            if not self.controller.wait_turn(index, t, timeout=timeout):
                raise TimeoutError(f"worker {index} stalled at iter {t} (SSP bound)")
            keys, labels = batch_fn()
            w_pos = kv.pull_sync(self.table, keys, timeout=timeout)
            g, _gb, loss = linear.grad_rows(
                jnp.asarray(w_pos), jnp.asarray(labels)
            )
            push_ts = kv.push(self.table, keys, np.asarray(g) / labels.shape[0])
            kv.wait(push_ts, timeout=timeout)
            self.controller.finish_iteration(index)
            with self._lock:
                self._losses.append(float(loss))
                self.dashboard.record(
                    len(self._losses), float(loss), examples=labels.shape[0]
                )
