"""ElasticTrainer: fault-tolerant PS training — the full recovery loop.

Glues the pieces SURVEY.md §5 lists for failure handling into one driver,
mirroring the reference's composition (heartbeats -> Manager REMOVE_NODE ->
``Executor::ReplaceNode`` re-slice + WorkloadPool re-assignment [U]):

- :class:`~parameter_server_tpu.core.manager.Manager` heartbeat monitoring
  detects silent nodes and fires ``on_node_dead``;
- a dead **worker**'s unfinished workloads return to the
  :class:`~parameter_server_tpu.learner.workload.WorkloadPool` and surviving
  workers drain them; the
  :class:`~parameter_server_tpu.core.clock.ConsistencyController` excludes the
  dead worker from the SSP bound so the window never wedges;
- a dead **server** means lost shard state: recovery restores the shard from
  the latest committed checkpoint (``checkpoint.restore_shard``), which the
  trainer writes every ``ckpt_every`` completed workloads — losing updates
  since the snapshot.  For ZERO-loss recovery, chain-replicate the shard
  instead: :mod:`parameter_server_tpu.kv.replica` forwards applied pushes
  to a hot standby and a :class:`~parameter_server_tpu.kv.replica.ReplicaSet`
  registered on the scheduler's manager promotes it on the same
  ``on_node_dead`` signal this trainer uses (the reference paper's §4.3
  replication, absent from the open tree).  Snapshot restore remains the
  fallback for un-replicated shards;
- a crashed server process restarted IN PLACE (same node id) goes through
  :func:`restart_server` → :func:`parameter_server_tpu.kv.replica.restart_same_id`:
  shard restored from the standby (zero loss) or checkpoint (bounded
  rewind), then re-registration with the scheduler, which bumps the node's
  transport incarnation so peers fence the dead process's zombie frames
  (``core/resender.py``) — workers resume against the same ``S{i}``
  identity without promotion or trajectory rewind.

The trainer is Van-agnostic: fault injection in tests uses
``LoopbackVan.disconnect`` (a dead socket) + a forced heartbeat sweep, and the
same code paths fire on a real DCN Van when a host drops.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.config import CheckpointConfig, ConsistencyConfig
from parameter_server_tpu.core.clock import ConsistencyController
from parameter_server_tpu.core.manager import Manager
from parameter_server_tpu.kv.consistency import BoundTuner
from parameter_server_tpu.kv.worker import KVWorker
from parameter_server_tpu.learner.workload import WorkloadPool
from parameter_server_tpu.models import linear
from parameter_server_tpu.utils.threads import run_threads

log = logging.getLogger(__name__)

#: one workload payload: list of (keys, labels) minibatches
Shard = List[Tuple[np.ndarray, np.ndarray]]


class ElasticTrainer:
    """Pool-driven sparse-LR training that survives node loss.

    Unlike :class:`~parameter_server_tpu.learner.sgd.AsyncLRLearner` (fixed
    steps per worker), workers here draw *workloads* (data shards) from the
    shared pool, so work lost to a death is re-drawn by survivors — the
    reference's SGD scaffold + WorkloadPool composition [U].
    """

    def __init__(
        self,
        workers: Dict[str, KVWorker],
        scheduler: Manager,
        shards: List[Shard],
        consistency: ConsistencyConfig,
        *,
        table: str = "w",
        managers: Optional[Dict[str, Manager]] = None,
        heartbeat_interval: float = 0.5,
        ckpt_root: Optional[str] = None,
        ckpt_every: int = 0,
        ckpt_config: Optional[CheckpointConfig] = None,
        timeout: float = 60.0,
        bound_tuner: Optional[BoundTuner] = None,
        wire_bottleneck: Optional[Callable[[], bool]] = None,
        retune_interval_s: float = 1.0,
    ) -> None:
        self.workers = workers
        self.scheduler = scheduler
        #: per-worker Manager instances for liveness reporting; without them
        #: the scheduler's heartbeat sweep would mark every worker dead.
        self.managers = managers or {}
        self.heartbeat_interval = heartbeat_interval
        self.table = table
        self.pool = WorkloadPool(shards)
        self.controller = ConsistencyController(consistency, len(workers))
        self._index = {wid: i for i, wid in enumerate(sorted(workers))}
        self.ckpt_root = ckpt_root
        self.ckpt_every = ckpt_every
        self.ckpt_config = ckpt_config or CheckpointConfig()
        self.timeout = timeout
        self._ckpt_lock = threading.Lock()
        self._ckpt_pending = 0
        self._ckpt_running = False
        self.last_ckpt_step: Optional[int] = None
        self.losses: List[float] = []
        self._loss_lock = threading.Lock()
        self._killed: set[str] = set()
        # wire-enforced consistency plane (ISSUE 20): the trainer announces
        # workers to the servers' FleetClocks up front and (optionally)
        # closes the loop over the SSP bound
        self.bound_tuner = bound_tuner
        self._wire_bottleneck = wire_bottleneck or (lambda: False)
        self.retune_interval_s = retune_interval_s
        self._retune_lock = threading.Lock()
        self._next_retune = 0.0
        # membership -> pool/clock wiring (Executor::ReplaceNode analogue)
        scheduler.on_node_dead.append(self._on_dead)
        scheduler.on_node_added.append(self._on_added)

    def kill(self, wid: str) -> None:
        """Fault injection: make worker ``wid`` stop executing (SURVEY.md §5
        kill-a-process hook).  The caller also disconnects its Van endpoint;
        the heartbeat sweep then detects the death and requeues its work."""
        self._killed.add(wid)

    # -- elasticity callbacks (scheduler thread) -----------------------------
    def _on_dead(self, node_id: str) -> None:
        requeued = self.pool.mark_dead(node_id)
        idx = self._index.get(node_id)
        if idx is not None:
            self.controller.mark_dead(idx)
        if requeued:
            log.warning("node %s dead: requeued workloads %s", node_id, requeued)

    def _on_added(self, node_id: str) -> None:
        self.pool.mark_alive(node_id)
        idx = self._index.get(node_id)
        if idx is not None:
            self.controller.mark_alive(idx)
        # a re-added worker re-announces to the servers' FleetClocks: its
        # hello carries the van's current incarnation, so a same-id restart
        # replaces the dead incarnation's entry instead of racing it
        kv = self.workers.get(node_id)
        if kv is not None:
            self._hello_one(node_id, kv)

    # -- wire-enforced consistency (ISSUE 20) --------------------------------
    def _gated_tables(self, kv: KVWorker) -> List[str]:
        return sorted(
            t for t, c in kv.table_cfgs.items() if c.consistency is not None
        )

    def _hello_one(self, wid: str, kv: KVWorker) -> None:
        """Best-effort ``consist_hello`` for one worker's gated tables.

        Registration keeps a slow-to-start worker from letting the rest of
        the fleet free-run past the bound before its first stamped request;
        a hello that times out (dead server mid-restart) is non-fatal — the
        worker's first stamped request registers it anyway.
        """
        for t in self._gated_tables(kv):
            try:
                kv.consist_hello(table=t, timeout=self.timeout)
            except (TimeoutError, RuntimeError) as e:
                log.warning("consist_hello(%s, %s) failed: %s", wid, t, e)

    def announce_consistency(self) -> None:
        """Register every live worker with the servers' FleetClocks."""
        for wid, kv in self.workers.items():
            if wid not in self._killed:
                self._hello_one(wid, kv)

    def _maybe_retune(self, kv: KVWorker, loss: float) -> None:
        """Feed the BoundTuner and apply its verdict fleet-wide.

        Runs on worker threads at loss-record time; the interval check and
        lock keep the tuner single-file.  A verdict is applied through any
        live worker's ``consist_set`` broadcast, which also records the
        ``consist.retune`` flight-recorder event with the tuner's reason.
        """
        tuner = self.bound_tuner
        if tuner is None:
            return
        with self._retune_lock:
            tuner.observe_loss(loss)
            now = time.monotonic()
            if now < self._next_retune:
                return
            self._next_retune = now + self.retune_interval_s
            verdict = tuner.maybe_retune(
                now, wire_bottleneck=self._wire_bottleneck()
            )
        if verdict is None:
            return
        new_bound, why = verdict
        try:
            kv.set_consistency(
                table=self.table, bound=new_bound, why=why,
                timeout=self.timeout,
            )
            log.info("retuned SSP bound -> %d (%s)", new_bound, why)
        except (TimeoutError, RuntimeError) as e:  # pragma: no cover
            log.warning("set_consistency(bound=%d) failed: %s", new_bound, e)

    # -- training ------------------------------------------------------------
    def run(self, *, poll: float = 0.02) -> List[float]:
        """Drain the pool with all workers; returns recorded losses.

        Individual worker failures (Van timeouts after a kill) are swallowed
        — the scheduler's failure detection re-queues their work; only a
        wholly-failed run (work left but no live workers) raises.
        """
        self.announce_consistency()
        hb_stop = threading.Event()
        hb_thread = None
        started_monitor = False
        if self.managers:
            hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                args=(hb_stop,),
                name="elastic-heartbeat",
                daemon=True,
            )
            hb_thread.start()
            # the detection side: run the scheduler's sweep unless the
            # caller already started one (tests may drive it manually too —
            # extra sweeps are idempotent)
            if self.scheduler._monitor_thread is None:
                self.scheduler.start_monitor(
                    interval=max(self.heartbeat_interval, 0.05)
                )
                started_monitor = True
        try:
            run_threads(
                [
                    (lambda wid=wid, kv=kv: self._worker_loop(wid, kv, poll))
                    for wid, kv in self.workers.items()
                ],
                name="elastic-worker",
            )
        finally:
            hb_stop.set()
            if hb_thread is not None:
                hb_thread.join(timeout=5)
            if started_monitor:
                self.scheduler.stop_monitor()
        if not self.pool.all_done():
            raise RuntimeError(
                f"workloads incomplete: {self.pool.num_done()}/{len(self.pool)}"
            )
        return list(self.losses)

    def _heartbeat_loop(self, stop: threading.Event) -> None:
        """Background liveness reporting for every managed node.

        A dedicated thread (the reference runs heartbeats off the worker
        compute thread too [U]) so a long device step / jit compile never
        reads as a death.  Killed nodes stop heartbeating — that IS the
        death signal the scheduler sweep detects.
        """
        from parameter_server_tpu.core.messages import SCHEDULER

        while not stop.wait(self.heartbeat_interval):
            for nid, mgr in self.managers.items():
                if nid == SCHEDULER or nid in self._killed:
                    continue
                # auto-stats attach resource usage + wire digests, feeding
                # the scheduler's FleetMonitor when one is installed
                mgr.send_heartbeat()

    def _worker_loop(self, wid: str, kv: KVWorker, poll: float) -> None:
        idx = self._index[wid]
        iteration = 0
        try:
            self._worker_loop_inner(wid, kv, idx, iteration, poll)
        finally:
            # Retire from the staleness bound on ANY exit (drained, died,
            # stalled): a stopped clock must not wedge survivors' SSP window.
            self.controller.mark_dead(idx)

    def _worker_loop_inner(
        self, wid: str, kv: KVWorker, idx: int, iteration: int, poll: float
    ) -> None:
        while True:
            if wid in self._killed:
                return  # the "process" is gone; no further sends, no finish
            wl = self.pool.get(wid)
            if wl is None:
                if self.pool.all_done() or not self.scheduler.is_alive(wid):
                    return
                time.sleep(poll)  # pool empty but stragglers outstanding
                continue
            try:
                for keys, labels in wl.payload:
                    if wid in self._killed:
                        return
                    if not self.controller.wait_turn(
                        idx, iteration, timeout=self.timeout
                    ):
                        raise TimeoutError(f"{wid} stalled (SSP bound)")
                    w_pos = kv.pull_sync(self.table, keys, timeout=self.timeout)
                    g, _gb, loss = linear.grad_rows(
                        jnp.asarray(w_pos), jnp.asarray(labels)
                    )
                    # push_sync, not fire-and-forget push: only the kept-
                    # responses path can see a routing fence (PR 6), so this
                    # is what lets a live migration reshard mid-training
                    # without losing or double-applying a single push
                    kv.push_sync(
                        self.table,
                        keys,
                        np.asarray(g) / labels.shape[0],
                        timeout=self.timeout,
                    )
                    self.controller.finish_iteration(idx)
                    iteration += 1
                    with self._loss_lock:
                        self.losses.append(float(loss))
                    self._maybe_retune(kv, float(loss))
            except (TimeoutError, RuntimeError) as e:
                # This worker is partitioned/dead from the cluster's view
                # (pull timeout, undeliverable sends, or a dead-server leg) —
                # its thread exits (the "process" dies).  Joining _killed
                # stops its heartbeats so the scheduler sweep actually
                # detects the death and requeues the workload for survivors.
                log.warning("worker %s failed (%s); exiting loop", wid, e)
                self._killed.add(wid)
                return
            if self.pool.finish(wid, wl.workload_id):
                self._maybe_checkpoint(kv)

    def _use_partitioned(self, kv: KVWorker) -> bool:
        """Pick the checkpoint plane per ``ckpt_config.mode``.

        ``auto`` decides client-side (a server's typed
        ``CheckpointLayoutError`` does not survive the wire): the
        partitioned durability plane whenever a snapshot chain already
        exists (keep extending it incrementally) or the routing layout has
        drifted from the uniform split the legacy shard-file format
        requires; the legacy format otherwise, for compatibility with
        pre-format-2 readers.
        """
        mode = self.ckpt_config.mode
        if mode != "auto":
            return mode == "partitioned"
        from parameter_server_tpu import checkpoint
        from parameter_server_tpu.kv.routing import TableRouting

        if checkpoint.latest_snapshot(self.ckpt_root) is not None:
            return True
        for tr in kv.routing.tables.values():
            u = TableRouting.uniform(tr.rows, kv.num_servers)
            if (tuple(tr.offsets), tuple(tr.owners)) != (
                tuple(u.offsets), tuple(u.owners)
            ):
                return True
        return False

    def _maybe_checkpoint(self, kv: KVWorker) -> None:
        if not self.ckpt_root or self.ckpt_every <= 0:
            return
        # decide under the lock; run the (blocking) save OUTSIDE it so other
        # workers finishing workloads never queue behind checkpoint IO
        with self._ckpt_lock:
            self._ckpt_pending += 1
            if self._ckpt_pending < self.ckpt_every or self._ckpt_running:
                return
            self._ckpt_pending = 0
            self._ckpt_running = True
        step = self.pool.num_done()
        if step == self.last_ckpt_step:
            with self._ckpt_lock:
                self._ckpt_running = False
            return
        from parameter_server_tpu import checkpoint

        try:
            clocks = self.controller.clock.snapshot()
            if self._use_partitioned(kv):
                kv.save_snapshot(
                    self.ckpt_root,
                    step,
                    base_step=checkpoint.latest_snapshot(self.ckpt_root),
                    clocks=clocks,
                    timeout=self.timeout,
                )
                if self.ckpt_config.retention > 0:
                    checkpoint.retain_snapshots(
                        self.ckpt_root, self.ckpt_config.retention
                    )
            else:
                kv.save_model(
                    self.ckpt_root, step, clocks=clocks, timeout=self.timeout
                )
            self.last_ckpt_step = step
        except (TimeoutError, RuntimeError, OSError) as e:
            # checkpoint failure must not kill training (a dead server
            # mid-save is exactly the scenario recovery handles); an
            # aborted snapshot leaves no manifest, so the previous one
            # stays the restore point
            log.warning("checkpoint at %s failed: %s", step, e)
        finally:
            with self._ckpt_lock:
                self._ckpt_running = False


def recover_server(
    make_server: Callable[[], object],
    ckpt_root: str,
    *,
    step: Optional[int] = None,
) -> object:
    """Rebuild a lost server shard from the latest committed checkpoint.

    ``make_server`` constructs the replacement
    :class:`~parameter_server_tpu.kv.server.KVServer` (fresh tables, same
    shard index) bound to a live Van endpoint; its shard rows are then
    restored in place.  Returns the new server.  Raises ``FileNotFoundError``
    when no committed checkpoint exists — the caller decides whether a cold
    restart is acceptable.
    """
    from parameter_server_tpu import checkpoint

    if step is None:
        step = checkpoint.latest_step(ckpt_root)
    if step is None:
        raise FileNotFoundError(f"no committed checkpoint under {ckpt_root}")
    server = make_server()
    server.restore_checkpoint(ckpt_root, step)
    return server


@dataclasses.dataclass(frozen=True)
class RebalanceConfig:
    """Trigger thresholds for monitor-driven rebalancing.

    Relative share with an absolute floor, like
    :class:`~parameter_server_tpu.core.fleet.StragglerPolicy`: share-only
    would fire on an idle fleet's noise, floor-only needs per-deployment
    tuning.
    """

    #: a server is HOT when its share of the fleet's inbound bytes since the
    #: previous check exceeds this (with >= 2 owners, uniform share is 1/n).
    hot_share: float = 0.5
    #: ignore observation windows with less total inbound traffic than this.
    min_window_bytes: int = 1
    #: fraction of the hot server's largest segment to move off (the tail
    #: end — one split point, so the routing table grows by at most one
    #: segment per move).
    move_fraction: float = 0.5


class RebalancePolicy:
    """Closes the loop: FleetMonitor load ranking -> ShardMigrator moves.

    Reads :meth:`~parameter_server_tpu.core.fleet.FleetMonitor.inbound_totals`
    (cumulative inbound wire bytes per node, off the heartbeat link digests),
    differences successive calls into a per-window load share, and when one
    server's share crosses ``hot_share`` — or the monitor flags it as a
    straggler — migrates the tail of its largest segment to the
    least-loaded owner.  Drive it from the training loop or a monitor sweep:
    ``routing, moved = policy.maybe_rebalance(routing)``.
    """

    def __init__(
        self,
        monitor,
        migrator,
        *,
        config: Optional[RebalanceConfig] = None,
        sched: Optional[Manager] = None,
    ) -> None:
        self.monitor = monitor
        self.migrator = migrator
        self.config = config or RebalanceConfig()
        self.sched = sched
        self._prev: Dict[str, int] = {}
        #: move log: one dict per executed migration (dashboards/tests).
        self.moves: List[dict] = []

    def inbound_window(self, routing) -> Dict[int, int]:
        """Inbound bytes per OWNING server since the previous call."""
        from parameter_server_tpu.core.messages import server_id

        totals = self.monitor.inbound_totals()
        out: Dict[int, int] = {}
        for s in routing.servers():
            nid = server_id(s)
            cur = int(totals.get(nid, {}).get("bytes", 0))
            out[s] = cur - self._prev.get(nid, cur)
            self._prev[nid] = cur
        return out

    def maybe_rebalance(self, routing, *, tables: Optional[List[str]] = None):
        """One control-loop tick.  Returns ``(routing, moved)``.

        At most one hot server is acted on per tick (the loop re-evaluates
        with fresh load next tick — chasing several moves off one stale
        window overshoots).
        """
        from parameter_server_tpu.core.messages import server_id

        window = self.inbound_window(routing)
        if len(window) < 2:
            return routing, False
        total = sum(max(v, 0) for v in window.values())
        flagged = set(self.monitor.stragglers())
        hot = max(window, key=lambda s: window[s])
        share = window[hot] / total if total >= self.config.min_window_bytes else 0.0
        if share < self.config.hot_share and server_id(hot) not in flagged:
            return routing, False
        cold = min(
            (s for s in window if s != hot), key=lambda s: window[s]
        )
        moved = False
        for t in tables or list(routing.tables):
            segs = routing.tables[t].owned_segments(hot)
            if not segs:
                continue
            lo, hi = max(segs, key=lambda ab: ab[1] - ab[0])
            n = hi - lo
            if n < 2:
                continue  # nothing left to split off this server
            cut = hi - max(1, int(n * self.config.move_fraction))
            routing = self.migrator.migrate(
                routing, t, cut, hi, cold, sched=self.sched
            )
            self.moves.append(
                {
                    "table": t,
                    "lo": cut,
                    "hi": hi,
                    "frm": hot,
                    "to": cold,
                    "epoch": routing.epoch,
                    "share": round(share, 4),
                }
            )
            moved = True
        return routing, moved


def scale_up(
    van,
    table_cfgs,
    routing,
    new_index: int,
    *,
    migrator,
    num_servers: Optional[int] = None,
    device_replies: bool = False,
    sched: Optional[Manager] = None,
    moves: Optional[List[tuple]] = None,
):
    """Spawn ``S{new_index}`` and migrate ranges onto it, live.

    The new server starts owning ZERO rows (present in the cluster, absent
    from the routing table), so workers never see it until the first
    migration commit flips the epoch — no global pause beyond each move's
    bounded freeze window.  ``moves``: explicit ``[(table, lo, hi), ...]``;
    default splits every table's largest segment in half and moves the tail.
    Returns ``(server, routing)``.
    """
    from parameter_server_tpu.core.messages import server_id
    from parameter_server_tpu.core.postoffice import Postoffice
    from parameter_server_tpu.kv.server import KVServer

    num_servers = num_servers if num_servers is not None else new_index + 1
    server = KVServer(
        Postoffice(server_id(new_index), van),
        table_cfgs,
        new_index,
        num_servers,
        device_replies=device_replies,
        routing=routing,
    )
    if moves is None:
        moves = []
        for t, tr in routing.tables.items():
            lo, hi = max(
                (
                    seg
                    for s in routing.servers()
                    for seg in tr.owned_segments(s)
                ),
                key=lambda ab: ab[1] - ab[0],
            )
            if hi - lo >= 2:
                moves.append((t, (lo + hi) // 2, hi))
    for t, lo, hi in moves:
        routing = migrator.migrate(routing, t, lo, hi, new_index, sched=sched)
    return server, routing


def drain_down(
    van,
    routing,
    server_index: int,
    *,
    migrator,
    sched: Optional[Manager] = None,
    plan: Optional[dict] = None,
):
    """Retire live server ``S{server_index}`` with zero loss.

    Data plane first (:meth:`ShardMigrator.drain` migrates every owned range
    off, each with its own bounded freeze), THEN the endpoints are unbound —
    by the time the identity disappears the routing table references it
    nowhere, so workers never time out against it.  Returns the new routing.
    """
    from parameter_server_tpu.core.messages import server_id

    routing = migrator.drain(routing, server_index, sched=sched, plan=plan)
    nid = server_id(server_index)
    for endpoint in (nid, f"{nid}.fw", f"{nid}.mig"):
        try:
            van.unbind(endpoint)
        except Exception:  # noqa: BLE001 — never-bound side endpoints
            pass
    return routing


def restart_server(
    van,
    table_cfgs,
    server_index: int,
    num_servers: int,
    *,
    num_workers: int,
    standby=None,
    ckpt_root: Optional[str] = None,
    heartbeat_timeout: float = 5.0,
    register_timeout: Optional[float] = 30.0,
    **server_kw,
):
    """Full same-id crash-restart lifecycle for server ``S{server_index}``.

    Thin composition over
    :func:`parameter_server_tpu.kv.replica.restart_same_id` that also runs
    the membership half: a fresh :class:`~parameter_server_tpu.core.manager.Manager`
    on the restarted node re-registers with the scheduler, which — seeing an
    existing row for the id — bumps the node's incarnation and broadcasts
    the new binding, fencing the dead process's in-flight frames fleet-wide.

    Restore preference is ``standby`` (zero loss) > ``ckpt_root`` (rewind
    bounded by the checkpoint interval) > cold.  Returns
    ``(server, source, manager)``.
    """
    from parameter_server_tpu.core.manager import Manager
    from parameter_server_tpu.kv.replica import restart_same_id

    restarted: dict = {}

    def register(post) -> None:
        mgr = Manager(
            post,
            num_workers=num_workers,
            num_servers=num_servers,
            heartbeat_timeout=heartbeat_timeout,
        )
        restarted["manager"] = mgr
        if not mgr.register_with_scheduler(register_timeout):
            raise TimeoutError(
                f"restarted {post.node_id} never saw the table broadcast"
            )

    server, source = restart_same_id(
        van,
        table_cfgs,
        server_index,
        num_servers,
        standby=standby,
        ckpt_root=ckpt_root,
        register=register,
        **server_kw,
    )
    return server, source, restarted.get("manager")


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Closed-loop fleet sizing off live SLO verdicts (ISSUE 19).

    The war-game runner ticks :class:`AutoscalePolicy` on its own clock
    with the telemetry plane's current per-node health; the policy answers
    with scale/heal intents.  Thresholds are fractions of the serving
    fleet so the same config drives 8-node smokes and 200-node drills.
    """

    #: fleet size bounds the policy may steer between.
    min_servers: int = 2
    max_servers: int = 16
    #: scale up when at least this fraction of servers is breaching ...
    breach_frac_up: float = 0.25
    #: ... for this many consecutive ticks (debounce single-sweep blips).
    up_after_ticks: int = 2
    #: drain down when the WHOLE fleet has been healthy this many ticks
    #: and utilization headroom exists.
    down_after_ticks: int = 10
    #: per-server load (msgs/s) below which a healthy fleet is considered
    #: overprovisioned; 0 disables drain-down on load.
    drain_below_load: float = 0.0
    #: fraction of the current fleet one scale_up adds (at least one
    #: server) — a 50-node drill needs +10% steps, not +1 node, for added
    #: capacity to outrun the load it is chasing.
    step_frac: float = 0.1
    #: seconds between ANY two actions — migrations must settle before the
    #: controller reads their effect, or it oscillates.
    cooldown_s: float = 30.0

    def __post_init__(self) -> None:
        if self.min_servers < 1:
            raise ValueError(
                f"min_servers must be >= 1, got {self.min_servers!r}"
            )
        if self.max_servers < self.min_servers:
            raise ValueError(
                f"max_servers ({self.max_servers!r}) must be >= "
                f"min_servers ({self.min_servers!r})"
            )
        if not 0.0 < self.breach_frac_up <= 1.0:
            raise ValueError(
                f"breach_frac_up must be in (0, 1], got "
                f"{self.breach_frac_up!r}"
            )
        if self.up_after_ticks < 1 or self.down_after_ticks < 1:
            raise ValueError("*_after_ticks must be >= 1")
        if self.step_frac <= 0.0:
            raise ValueError(
                f"step_frac must be > 0, got {self.step_frac!r}"
            )
        if self.cooldown_s < 0:
            raise ValueError(
                f"cooldown_s must be >= 0, got {self.cooldown_s!r}"
            )


class AutoscalePolicy:
    """SLO-driven fleet sizing: telemetry verdicts in, scale intents out.

    Pure control logic on an EXPLICIT clock — no wall time, no threads —
    so the scenario runner can drive it deterministically in virtual time
    and production can tick it from a monitor sweep.  Each ``tick`` takes
    the current per-node view (``{node: {"healthy": bool, "load": float}}``)
    and returns zero or more intents::

        [{"kind": "scale_up", "count": 5}]           # add count servers
        [{"kind": "drain_down", "node": "S3"}]       # retire the coldest
        [{"kind": "rebalance", "node": "S1"}]        # shed the hottest

    The caller owns execution (``scale_up``/``drain_down``/
    ``RebalancePolicy`` in a live fleet, the simulated equivalents in a
    war game) and reports the fleet size back on the next tick.  Every
    decision lands in ``self.decisions`` for the scorecard.
    """

    def __init__(self, config: Optional[AutoscaleConfig] = None) -> None:
        self.config = config or AutoscaleConfig()
        self._breach_ticks = 0
        self._healthy_ticks = 0
        self._last_action_t: Optional[float] = None
        #: decision log: {"t", "kind", "node"?, "reason"} per intent.
        self.decisions: List[dict] = []

    def _emit(self, now: float, kind: str, reason: str,
              node: Optional[str] = None) -> dict:
        intent = {"t": now, "kind": kind, "reason": reason}
        if node is not None:
            intent["node"] = node
        self.decisions.append(intent)
        self._last_action_t = now
        return intent

    def tick(self, now: float, view: Dict[str, dict]) -> List[dict]:
        """One control sweep at virtual/real time ``now``.

        ``view`` maps server node id -> ``{"healthy": bool, "load":
        float}`` (load in msgs/s or any consistent per-node rate).
        Returns the intents the caller should execute, possibly empty.
        """
        cfg = self.config
        if not view:
            return []
        unhealthy = sorted(n for n, v in view.items() if not v.get("healthy", True))
        frac = len(unhealthy) / len(view)
        if unhealthy:
            self._breach_ticks += 1
            self._healthy_ticks = 0
        else:
            self._healthy_ticks += 1
            self._breach_ticks = 0
        in_cooldown = (
            self._last_action_t is not None
            and now - self._last_action_t < cfg.cooldown_s
        )
        if in_cooldown:
            return []
        intents: List[dict] = []
        if (
            frac >= cfg.breach_frac_up
            and self._breach_ticks >= cfg.up_after_ticks
        ):
            if len(view) < cfg.max_servers:
                count = min(
                    max(1, int(len(view) * cfg.step_frac)),
                    cfg.max_servers - len(view),
                )
                intent = self._emit(
                    now, "scale_up",
                    f"{len(unhealthy)}/{len(view)} breaching",
                )
                intent["count"] = count
                intents.append(intent)
            else:
                # at the ceiling: shed the hottest breaching server's load
                hottest = max(
                    unhealthy, key=lambda n: view[n].get("load", 0.0)
                )
                intents.append(self._emit(
                    now, "rebalance", "breaching at max_servers", hottest
                ))
            self._breach_ticks = 0
        elif (
            not unhealthy
            and self._healthy_ticks >= cfg.down_after_ticks
            and len(view) > cfg.min_servers
            and cfg.drain_below_load > 0.0
        ):
            loads = {n: v.get("load", 0.0) for n, v in view.items()}
            if max(loads.values()) < cfg.drain_below_load:
                coldest = min(sorted(loads), key=lambda n: loads[n])
                intents.append(self._emit(
                    now, "drain_down",
                    f"all healthy, peak load {max(loads.values()):.1f} < "
                    f"{cfg.drain_below_load:.1f}",
                    coldest,
                ))
                self._healthy_ticks = 0
        return intents
