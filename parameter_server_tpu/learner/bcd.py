"""Block coordinate descent scaffold + DARLIN L1-LR (delayed block proximal
gradient with KKT filtering).

Reference analogues (all [U] — reference mount empty, public layout):
``src/learner/bcd.h`` (BCDScheduler/Server/Worker triad, feature-block
partition), ``src/app/linear_method/darlin*.h/.cc`` (delayed block proximal
gradient, bounded delay τ, KKT filter skipping inactive features),
``src/app/linear_method/loss.h`` / ``penalty.h`` (logit loss, L1 prox).

TPU-native shape of the algorithm (SURVEY.md §3.3 "TPU mapping"):

- Workers keep the per-example **margin** vector ``Xw`` on device.  A block
  update only needs ``margin += X[:,b] @ delta_b`` — a segment scatter-add —
  so no full passes over the data are ever taken (this is the whole point of
  the delayed *block* scheme and it maps 1:1 onto device segment ops).
- Block gradient ``g_b = X[:,b]^T (sigma(margin) - y)`` and the diagonal
  curvature bound ``u_b`` are jit-compiled segment-sums over the block's
  nonzeros (static shapes per block).
- The server applies the proximal step ``w_b <- S(w_b - g/u, lambda/u)``
  (soft threshold ``S``) as a jit step and keeps the **KKT active mask**:
  a feature with ``w_j == 0`` and ``|g_j| <= lambda - kkt_delta`` is
  *inactive* — provably ``d_j = 0`` — and is skipped/reported, the
  reference's traffic- and compute-saving filter.
- Within a block the update is BSP (server waits for every worker's partial
  gradient); across blocks up to ``tau`` block-tasks are in flight per
  worker — the reference's bounded-delay pipeline, implemented with parked
  pull replies (the Executor's dependency-park behavior) rather than a DAG.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from parameter_server_tpu.core.messages import Message, Task, TaskKind, server_id
from parameter_server_tpu.core.postoffice import Customer, Postoffice
from parameter_server_tpu.utils import metrics as metrics_lib
from parameter_server_tpu.utils.threads import ErrorGroup


@dataclasses.dataclass(frozen=True)
class BCDConfig:
    num_features: int
    num_blocks: int
    #: L1 penalty weight (lambda) and optional L2.
    l1: float = 1e-3
    l2: float = 0.0
    #: bounded delay: block-tasks in flight per worker (1 = sequential BSP).
    tau: int = 2
    #: KKT filter slack: inactive iff w==0 and |g| <= l1 - kkt_delta.
    kkt_delta: float = 1e-4
    #: trust-region cap on a single coordinate step (DARLIN's delta_max).
    delta_max: float = 1.0
    loss: str = "logistic"  # or "squared"


class BlockPartition:
    """Even contiguous split of the localized feature space into blocks."""

    def __init__(self, num_features: int, num_blocks: int) -> None:
        from parameter_server_tpu.kv.partition import RangePartition

        self.num_features = num_features
        self.num_blocks = num_blocks
        self.offsets = RangePartition(num_features, num_blocks).offsets

    def block_range(self, b: int) -> tuple[int, int]:
        return int(self.offsets[b]), int(self.offsets[b + 1])

    def block_size(self, b: int) -> int:
        lo, hi = self.block_range(b)
        return hi - lo


# -- jit kernels -------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_feat", "loss"))
def _block_grad(margin, labels, rows, cols, n_feat: int, loss: str):
    """Partial gradient + curvature bound of one feature block.

    ``rows``/``cols``: the block's nonzero coordinates (example idx, local
    feature idx), fixed-shape int32.  Binary features (value 1), the CTR
    case; feature values would multiply into the segment sums.
    """
    if loss == "logistic":
        p = jax.nn.sigmoid(margin)
        resid = p - labels  # dl/dmargin for y in {0,1}
        curv_cap = 0.25  # max p(1-p)
    else:  # squared: l = 0.5 (margin - y)^2
        resid = margin - labels
        curv_cap = 1.0
    g = jax.ops.segment_sum(resid[rows], cols, num_segments=n_feat)
    cnt = jax.ops.segment_sum(
        jnp.ones_like(rows, jnp.float32), cols, num_segments=n_feat
    )
    # Joint block update: the diagonal bound alone is NOT a majorizer (cross
    # terms).  For binary X, X_b^T X_b <= r * diag(colsum) with r = max
    # block-nonzeros in any example, so scale u by r to keep the prox step a
    # true descent step (the reference's per-block learning-rate scaling).
    row_cnt = jax.ops.segment_sum(
        jnp.ones_like(rows, jnp.float32), rows, num_segments=margin.shape[0]
    )
    maxrow = jnp.maximum(jnp.max(row_cnt, initial=0.0), 1.0)
    u = curv_cap * cnt * maxrow
    return g, u


@jax.jit
def _apply_margin_delta(margin, rows, cols, delta):
    """margin_i += sum_{nonzeros (i,j) in block} delta_j."""
    return margin.at[rows].add(delta[cols])


@jax.jit
def _prox_step(w, g, u, l1, l2, delta_max, kkt_delta):
    """DARLIN server update for one block.

    Returns (new_w, delta, new_active).  Minimizes the quadratic model
    ``g*d + 0.5*u*d^2 + l1*|w+d|`` per coordinate: ``z = S(w - g/u, l1/u)``,
    ``d = clip(z - w, +-delta_max)``; only KKT-active coordinates move.
    """
    u = u + l2 + 1e-12
    z = w - g / u
    thr = l1 / u
    z = jnp.sign(z) * jnp.maximum(jnp.abs(z) - thr, 0.0)
    d = jnp.clip(z - w, -delta_max, delta_max)
    # KKT check at the *current* point: w==0 and |g| within the subgradient
    # interval (slack kkt_delta) => coordinate provably stays at 0.
    inactive_now = (w == 0.0) & (jnp.abs(g) <= l1 - kkt_delta)
    new_active = ~inactive_now
    d = jnp.where(new_active, d, 0.0)
    return w + d, d, new_active


# -- server ------------------------------------------------------------------


class DarlinServer(Customer):
    """Owns the weight blocks routed to it; aggregates worker partials.

    Blocks are assigned block-cyclically to servers (``b % num_servers``) —
    a block is the key-range unit here, matching the reference's range-
    partitioned weight vector at block granularity.  A PULL for a block
    version not yet applied is parked and answered when the last worker's
    PUSH triggers the prox step (the Executor dependency park).
    """

    def __init__(
        self,
        post: Postoffice,
        cfg: BCDConfig,
        blocks: BlockPartition,
        server_index: int,
        num_servers: int,
        num_workers: int,
        *,
        name: str = "darlin",
    ) -> None:
        super().__init__(name, post)
        self.cfg = cfg
        self.blocks = blocks
        self.server_index = server_index
        self.num_workers = num_workers
        self._state_lock = threading.Lock()
        #: per owned block: weights, active mask, accumulators, applied iter
        self._w: Dict[int, jax.Array] = {}
        self._active: Dict[int, jax.Array] = {}
        self._acc: Dict[tuple, dict] = {}  # (block, iter) -> partial sums
        self._applied: Dict[int, int] = {}  # block -> latest applied iter
        self._delta: Dict[tuple, np.ndarray] = {}  # (block, iter) -> delta
        self._served: Dict[tuple, int] = {}  # (block, iter) -> pulls served
        self._parked: Dict[tuple, List[Message]] = {}
        for b in range(blocks.num_blocks):
            if b % num_servers == server_index:
                n = blocks.block_size(b)
                self._w[b] = jnp.zeros(n, jnp.float32)
                self._active[b] = jnp.ones(n, bool)
                self._applied[b] = -1

    def handle_request(self, msg: Message) -> Optional[Message]:
        b = msg.task.payload["block"]
        it = msg.task.payload["iter"]
        if msg.task.kind == TaskKind.PUSH:
            self._on_push(b, it, msg)
            return msg.reply()
        if msg.task.kind == TaskKind.PULL:
            with self._state_lock:
                if self._applied[b] >= it:
                    return msg.reply(values=[self._take_delta_locked(b, it)])
                self._parked.setdefault((b, it), []).append(msg)
                return None  # parked: answered after the prox step
        raise ValueError(f"unsupported task kind {msg.task.kind}")

    def _take_delta_locked(self, b: int, it: int) -> np.ndarray:
        """Serve one worker's delta pull; free it after the last worker."""
        d = self._delta[(b, it)]
        served = self._served.get((b, it), 0) + 1
        if served >= self.num_workers:
            self._delta.pop((b, it), None)
            self._served.pop((b, it), None)
        else:
            self._served[(b, it)] = served
        return d

    def _on_push(self, b: int, it: int, msg: Message) -> None:
        g, u = msg.values
        release: List[Message] = []
        with self._state_lock:
            acc = self._acc.setdefault(
                (b, it),
                {"g": np.zeros_like(g), "u": np.zeros_like(u), "n": 0},
            )
            acc["g"] += g
            acc["u"] += u
            acc["n"] += 1
            if acc["n"] < self.num_workers:
                return
            del self._acc[(b, it)]
            cfg = self.cfg
            new_w, delta, new_active = _prox_step(
                self._w[b],
                jnp.asarray(acc["g"]),
                jnp.asarray(acc["u"]),
                cfg.l1,
                cfg.l2,
                cfg.delta_max,
                cfg.kkt_delta,
            )
            self._w[b] = new_w
            self._active[b] = new_active
            dnp = np.asarray(delta)
            self._delta[(b, it)] = dnp
            self._applied[b] = it
            release = self._parked.pop((b, it), [])
            # parked pulls count toward the serve quota that frees the delta
            for _ in release:
                self._take_delta_locked(b, it)
        for parked in release:
            self.post.send(parked.reply(values=[dnp]))

    # -- dashboard / eval ----------------------------------------------------
    def weight_stats(self) -> dict:
        with self._state_lock:
            nnz = sum(int((np.asarray(w) != 0).sum()) for w in self._w.values())
            l1_norm = sum(float(np.abs(np.asarray(w)).sum()) for w in self._w.values())
            active = sum(int(np.asarray(a).sum()) for a in self._active.values())
            total = sum(int(w.shape[0]) for w in self._w.values())
        return {"nnz": nnz, "l1_norm": l1_norm, "active": active, "total": total}

    def dense_weights(self) -> np.ndarray:
        """Full weight vector over this server's blocks, for evaluation."""
        out = np.zeros(self.blocks.num_features, np.float32)
        with self._state_lock:
            for b, w in self._w.items():
                lo, hi = self.blocks.block_range(b)
                out[lo:hi] = np.asarray(w)
        return out


# -- worker ------------------------------------------------------------------


class DarlinWorker(Customer):
    """Holds a data shard (CSR over localized features) + the margin vector.

    ``indptr``/``indices`` describe the examples' features (binary values);
    per-block coordinate lists are precomputed once (the SlotReader's
    column-block role) so each block task is two fixed-shape device calls.
    """

    def __init__(
        self,
        post: Postoffice,
        cfg: BCDConfig,
        blocks: BlockPartition,
        num_servers: int,
        indptr: np.ndarray,
        indices: np.ndarray,
        labels: np.ndarray,
        *,
        name: str = "darlin",
    ) -> None:
        super().__init__(name, post)
        self.cfg = cfg
        self.blocks = blocks
        self.num_servers = num_servers
        self.num_examples = labels.shape[0]
        self.labels = jnp.asarray(labels, jnp.float32)
        self.margin = jnp.zeros(self.num_examples, jnp.float32)
        self._margin_lock = threading.Lock()
        # column-block views: example row / local feature col per block
        row_of_nnz = np.repeat(
            np.arange(self.num_examples, dtype=np.int32), np.diff(indptr)
        )
        # device-resident once: block tasks reuse these every epoch
        self._block_rows: List[jnp.ndarray] = []
        self._block_cols: List[jnp.ndarray] = []
        for b in range(blocks.num_blocks):
            lo, hi = blocks.block_range(b)
            sel = (indices >= lo) & (indices < hi)
            self._block_rows.append(jnp.asarray(row_of_nnz[sel]))
            self._block_cols.append(
                jnp.asarray((indices[sel] - lo).astype(np.int32))
            )

    def block_task(self, b: int, it: int, timeout: float = 60.0) -> None:
        """One DARLIN block step: grad -> push -> pull delta -> margin."""
        rows = self._block_rows[b]
        cols = self._block_cols[b]
        n = self.blocks.block_size(b)
        with self._margin_lock:
            margin = self.margin
        g, u = _block_grad(margin, self.labels, rows, cols, n, self.cfg.loss)
        sid = server_id(b % self.num_servers)
        push_ts = self.submit(
            [
                Message(
                    task=Task(
                        TaskKind.PUSH, self.name, payload={"block": b, "iter": it}
                    ),
                    recver=sid,
                    values=[np.asarray(g), np.asarray(u)],
                )
            ]
        )
        pull_ts = self.submit(
            [
                Message(
                    task=Task(
                        TaskKind.PULL, self.name, payload={"block": b, "iter": it}
                    ),
                    recver=sid,
                )
            ],
            keep_responses=True,
        )
        if not self.wait(pull_ts, timeout):
            raise TimeoutError(f"block {b} iter {it} pull timed out")
        (resp,) = self.take_responses(pull_ts)
        delta = jnp.asarray(resp.values[0])
        with self._margin_lock:
            self.margin = _apply_margin_delta(self.margin, rows, cols, delta)
        if not self.wait(push_ts, timeout):
            raise TimeoutError(f"block {b} iter {it} push timed out")

    def logloss(self) -> float:
        """Total (sum) loss over this worker's shard — the unit the DARLIN
        objective is minimized in (gradients are sums, l1 applies to sums)."""
        with self._margin_lock:
            margin = self.margin
        if self.cfg.loss == "logistic":
            ll = jnp.sum(jnp.logaddexp(0.0, margin) - self.labels * margin)
        else:
            ll = 0.5 * jnp.sum((margin - self.labels) ** 2)
        return float(ll)

    def scores(self) -> np.ndarray:
        with self._margin_lock:
            return np.asarray(self.margin)


# -- scheduler ---------------------------------------------------------------


class DarlinScheduler:
    """Drives randomized block iterations with a tau-bounded pipeline.

    Per epoch: shuffle blocks; each worker walks the same order.  A worker
    may start block-task t only once its own task t - tau has fully applied
    (margin updated) — the reference's bounded-delay window.  Within a block
    the server's prox step waits for all workers (BSP), so no per-block
    consistency controller is needed.
    """

    def __init__(
        self,
        cfg: BCDConfig,
        workers: List[DarlinWorker],
        servers: List[DarlinServer],
        *,
        seed: int = 0,
        dashboard: Optional[metrics_lib.Dashboard] = None,
    ) -> None:
        self.cfg = cfg
        self.workers = workers
        self.servers = servers
        self.rng = np.random.default_rng(seed)
        self.dashboard = dashboard or metrics_lib.Dashboard(print_every=0)
        self.history: List[dict] = []

    def objective(self) -> dict:
        """Global objective in sum units: total logloss + l1 penalty.

        (Sum, not mean: worker gradients are sums over examples, so this is
        the function the prox steps provably decrease.)
        """
        loss = float(np.sum([w.logloss() for w in self.workers]))
        n = sum(w.num_examples for w in self.workers)
        stats = [s.weight_stats() for s in self.servers]
        l1_norm = sum(s["l1_norm"] for s in stats)
        return {
            "loss": loss,
            "mean_loss": loss / max(n, 1),
            "objective": loss + self.cfg.l1 * l1_norm,
            "nnz": sum(s["nnz"] for s in stats),
            "active": sum(s["active"] for s in stats),
            "total": sum(s["total"] for s in stats),
        }

    def run(self, num_epochs: int, *, timeout: float = 120.0) -> List[dict]:
        tau = max(1, self.cfg.tau)
        task_iter = 0
        for epoch in range(num_epochs):
            order = self.rng.permutation(self.cfg.num_blocks)
            iters = list(range(task_iter, task_iter + len(order)))
            task_iter += len(order)
            group = ErrorGroup()

            def worker_run(w: DarlinWorker) -> None:
                # tau-bounded pipeline: block-task t starts once t - tau has
                # fully applied; each task runs in a child thread so its
                # gradient/push can overlap the previous task's parked pull.
                done: List[threading.Thread] = []
                for t, (b, it) in enumerate(zip(order, iters)):
                    group.check()
                    if t >= tau:
                        done[t - tau].join(timeout)
                        if done[t - tau].is_alive():
                            raise TimeoutError(
                                f"block task {t - tau} never completed"
                            )
                    done.append(group.spawn(w.block_task, int(b), it, timeout))
                for th in done:
                    th.join(timeout)
                    if th.is_alive():
                        raise TimeoutError("block task never completed")

            threads = [group.spawn(worker_run, w) for w in self.workers]
            for th in threads:
                th.join()
            group.check()
            row = {"epoch": epoch, **self.objective()}
            self.history.append(row)
            self.dashboard.record(epoch, row["objective"], extra=row)
        return self.history

    def dense_weights(self) -> np.ndarray:
        out = np.zeros(self.cfg.num_features, np.float32)
        for s in self.servers:
            out += s.dense_weights()
        return out
