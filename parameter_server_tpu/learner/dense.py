"""Dense-model trainers: GSPMD data-parallel and Van-path async PS.

Covers BASELINE configs #2 (ResNet-50 DP under BSP/SSP) and #4 (BERT-style
async push/pull of dense layers):

- :class:`SpmdDenseTrainer`: one jit-compiled train step over the mesh;
  batch sharded on ``data``, params replicated (DP); the gradient mean over
  the global batch IS the psum over ICI.  BSP by construction.
- :class:`AsyncDenseLearner`: N worker threads each holding a local jit
  train-grad function; per iteration they pull the flat parameter vector
  from the :class:`~parameter_server_tpu.kv.dense.DenseKVServer`s, compute
  gradients on their shard, push, and advance the consistency clock —
  BSP/SSP/ASP selected exactly as in the sparse path.
"""

from __future__ import annotations

import functools
import threading
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

import collections

from parameter_server_tpu.config import ConsistencyConfig
from parameter_server_tpu.core.clock import ConsistencyController
from parameter_server_tpu.core.filters import CompressingFilter
from parameter_server_tpu.kv.dense import (
    DenseKVWorker,
    PytreeCodec,
    fixed_segments,
)
from parameter_server_tpu.parallel import mesh as mesh_lib
from parameter_server_tpu.utils import metrics as metrics_lib
from parameter_server_tpu.utils.threads import run_threads

Batch = Tuple[np.ndarray, np.ndarray]
BatchFn = Callable[[], Batch]


def softmax_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean cross-entropy with integer labels."""
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def _split_variables(variables):
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    return params, extra


class SpmdDenseTrainer:
    """Pure-DP GSPMD trainer for a flax model (BSP)."""

    def __init__(
        self,
        model,
        tx: optax.GradientTransformation,
        mesh,
        example_batch: Batch,
        *,
        seed: int = 0,
        loss_fn=softmax_xent,
        dashboard: Optional[metrics_lib.Dashboard] = None,
    ) -> None:
        self.model = model
        self.tx = tx
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.dashboard = metrics_lib.trainer_dashboard(
            dashboard, mesh.devices.size
        )
        self.step_count = 0
        images, labels = example_batch
        variables = model.init(
            jax.random.PRNGKey(seed), jnp.asarray(images[:1]), train=False
        )
        params, extra = _split_variables(variables)
        repl = mesh_lib.replicated(mesh)
        self.params = jax.device_put(params, repl)
        self.extra = jax.device_put(extra, repl)
        self.opt_state = jax.device_put(tx.init(params), repl)
        self._batch_img = mesh_lib.batch_sharding(mesh, np.asarray(images).ndim)
        self._batch_lbl = mesh_lib.batch_sharding(mesh, 1)

        def train_step(params, extra, opt_state, images, labels):
            def loss(p):
                # mutable=[] (norm-free model) still returns (out, {}) —
                # `or False` would collapse it and break the unpack
                out, new_extra = model.apply(
                    {"params": p, **extra},
                    images,
                    train=True,
                    mutable=list(extra.keys()),
                )
                return self.loss_fn(out, labels), new_extra

            (l, new_extra), grads = jax.value_and_grad(loss, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_extra, opt_state, l

        self._step = jax.jit(
            train_step,
            in_shardings=(repl, repl, repl, self._batch_img, self._batch_lbl),
            out_shardings=(repl, repl, repl, repl),
            donate_argnums=(0, 1, 2),
        )
        # MFU wiring (VERDICT r3 weak #4): no clean closed form for conv
        # nets, so the numerator is XLA's own per-conv FLOP count of the
        # full train step (fwd+bwd+update), from the pre-compile HLO cost
        # analysis of the example batch's shapes.
        img = np.asarray(images)
        lbl = np.asarray(labels)
        step_flops = metrics_lib.lowered_flops(
            self._step,
            self.params,
            self.extra,
            self.opt_state,
            jax.ShapeDtypeStruct(img.shape, jnp.float32),
            jax.ShapeDtypeStruct(lbl.shape, jnp.int32),
        )
        self.dashboard.flops_per_example = step_flops / max(img.shape[0], 1)

    def step(self, images: np.ndarray, labels: np.ndarray) -> float:
        images = jax.device_put(jnp.asarray(images), self._batch_img)
        labels = jax.device_put(jnp.asarray(labels), self._batch_lbl)
        self.params, self.extra, self.opt_state, loss = self._step(
            self.params, self.extra, self.opt_state, images, labels
        )
        loss_f = float(loss)
        self.step_count += 1
        self.dashboard.record(
            self.step_count, loss_f, examples=int(images.shape[0])
        )
        return loss_f

    def eval_logits(self, images: np.ndarray) -> np.ndarray:
        out = self.model.apply(
            {"params": self.params, **self.extra},
            jnp.asarray(images),
            train=False,
        )
        return np.asarray(out)


class AsyncDenseLearner:
    """Async PS training of a dense (flax) model over the Van.

    Workers keep local BatchNorm-style collections (standard async-PS
    behavior); only ``params`` travel through the store.
    """

    def __init__(
        self,
        model,
        workers: list[DenseKVWorker],
        consistency: ConsistencyConfig,
        example_batch: Batch,
        *,
        table: str = "model",
        seed: int = 0,
        loss_fn=softmax_xent,
        dashboard: Optional[metrics_lib.Dashboard] = None,
    ) -> None:
        self.model = model
        self.kv_workers = workers
        self.table = table
        self.controller = ConsistencyController(consistency, len(workers))
        self.dashboard = dashboard or metrics_lib.Dashboard(print_every=0)
        images, labels = example_batch
        variables = model.init(
            jax.random.PRNGKey(seed), jnp.asarray(images[:1]), train=False
        )
        params, extra = _split_variables(variables)
        self.codec = PytreeCodec(params)
        self.init_params = params
        self._extra0 = extra
        self.loss_fn = loss_fn
        self._lock = threading.Lock()
        self._losses: list[float] = []

        def grad_step(params, extra, images, labels):
            def loss(p):
                # mutable=[] (norm-free model) still returns (out, {}) —
                # the old `or False` collapsed that to a bare output and
                # broke the tuple unpack for models with no collections
                out, new_extra = model.apply(
                    {"params": p, **extra},
                    images,
                    train=True,
                    mutable=list(extra.keys()),
                )
                return self.loss_fn(out, labels), new_extra

            (l, new_extra), grads = jax.value_and_grad(loss, has_aux=True)(params)
            return grads, new_extra, l

        self._grad_step = jax.jit(grad_step)

    def initial_vector(self) -> np.ndarray:
        """Flat init vector to seed the servers (pass as init_vectors)."""
        return self.codec.flatten(self.init_params)

    def run(
        self,
        batch_fns: list[BatchFn],
        steps_per_worker: int,
        *,
        timeout: float = 120.0,
    ) -> list[float]:
        run_threads(
            [
                functools.partial(
                    self._worker_loop, kv, batch_fns[i], i, steps_per_worker,
                    timeout,
                )
                for i, kv in enumerate(self.kv_workers)
            ],
            name="dense-worker",
        )
        return list(self._losses)

    def _worker_loop(self, kv, batch_fn, index, steps, timeout):
        extra = self._extra0
        for t in range(steps):
            if not self.controller.wait_turn(index, t, timeout=timeout):
                raise TimeoutError(f"worker {index} stalled at iter {t}")
            images, labels = batch_fn()
            params = self.codec.unflatten(kv.pull_sync(self.table, timeout))
            grads, extra, loss = self._grad_step(
                params, extra, jnp.asarray(images), jnp.asarray(labels)
            )
            ts = kv.push(self.table, self.codec.flatten(grads))
            kv.wait(ts, timeout)
            self.controller.finish_iteration(index)
            with self._lock:
                self._losses.append(float(loss))
                self.dashboard.record(
                    len(self._losses), float(loss), examples=labels.shape[0]
                )


class ChunkedAsyncDenseLearner:
    """Config #4's spine: async PS training with per-segment overlapped
    push/pull of the dense parameter vector (VERDICT r2 missing #2).

    Where :class:`AsyncDenseLearner` ships the whole flat vector per step
    (infeasible for BERT-base over DCN: ~440 MB/worker/step), this learner
    streams fixed-size (or per-layer, ``kv.dense.layer_segments``) element
    segments, each with its own timestamp:

    - every segment push is immediately followed by the NEXT step's pull of
      the same segment — per-link FIFO delivery (Loopback queues / TCP
      streams) guarantees the server applies the push before answering the
      pull.  This eager overlap is exact for a single worker and is the
      normal staleness-tolerant shape under SSP/ASP; under BSP with MULTIPLE
      workers FIFO cannot order one worker's pull after its PEERS' pushes,
      so the learner automatically falls back to pulling after the barrier
      (correct BSP, overlap only within the step);
    - pushes are not individually waited: a bounded-delay window of
      ``consistency.max_delay`` STEPS of unacked pushes may be outstanding
      (the reference's ``Task.wait_time`` τ applied to chunk traffic);
    - ``max_inflight`` records the high-water mark of concurrently pending
      segment tasks — the "&ge;2 chunks in flight" observability hook;
    - byte accounting per step rides the dashboard rows (``push_mb``,
      ``pull_mb``, and ``wire_mb`` when the Van carries a compressing
      ``FilterChain``).

    ``loss_fn(params, *batch) -> scalar`` makes the learner model-agnostic
    (images/labels, MLM triples, ...).
    """

    def __init__(
        self,
        loss_fn,
        example_params,
        workers: list[DenseKVWorker],
        consistency: ConsistencyConfig,
        *,
        table: str = "model",
        segments: Optional[list] = None,
        chunk_elems: int = 1 << 16,
        dashboard: Optional[metrics_lib.Dashboard] = None,
    ) -> None:
        self.kv_workers = workers
        self.table = table
        self.codec = PytreeCodec(example_params)
        self.segments = (
            list(segments)
            if segments is not None
            else fixed_segments(self.codec.total, chunk_elems)
        )
        if not self.segments or self.segments[-1][1] != self.codec.total:
            raise ValueError("segments must cover the full parameter vector")
        self.consistency = consistency
        self.controller = ConsistencyController(consistency, len(workers))
        self.dashboard = dashboard or metrics_lib.Dashboard(print_every=0)
        self.init_params = example_params
        self._grad = jax.jit(jax.value_and_grad(loss_fn))
        self._lock = threading.Lock()
        self._losses: list[float] = []
        #: high-water mark of concurrently in-flight segment tasks
        self.max_inflight = 0

    def initial_vector(self) -> np.ndarray:
        """Flat init vector to seed the servers (pass as init_vectors)."""
        return self.codec.flatten(self.init_params)

    def _note_inflight(self, kv: DenseKVWorker) -> None:
        n = kv.pending_count()
        with self._lock:
            if n > self.max_inflight:
                self.max_inflight = n

    def _wire_mb(self, kv: DenseKVWorker) -> Optional[float]:
        chain = getattr(kv.post.van, "filter_chain", None)
        if chain is None:
            return None
        _bytes_in, out = chain.compressed_bytes()
        return out / 1e6 if out else None

    def run(
        self,
        batch_fns: list,
        steps_per_worker: int,
        *,
        timeout: float = 120.0,
    ) -> list[float]:
        run_threads(
            [
                functools.partial(
                    self._worker_loop, kv, batch_fns[i], i, steps_per_worker,
                    timeout,
                )
                for i, kv in enumerate(self.kv_workers)
            ],
            name="chunked-dense-worker",
        )
        return list(self._losses)

    def _worker_loop(self, kv, batch_fn, index, steps, timeout):
        table, segs = self.table, self.segments
        delay = self.consistency.bound  # None = ASP (unbounded pushes)
        # Eager pulls (issued right behind the pushes) are only sound when
        # no BARRIER-peer update can land later: single worker, or a
        # staleness-tolerant mode.  Multi-worker BSP must pull after the
        # barrier or it reads weights missing its peers' current step.
        eager = len(self.kv_workers) == 1 or delay != 0
        pulls = (
            {i: kv.pull_segment(table, a, b - a) for i, (a, b) in enumerate(segs)}
            if eager
            else None
        )
        push_window: collections.deque[list[int]] = collections.deque()
        vec = np.empty(self.codec.total, np.float32)
        for t in range(steps):
            if not self.controller.wait_turn(index, t, timeout=timeout):
                raise TimeoutError(f"worker {index} stalled at iter {t}")
            bytes0 = (kv.bytes_pushed, kv.bytes_pulled)
            if pulls is None:  # post-barrier pulls (multi-worker BSP)
                pulls = {
                    i: kv.pull_segment(table, a, b - a)
                    for i, (a, b) in enumerate(segs)
                }
            for i, (a, b) in enumerate(segs):
                vec[a:b] = kv.pull_segment_result(pulls[i], timeout)
            params = self.codec.unflatten(vec)
            loss, grads = self._grad(params, *batch_fn())
            gvec = self.codec.flatten(grads)
            step_pushes = []
            pulls = {} if eager else None
            for i, (a, b) in enumerate(segs):
                # push chunk i, then (eager mode) immediately request next
                # step's weights for chunk i: FIFO per link applies the push
                # first, and the pull's latency hides behind the remaining
                # chunks' pushes
                step_pushes.append(kv.push_segment(table, a, gvec[a:b]))
                if eager:
                    pulls[i] = kv.pull_segment(table, a, b - a)
                self._note_inflight(kv)
            push_window.append(step_pushes)
            while len(push_window) > (delay if delay is not None else len(push_window)):
                for ts in push_window.popleft():
                    if not kv.wait(ts, timeout):
                        raise TimeoutError(f"segment push ts={ts} not acked")
            self.controller.finish_iteration(index)
            with self._lock:
                self._losses.append(float(loss))
                extra = {
                    "push_mb": round((kv.bytes_pushed - bytes0[0]) / 1e6, 3),
                    "pull_mb": round((kv.bytes_pulled - bytes0[1]) / 1e6, 3),
                    "inflight_max": self.max_inflight,
                }
                wire = self._wire_mb(kv)
                if wire is not None:
                    extra["wire_mb_total"] = round(wire, 3)
                self.dashboard.record(
                    len(self._losses), float(loss), extra=extra
                )
        # epoch end: drain the push window and any prefetched pulls
        for step_ts in push_window:
            for ts in step_ts:
                kv.wait(ts, timeout)
        for i in pulls or {}:
            kv.pull_segment_result(pulls[i], timeout)
