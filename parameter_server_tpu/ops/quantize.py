"""Quantization codecs for the DCN plane.

ICI traffic needs none of this (XLA collectives ride full-bandwidth links);
cross-host Push/Pull over DCN benefits from int8/fp8 payloads — the analogue
of the reference's fixing_float filter (``src/filter/fixing_float.h`` [U])
and of quantized-allreduce schemes (EQuARX, PAPERS.md [V]).

Symmetric per-tensor (or per-row) int8 with float32 scale; fp8 (e4m3/e5m2)
via pure-numpy bit tricks — no hardware or ml_dtypes dependency, codes ARE
the standard fp8 bit patterns; stochastic rounding optionally matches the
reference's random-round behavior (seeded, caller-provided rng).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


def quantize_int8(
    x: np.ndarray,
    *,
    per_row: bool = False,
    stochastic: bool = False,
    rng: Optional[np.random.Generator] = None,
    seed: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """float array -> (int8 array, float32 scale).  scale shape: [] or [rows,1].

    Stochastic rounding REQUIRES a caller-provided ``rng`` (the filter's
    seeded, lock-guarded generator — ``core/filters.FixingFloatFilter``) or
    an explicit ``seed``.  It used to fall back to an unseeded
    ``np.random.default_rng()`` per call, which silently broke the repo-wide
    seeded-determinism contract (every other randomness source — chaos
    schedules, data shards, noise filters — replays bitwise from a seed).
    """
    x = np.asarray(x, np.float32)
    if per_row and x.ndim >= 2:
        amax = np.max(np.abs(x), axis=tuple(range(1, x.ndim)), keepdims=True)
    else:
        amax = np.max(np.abs(x)) if x.size else np.float32(0.0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    y = x / scale
    if stochastic:
        if rng is None:
            if seed is None:
                raise ValueError(
                    "quantize_int8(stochastic=True) needs rng= or seed=: an "
                    "implicit unseeded generator would break seeded replay "
                    "determinism (thread one from the filter config instead)"
                )
            rng = np.random.default_rng(seed)
        y = np.floor(y + rng.random(y.shape, dtype=np.float32))
    else:
        y = np.rint(y)
    return np.clip(y, -127, 127).astype(np.int8), scale


def dequantize_int8(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return q.astype(np.float32) * np.asarray(scale, np.float32)


# ------------------------------------------------------------------- fp8
#
# fp8 via numpy bit arithmetic: the decode table is generated from the bit
# fields (sign / E exponent bits / M mantissa bits), so a code byte IS the
# standard fp8 bit pattern — a future hardware path can reinterpret the
# same wire plane.  e4m3 follows the "fn" convention (no inf; exp=15,
# man=7 is NaN; max finite 448); e5m2 is IEEE-like (exp=31 non-finite;
# max finite 57344).  Encode is a vectorized nearest/stochastic pick over
# the 2^7 non-negative representable values.

#: fmt -> (exponent bits, mantissa bits, bias, max finite magnitude)
FP8_FORMATS: Dict[str, Tuple[int, int, int, float]] = {
    "e4m3": (4, 3, 7, 448.0),
    "e5m2": (5, 2, 15, 57344.0),
}

#: fmt -> (decode table[256] f32, sorted non-negative values, their codes)
_FP8_TABLES: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}


def _fp8_tables(fmt: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    cached = _FP8_TABLES.get(fmt)
    if cached is not None:
        return cached
    if fmt not in FP8_FORMATS:
        raise ValueError(f"fp8 format must be one of {sorted(FP8_FORMATS)}, "
                         f"got {fmt!r}")
    e_bits, m_bits, bias, _fmax = FP8_FORMATS[fmt]
    codes = np.arange(256, dtype=np.uint16)
    sign = np.where(codes >> 7, -1.0, 1.0)
    exp = ((codes >> m_bits) & ((1 << e_bits) - 1)).astype(np.int64)
    man = (codes & ((1 << m_bits) - 1)).astype(np.float64)
    vals = sign * np.where(
        exp > 0,                                   # normals
        (1.0 + man / (1 << m_bits)) * np.exp2(exp - bias),
        man * np.exp2(1 - bias - m_bits),          # subnormals (exp == 0)
    )
    exp_max = (1 << e_bits) - 1
    if fmt == "e4m3":  # fn: only the all-ones code per sign is non-finite
        bad = (exp == exp_max) & (man == (1 << m_bits) - 1)
    else:              # e5m2: the whole top exponent is inf/NaN
        bad = exp == exp_max
    decode = np.where(bad, np.nan, vals).astype(np.float32)
    # non-negative finite values, ascending (monotone in the bit pattern)
    pos_codes = np.nonzero((codes < 128) & ~bad)[0].astype(np.uint8)
    pos_vals = decode[pos_codes]
    order = np.argsort(pos_vals, kind="stable")
    entry = (decode, pos_vals[order], pos_codes[order])
    _FP8_TABLES[fmt] = entry
    return entry


def quantize_fp8(
    x: np.ndarray,
    *,
    fmt: str = "e4m3",
    per_row: bool = False,
    stochastic: bool = False,
    rng: Optional[np.random.Generator] = None,
    seed: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """float array -> (uint8 fp8 codes, float32 scale).

    The scale maps the array's (per-tensor or per-row) absmax onto the
    format's max finite value, so the fp8 dynamic range is fully used.
    Stochastic rounding picks the bracketing representable value with
    probability proportional to proximity — same seeded-rng contract as
    :func:`quantize_int8` (an implicit unseeded generator is refused).
    """
    decode, pos_vals, pos_codes = _fp8_tables(fmt)
    fmax = FP8_FORMATS[fmt][3]
    x = np.asarray(x, np.float32)
    if per_row and x.ndim >= 2:
        amax = np.max(np.abs(x), axis=tuple(range(1, x.ndim)), keepdims=True)
    else:
        amax = np.max(np.abs(x)) if x.size else np.float32(0.0)
    scale = np.where(amax > 0, amax / fmax, 1.0).astype(np.float32)
    y = np.minimum(np.abs(x / scale), np.float32(fmax))
    if stochastic:
        if rng is None:
            if seed is None:
                raise ValueError(
                    "quantize_fp8(stochastic=True) needs rng= or seed=: an "
                    "implicit unseeded generator would break seeded replay "
                    "determinism (thread one from the filter config instead)"
                )
            rng = np.random.default_rng(seed)
        lo = np.maximum(
            np.searchsorted(pos_vals, y, side="right") - 1, 0
        )
        hi = np.minimum(lo + 1, len(pos_vals) - 1)
        v_lo, v_hi = pos_vals[lo], pos_vals[hi]
        gap = v_hi - v_lo
        frac = np.where(gap > 0, (y - v_lo) / np.where(gap > 0, gap, 1.0), 0.0)
        idx = np.where(rng.random(y.shape, dtype=np.float32) < frac, hi, lo)
    else:
        mid = (pos_vals[:-1] + pos_vals[1:]) * 0.5
        idx = np.searchsorted(mid, y, side="right")
    q = pos_codes[idx]
    return np.where(x < 0, q | np.uint8(0x80), q).astype(np.uint8), scale


def dequantize_fp8(
    q: np.ndarray, scale: np.ndarray, *, fmt: str = "e4m3"
) -> np.ndarray:
    """fp8 codes + scale -> float32.  One table gather — works directly on
    a read-only ``frombuffer`` wire view (the server's pre-H2D path)."""
    decode = _fp8_tables(fmt)[0]
    return decode[np.asarray(q)] * np.asarray(scale, np.float32)
