"""Quantization codecs for the DCN plane.

ICI traffic needs none of this (XLA collectives ride full-bandwidth links);
cross-host Push/Pull over DCN benefits from int8 payloads — the analogue of
the reference's fixing_float filter (``src/filter/fixing_float.h`` [U]) and
of quantized-allreduce schemes (EQuARX, PAPERS.md [V]).

Symmetric per-tensor (or per-row) int8 with float32 scale; stochastic
rounding optionally matches the reference's random-round behavior.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def quantize_int8(
    x: np.ndarray,
    *,
    per_row: bool = False,
    stochastic: bool = False,
    rng: Optional[np.random.Generator] = None,
    seed: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """float array -> (int8 array, float32 scale).  scale shape: [] or [rows,1].

    Stochastic rounding REQUIRES a caller-provided ``rng`` (the filter's
    seeded, lock-guarded generator — ``core/filters.FixingFloatFilter``) or
    an explicit ``seed``.  It used to fall back to an unseeded
    ``np.random.default_rng()`` per call, which silently broke the repo-wide
    seeded-determinism contract (every other randomness source — chaos
    schedules, data shards, noise filters — replays bitwise from a seed).
    """
    x = np.asarray(x, np.float32)
    if per_row and x.ndim >= 2:
        amax = np.max(np.abs(x), axis=tuple(range(1, x.ndim)), keepdims=True)
    else:
        amax = np.max(np.abs(x)) if x.size else np.float32(0.0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    y = x / scale
    if stochastic:
        if rng is None:
            if seed is None:
                raise ValueError(
                    "quantize_int8(stochastic=True) needs rng= or seed=: an "
                    "implicit unseeded generator would break seeded replay "
                    "determinism (thread one from the filter config instead)"
                )
            rng = np.random.default_rng(seed)
        y = np.floor(y + rng.random(y.shape, dtype=np.float32))
    else:
        y = np.rint(y)
    return np.clip(y, -127, 127).astype(np.int8), scale


def dequantize_int8(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return q.astype(np.float32) * np.asarray(scale, np.float32)
